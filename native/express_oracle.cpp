// Native event-loop oracle for the Ben-Or reference semantics.
//
// A C++ re-implementation of benor_tpu/backends/express.py — the
// deterministic re-host of the reference's per-node Express servers
// (/root/reference/src/nodes/node.ts) — used for large-N differential
// testing where the Python oracle's per-message interpreter overhead
// dominates (the drain loop delivers O(N^2) messages per round).
//
// Semantics preserved bit-for-bit with the Python oracle, including the
// reference's behavioral quirks (SURVEY.md §2.1):
//   * unbounded per-round buffers re-firing the tally on every arrival
//     past N-F (quirk 8),
//   * quorum threshold counts raw messages including "?" (quirk 4),
//   * plurality-adopt before the coin (quirk 9),
//   * broadcasts include self (quirk 6),
//   * killed nodes silently drop messages (quirk 3),
//   * global-halt probe after each vote tally (sub-behavior 5e),
//   * faulty nodes crash-from-birth with null state (node.ts:21-26).
//
// The coin stream reproduces CPython's random.Random(seed).random()
// exactly: MT19937 with init_by_array seeding and 53-bit double output,
// so native and Python oracles generate IDENTICAL traces for the same
// (seed, scenario) — verified by tests/test_native_oracle.py.

#include <cstdint>
#include <cstring>
#include <deque>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// MT19937 matching CPython's _randommodule.c (init_by_array seeding).
// ---------------------------------------------------------------------------
class PyMT19937 {
 public:
  explicit PyMT19937(uint32_t seed) {
    // CPython random.seed(int) for small non-negative ints passes the
    // absolute value as a single-element key to init_by_array.
    uint32_t key[1] = {seed};
    init_by_array(key, 1);
  }

  // CPython random_random(): 53-bit double in [0, 1).
  double random() {
    uint32_t a = genrand() >> 5;  // 27 bits
    uint32_t b = genrand() >> 6;  // 26 bits
    return (a * 67108864.0 + b) * (1.0 / 9007199254740992.0);
  }

  // CPython Random.getrandbits(k) for k <= 32.
  uint32_t getrandbits(int k) { return genrand() >> (32 - k); }

  // CPython Random._randbelow_with_getrandbits(n): rejection sampling over
  // n.bit_length() bits — matches random.Random.randrange(n) draw-for-draw.
  uint32_t randbelow(uint32_t n) {
    int k = 32 - __builtin_clz(n);  // bit_length; caller ensures n >= 1
    uint32_t r = getrandbits(k);
    while (r >= n) r = getrandbits(k);
    return r;
  }

 private:
  static constexpr int N = 624;
  static constexpr int M = 397;
  static constexpr uint32_t MATRIX_A = 0x9908b0dfU;
  static constexpr uint32_t UPPER_MASK = 0x80000000U;
  static constexpr uint32_t LOWER_MASK = 0x7fffffffU;

  uint32_t mt_[N];
  int mti_ = N + 1;

  void init_genrand(uint32_t s) {
    mt_[0] = s;
    for (mti_ = 1; mti_ < N; mti_++) {
      mt_[mti_] =
          1812433253U * (mt_[mti_ - 1] ^ (mt_[mti_ - 1] >> 30)) + mti_;
    }
  }

  void init_by_array(const uint32_t *key, int key_length) {
    init_genrand(19650218U);
    int i = 1, j = 0;
    int k = (N > key_length) ? N : key_length;
    for (; k; k--) {
      mt_[i] = (mt_[i] ^ ((mt_[i - 1] ^ (mt_[i - 1] >> 30)) * 1664525U)) +
               key[j] + j;
      i++;
      j++;
      if (i >= N) {
        mt_[0] = mt_[N - 1];
        i = 1;
      }
      if (j >= key_length) j = 0;
    }
    for (k = N - 1; k; k--) {
      mt_[i] = (mt_[i] ^ ((mt_[i - 1] ^ (mt_[i - 1] >> 30)) * 1566083941U)) -
               i;
      i++;
      if (i >= N) {
        mt_[0] = mt_[N - 1];
        i = 1;
      }
    }
    mt_[0] = 0x80000000U;
  }

  uint32_t genrand() {
    uint32_t y;
    static const uint32_t mag01[2] = {0U, MATRIX_A};
    if (mti_ >= N) {
      int kk;
      for (kk = 0; kk < N - M; kk++) {
        y = (mt_[kk] & UPPER_MASK) | (mt_[kk + 1] & LOWER_MASK);
        mt_[kk] = mt_[kk + M] ^ (y >> 1) ^ mag01[y & 1U];
      }
      for (; kk < N - 1; kk++) {
        y = (mt_[kk] & UPPER_MASK) | (mt_[kk + 1] & LOWER_MASK);
        mt_[kk] = mt_[kk + (M - N)] ^ (y >> 1) ^ mag01[y & 1U];
      }
      y = (mt_[N - 1] & UPPER_MASK) | (mt_[0] & LOWER_MASK);
      mt_[N - 1] = mt_[M - 1] ^ (y >> 1) ^ mag01[y & 1U];
      mti_ = 0;
    }
    y = mt_[mti_++];
    y ^= (y >> 11);
    y ^= (y << 7) & 0x9d2c5680U;
    y ^= (y << 15) & 0xefc60000U;
    y ^= (y >> 18);
    return y;
  }
};

// ---------------------------------------------------------------------------
// Oracle state. Values: 0, 1, 2 == "?", -1 == null (faulty).
// ---------------------------------------------------------------------------
constexpr int8_t VALQ = 2;

struct Message {
  int32_t dest;
  int32_t k;
  int8_t x;
  uint8_t phase;  // 0 = proposal, 1 = voting
};

struct Oracle {
  int32_t n, f, max_rounds;
  int64_t step_cap;
  bool shuffle;        // delivery order: false = fifo, true = seeded shuffle
  PyMT19937 rng;       // the protocol coin stream (node.ts:111)
  PyMT19937 drng;      // delivery-order stream (seed derivation matches
                       // backends/express.py: (seed ^ 0x9E3779B9) & 2^32-1)
  std::deque<Message> queue;  // fifo order
  std::vector<Message> bag;   // shuffle order: swap-pop bag
  bool halt_pending = false;

  std::vector<uint8_t> killed, is_faulty, decided;
  std::vector<int8_t> x;
  std::vector<int32_t> k;
  // per-node, per-round tally counts (values 0/1/"?") — equivalent to the
  // Python oracle's unbounded lists, but only counts are ever consumed
  // (node.ts:54-69, 89-98 count; the raw list is never re-read otherwise),
  // and `len >= N-F` re-fires identically off the running total.
  struct Tally {
    int32_t c0 = 0, c1 = 0, cq = 0;
    int32_t len() const { return c0 + c1 + cq; }
  };
  std::vector<std::vector<Tally>> proposals, votes;  // [node][round]

  Oracle(int32_t n_, int32_t f_, int32_t max_rounds_, uint32_t seed,
         int64_t step_cap_, uint8_t order, const int8_t *vals,
         const uint8_t *faulty, const uint8_t *initial_killed)
      : n(n_), f(f_), max_rounds(max_rounds_), step_cap(step_cap_),
        shuffle(order != 0), rng(seed), drng((seed ^ 0x9E3779B9U)),
        killed(n_), is_faulty(faulty, faulty + n_), decided(n_),
        x(n_), k(n_, 0), proposals(n_), votes(n_) {
    for (int32_t i = 0; i < n; i++) {
      // pre-start /stop calls arrive via initial_killed (a healthy node
      // stopped before /start keeps its state but never participates —
      // parity with the Python oracle's stop_node-before-start behavior)
      killed[i] = is_faulty[i] | initial_killed[i];
      x[i] = is_faulty[i] ? -1 : vals[i];
      decided[i] = 0;
      if (is_faulty[i]) k[i] = -1;  // projected to null in the wrapper
      proposals[i].resize(max_rounds + 2);
      votes[i].resize(max_rounds + 2);
    }
  }

  void push(const Message &m) {
    if (shuffle) bag.push_back(m);
    else queue.push_back(m);
  }

  void broadcast(int32_t round, int8_t val, uint8_t phase) {
    if (round > max_rounds) return;  // round cap bounds livelock configs
    for (int32_t i = 0; i < n; i++) push({i, round, val, phase});
  }

  static void bump(Tally &t, int8_t v) {
    if (v == 0) t.c0++;
    else if (v == 1) t.c1++;
    else t.cq++;
  }

  void on_message(const Message &m) {
    int32_t i = m.dest;
    if (killed[i]) return;             // quirk 3: silent drop
    // protocol broadcasts keep 1 <= k <= max_rounds + 1 by construction;
    // INJECTED messages are range-checked by the Python wrapper, and this
    // guard keeps an out-of-range k memory-safe regardless (the tally
    // vectors are sized max_rounds + 2)
    if (m.k < 0 || m.k > max_rounds + 1) return;
    if (m.phase == 0) {                // proposal phase (node.ts:46-82)
      Tally &t = proposals[i][m.k];
      bump(t, m.x);
      if (t.len() >= n - f) {          // quirks 4/8: >=, counts "?"
        int8_t nx = t.c0 > t.c1 ? 0 : (t.c1 > t.c0 ? 1 : VALQ);
        broadcast(m.k, nx, 1);
      }
    } else if (m.phase == 1) {         // voting phase (node.ts:83-158)
      Tally &t = votes[i][m.k];
      bump(t, m.x);
      if (t.len() >= n - f) {
        if (t.c0 > f) {                // node.ts:99-104
          x[i] = 0;
          decided[i] = 1;
        } else if (t.c1 > f) {
          x[i] = 1;
          decided[i] = 1;
        } else if (t.c0 + t.c1 > 0 && t.c0 > t.c1) {  // quirk 9
          x[i] = 0;
        } else if (t.c0 + t.c1 > 0 && t.c0 < t.c1) {
          x[i] = 1;
        } else {
          x[i] = rng.random() > 0.5 ? 0 : 1;  // node.ts:111
        }
        halt_pending = true;           // sub-behavior 5e
        k[i] = m.k + 1;                // node.ts:147 — even if decided
        broadcast(k[i], x[i], 0);
      }
    }
    // phase >= 2: an injected unknown messageType — delivered as a no-op
    // (the reference handler's if/else-if chain ignores it).  It must
    // still occupy a queue slot: under shuffle delivery every pending
    // message perturbs the seeded randbelow draws, so dropping it at
    // enqueue time would shift the whole delivery permutation away from
    // the Python oracle's.
  }

  void run_halt_probe() {
    halt_pending = false;
    // reachedFinality: only decided == false blocks (tests/utils.ts:22-24)
    for (int32_t i = 0; i < n; i++)
      if (!is_faulty[i] && !decided[i]) return;
    for (int32_t i = 0; i < n; i++) killed[i] = 1;
  }

  // Returns delivered-message count, or -1 if the step cap tripped.
  int64_t start() {
    for (int32_t i = 0; i < n; i++) {  // /start fan-out (consensus.ts:3-8)
      if (!killed[i]) {
        k[i] = 1;
        broadcast(1, x[i], 0);
      }
    }
    int64_t steps = 0;
    if (shuffle) {
      while (!bag.empty()) {
        if (steps >= step_cap) return -1;
        uint32_t j = drng.randbelow(static_cast<uint32_t>(bag.size()));
        std::swap(bag[j], bag.back());
        Message m = bag.back();
        bag.pop_back();
        on_message(m);
        if (halt_pending) run_halt_probe();
        steps++;
      }
    } else {
      while (!queue.empty()) {
        if (steps >= step_cap) return -1;
        Message m = queue.front();
        queue.pop_front();
        on_message(m);
        if (halt_pending) run_halt_probe();
        steps++;
      }
    }
    return steps;
  }
};

}  // namespace

extern "C" {

// Runs the full oracle; writes final per-node state into the out arrays.
// `order`: 0 = fifo, 1 = seeded-shuffle delivery.  `killed_io` is in/out:
// on entry the initial killed mask (faulty nodes plus any pre-start /stop
// calls), on exit the final one.
// Returns delivered-message count, or -1 if the safety step cap tripped.
int64_t benor_express_run(int32_t n, int32_t f, int32_t max_rounds,
                          uint32_t seed, int64_t step_cap, uint8_t order,
                          const int8_t *initial_values,
                          const uint8_t *faulty, int8_t *out_x,
                          uint8_t *out_decided, int32_t *out_k,
                          uint8_t *killed_io) {
  Oracle o(n, f, max_rounds, seed, step_cap, order, initial_values, faulty,
           killed_io);
  int64_t steps = o.start();
  std::memcpy(out_x, o.x.data(), n);
  std::memcpy(out_decided, o.decided.data(), n);
  std::memcpy(out_k, o.k.data(), n * sizeof(int32_t));
  std::memcpy(killed_io, o.killed.data(), n);
  return steps;
}

// Injection variant (r5): benor_express_run plus n_inj externally injected
// messages (the reference's POST /message surface, node.ts:43-163) pushed
// into the delivery queue BEFORE the /start fan-out — exactly where the
// Python oracle's pre-start ExpressNetwork.inject_message puts them, so
// injected traces stay bit-equal across languages for either order.
// Killed-at-injection-time targets are skipped (the reference's handler
// body sits inside !killed; the wrapper mirrors the no-response wire
// behavior).  inj_phase: 0 = proposal, 1 = voting.
int64_t benor_express_run_inj(int32_t n, int32_t f, int32_t max_rounds,
                              uint32_t seed, int64_t step_cap, uint8_t order,
                              const int8_t *initial_values,
                              const uint8_t *faulty,
                              int64_t n_inj, const int32_t *inj_dest,
                              const int32_t *inj_k, const int8_t *inj_x,
                              const uint8_t *inj_phase, int8_t *out_x,
                              uint8_t *out_decided, int32_t *out_k,
                              uint8_t *killed_io) {
  Oracle o(n, f, max_rounds, seed, step_cap, order, initial_values, faulty,
           killed_io);
  for (int64_t j = 0; j < n_inj; j++) {
    if (inj_dest[j] < 0 || inj_dest[j] >= n) continue;
    if (o.killed[inj_dest[j]]) continue;
    o.push({inj_dest[j], inj_k[j], inj_x[j], inj_phase[j]});
  }
  int64_t steps = o.start();
  std::memcpy(out_x, o.x.data(), n);
  std::memcpy(out_decided, o.decided.data(), n);
  std::memcpy(out_k, o.k.data(), n * sizeof(int32_t));
  std::memcpy(killed_io, o.killed.data(), n);
  return steps;
}

// Batched variant (r3 VERDICT item 7): one call runs the oracle over an
// [S] seed vector with the same scenario, writing [S, N] out arrays and a
// per-seed delivered-message count into out_steps (-1 where the step cap
// tripped).  Lifts the one-seed-per-ctypes-call restriction so
// differential and DISTRIBUTIONAL tests (rounds-to-decide over ~10^3
// seeds, VERDICT item 4) run at C++ speed end-to-end.  No pre-start
// /stop support in batch mode (initial killed = the faulty mask), which
// is the only mode the distribution studies use.  Returns the number of
// seeds whose step cap tripped (0 = all clean).
int64_t benor_express_run_batch(int32_t n, int32_t f, int32_t max_rounds,
                                const uint32_t *seeds, int64_t n_seeds,
                                int64_t step_cap, uint8_t order,
                                const int8_t *initial_values,
                                const uint8_t *faulty, int8_t *out_x,
                                uint8_t *out_decided, int32_t *out_k,
                                uint8_t *out_killed, int64_t *out_steps) {
  int64_t tripped = 0;
  for (int64_t s = 0; s < n_seeds; s++) {
    // initial killed mask == the faulty mask (no pre-start /stop in batch
    // mode); the ctor only reads it, so the same buffer serves every seed
    Oracle o(n, f, max_rounds, seeds[s], step_cap, order, initial_values,
             faulty, faulty);
    int64_t steps = o.start();
    out_steps[s] = steps;
    if (steps < 0) tripped++;
    std::memcpy(out_x + s * n, o.x.data(), n);
    std::memcpy(out_decided + s * n, o.decided.data(), n);
    std::memcpy(out_k + s * n, o.k.data(), n * sizeof(int32_t));
    std::memcpy(out_killed + s * n, o.killed.data(), n);
  }
  return tripped;
}

}  // extern "C"
