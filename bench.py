"""Headline benchmark: the BASELINE.json north-star workload.

Runs million-node Ben-Or to termination over a grid of fault fractions f —
the "expected-rounds-vs-f curves at N=1M in under 60 s" target — on one real
TPU chip (the driver's default), falling back to a clearly-labeled CPU smoke
run if the TPU backend is unavailable.

Always prints exactly ONE JSON line on stdout and exits 0:
    {"metric": "mc_trials_per_sec_n1e6", "value": <trials/s>,
     "unit": "trials/s", "vs_baseline": <north-star 60s budget / elapsed>,
     "platform": "tpu" | "cpu", ...}
On unrecoverable failure the line carries value 0.0 and an "error" field —
never a bare traceback / non-zero exit (round-1 BENCH_r01.json was rc=1 with
parsed: null; this file's whole job is to make that impossible).

vs_baseline > 1.0 means the full rounds-vs-f sweep finished inside the
60-second north-star budget (the reference itself publishes no numbers and
tops out at N=10 nodes on localhost HTTP — see BASELINE.md).

Modes (env BENCH_MODE):
  sweep  (default) — the N=1M rounds-vs-f sweep described above.
  pallas           — on-chip dense-path tally: pallas kernel vs XLA einsum at
                     N=2048, asserts bit-equality, reports both timings and
                     the speedup (VERDICT r1 item 3: the kernel had only ever
                     run in interpreter mode).

Knobs (env): BENCH_N (default 1_000_000), BENCH_TRIALS (32 — the [T, m]
hypergeometric CDF tables scale with T*N; 32 fits a 16GB v5e chip with
headroom), BENCH_F_FRACS (comma floats, default 0,0.05,0.1,0.15,0.2),
BENCH_MAX_ROUNDS (64), BENCH_REPS (8 timed sweep repetitions),
BENCH_ALLOW_CPU=1 (skip the TPU probe, run the CPU smoke directly),
BENCH_INIT_RETRIES (3), BENCH_PROBE_TIMEOUT (150 s per attempt — first
compile on the real chip is 20-40 s, so 150 s is generous; worst case the
whole probe phase spends ~8 min before the CPU fallback).
Details (per-f curves, compile time) go to stderr.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))

#: The backend probe runs in a THROWAWAY subprocess because the axon TPU
#: plugin's failure modes include both a fast UNAVAILABLE raise (BENCH_r01)
#: and an indefinite hang at backend init (observed round 2) — a hang in the
#: main process would make the whole bench rc-timeout with no JSON line.
_PROBE_CODE = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(obj) -> None:
    print(json.dumps(obj), flush=True)


def probe_backend(timeout_s: float) -> str | None:
    """Initialize the ambient JAX backend in a subprocess; return its
    platform name ('tpu'/'axon'/'cpu'/...), or None on failure/timeout."""
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                           capture_output=True, text=True,
                           timeout=timeout_s, cwd=HERE)
    except subprocess.TimeoutExpired:
        log(f"bench: backend probe timed out after {timeout_s:.0f}s")
        return None
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:]
        log(f"bench: backend probe failed rc={r.returncode} {tail}")
        return None
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1].strip()
    return None


def acquire_platform() -> tuple[str, bool]:
    """Pick the platform to measure on -> (platform, is_fallback).

    BENCH_ALLOW_CPU=1 forces a CPU smoke run.  Otherwise: probe the ambient
    (TPU) backend with retries + backoff; if it never comes up, fall back to
    CPU rather than producing no number at all (the fallback is labeled in
    the output JSON so the artifact stays honest).
    """
    if os.environ.get("BENCH_ALLOW_CPU") == "1":
        return "cpu", False
    retries = int(os.environ.get("BENCH_INIT_RETRIES", 3))
    timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", 150))
    for attempt in range(retries):
        plat = probe_backend(timeout_s)
        if plat and plat != "cpu":
            return plat, False
        if plat == "cpu":  # no accelerator plugged in at all
            log("bench: ambient backend is CPU (no TPU present)")
            return "cpu", True
        if attempt < retries - 1:   # no point sleeping after the last probe
            backoff = 15.0 * (attempt + 1)
            log(f"bench: TPU backend unavailable "
                f"(attempt {attempt + 1}/{retries}); retry in {backoff:.0f}s")
            time.sleep(backoff)
    log("bench: TPU never came up; falling back to CPU smoke run")
    return "cpu", True


def _force_cpu() -> None:
    """conftest.py-style platform forcing (the axon plugin overrides
    JAX_PLATFORMS at import; the config update below wins regardless)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")


def bench_sweep(platform: str, fallback: bool) -> dict:
    """The north-star workload: rounds-vs-f sweep, N=1M (TPU) / 50k (CPU)."""
    import jax

    from benor_tpu.config import SimConfig
    from benor_tpu.sim import run_consensus
    from benor_tpu.state import FaultSpec, init_state
    from benor_tpu.sweep import random_inputs, summarize_final

    on_cpu = platform == "cpu"
    n = int(os.environ.get("BENCH_N", 50_000 if on_cpu else 1_000_000))
    trials = int(os.environ.get("BENCH_TRIALS", 8 if on_cpu else 32))
    reps = int(os.environ.get("BENCH_REPS", 2 if on_cpu else 8))
    fracs = [float(x) for x in os.environ.get(
        "BENCH_F_FRACS", "0,0.05,0.1,0.15,0.2").split(",")]
    max_rounds = int(os.environ.get("BENCH_MAX_ROUNDS", 64))
    seed = int(os.environ.get("BENCH_SEED", 0))

    dev = jax.devices()[0]
    log(f"bench: N={n} trials={trials} f_fracs={fracs} on {dev.platform} "
        f"({dev.device_kind})")

    init_vals = random_inputs(seed, trials, n)

    configs = []
    for frac in fracs:
        f = int(frac * n)
        cfg = SimConfig(
            n_nodes=n, n_faulty=f, trials=trials, max_rounds=max_rounds,
            delivery="quorum", scheduler="uniform", path="histogram",
            fault_model="crash", seed=seed)
        faulty = np.zeros(n, bool)
        faulty[:f] = True  # crash-from-birth mask (launchNodes.ts:8)
        faults = FaultSpec.from_faulty_list(cfg, faulty)
        state = init_state(cfg, init_vals, faults)
        configs.append((frac, cfg, state, faults))

    base_key = jax.random.key(seed)

    # Warm-up: compile every (shape-distinct) config once; compile time is
    # reported separately and excluded from the timed sweep (the cache makes
    # repeat invocations free).
    t0 = time.perf_counter()
    for _, cfg, state, faults in configs:
        r, final = run_consensus(cfg, state, faults, base_key)
        int(r)  # scalar fetch = real completion barrier under the tunnel
    compile_s = time.perf_counter() - t0
    log(f"bench: warm-up (compile+run) {compile_s:.1f}s")

    # Timed sweep: the north-star workload end-to-end, repeated BENCH_REPS
    # times. NOTE: block_until_ready does not actually wait under the axon
    # tunnel runtime — fetching the scalar `rounds` output is what forces
    # (and therefore times) program completion.
    curve = []
    t0 = time.perf_counter()
    for rep in range(reps):
        curve = []
        for frac, cfg, state, faults in configs:
            rounds, final = run_consensus(cfg, state, faults, base_key)
            curve.append((frac, cfg, int(rounds), final, faults))
    elapsed = (time.perf_counter() - t0) / reps

    for frac, cfg, rounds, final, faults in curve:
        dec_frac, mean_k, ones_frac, _ = summarize_final(
            final, faults.faulty, cfg.max_rounds)
        log(f"  f={frac:.2f}: rounds_executed={rounds} "
            f"decided={float(dec_frac):.3f} mean_k={float(mean_k):.2f} "
            f"x1_frac={float(ones_frac):.3f}")

    total_trials = trials * len(fracs)
    log(f"bench: sweep elapsed {elapsed:.2f}s for {total_trials} trials")
    return {
        "metric": _labels("sweep", platform)[0],
        "value": round(total_trials / elapsed, 3),
        "unit": "trials/s",
        "vs_baseline": round(60.0 / elapsed, 3),
        "platform": platform,
        "fallback_cpu": fallback,
        "n": n, "trials": trials, "elapsed_s": round(elapsed, 3),
    }


def bench_pallas(platform: str, fallback: bool) -> dict:
    """Dense-path tally: pallas kernel vs XLA einsum, bit-equality + timing.

    Exercises ops/pallas_tally.py compiled for the REAL chip (interpret=False
    on TPU) — the round-1 gap was that it had only ever run in interpreter
    mode on CPU, so its TPU lowering and HBM-traffic claim were unvalidated.
    """
    import jax
    import jax.numpy as jnp

    from benor_tpu.ops.pallas_tally import dense_counts_pallas
    from benor_tpu.ops.tally import dense_counts

    n = int(os.environ.get("BENCH_N", 2048))
    trials = int(os.environ.get("BENCH_TRIALS", 8))
    reps = int(os.environ.get("BENCH_REPS", 20))
    seed = int(os.environ.get("BENCH_SEED", 0))
    # compile for any accelerator backend (the axon plugin reports platform
    # 'axon', not 'tpu'); interpret only on plain CPU
    interpret = jax.default_backend() == "cpu"

    dev = jax.devices()[0]
    log(f"bench[pallas]: T={trials} N={n} on {dev.platform} "
        f"({dev.device_kind}) interpret={interpret}")

    key = jax.random.key(seed)
    k1, k2, k3 = jax.random.split(key, 3)
    mask = jax.random.bernoulli(k1, 0.8, (trials, n, n))
    sent = jax.random.randint(k2, (trials, n), 0, 3, dtype=jnp.int8)
    alive = jax.random.bernoulli(k3, 0.9, (trials, n))

    xla_fn = jax.jit(dense_counts)

    def run_xla():
        return int(jnp.sum(xla_fn(mask, sent, alive)))

    def run_pallas():
        return int(jnp.sum(dense_counts_pallas(mask, sent, alive,
                                               interpret=interpret)))

    # bit-equality on the real lowering (the parity claim of the kernel)
    a = np.asarray(xla_fn(mask, sent, alive))
    b = np.asarray(dense_counts_pallas(mask, sent, alive,
                                       interpret=interpret))
    np.testing.assert_array_equal(a, b)
    log("bench[pallas]: bit-equality OK")

    run_xla(); run_pallas()  # warm-up / compile
    t0 = time.perf_counter()
    for _ in range(reps):
        run_xla()
    t_xla = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        run_pallas()
    t_pallas = (time.perf_counter() - t0) / reps
    speedup = t_xla / t_pallas if t_pallas > 0 else float("inf")
    log(f"bench[pallas]: xla={t_xla * 1e3:.2f}ms "
        f"pallas={t_pallas * 1e3:.2f}ms speedup={speedup:.2f}x")

    return {
        "metric": "pallas_dense_tally_speedup",
        "value": round(speedup, 3),
        "unit": "x_vs_xla_einsum",
        "vs_baseline": round(speedup, 3),
        "platform": platform,
        "fallback_cpu": fallback,
        "interpret": interpret,
        "xla_ms": round(t_xla * 1e3, 3),
        "pallas_ms": round(t_pallas * 1e3, 3),
        "n": n, "trials": trials,
    }


def _labels(mode: str, platform: str) -> tuple[str, str]:
    """(metric, unit) for the JSON line — shared by success and error paths
    so a failure record is filed under the same metric it would have
    produced."""
    if mode == "pallas":
        return "pallas_dense_tally_speedup", "x_vs_xla_einsum"
    on_cpu = platform == "cpu"
    n = int(os.environ.get("BENCH_N", 50_000 if on_cpu else 1_000_000))
    metric = ("mc_trials_per_sec_n1e6" if n == 1_000_000
              else f"mc_trials_per_sec_n{n}")
    return metric, "trials/s"


def main() -> None:
    mode = os.environ.get("BENCH_MODE", "sweep")
    platform, fallback = acquire_platform()
    if platform == "cpu":
        _force_cpu()
    try:
        if mode == "pallas":
            out = bench_pallas(platform, fallback)
        else:
            out = bench_sweep(platform, fallback)
    except Exception as e:  # noqa: BLE001 — the contract is ONE JSON line
        import traceback
        traceback.print_exc(file=sys.stderr)
        metric, unit = _labels(mode, platform)
        out = {
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "platform": platform,
            "fallback_cpu": fallback,
            "error": f"{type(e).__name__}: {e}",
        }
    emit(out)


if __name__ == "__main__":
    main()
