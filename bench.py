"""Headline benchmark: the BASELINE.json north-star workload.

Runs million-node Ben-Or to termination over a grid of fault fractions f —
the "expected-rounds-vs-f curves at N=1M in under 60 s" target — on
whatever accelerator JAX finds (the driver runs it on one real TPU chip).

Prints ONE JSON line:
    {"metric": "mc_trials_per_sec_n1e6", "value": <trials/s>,
     "unit": "trials/s", "vs_baseline": <north-star 60s budget / elapsed>}

vs_baseline > 1.0 means the full rounds-vs-f sweep finished inside the
60-second north-star budget (the reference itself publishes no numbers and
tops out at N=10 nodes on localhost HTTP — see BASELINE.md).

Knobs (env): BENCH_N (default 1_000_000), BENCH_TRIALS (32 — the [T, m]
hypergeometric CDF tables scale with T*N; 32 fits a 16GB v5e chip with
headroom), BENCH_F_FRACS (comma floats, default 0,0.05,0.1,0.15,0.2),
BENCH_MAX_ROUNDS (64), BENCH_REPS (8 timed sweep repetitions).
Details (per-f curves, compile time) go to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from benor_tpu.config import SimConfig
    from benor_tpu.sim import run_consensus
    from benor_tpu.state import FaultSpec, init_state
    from benor_tpu.sweep import random_inputs, summarize_final

    n = int(os.environ.get("BENCH_N", 1_000_000))
    trials = int(os.environ.get("BENCH_TRIALS", 32))
    reps = int(os.environ.get("BENCH_REPS", 8))
    fracs = [float(x) for x in os.environ.get(
        "BENCH_F_FRACS", "0,0.05,0.1,0.15,0.2").split(",")]
    max_rounds = int(os.environ.get("BENCH_MAX_ROUNDS", 64))
    seed = int(os.environ.get("BENCH_SEED", 0))

    dev = jax.devices()[0]
    log(f"bench: N={n} trials={trials} f_fracs={fracs} on {dev.platform} "
        f"({dev.device_kind})")

    init_vals = random_inputs(seed, trials, n)

    configs = []
    for frac in fracs:
        f = int(frac * n)
        cfg = SimConfig(
            n_nodes=n, n_faulty=f, trials=trials, max_rounds=max_rounds,
            delivery="quorum", scheduler="uniform", path="histogram",
            fault_model="crash", seed=seed)
        faulty = np.zeros(n, bool)
        faulty[:f] = True  # crash-from-birth mask (launchNodes.ts:8)
        faults = FaultSpec.from_faulty_list(cfg, faulty)
        state = init_state(cfg, init_vals, faults)
        configs.append((frac, cfg, state, faults))

    base_key = jax.random.key(seed)

    # Warm-up: compile every (shape-distinct) config once; compile time is
    # reported separately and excluded from the timed sweep (the cache makes
    # repeat invocations free).
    t0 = time.perf_counter()
    for _, cfg, state, faults in configs:
        r, final = run_consensus(cfg, state, faults, base_key)
        int(r)  # scalar fetch = real completion barrier under the tunnel
    compile_s = time.perf_counter() - t0
    log(f"bench: warm-up (compile+run) {compile_s:.1f}s")

    # Timed sweep: the north-star workload end-to-end, repeated BENCH_REPS
    # times. NOTE: block_until_ready does not actually wait under the axon
    # tunnel runtime — fetching the scalar `rounds` output is what forces
    # (and therefore times) program completion.
    curve = []
    t0 = time.perf_counter()
    for rep in range(reps):
        curve = []
        for frac, cfg, state, faults in configs:
            rounds, final = run_consensus(cfg, state, faults, base_key)
            curve.append((frac, cfg, int(rounds), final, faults))
    elapsed = (time.perf_counter() - t0) / reps

    for frac, cfg, rounds, final, faults in curve:
        dec_frac, mean_k, ones_frac, _ = summarize_final(
            final, faults.faulty, cfg.max_rounds)
        log(f"  f={frac:.2f}: rounds_executed={rounds} "
            f"decided={float(dec_frac):.3f} mean_k={float(mean_k):.2f} "
            f"x1_frac={float(ones_frac):.3f}")

    total_trials = trials * len(fracs)
    out = {
        "metric": "mc_trials_per_sec_n1e6",
        "value": round(total_trials / elapsed, 3),
        "unit": "trials/s",
        "vs_baseline": round(60.0 / elapsed, 3),
    }
    log(f"bench: sweep elapsed {elapsed:.2f}s for {total_trials} trials")
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
