"""Headline benchmark: the BASELINE.json north-star workload.

Runs million-node Ben-Or to termination over a grid of fault fractions f —
the "expected-rounds-vs-f curves at N=1M in under 60 s" target — on one real
TPU chip (the driver's default), falling back to a clearly-labeled CPU smoke
run if the TPU backend is unavailable.

Always prints exactly ONE JSON line on stdout and exits 0:
    {"metric": "mc_trials_per_sec_n1e6", "value": <trials/s>,
     "unit": "trials/s", "vs_baseline": <north-star 60s budget / elapsed>,
     "platform": "tpu" | "cpu", ...}
On unrecoverable failure the line carries value 0.0 and an "error" field —
never a bare traceback / non-zero exit (round-1 BENCH_r01.json was rc=1 with
parsed: null; this file's whole job is to make that impossible).

The stdout line is the COMPACT headline only (~1 KB) because the driver
keeps just the last 2,000 chars of stdout — round 3's ~4 KB line
truncated the head fields and parsed: null happened anyway.  The full
record (per-regime curve + complete check blobs) goes to the
`BENCH_DETAIL.json` sidecar and stderr.  The EXACT key set of both —
headline and sidecar — is pinned by `tools/bench_detail_schema.json`,
the single source of truth this docstring deliberately stops
restating (PRs 8-11 each grew the headline's gate-bool set and an
enumerated list here silently drifted): ``_DETAIL_KEYS`` below decides
which blobs leave the stdout line, `_split_headline` derives the
per-blob headline bools, and `tools/check_metrics_schema.py`
(tier-1 via tests/test_metrics_schema.py) validates every capture
against the schema file and recomputes the headline byte budget.

vs_baseline > 1.0 means the full rounds-vs-f sweep finished inside the
60-second north-star budget (the reference itself publishes no numbers and
tops out at N=10 nodes on localhost HTTP — see BASELINE.md).

Modes (env BENCH_MODE):
  sweep  (default) — multi-regime N=1M science sweep: the balanced-input
                     rounds-vs-f curve (genuinely multi-round: balanced
                     inputs + zero crashes + f > 1/3 put the decide
                     threshold above the typical class count), the split
                     delay adversary at s in {0.5, 1.5}, and the
                     private-vs-common-coin contrast under the worst-case
                     adversary — plus hardware accounting (node-rounds/s,
                     XLA cost-model bytes -> HBM roofline estimate) and an
                     embedded pallas bit-equality check so the default
                     driver artifact carries the kernel's on-chip proof.
  pallas           — standalone dense-path tally benchmark: pallas kernel vs
                     XLA einsum at N=2048, bit-equality + timings.

Knobs (env): BENCH_N (default 1_000_000), BENCH_TRIALS (32 — the [T, m]
hypergeometric CDF tables scale with T*N; 32 fits a 16GB v5e chip with
headroom), BENCH_F_FRACS (comma floats, default 0.10,0.25,0.35,0.40,0.45 —
the balanced-curve fault fractions), BENCH_MAX_ROUNDS (64),
BENCH_REPS (8 timed sweep repetitions),
BENCH_ALLOW_CPU=1 (skip the TPU probe, run the CPU smoke directly),
BENCH_INIT_RETRIES (3), BENCH_PROBE_TIMEOUT (150 s per attempt — first
compile on the real chip is 20-40 s, so 150 s is generous; worst case the
whole probe phase spends ~8 min before the CPU fallback).
Details (per-f curves, compile time) go to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(obj) -> None:
    print(json.dumps(obj), flush=True)


#: Fields moved OFF the stdout headline into the sidecar + stderr.  The
#: driver records only the last 2,000 chars of stdout; round 3's line grew
#: to ~4 KB (curve + four embedded pallas-check blobs) and the tail lost the
#: head fields, leaving `parsed: null` — no headline number in the artifact.
_DETAIL_KEYS = ("curve", "pallas_check", "pallas_hist_check",
                "pallas_equiv_check", "pallas_weak_coin_check",
                "pallas_round_check", "pallas_demoted",
                "batched_sweep_check", "flight_recorder", "perfscope",
                "meshscope", "serve", "topo", "sweepscope",
                "kernelscope", "faults", "atlas", "lint")


def _split_headline(out: dict) -> tuple[dict, dict]:
    """(headline, detail): headline is the ONE compact stdout line (science
    gates + a one-number-per-kernel pallas summary + one ``*_ok`` bool
    per sidecar blob); detail carries the full curve and check blobs for
    the sidecar file.  The authoritative key inventory for BOTH halves is
    tools/bench_detail_schema.json — new keys land there first, and
    check_metrics_schema.check_headline re-runs this very function to
    enforce the byte budget."""
    detail = {k: out[k] for k in _DETAIL_KEYS if k in out}
    head = {k: v for k, v in out.items() if k not in _DETAIL_KEYS}
    kernels = {}
    interpret = None
    for short, key in (("dense", "pallas_check"), ("hist", "pallas_hist_check"),
                       ("equiv", "pallas_equiv_check"),
                       ("wcoin", "pallas_weak_coin_check"),
                       ("round", "pallas_round_check")):
        c = out.get(key)
        if not isinstance(c, dict):
            continue
        if "error" in c:
            kernels[short] = "ERR"
        else:
            kernels[short] = c.get("speedup")
            if c.get("interpret") is not None:
                interpret = bool(c["interpret"]) if interpret is None \
                    else (interpret or bool(c["interpret"]))
    head["pallas_speedups"] = kernels
    head["pallas_interpret"] = interpret
    # explicit label next to the ratios: "interpret" numbers price the
    # CPU pallas EMULATOR, not the kernels — perfscope/baseline.py's
    # trajectory walks exclude them from kernel-ratio gating ("compiled"
    # numbers are the real ones)
    head["pallas_speedups_mode"] = (
        None if interpret is None
        else ("interpret" if interpret else "compiled"))
    head["n_regimes"] = len(out.get("curve", []))
    head["pallas_demoted_n"] = len(out.get("pallas_demoted", []))
    fr = out.get("flight_recorder")
    if isinstance(fr, dict):
        # two compact bools on the headline; the recorder-derived series
        # (decide velocity, quiescence histogram) and the audit detail
        # stay in the sidecar.  audit_ok = the witnessed flagship regime
        # upheld every Ben-Or invariant (benor_tpu/audit.py).
        head["recorder_ok"] = bool(fr.get("bit_equal_record_off_on"))
        head["audit_ok"] = bool(fr.get("audit_ok"))
    ps = out.get("perfscope")
    if isinstance(ps, dict):
        # ONE compact bool: manifest complete + non-zero cost model +
        # in-band vs the committed baseline (when comparable); the full
        # per-regime PerfReports live in the sidecar's perfscope blob
        head["perf_ok"] = bool(ps.get("ok"))
    ms = out.get("meshscope")
    if isinstance(ms, dict):
        # ONE compact bool: scaling manifest schema-valid + no straggler
        # trip + in-band vs SCALING_BASELINE.json when comparable; the
        # manifest itself lives in the sidecar's meshscope blob
        head["scaling_ok"] = bool(ms.get("ok"))
    sv = out.get("serve")
    if isinstance(sv, dict):
        # ONE compact bool: serve load test schema-valid + zero client
        # errors + coalescing ratio > 1 + in-band vs SERVE_BASELINE.json
        # when comparable; the manifest lives in the sidecar's serve blob
        head["serve_ok"] = bool(sv.get("ok"))
    sw = out.get("sweepscope")
    if isinstance(sw, dict):
        # ONE compact bool: journal off/on AND resume bit-equal in
        # results + compile counts, overlap-headroom attribution
        # present, sweep manifest schema-valid + in-band vs
        # SWEEP_BASELINE.json when comparable; the manifest lives in
        # the sidecar's sweepscope blob
        head["sweep_obs_ok"] = bool(sw.get("ok"))
    ks = out.get("kernelscope")
    if isinstance(ks, dict):
        # ONE compact bool: telemetry off/on bit-identical in results +
        # compile counts, kernel manifest schema-valid with the
        # predicted/measured byte telescoping present, and in-band vs
        # KERNEL_BASELINE.json when comparable; the per-stage/per-tile
        # attribution lives in the sidecar's kernelscope blob
        head["kernel_obs_ok"] = bool(ks.get("ok"))
    tp = out.get("topo")
    if isinstance(tp, dict):
        # ONE compact bool: topology='complete' bit-identical (results +
        # compile counts) + degree/committee curves ran batched (the
        # committee sweep in one bucket executable) + the torus point
        # audited clean under the relaxed neighborhood invariants; the
        # curves live in the sidecar's topo blob
        head["topo_ok"] = bool(tp.get("ok"))
    atl = out.get("atlas")
    if isinstance(atl, dict):
        # ONE compact bool: search-off bit-identity (results + compile
        # counts), one-bucket-per-generation compile pin on the
        # drop_prob axis, atlas manifest schema-valid with every cliff
        # repro replaying + the partition boundary auditing clean, and
        # in-band vs ATLAS_BASELINE.json when comparable; the full
        # phase atlas lives in the sidecar's atlas blob
        head["atlas_ok"] = bool(atl.get("ok"))
    fl = out.get("faults")
    if isinstance(fl, dict):
        # ONE compact bool: injection off bit-identical (results +
        # compile counts) + the rounds-vs-drop_prob curve ran as ONE
        # bucket executable + the churn/omission/partition points
        # audited clean under the new invariants (down_silence,
        # partition-epoch quorum bound); the curves live in the
        # sidecar's faults blob (kind: faults_manifest)
        head["faults_ok"] = bool(fl.get("ok"))
    head["detail_file"] = "BENCH_DETAIL.json"
    return head, detail


def acquire_platform() -> tuple[str, bool]:
    """Pick the platform to measure on -> (platform, is_fallback).

    BENCH_ALLOW_CPU=1 forces a CPU smoke run.  Otherwise: probe the ambient
    (TPU) backend in a THROWAWAY subprocess (the axon plugin's failure
    modes include both a fast UNAVAILABLE raise — BENCH_r01 — and an
    indefinite hang at backend init — round 2; a hang in the main process
    would make the whole bench rc-timeout with no JSON line) with retries +
    backoff via the shared helper (benor_tpu/utils/backend.py); if it never
    comes up, fall back to CPU rather than producing no number at all (the
    fallback is labeled in the output JSON so the artifact stays honest).
    """
    from benor_tpu.utils.backend import probe_with_retries

    if os.environ.get("BENCH_ALLOW_CPU") == "1":
        return "cpu", False
    retries = int(os.environ.get("BENCH_INIT_RETRIES", 3))
    timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", 150))
    plat = probe_with_retries(retries, timeout_s, backoff_s=15.0,
                              log=lambda s: log(f"bench: {s}"), cwd=HERE)
    if plat and plat != "cpu":
        return plat, False
    if plat == "cpu":  # no accelerator plugged in at all
        log("bench: ambient backend is CPU (no TPU present)")
        return "cpu", True
    log("bench: TPU never came up; falling back to CPU smoke run")
    return "cpu", True


def _force_cpu() -> None:
    """conftest.py-style platform forcing (the axon plugin overrides
    JAX_PLATFORMS at import; the config update below wins regardless)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")


def _enable_compile_cache() -> None:
    """Repo-local persistent compilation cache: the 10-regime warm-up costs
    ~50-75 s of (remote) compiles per cold bench invocation; the cache cuts
    repeats to ~13 s.  Best-effort — a failure must not take the bench
    down (the helper defaults to the same repo-root .jax_cache and catches
    everything except a broken import)."""
    try:
        from benor_tpu.utils.cache import enable_compile_cache
        enable_compile_cache()
    except Exception as e:  # noqa: BLE001
        log(f"bench: compile cache unavailable: {e}")


def _hbm_peak_for(device_kind: str):
    """Peak HBM bandwidth for the roofline estimate.  The table itself
    moved to benor_tpu/perfscope/roofline.py (with a FLOPs twin) so the
    per-regime PerfReports and this end-to-end estimate read the same
    published numbers; lazy import because platform forcing must precede
    any jax-importing module."""
    from benor_tpu.perfscope.roofline import hbm_peak_for
    return hbm_peak_for(device_kind)


def _regimes(n, trials, fracs, max_rounds, seed, use_pallas_hist=False):
    """The measured workload set -> [(name, cfg, state, faults)].

    Three families (round-2 VERDICT item 1 — each exercises multi-round
    dynamics at N=1M instead of the degenerate always-1-round curve):

      balanced_f*:  perfectly balanced inputs, ZERO crashes (F is only the
                    protocol parameter — with crash-from-birth faults alive
                    equals the quorum and the hypergeometric sampler draws
                    the whole population, deterministically).  For f > 1/3
                    the decide threshold count > F sits above the typical
                    class count m/2, so lanes random-walk for a few rounds:
                    mean_k genuinely varies with f.
      biased_s*:    the split delay adversary (even receivers starved of 1s,
                    odd of 0s) at fractional and strict strength.
      adv_*:        the worst-case count-controlling adversary: private
                    coins livelock (decided ~ 0 at the round cap), the
                    shared common coin escapes in O(1) rounds — the classic
                    Ben-Or-vs-Rabin contrast, at N=1M.

    Plus iid_crash_f0.20: round-2's original workload (iid inputs, crash
    faults) kept for continuity with BENCH_r02.json.
    """
    from benor_tpu.config import SimConfig
    from benor_tpu.state import FaultSpec, init_state
    from benor_tpu.sweep import balanced_inputs, random_inputs
    import jax.numpy as jnp

    def no_crash(cfg):
        return FaultSpec.none(trials, n)

    # The fused pallas sampler serves the uniform-scheduler CF regime (the
    # flagship path) ~5x faster; engaged on TPU only — its interpret-mode
    # fallback would dominate the CPU smoke run.  Statistically identical
    # stream (tests/test_pallas_hist.py), so the curve is the same science.
    # The fully-fused round kernels ride on top for every regime except
    # the biased scheduler (no closed form): the uniform regimes sample
    # tallies in-kernel; the adversarial/targeted regimes feed their
    # closed-form counts in as broadcast scalars (counts_mode
    # delivered/camps).  Adjudicated ON-CHIP at N=1M x 32 on v5 lite —
    # 1.174x (crash) / 1.076x (equivocate) vs the unfused pallas path,
    # bit-identical (BENCH_TPU.json pallas_round_check, 2026-07-31; the
    # r4 interpret-mode 0.478x was interpreter overhead, not kernel
    # truth).
    base = dict(n_nodes=n, trials=trials, max_rounds=max_rounds,
                delivery="quorum", path="histogram", fault_model="crash",
                seed=seed, use_pallas_hist=use_pallas_hist,
                use_pallas_round=use_pallas_hist)
    # zero-margin inputs (the round-2 degenerate curve came from iid
    # inputs whose sqrt(N) margin drowned the sampling noise)
    bal = balanced_inputs(trials, n)
    regs = []

    # r2-continuity point: iid inputs, crash-from-birth faults, f=0.2
    f = int(0.2 * n)
    cfg = SimConfig(scheduler="uniform", n_faulty=f, **base)
    faulty = np.zeros(n, bool)
    faulty[:f] = True  # crash-from-birth mask (launchNodes.ts:8)
    faults = FaultSpec.from_faulty_list(cfg, faulty)
    regs.append(("iid_crash_f0.20", cfg,
                 init_state(cfg, random_inputs(seed, trials, n), faults),
                 faults))

    # the rounds-vs-f curve: balanced inputs, no crashes, uniform scheduler
    for frac in fracs:
        cfg = SimConfig(scheduler="uniform", n_faulty=int(frac * n), **base)
        fl = no_crash(cfg)
        regs.append((f"balanced_f{frac:.2f}", cfg,
                     init_state(cfg, bal, fl), fl))

    # split delay adversary, fractional + strict strength, f = 0.25
    for s in (0.5, 1.5):
        cfg = SimConfig(scheduler="biased", adversary_strength=s,
                        n_faulty=int(0.25 * n), **base)
        fl = no_crash(cfg)
        regs.append((f"biased_s{s}", cfg, init_state(cfg, bal, fl), fl))

    # count-controlling adversary: private coin livelocks (cap the rounds),
    # common coin escapes — even quorum required for a perfect tie (f=0.2)
    f = int(0.2 * n)
    f += (n - f) % 2          # make the quorum N-F even
    for coin, cap in (("private", min(12, max_rounds)),
                      ("common", max_rounds)):
        cfg = SimConfig(scheduler="adversarial", coin_mode=coin,
                        **{**base, "max_rounds": cap, "n_faulty": f})
        fl = no_crash(cfg)
        regs.append((f"adv_{coin}", cfg, init_state(cfg, bal, fl), fl))

    # weak-coin termination transition: the count adversary ties off the
    # deviating minority, so eps* = 1 - f (= 0.6 at f = 0.4); one eps
    # either side, offsets wide enough to stay decisive at the CPU-smoke N
    f_wk = int(0.4 * n)
    f_wk += (n - f_wk) % 2    # even quorum: ties need it (cf. adv_* above)
    for eps in (0.55, 0.65):
        cfg = SimConfig(scheduler="adversarial", coin_mode="weak_common",
                        adversary_strength=0.0, coin_eps=eps,
                        **{**base, "max_rounds": min(12, max_rounds),
                           "n_faulty": f_wk})
        fl = no_crash(cfg)
        regs.append((f"weak_eps{eps}", cfg, init_state(cfg, bal, fl), fl))

    # the targeted (partitioned) adversary's 0/1 safety curve, one point
    # each side of the f = 1/2 boundary: below it agreement is violated
    # outright (disagree = 1), above it the decide bar is unreachable
    f_tg = int(0.25 * n)
    f_tg += (n - f_tg) % 2    # even quorum: the "?"-manufacturing needs it
    for name, f, cap in (("targeted_f0.25", f_tg, 16),
                         ("targeted_f0.50", n // 2 + 1, 12)):
        # use_pallas_hist off: no sampler exists for this scheduler.  The
        # fused ROUND kernels still serve it (counts_mode='camps' — the
        # closed-form camp triples broadcast in-VMEM), riding base's
        # use_pallas_round.
        cfg = SimConfig(scheduler="targeted",
                        **{**base, "max_rounds": min(cap, max_rounds),
                           "n_faulty": f, "use_pallas_hist": False})
        fl = no_crash(cfg)
        regs.append((name, cfg, init_state(cfg, bal, fl), fl))

    # the N > 3F Byzantine bound, one F either side: adversary-controlled
    # equivocators vs the common coin.  sub (3F < N) must decide; super
    # (3F > N) must livelock even with the common coin (the impossibility).
    f_sub = n // 3 - (1 if n % 3 == 0 else 0)   # largest F with 3F < N
    for name, f, cap in (("equiv_3f_sub", f_sub, max_rounds),
                         ("equiv_3f_super", n // 3 + 1,
                          min(12, max_rounds))):
        # like the targeted regimes: no sampler (counts are closed-form
        # under the count adversary), but the fused round kernels engage
        # via base's use_pallas_round (counts_mode='delivered')
        cfg = SimConfig(scheduler="adversarial", coin_mode="common",
                        **{**base, "fault_model": "equivocate",
                           "max_rounds": cap, "n_faulty": f,
                           "use_pallas_hist": False})
        fl = FaultSpec.first_f(cfg)             # alive equivocators
        regs.append((name, cfg, init_state(cfg, bal, fl), fl))

    # uniform-scheduler equivocate at flagship scale: the regime whose
    # tallies run the fused mixed-population ROUND kernels at N=1M when
    # the pallas path is on (r4 VERDICT task 6); equivocators are alive,
    # so the quorum sees the full population and n_equiv = F
    f_eq = int(0.2 * n)
    cfg = SimConfig(scheduler="uniform",
                    **{**base, "fault_model": "equivocate",
                       "n_faulty": f_eq})
    fl = FaultSpec.first_f(cfg)
    regs.append(("equiv_uniform_f0.20", cfg, init_state(cfg, bal, fl), fl))
    return regs


def _dense_parity_case(seed: int, trials: int, n: int):
    """The dense-tally parity fixture + bit-equality assertion shared by the
    embedded default-mode check and the standalone BENCH_MODE=pallas mode —
    one copy so both artifacts always validate the same workload.
    Returns (mask, sent, alive, interpret)."""
    import jax
    import jax.numpy as jnp

    from benor_tpu.ops.pallas_tally import dense_counts_pallas
    from benor_tpu.ops.tally import dense_counts

    interpret = jax.default_backend() == "cpu"
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    mask = jax.random.bernoulli(k1, 0.8, (trials, n, n))
    sent = jax.random.randint(k2, (trials, n), 0, 3, dtype=jnp.int8)
    alive = jax.random.bernoulli(k3, 0.9, (trials, n))

    a = np.asarray(jax.jit(dense_counts)(mask, sent, alive))
    b = np.asarray(dense_counts_pallas(mask, sent, alive,
                                       interpret=interpret))
    np.testing.assert_array_equal(a, b)
    return mask, sent, alive, interpret


def _pallas_check(seed: int) -> dict:
    """Compact on-chip pallas artifact inside the default bench (round-2
    VERDICT item 4: BENCH_MODE=pallas existed but the driver only captures
    the default invocation, so the kernel's TPU lowering had no shipped
    proof).  Asserts bit-equality vs the XLA einsum and times both."""
    import jax
    import jax.numpy as jnp

    from benor_tpu.ops.pallas_tally import dense_counts_pallas
    from benor_tpu.ops.tally import dense_counts

    trials, n = 8, 2048
    mask, sent, alive, interpret = _dense_parity_case(seed, trials, n)

    # Time with an IN-GRAPH repetition loop: a per-dispatch host loop would
    # measure mostly tunnel round-trip latency (~60 ms), not the kernel.
    loops = 2 if interpret else 30

    def time_it(op):
        @jax.jit
        def reps_fn(m, s, al):
            def body(_, acc):
                return acc + jnp.sum(op(m, s, al))
            return jax.lax.fori_loop(0, loops, body, jnp.int32(0))
        int(reps_fn(mask, sent, alive))              # warm-up barrier
        t0 = time.perf_counter()
        int(reps_fn(mask, sent, alive))
        return (time.perf_counter() - t0) / loops

    t_xla = time_it(dense_counts)
    t_pallas = time_it(lambda m, s, al: dense_counts_pallas(
        m, s, al, interpret=interpret))
    return {
        "bit_equal": True, "interpret": interpret,
        "n": n, "trials": trials,
        "xla_ms": round(t_xla * 1e3, 3),
        "pallas_ms": round(t_pallas * 1e3, 3),
        "speedup": round(t_xla / t_pallas, 3) if t_pallas > 0 else None,
    }


def _pallas_hist_check(n: int, trials: int, seed: int) -> dict:
    """On-chip proof + timing for the flagship-path kernel
    (ops/pallas_hist.py): the fused threefry+CF sampler vs the XLA
    grid_uniforms pipeline at the bench's own (N, T) operating point.
    In-graph repetition loops, so tunnel dispatch latency cancels."""
    import jax
    import jax.numpy as jnp

    from benor_tpu.ops import rng, sampling
    from benor_tpu.ops.pallas_hist import cf_counts_pallas

    interpret = jax.default_backend() == "cpu"
    m = int(0.55 * n)
    hist = jnp.tile(jnp.array(
        [[int(0.4 * n), int(0.38 * n), n - int(0.4 * n) - int(0.38 * n)]],
        jnp.int32), (trials, 1))
    loops = 2 if interpret else 10

    @jax.jit
    def xla_loop(key):
        def body(i, acc):
            tid, nid = rng.ids(trials), rng.ids(n)
            u0 = rng.grid_uniforms(key, i, 0, tid, nid)
            u1 = rng.grid_uniforms(key, i, 16, tid, nid)
            c = sampling.multivariate_hypergeom_counts(u0, u1, hist, m)
            return acc + jnp.sum(c[0, 0])
        return jax.lax.fori_loop(0, loops, body, jnp.int32(0))

    @jax.jit
    def pallas_loop(key):
        def body(i, acc):
            c = cf_counts_pallas(key, i, 0, hist, m, n,
                                 interpret=interpret)
            return acc + jnp.sum(c[0, 0])
        return jax.lax.fori_loop(0, loops, body, jnp.int32(0))

    key = jax.random.key(seed)
    int(xla_loop(key)); int(pallas_loop(key))    # warm-up barriers
    t0 = time.perf_counter(); int(xla_loop(key))
    t_xla = (time.perf_counter() - t0) / loops
    t0 = time.perf_counter(); int(pallas_loop(key))
    t_pallas = (time.perf_counter() - t0) / loops

    # moment sanity on one draw (exact mean m*c0/total, std per sampler)
    c = np.asarray(cf_counts_pallas(key, jnp.int32(1), 0, hist, m, n,
                                    interpret=interpret))
    h0 = c[..., 0].astype(np.float64)
    exp_mean = m * 0.4
    assert abs(h0.mean() - exp_mean) < 0.01 * exp_mean
    assert (c.sum(-1) == m).all()

    return {
        "interpret": interpret, "n": n, "trials": trials, "m": m,
        "xla_ms": round(t_xla * 1e3, 3),
        "pallas_ms": round(t_pallas * 1e3, 3),
        "speedup": round(t_xla / t_pallas, 3) if t_pallas > 0 else None,
    }


def _pallas_equiv_check(n: int, trials: int, seed: int) -> dict:
    """On-chip proof + timing for the equivocate-regime kernel
    (ops/pallas_hist.py:equiv_counts_pallas) vs its four-grid_uniforms XLA
    pipeline at the bench's own (N, T) operating point — the source of the
    README's equivocate-kernel speedup figure, regenerated by every bench
    run (a Mosaic lowering failure of this kernel surfaces here, not in
    some unshipped side script)."""
    import jax
    import jax.numpy as jnp

    from benor_tpu.ops import rng, sampling
    from benor_tpu.ops.pallas_hist import equiv_counts_pallas

    interpret = jax.default_backend() == "cpu"
    m = int(0.55 * n)
    hist = jnp.tile(jnp.array(
        [[int(0.3 * n), int(0.28 * n), int(0.12 * n)]], jnp.int32),
        (trials, 1))
    n_equiv = jnp.full((trials,), int(0.3 * n), jnp.int32)
    loops = 2 if interpret else 10

    @jax.jit
    def xla_loop(key):
        def body(i, acc):
            tid, nid = rng.ids(trials), rng.ids(n)
            u_b = rng.grid_uniforms(key, i, 32, tid, nid)
            u0 = rng.grid_uniforms(key, i, 0, tid, nid)
            u1 = rng.grid_uniforms(key, i, 16, tid, nid)
            u_s = rng.grid_uniforms(key, i, 48, tid, nid)
            c = sampling.equivocate_hypergeom_counts(
                u_b, u0, u1, u_s, hist, n_equiv, m)
            return acc + jnp.sum(c[0, 0])
        return jax.lax.fori_loop(0, loops, body, jnp.int32(0))

    @jax.jit
    def pallas_loop(key):
        def body(i, acc):
            c = equiv_counts_pallas(key, i, 0, hist, n_equiv, m, n,
                                    interpret=interpret)
            return acc + jnp.sum(c[0, 0])
        return jax.lax.fori_loop(0, loops, body, jnp.int32(0))

    key = jax.random.key(seed)
    int(xla_loop(key)); int(pallas_loop(key))    # warm-up barriers
    t0 = time.perf_counter(); int(xla_loop(key))
    t_xla = (time.perf_counter() - t0) / loops
    t0 = time.perf_counter(); int(pallas_loop(key))
    t_pallas = (time.perf_counter() - t0) / loops

    c = np.asarray(equiv_counts_pallas(key, jnp.int32(1), 0, hist, n_equiv,
                                       m, n, interpret=interpret))
    assert (c.sum(-1) == m).all()
    # class-mean sanity on the real lowering (sum-to-m alone is trivially
    # true by construction — hq is derived): class-0 draws come from the
    # honest c0 pool plus half the delivered equivocators in expectation
    h0 = c[..., 0].astype(np.float64)
    exp_mean = m * (int(0.3 * n) + int(0.3 * n) / 2) / float(n)
    assert abs(h0.mean() - exp_mean) < 0.01 * exp_mean, (h0.mean(), exp_mean)

    return {
        "interpret": interpret, "n": n, "trials": trials, "m": m,
        "xla_ms": round(t_xla * 1e3, 3),
        "pallas_ms": round(t_pallas * 1e3, 3),
        "speedup": round(t_xla / t_pallas, 3) if t_pallas > 0 else None,
    }


def _pallas_weak_coin_check(n: int, trials: int, seed: int) -> dict:
    """On-chip proof + timing for the fused weak-coin kernel
    (ops/pallas_hist.py:weak_coin_flips_pallas) vs the XLA three-stream
    helper, plus the eps-limit identities (private kernel / shared bit)."""
    import jax
    import jax.numpy as jnp

    from benor_tpu.ops import rng
    from benor_tpu.ops.pallas_hist import (coin_flips_pallas,
                                           weak_coin_flips_pallas)

    interpret = jax.default_backend() == "cpu"
    eps = 0.5
    key = jax.random.key(seed)
    shared = rng.coin_flips(key, jnp.int32(2), rng.ids(trials), rng.ids(1),
                            common=True)[:, 0]
    loops = 2 if interpret else 10

    @jax.jit
    def xla_loop(key):
        def body(i, acc):
            c = rng.weak_common_coin_flips(key, i, rng.ids(trials),
                                           rng.ids(n), eps)
            return acc + jnp.sum(c[0].astype(jnp.int32))
        return jax.lax.fori_loop(0, loops, body, jnp.int32(0))

    @jax.jit
    def pallas_loop(key):
        def body(i, acc):
            sh = rng.coin_flips(key, i, rng.ids(trials), rng.ids(1),
                                common=True)[:, 0]
            c = weak_coin_flips_pallas(key, i, trials, n, eps, sh,
                                       interpret=interpret)
            return acc + jnp.sum(c[0].astype(jnp.int32))
        return jax.lax.fori_loop(0, loops, body, jnp.int32(0))

    int(xla_loop(key)); int(pallas_loop(key))    # warm-up barriers
    t0 = time.perf_counter(); int(xla_loop(key))
    t_xla = (time.perf_counter() - t0) / loops
    t0 = time.perf_counter(); int(pallas_loop(key))
    t_pallas = (time.perf_counter() - t0) / loops

    # eps-limit identities on the real lowering
    a = np.asarray(weak_coin_flips_pallas(key, jnp.int32(2), trials, n, 1.0,
                                          shared, interpret=interpret))
    b = np.asarray(coin_flips_pallas(key, jnp.int32(2), trials, n,
                                     interpret=interpret))
    np.testing.assert_array_equal(a, b)
    c0 = np.asarray(weak_coin_flips_pallas(key, jnp.int32(2), trials, n, 0.0,
                                           shared, interpret=interpret))
    assert (c0 == np.asarray(shared)[:, None]).all()

    return {
        "interpret": interpret, "n": n, "trials": trials, "eps": eps,
        "limits_bit_equal": True,
        "xla_ms": round(t_xla * 1e3, 3),
        "pallas_ms": round(t_pallas * 1e3, 3),
        "speedup": round(t_xla / t_pallas, 3) if t_pallas > 0 else None,
    }


def _pallas_round_check(n: int, trials: int, seed: int) -> dict:
    """On-chip proof + timing for the fully-fused vote-phase kernel
    (ops/pallas_round.py, r3 VERDICT item 2): a full consensus run with
    use_pallas_round on must be BIT-IDENTICAL to the unfused pallas path
    (same streams) and is timed end-to-end on the flagship multi-round
    regime (balanced inputs, zero crashes, f=0.40)."""
    import jax
    import numpy as np

    from benor_tpu.config import SimConfig
    from benor_tpu.sim import run_consensus
    from benor_tpu.state import FaultSpec, init_state
    from benor_tpu.sweep import balanced_inputs

    interpret = jax.default_backend() == "cpu"
    if interpret:
        # interpret-mode pallas inside the while-loop is far slower than
        # the compiled CPU smoke regimes (which run pallas off-CPU only);
        # shrink to the smallest N whose quorum still clears the CF-regime
        # gate so the check exercises the real kernel branch
        from benor_tpu.ops import sampling
        n = min(n, 2 * sampling.EXACT_TABLE_MAX)
        trials = min(trials, 4)

    def pair(fault_model, f_frac, scheduler="uniform", coin_mode="private",
             max_rounds=64):
        f = int(f_frac * n)
        if scheduler == "adversarial":
            f += (n - f) % 2          # even quorum: the tie needs it
        outs, times = [], []
        for use_round in (False, True):
            cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials,
                            delivery="quorum", scheduler=scheduler,
                            coin_mode=coin_mode,
                            path="histogram", fault_model=fault_model,
                            use_pallas_hist=scheduler == "uniform",
                            use_pallas_round=use_round,
                            max_rounds=max_rounds,
                            seed=seed)
            # zero crashes on the flagship regime (crash faults clamp the
            # draws); equivocators stay ALIVE, so first_f is non-vacuous
            faults = (FaultSpec.first_f(cfg)
                      if fault_model == "equivocate"
                      else FaultSpec.none(trials, n))
            state = init_state(cfg, balanced_inputs(trials, n), faults)
            key = jax.random.key(seed)
            r, fin = run_consensus(cfg, state, faults, key)
            int(r)                               # compile + completion
            loops = 1 if interpret else 5
            t0 = time.perf_counter()
            for _ in range(loops):
                r, fin = run_consensus(cfg, state, faults, key)
            int(r)
            times.append((time.perf_counter() - t0) / loops)
            outs.append((int(r), np.asarray(fin.x),
                         np.asarray(fin.decided), np.asarray(fin.k)))
        (r0, x0, d0, k0), (r1, x1, d1, k1) = outs
        assert r0 == r1
        np.testing.assert_array_equal(x0, x1)
        np.testing.assert_array_equal(d0, d1)
        np.testing.assert_array_equal(k0, k1)
        return {
            "bit_equal": True, "interpret": interpret,
            "n": n, "trials": trials, "rounds": r0,
            "unfused_ms": round(times[0] * 1e3, 3),
            "fused_ms": round(times[1] * 1e3, 3),
            "speedup": (round(times[0] / times[1], 3)
                        if times[1] > 0 else None),
        }

    res = pair("crash", 0.40)          # the flagship multi-round regime
    # the equivocate regime's fused mixed-population kernels (r4 VERDICT
    # task 6): same bit-identity contract, separate timing
    res["equiv"] = pair("equivocate", 0.20)
    # the fused ADVERSARIAL round (counts_mode='delivered'): vs the plain
    # XLA path — with the common coin both share every random bit, so
    # this bit-equality is exact, and the timing covers the regimes that
    # dominate the sweep's rounds (the livelock-capped adversarial set)
    res["adv"] = pair("crash", 0.20, scheduler="adversarial",
                      coin_mode="common", max_rounds=16)
    return res


def _batched_sweep_check(n: int, trials: int, seed: int) -> dict:
    """Compile-amortization proof for the batched dynamic-F sweep engine
    (sweep.run_curve_batched): a fresh 5-point balanced rounds-vs-f curve
    run per-point (one cold compile per f — the classic path) and then
    batched (one compile per static bucket), wall-clocks with compiles
    INCLUDED on both sides, compile counts measured by the jax.monitoring
    hook, and per-f summaries asserted bit-identical.  Fresh f fractions
    + a distinct max_rounds keep every config cold (the main sweep's
    warm-up must not subsidize either side)."""
    import jax

    from benor_tpu.config import SimConfig
    from benor_tpu.sim import run_consensus
    from benor_tpu.state import FaultSpec, init_state
    from benor_tpu.sweep import (balanced_inputs, run_curve_batched,
                                 summarize_final)
    from benor_tpu.utils.compile_counter import count_backend_compiles

    fracs = (0.12, 0.22, 0.32, 0.42, 0.44)
    max_rounds = 16
    base = SimConfig(n_nodes=n, n_faulty=0, trials=trials,
                     delivery="quorum", scheduler="uniform",
                     path="histogram", max_rounds=max_rounds, seed=seed)
    fs = [int(fr * n) for fr in fracs]
    bal = balanced_inputs(trials, n)
    none = FaultSpec.none(trials, n)
    key = jax.random.key(seed)

    # per-point oracle: O(points) compiles, timed end-to-end
    per_point = []
    with count_backend_compiles() as cc:
        t0 = time.perf_counter()
        for f in fs:
            cfg = base.replace(n_faulty=f)
            state = init_state(cfg, bal, none)
            r, fin = run_consensus(cfg, state, none, key)
            summ = summarize_final(fin, none.faulty, cfg.max_rounds)
            per_point.append((int(r),)
                             + tuple(np.asarray(s) for s in summ))
        per_point_s = time.perf_counter() - t0
    per_point_compiles = cc.count

    # batched engine: O(buckets) compiles, same inputs, same streams
    t0 = time.perf_counter()
    cb = run_curve_batched(base, fs, initial_values=bal,
                           faults_for=lambda c: none)
    batched_s = time.perf_counter() - t0

    for (r, dec, mk, ones, khist, dis), pt in zip(per_point, cb.points):
        assert r == pt.rounds_executed
        assert float(dec) == pt.decided_frac
        assert float(mk) == pt.mean_k
        assert float(ones) == pt.ones_frac
        assert float(dis) == pt.disagree_frac
        np.testing.assert_array_equal(np.asarray(khist, np.int64),
                                      pt.k_hist)

    return {
        "n": n, "trials": trials, "f_fracs": list(fracs),
        "max_rounds": max_rounds, "bit_identical": True,
        "per_point_s": round(per_point_s, 3),
        "per_point_compiles": per_point_compiles,
        "batched_total_s": round(batched_s, 3),
        "batched_compile_s": round(cb.compile_s, 3),
        "batched_run_s": round(cb.run_s, 3),
        "compile_count": cb.compile_count,
        "n_buckets": cb.n_buckets,
        "speedup_incl_compile": (round(per_point_s / batched_s, 3)
                                 if batched_s > 0 else None),
    }


def _flight_recorder_check(n: int, trials: int, max_rounds: int, seed: int,
                           use_pallas: bool) -> dict:
    """Flight-recorder + witness proof + recorder-derived science on the
    flagship balanced f=0.40 regime (the same config the main sweep runs,
    so the record=False executable is cache-warm):

      * record=True results are BIT-IDENTICAL to record=False (the
        recorder only reduces values the round already computes), and so
        are witness-armed results — ONE bench pass guards both on-device
        recorders;
      * record=False costs zero extra backend compiles (its executable
        was built by the sweep warm-up — the flag never enters the
        trace);
      * the buffer yields the per-round decide velocity and the
        rounds-to-quiescence histogram over lanes
        (utils/metrics.round_history_summary) — full round history from
        a regime that previously ran blind (cfg.debug would demote the
        fused pallas loop; the recorder runs inside it);
      * the witness buffer is machine-checked by the invariant auditor
        (benor_tpu/audit.py) — ``audit_ok`` is the headline bool saying
        this capture's flagship regime upheld the Ben-Or invariants.
    """
    import jax

    from benor_tpu.audit import (WitnessBundle, audit_witness,
                                 default_witness_overrides)
    from benor_tpu.config import SimConfig
    from benor_tpu.sim import run_consensus
    from benor_tpu.state import FaultSpec, init_state
    from benor_tpu.sweep import balanced_inputs
    from benor_tpu.utils.compile_counter import count_backend_compiles
    from benor_tpu.utils.metrics import round_history_summary

    base = dict(n_nodes=n, n_faulty=int(0.40 * n), trials=trials,
                max_rounds=max_rounds, delivery="quorum",
                scheduler="uniform", path="histogram", fault_model="crash",
                seed=seed, use_pallas_hist=use_pallas,
                use_pallas_round=use_pallas)
    cfg_off = SimConfig(**base)
    cfg_on = SimConfig(record=True, **base)
    cfg_wit = SimConfig(record=True,
                        **default_witness_overrides(trials, n), **base)
    faults = FaultSpec.none(trials, n)
    state = init_state(cfg_off, balanced_inputs(trials, n), faults)
    key = jax.random.key(seed)

    with count_backend_compiles() as cc_off:
        r0, fin0 = run_consensus(cfg_off, state, faults, key)
        int(r0)
    r1, fin1, rec = run_consensus(cfg_on, state, faults, key)
    int(r1)
    r2, fin2, rec2, wit = run_consensus(cfg_wit, state, faults, key)
    int(r2)

    assert int(r0) == int(r1) == int(r2)
    for fin in (fin1, fin2):
        np.testing.assert_array_equal(np.asarray(fin0.x),
                                      np.asarray(fin.x))
        np.testing.assert_array_equal(np.asarray(fin0.decided),
                                      np.asarray(fin.decided))
        np.testing.assert_array_equal(np.asarray(fin0.k),
                                      np.asarray(fin.k))
    # the witness run's recorder must match the record-only run's too
    np.testing.assert_array_equal(np.asarray(rec), np.asarray(rec2))

    report = audit_witness(WitnessBundle.from_run(
        cfg_wit, wit, faults=faults, label="bench balanced_f0.40"))

    # post-compile overhead of recording (one extra HBM buffer + the
    # kernels' telemetry partials; zero host round trips either way)
    times = []
    for cfg in (cfg_off, cfg_on):
        loops = 3
        t0 = time.perf_counter()
        for _ in range(loops):
            out = run_consensus(cfg, state, faults, key)
        int(out[0])
        times.append((time.perf_counter() - t0) / loops)

    return {
        "regime": "balanced_f0.40", "n": n, "trials": trials,
        "fused_round": use_pallas,
        "bit_equal_record_off_on": True,
        "bit_equal_witness_off_on": True,
        "audit_ok": report.ok,
        "audit_violations": len(report.violations),
        "audit_checks": sum(report.checks.values()),
        "compiles_record_off_warm": cc_off.count,
        "unrecorded_ms": round(times[0] * 1e3, 3),
        "recorded_ms": round(times[1] * 1e3, 3),
        "record_overhead_x": (round(times[1] / times[0], 3)
                              if times[0] > 0 else None),
        **round_history_summary(rec),
    }


def bench_sweep(platform: str, fallback: bool) -> dict:
    """The north-star workload: multi-regime rounds-vs-f science sweep at
    N=1M (TPU) / 50k (CPU smoke), with hardware-capability accounting."""
    import jax

    from benor_tpu.sim import run_consensus
    from benor_tpu.sweep import summarize_final

    on_cpu = platform == "cpu"
    from benor_tpu.utils.backend import default_scale
    dn, dt = default_scale(on_cpu)
    n = int(os.environ.get("BENCH_N", dn))
    trials = int(os.environ.get("BENCH_TRIALS", dt))
    reps = int(os.environ.get("BENCH_REPS", 2 if on_cpu else 8))
    fracs = [float(x) for x in os.environ.get(
        "BENCH_F_FRACS", "0.10,0.25,0.35,0.40,0.45").split(",")]
    max_rounds = int(os.environ.get("BENCH_MAX_ROUNDS", 64))
    seed = int(os.environ.get("BENCH_SEED", 0))

    dev = jax.devices()[0]
    log(f"bench: N={n} trials={trials} f_fracs={fracs} on {dev.platform} "
        f"({dev.device_kind})")

    regimes = _regimes(n, trials, fracs, max_rounds, seed,
                       use_pallas_hist=not on_cpu)
    base_key = jax.random.key(seed)

    # Warm-up: compile every (shape-distinct) config once; compile time is
    # excluded from the timed sweep (the cache makes repeats free).  A
    # pallas-kernel compile failure on this chip generation demotes that
    # regime to the XLA path instead of killing the whole artifact.
    # Backend compiles are COUNTED via the jax.monitoring hook so the
    # compile-vs-run split is a first-class artifact metric.
    from benor_tpu.utils.compile_counter import count_backend_compiles

    t0 = time.perf_counter()
    demoted = []
    with count_backend_compiles() as warm_cc:
        for i, (name, cfg, state, faults) in enumerate(regimes):
            try:
                r, final = run_consensus(cfg, state, faults, base_key)
                int(r)  # scalar fetch = completion barrier under the tunnel
            except Exception as e:  # noqa: BLE001
                # demote ONLY for kernel-lowering failures: an unrelated
                # error (e.g. OOM) would hit the XLA path too — fail fast
                # with the right attribution instead of paying a doomed
                # second compile
                if not cfg.use_pallas_hist or not any(
                        s in f"{type(e).__name__}: {e}"
                        for s in ("Mosaic", "mosaic", "pallas", "Pallas")):
                    raise
                log(f"bench: {name} pallas kernel failed "
                    f"({type(e).__name__}); "
                    f"falling back to the XLA sampler for this regime")
                demoted.append({"regime": name,
                                "error": f"{type(e).__name__}: {e}"[:300]})
                cfg = cfg.replace(use_pallas_hist=False,
                                  use_pallas_round=False)
                regimes[i] = (name, cfg, state, faults)
                r, final = run_consensus(cfg, state, faults, base_key)
                int(r)
    compile_s = time.perf_counter() - t0
    log(f"bench: warm-up (compile+run) {compile_s:.1f}s "
        f"for {len(regimes)} regimes ({warm_cc.count} backend compiles, "
        f"{warm_cc.seconds:.1f}s inside XLA)")

    # Per-regime bytes-accessed from XLA's post-optimization cost model
    # (free: the executable cache is warm).  The estimate counts the
    # while-loop body once, so bytes/round ~ 'bytes accessed'.  cost_of
    # (benor_tpu/perfscope/instrument.py) owns the failure handling the
    # old inline block did by hand: a backend without a cost model yields
    # {} and ticks perfscope.cost_failures instead of killing the run.
    from benor_tpu.perfscope import cost_of
    bytes_per_round = {}
    for name, cfg, state, faults in regimes:
        ca = cost_of(run_consensus, cfg, state, faults, base_key,
                     label=f"bench.{name}")
        bytes_per_round[name] = float(ca.get("bytes accessed", 0.0))

    # Timed sweep: the whole regime set end-to-end, repeated BENCH_REPS
    # times.  NOTE: block_until_ready does not actually wait under the axon
    # tunnel runtime — fetching a scalar output is what forces completion.
    # All dispatches are queued first and the scalars fetched AFTER the
    # loops: a fetch inside the loop would serialize every run on a ~60 ms
    # tunnel round-trip and the "throughput" would mostly measure latency.
    results = []
    t0 = time.perf_counter()
    for rep in range(reps):
        results = []
        for name, cfg, state, faults in regimes:
            rounds, final = run_consensus(cfg, state, faults, base_key)
            results.append((name, cfg, rounds, final, faults))
    # completion barrier: ONE scalar fetch of the last-queued program —
    # device execution is stream-ordered, so its completion implies all
    # prior queued programs finished; fetching every regime's scalar here
    # would add len(regimes)-1 tunnel round-trips (~60 ms each) of pure
    # latency to the timed window
    int(results[-1][2])
    elapsed = (time.perf_counter() - t0) / reps
    results = [(name, cfg, int(rounds), final, faults)
               for name, cfg, rounds, final, faults in results]

    curve = []
    total_node_rounds = 0
    total_bytes = 0.0
    for name, cfg, rounds, final, faults in results:
        dec_frac, mean_k, ones_frac, _, disagree = summarize_final(
            final, faults.faulty, cfg.max_rounds)
        # report the compute path actually TAKEN, not the flags requested:
        # base sets both flags for every regime, but the kernels silently
        # gate off where they don't serve the config (e.g. the biased
        # scheduler has no closed form and no sampler kernel)
        from benor_tpu.ops.tally import (pallas_equiv_active,
                                         pallas_hist_active,
                                         pallas_round_active)
        row = {
            "regime": name, "f_frac": round(cfg.n_faulty / n, 3),
            "scheduler": cfg.scheduler, "coin": cfg.coin_mode,
            "pallas": pallas_hist_active(cfg) or pallas_equiv_active(cfg),
            "fused_round": pallas_round_active(cfg),
            "rounds_executed": rounds,
            "decided": round(float(dec_frac), 4),
            "mean_k": round(float(mean_k), 3),
            "ones_frac": round(float(ones_frac), 4),
            "disagree_frac": round(float(disagree), 4),
        }
        curve.append(row)
        total_node_rounds += rounds * n * trials
        total_bytes += bytes_per_round[name] * rounds
        log(f"  {name}: rounds={rounds} decided={row['decided']:.3f} "
            f"mean_k={row['mean_k']:.2f} ones={row['ones_frac']:.3f}")

    # Science gates the artifact is judged on: the curve must not be flat,
    # the coin contrast must be visible at N=1M, and the N > 3F bound must
    # flip between the two equivocation regimes (one F apart).
    bal_ks = [r["mean_k"] for r in curve if r["regime"].startswith("balanced")]
    adv = {r["regime"]: r for r in curve if r["regime"].startswith("adv_")}
    eq = {r["regime"]: r for r in curve if r["regime"].startswith("equiv_")}
    curve_spread = round(max(bal_ks) - min(bal_ks), 3) if bal_ks else 0.0
    coin_contrast = {
        "private_decided": adv.get("adv_private", {}).get("decided"),
        "common_decided": adv.get("adv_common", {}).get("decided"),
        "common_mean_k": adv.get("adv_common", {}).get("mean_k"),
    }
    equiv_threshold = {
        "sub_3f_decided": eq.get("equiv_3f_sub", {}).get("decided"),
        "super_3f_decided": eq.get("equiv_3f_super", {}).get("decided"),
    }
    wk = {r["regime"]: r for r in curve if r["regime"].startswith("weak_")}
    weak_coin_transition = {
        "below_eps_star_decided": wk.get("weak_eps0.55", {}).get("decided"),
        "above_eps_star_decided": wk.get("weak_eps0.65", {}).get("decided"),
    }
    tg = {r["regime"]: r for r in curve if r["regime"].startswith("targeted_")}
    safety_violation = {
        "below_half_disagree": tg.get("targeted_f0.25",
                                      {}).get("disagree_frac"),
        "past_half_decided": tg.get("targeted_f0.50", {}).get("decided"),
    }

    hbm_gbps = total_bytes / elapsed / 1e9 if total_bytes else None
    peak = _hbm_peak_for(dev.device_kind)
    hbm_util = (round(total_bytes / elapsed / peak, 4)
                if (peak and total_bytes) else None)

    try:
        pallas = _pallas_check(seed)
    except Exception as e:  # noqa: BLE001
        pallas = {"error": f"{type(e).__name__}: {e}"}
    log(f"bench: pallas check {pallas}")
    try:
        pallas_hist = _pallas_hist_check(n, trials, seed)
    except Exception as e:  # noqa: BLE001
        pallas_hist = {"error": f"{type(e).__name__}: {e}"}
    log(f"bench: pallas hist check {pallas_hist}")
    try:
        pallas_equiv = _pallas_equiv_check(n, trials, seed)
    except Exception as e:  # noqa: BLE001
        pallas_equiv = {"error": f"{type(e).__name__}: {e}"}
    log(f"bench: pallas equiv check {pallas_equiv}")
    try:
        pallas_wcoin = _pallas_weak_coin_check(n, trials, seed)
    except Exception as e:  # noqa: BLE001
        pallas_wcoin = {"error": f"{type(e).__name__}: {e}"}
    log(f"bench: pallas weak-coin check {pallas_wcoin}")
    try:
        pallas_round = _pallas_round_check(n, trials, seed)
    except Exception as e:  # noqa: BLE001
        pallas_round = {"error": f"{type(e).__name__}: {e}"}
    log(f"bench: pallas fused-round check {pallas_round}")
    try:
        batched_check = _batched_sweep_check(n, trials, seed)
    except Exception as e:  # noqa: BLE001
        batched_check = {"error": f"{type(e).__name__}: {e}"}
    log(f"bench: batched dynamic-F sweep check {batched_check}")
    try:
        recorder_check = _flight_recorder_check(n, trials, max_rounds,
                                                seed,
                                                use_pallas=not on_cpu)
    except Exception as e:  # noqa: BLE001
        recorder_check = {"error": f"{type(e).__name__}: {e}"}
    log(f"bench: flight recorder check {recorder_check}")
    # The serve load test runs BEFORE the heavyweight observatory
    # captures (perfscope/meshscope/sweepscope AOT-compile dozens of
    # executables): its 1000-client latency-ATTRIBUTION window is the
    # one wall-clock-sensitive measurement in the bench, and on slower
    # hosts the accumulated allocator/GC state of those captures pushes
    # the unattributed ingress share past gate.ATTRIBUTION_BAND — a
    # measurement-hygiene artifact, not a serve regression (the same
    # window run early passes with the committed-baseline coverage).
    try:
        serve_check = _serve_check()
    except Exception as e:  # noqa: BLE001 — accounting must not kill the run
        serve_check = {"ok": False,
                       "error": f"{type(e).__name__}: {e}"}
    m = serve_check.get("manifest", {})
    log(f"bench: serve check ok={serve_check.get('ok')} "
        f"clients={m.get('clients')} "
        f"jobs_per_launch={m.get('jobs_per_launch')} "
        f"p99_ms={(m.get('latency_ms') or {}).get('p99')} "
        f"attribution_coverage="
        f"{(m.get('attribution') or {}).get('coverage')} "
        f"baseline_comparable={serve_check.get('baseline_comparable')}")
    try:
        perfscope_check = _perfscope_check()
    except Exception as e:  # noqa: BLE001 — accounting must not kill the run
        perfscope_check = {"ok": False,
                           "error": f"{type(e).__name__}: {e}"}
    log(f"bench: perfscope check ok={perfscope_check.get('ok')} "
        f"regressions={len(perfscope_check.get('regressions', []))} "
        f"baseline_comparable={perfscope_check.get('baseline_comparable')}")
    try:
        meshscope_check = _meshscope_check()
    except Exception as e:  # noqa: BLE001 — accounting must not kill the run
        meshscope_check = {"ok": False,
                           "error": f"{type(e).__name__}: {e}"}
    log(f"bench: meshscope check ok={meshscope_check.get('ok')} "
        f"straggler_max={meshscope_check.get('straggler_max')} "
        f"baseline_comparable={meshscope_check.get('baseline_comparable')}")
    try:
        sweepscope_check = _sweepscope_check()
    except Exception as e:  # noqa: BLE001 — accounting must not kill the run
        sweepscope_check = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
    sm = sweepscope_check.get("manifest", {})
    log(f"bench: sweepscope check ok={sweepscope_check.get('ok')} "
        f"buckets={sm.get('n_buckets')} "
        f"compiles={sm.get('compile_count')} "
        f"headroom_frac={sm.get('overlap_headroom_frac')} "
        f"resume_compiles={sweepscope_check.get('resume_compiles')} "
        f"baseline_comparable="
        f"{sweepscope_check.get('baseline_comparable')}")
    try:
        topo_check = _topo_check(seed)
    except Exception as e:  # noqa: BLE001 — accounting must not kill the run
        topo_check = {"ok": False,
                      "error": f"{type(e).__name__}: {e}"}
    try:
        faults_check = _faults_check(seed)
    except Exception as e:  # noqa: BLE001 — accounting must not kill the run
        faults_check = {"ok": False,
                        "error": f"{type(e).__name__}: {e}"}
    log(f"bench: faults check ok={faults_check.get('ok')} "
        f"identity={faults_check.get('off_identity')} "
        f"drop_rows={len(faults_check.get('drop_curve', []))} "
        f"drop_compiles={faults_check.get('drop_compile_count')} "
        f"churn_rows={len(faults_check.get('churn_curve', []))} "
        f"audits={ {k: v.get('ok') for k, v in (faults_check.get('audits') or {}).items()} }")
    log(f"bench: topo check ok={topo_check.get('ok')} "
        f"identity={topo_check.get('complete_identity')} "
        f"degree_rows={len(topo_check.get('degree_curve', []))} "
        f"committee_rows={len(topo_check.get('committee_curve', []))} "
        f"committee_compiles={topo_check.get('committee_compile_count')} "
        f"audit_ok={topo_check.get('audit_ok')}")
    try:
        kernelscope_check = _kernelscope_check()
    except Exception as e:  # noqa: BLE001 — accounting must not kill the run
        kernelscope_check = {"ok": False,
                             "error": f"{type(e).__name__}: {e}"}
    km = kernelscope_check.get("manifest", {})
    log(f"bench: kernelscope check ok={kernelscope_check.get('ok')} "
        f"kernels={sorted(km.get('kernels', {}))} "
        f"bit_equal={kernelscope_check.get('bit_equal_off_on')} "
        f"compile_parity={kernelscope_check.get('compile_parity')} "
        f"baseline_comparable="
        f"{kernelscope_check.get('baseline_comparable')}")
    try:
        atlas_check = _atlas_check()
    except Exception as e:  # noqa: BLE001 — accounting must not kill the run
        atlas_check = {"ok": False,
                       "error": f"{type(e).__name__}: {e}"}
    am = atlas_check.get("manifest", {})
    log(f"bench: atlas check ok={atlas_check.get('ok')} "
        f"cliffs={am.get('cliff_count')} "
        f"probes={am.get('probe_count')} "
        f"off_identity={atlas_check.get('off_identity')} "
        f"one_bucket="
        f"{atlas_check.get('omission_one_bucket_per_generation')} "
        f"baseline_comparable={atlas_check.get('baseline_comparable')}")

    total_trials = trials * len(regimes)
    log(f"bench: sweep elapsed {elapsed:.2f}s for {total_trials} trials; "
        f"node-rounds/s {total_node_rounds / elapsed:.3e}; "
        f"hbm ~{hbm_gbps or 0:.0f} GB/s (util {hbm_util})")
    # The compile-vs-run split under both naming schemes, derived at this
    # ONE site: sweep_compile_s/sweep_run_s are the canonical
    # compile-amortization metrics (ISSUE 1 satellite); compile_s/
    # elapsed_s are the same values under the BENCH_r01-r05 names, kept
    # so the round-over-round artifacts stay directly comparable.
    timing = {"sweep_compile_s": round(compile_s, 1),
              "sweep_run_s": round(elapsed, 3)}
    timing["compile_s"] = timing["sweep_compile_s"]
    timing["elapsed_s"] = timing["sweep_run_s"]
    return {
        "metric": _labels("sweep", platform)[0],
        "value": round(total_trials / elapsed, 3),
        "unit": "trials/s",
        "vs_baseline": round(60.0 / elapsed, 3),
        "platform": platform,
        "fallback_cpu": fallback,
        "n": n, "trials": trials, **timing,
        # compile-amortization accounting (the batched dynamic-F engine's
        # reason to exist): how many backend compiles the regime warm-up
        # cost, plus the batched-curve proof numbers
        "compile_count": warm_cc.count,
        "batched_curve_speedup": batched_check.get("speedup_incl_compile"),
        "batched_compile_count": batched_check.get("compile_count"),
        "device_kind": dev.device_kind,
        # total protocol rounds executed across the regime set — the
        # workload size behind value/node_rounds_per_sec.  trials/s is NOT
        # comparable across rounds whose regime sets differ (r3's 10
        # regimes ran 25 rounds; the 17-regime set runs ~82, most of them
        # livelock-capped adversarial regimes) — node_rounds_per_sec is
        # the workload-invariant throughput number.
        "total_rounds": sum(r["rounds_executed"] for r in curve),
        "node_rounds_per_sec": round(total_node_rounds / elapsed, 1),
        "hbm_gbps_est": round(hbm_gbps, 1) if hbm_gbps else None,
        "hbm_util_est": hbm_util,
        "curve": curve,
        "curve_mean_k_spread": curve_spread,
        "coin_contrast": coin_contrast,
        "equiv_threshold": equiv_threshold,
        "weak_coin_transition": weak_coin_transition,
        "safety_violation": safety_violation,
        "pallas_check": pallas,
        "pallas_hist_check": pallas_hist,
        "pallas_equiv_check": pallas_equiv,
        "pallas_weak_coin_check": pallas_wcoin,
        "pallas_round_check": pallas_round,
        "batched_sweep_check": batched_check,
        "flight_recorder": recorder_check,
        "perfscope": perfscope_check,
        "meshscope": meshscope_check,
        "serve": serve_check,
        "topo": topo_check,
        "faults": faults_check,
        "sweepscope": sweepscope_check,
        "kernelscope": kernelscope_check,
        "atlas": atlas_check,
        "pallas_demoted": demoted,
    }


def bench_pallas(platform: str, fallback: bool) -> dict:
    """Dense-path tally: pallas kernel vs XLA einsum, bit-equality + timing.

    Exercises ops/pallas_tally.py compiled for the REAL chip (interpret=False
    on TPU) — the round-1 gap was that it had only ever run in interpreter
    mode on CPU, so its TPU lowering and HBM-traffic claim were unvalidated.
    """
    import jax
    import jax.numpy as jnp

    from benor_tpu.ops.pallas_tally import dense_counts_pallas
    from benor_tpu.ops.tally import dense_counts

    n = int(os.environ.get("BENCH_N", 2048))
    trials = int(os.environ.get("BENCH_TRIALS", 8))
    reps = int(os.environ.get("BENCH_REPS", 20))
    seed = int(os.environ.get("BENCH_SEED", 0))

    dev = jax.devices()[0]
    # bit-equality on the real lowering (the parity claim of the kernel);
    # same fixture as the embedded default-mode check (_dense_parity_case)
    mask, sent, alive, interpret = _dense_parity_case(seed, trials, n)
    log(f"bench[pallas]: T={trials} N={n} on {dev.platform} "
        f"({dev.device_kind}) interpret={interpret}; bit-equality OK")

    xla_fn = jax.jit(dense_counts)

    def run_xla():
        return int(jnp.sum(xla_fn(mask, sent, alive)))

    def run_pallas():
        return int(jnp.sum(dense_counts_pallas(mask, sent, alive,
                                               interpret=interpret)))

    run_xla(); run_pallas()  # warm-up / compile
    t0 = time.perf_counter()
    for _ in range(reps):
        run_xla()
    t_xla = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        run_pallas()
    t_pallas = (time.perf_counter() - t0) / reps
    speedup = t_xla / t_pallas if t_pallas > 0 else float("inf")
    log(f"bench[pallas]: xla={t_xla * 1e3:.2f}ms "
        f"pallas={t_pallas * 1e3:.2f}ms speedup={speedup:.2f}x")

    return {
        "metric": "pallas_dense_tally_speedup",
        "value": round(speedup, 3),
        "unit": "x_vs_xla_einsum",
        "vs_baseline": round(speedup, 3),
        "platform": platform,
        "fallback_cpu": fallback,
        "interpret": interpret,
        "xla_ms": round(t_xla * 1e3, 3),
        "pallas_ms": round(t_pallas * 1e3, 3),
        "n": n, "trials": trials,
    }


def _labels(mode: str, platform: str) -> tuple[str, str]:
    """(metric, unit) for the JSON line — shared by success and error paths
    so a failure record is filed under the same metric it would have
    produced."""
    if mode == "pallas":
        return "pallas_dense_tally_speedup", "x_vs_xla_einsum"
    on_cpu = platform == "cpu"
    from benor_tpu.utils.backend import default_scale
    n = int(os.environ.get("BENCH_N", default_scale(on_cpu)[0]))
    metric = ("mc_trials_per_sec_n1e6" if n == 1_000_000
              else f"mc_trials_per_sec_n{n}")
    return metric, "trials/s"


def _perfscope_check() -> dict:
    """The AOT cost/memory observatory over all five compiled regimes
    (benor_tpu/perfscope): per-stage pipeline timings, the XLA cost model
    (FLOPs / bytes accessed) and memory footprint (argument/output/temp/
    peak bytes) per regime, reduced to a manifest and compared against
    the committed PERF_BASELINE.json tolerance bands.  ``perf_ok`` is the
    headline bool: the manifest is complete (five regimes, non-zero cost
    model) and in-band vs the baseline when the baseline is comparable
    (an accelerator capture vs the committed CPU baseline is honestly
    reported as incomparable, not silently passed through the bands).

    The capture runs at the FIXED smoke scale the committed baseline was
    taken at — one small extra AOT compile per regime, out-of-band of the
    science sweep's executables — so the structural numbers band-compare
    across rounds regardless of BENCH_N."""
    from benor_tpu.perfscope import (IncomparableManifests, build_manifest,
                                     capture_all, compare_manifests,
                                     load_manifest, missing_regimes)

    from benor_tpu.perfscope.regimes import capture_fused_vs_xla

    scale = {"n_nodes": 256, "trials": 8, "max_rounds": 12, "seed": 0}
    reports = capture_all(**scale)
    fvx = capture_fused_vs_xla(**scale)
    manifest = build_manifest(reports, scale, fused_vs_xla=fvx)
    missing = missing_regimes(manifest)
    nonzero = all(rep["flops"] > 0 and rep["bytes_accessed"] > 0
                  and rep["peak_bytes"] > 0
                  for rep in manifest["regimes"].values())
    # the PR-8 acceptance pair, judged by the SAME gate function CI runs
    # (baseline.check_fused_vs_xla via tools/check_perf_regression.py):
    # fused must beat the baseline loop on a real backend; interpret-mode
    # ratios are excluded and the geometry-normalized traffic ratio
    # carries the bound instead — one verdict, never two diverging copies
    from benor_tpu.perfscope.baseline import check_fused_vs_xla
    fvx_findings = check_fused_vs_xla(manifest)
    fused_ok = not any(f.startswith("REGRESSION") for f in fvx_findings)
    blob = {
        "manifest": manifest,
        "missing_regimes": missing,
        "nonzero_cost_model": nonzero,
        "fused_vs_xla_ok": fused_ok,
        "fused_vs_xla_findings": fvx_findings,
    }
    regressions = []
    comparable = None
    baseline_path = os.path.join(HERE, "PERF_BASELINE.json")
    if os.path.exists(baseline_path):
        try:
            regressions = compare_manifests(manifest,
                                            load_manifest(baseline_path))
            comparable = True
        except (IncomparableManifests, ValueError) as e:
            comparable = False
            blob["baseline_note"] = f"{e}"
    else:
        blob["baseline_note"] = "no committed PERF_BASELINE.json"
    blob["baseline_comparable"] = comparable
    blob["regressions"] = [r.to_dict() for r in regressions]
    blob["ok"] = (not missing and nonzero and not regressions
                  and fused_ok)
    return blob


def _meshscope_check() -> dict:
    """The runtime/scaling observatory (benor_tpu/meshscope): run a
    small scaling ladder over whatever devices this capture actually
    has (1 rung on a single chip, 1+2 when a mesh is available), emit
    the pinned-schema scaling manifest into the sidecar blob, and
    reduce it to the ``scaling_ok`` headline bool: manifest
    schema-valid (tools/scaling_manifest_schema.json, loaded by file
    path — the checker must not drift from CI's) + no straggler trip
    (max/median per-shard step time under scalegate.STRAGGLER_TRIP) +
    in-band vs the committed SCALING_BASELINE.json when the rung sets
    are comparable (a single-chip smoke vs the 3-rung CPU baseline is
    honestly reported incomparable, not silently passed)."""
    import importlib.util

    import jax

    from benor_tpu.meshscope import (STRAGGLER_TRIP, IncomparableScaling,
                                     build_scaling_manifest,
                                     compare_scaling,
                                     load_scaling_manifest,
                                     run_scaling_ladder)

    sizes = [1] + ([2] if len(jax.devices()) >= 2 else [])
    rows, scale = run_scaling_ladder(sizes)
    manifest = build_scaling_manifest(rows, "weak", "nodes", scale)
    spec = importlib.util.spec_from_file_location(
        "_check_metrics_schema",
        os.path.join(HERE, "tools", "check_metrics_schema.py"))
    cms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cms)
    schema_errors = cms.check_scaling_manifest(manifest)
    straggler_max = max(r["straggler_ratio"] for r in rows)
    blob = {
        "manifest": manifest,
        "schema_errors": schema_errors,
        "straggler_max": straggler_max,
        "straggler_trip": STRAGGLER_TRIP,
    }
    regressions = []
    comparable = None
    baseline_path = os.path.join(HERE, "SCALING_BASELINE.json")
    if os.path.exists(baseline_path):
        try:
            base = load_scaling_manifest(baseline_path)
            base_rungs = {(r["devices"], r["n_nodes"])
                          for r in base.get("rows", [])}
            new_rungs = {(r["devices"], r["n_nodes"]) for r in rows}
            if base_rungs <= new_rungs:
                regressions = [f.to_dict()
                               for f in compare_scaling(manifest, base)]
                comparable = True
            else:
                comparable = False
                blob["baseline_note"] = (
                    f"smoke ladder rungs {sorted(new_rungs)} do not "
                    f"cover the baseline's {sorted(base_rungs)}")
        except (IncomparableScaling, ValueError) as e:
            comparable = False
            blob["baseline_note"] = f"{e}"
    else:
        blob["baseline_note"] = "no committed SCALING_BASELINE.json"
    blob["baseline_comparable"] = comparable
    blob["regressions"] = regressions
    blob["ok"] = (not schema_errors and straggler_max < STRAGGLER_TRIP
                  and not regressions)
    return blob


def _serve_check() -> dict:
    """The serving acceptance (benor_tpu/serve): drive the load
    generator's concurrent SSE clients against an in-process request
    plane — BENCH_SERVE_CLIENTS concurrent clients, default 1000, the
    acceptance scale — emit the pinned-schema serve manifest into the
    sidecar blob, and reduce it to the ``serve_ok`` headline bool:
    manifest schema-valid (tools/serve_manifest_schema.json, loaded by
    file path), zero client errors, jobs-per-launch coalescing ratio
    above 1 (the number serving exists to produce), servescope's
    stage-latency attribution complete (the v2 manifest's stage means
    telescope to the client mean within gate.ATTRIBUTION_BAND), and
    in-band vs the committed SERVE_BASELINE.json when comparable (a
    smaller smoke run vs the 1000-client baseline is honestly reported
    incomparable, not silently passed)."""
    import importlib.util

    from benor_tpu.serve import IncomparableServe, compare_serve, run_load

    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 1000))
    manifest = run_load(clients=clients)
    spec = importlib.util.spec_from_file_location(
        "_check_metrics_schema",
        os.path.join(HERE, "tools", "check_metrics_schema.py"))
    cms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cms)
    schema_errors = cms.check_serve_manifest(manifest)
    blob = {
        "manifest": manifest,
        "schema_errors": schema_errors,
        "clients": clients,
    }
    regressions = []
    comparable = None
    baseline_path = os.path.join(HERE, "SERVE_BASELINE.json")
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as fh:
                base = json.load(fh)
            regressions = [f.to_dict()
                           for f in compare_serve(manifest, base)]
            comparable = True
        except (IncomparableServe, ValueError) as e:
            comparable = False
            blob["baseline_note"] = f"{e}"
    else:
        blob["baseline_note"] = "no committed SERVE_BASELINE.json"
    blob["baseline_comparable"] = comparable
    blob["regressions"] = regressions
    blob["ok"] = (not schema_errors and manifest["errors"] == 0
                  and manifest["jobs_per_launch"] > 1.0
                  and bool(manifest.get("attribution", {}).get("ok"))
                  and not regressions)
    return blob


def _topo_check(seed: int) -> dict:
    """The structured-delivery workloads' embedded proof (PR 12,
    benor_tpu/topo) at a fixed CPU-safe geometry:

      * identity — ``topology='complete'`` normalizes to the pre-topology
        config, so the same point re-run under it must be bit-identical
        in the science fields AND cost zero new backend compiles (the
        jit cache simply hits);
      * the rounds-vs-degree curve (ring/torus/random-regular ladder)
        and the committee-size sweep, both through the batched engine —
        the committee curve's compile count must be 1 (size rides
        DynParams: one bucket executable for the whole sweep);
      * a witnessed torus run audited CLEAN under the relaxed
        neighborhood invariants (quorum evidence bounded by the d+1
        neighborhood — benor_tpu/audit.py).

    The blob's cross-field facts (degree/diameter recomputation, row
    ordering, the recomputed ok verdict) are pinned by
    check_metrics_schema.check_topo_blob."""
    from benor_tpu import audit, results
    from benor_tpu.config import SimConfig
    from benor_tpu.state import FaultSpec
    from benor_tpu.sweep import run_point
    from benor_tpu.utils.compile_counter import count_backend_compiles

    n_topo, trials, max_rounds = 64, 16, 24
    base = SimConfig(n_nodes=n_topo, n_faulty=8, trials=trials,
                     max_rounds=max_rounds, seed=seed, delivery="quorum",
                     scheduler="uniform", path="histogram")
    pt0 = run_point(base)
    with count_backend_compiles() as cc:
        pt1 = run_point(base.replace(topology="complete"))
    identity = {
        "bit_equal": bool(
            pt0.rounds_executed == pt1.rounds_executed
            and pt0.decided_frac == pt1.decided_frac
            and pt0.mean_k == pt1.mean_k
            and pt0.ones_frac == pt1.ones_frac
            and pt0.disagree_frac == pt1.disagree_frac
            and (pt0.k_hist == pt1.k_hist).all()),
        "extra_compiles": cc.count,
    }

    curves = results.topo_curves(n_topo, trials, seed=seed,
                                 max_rounds=max_rounds)

    acfg = SimConfig(n_nodes=n_topo, n_faulty=2, topology="torus2d:8x8",
                     trials=trials, max_rounds=max_rounds, seed=seed,
                     witness_trials=(0, 1), witness_nodes=8)
    report, _ = audit.audit_point(
        acfg, initial_values=np.ones((trials, n_topo), np.int8),
        faults=FaultSpec.none(trials, n_topo), unanimous=1,
        label="bench topo torus")

    ok = (identity["bit_equal"] and identity["extra_compiles"] == 0
          and report.ok and len(curves["degree_curve"]) > 0
          and len(curves["committee_curve"]) > 0
          and curves["committee_compile_count"] == 1)
    # the sim.demotion.* counter family (PR 14): how many DEMOTED
    # EXECUTABLE BUILDS this process traced (the announcers live inside
    # jitted bodies, so a warm jit cache does not re-tick) — the
    # structured topo demotion's one-shot warning made visible to
    # tooling; counters are process-wide, so this is the whole bench
    # run's tally
    from benor_tpu.utils.metrics import REGISTRY
    demotions = {
        "structured": int(REGISTRY.counter(
            "sim.demotion.structured").value),
        "debug": int(REGISTRY.counter("sim.demotion.debug").value),
    }
    return {"ok": bool(ok), "n": n_topo, "trials": trials,
            "complete_identity": identity, **curves,
            "demotions": demotions,
            "audit_ok": bool(report.ok),
            "audit_checks": sum(report.checks.values()),
            "audit_violations": len(report.violations)}


def _faults_check(seed: int) -> dict:
    """The faultlab workloads' embedded proof (PR 15, benor_tpu/faults)
    at a fixed CPU-safe geometry — the ``kind: faults_manifest`` blob
    (faults/report.py) behind the ``faults_ok`` headline:

      * injection-off identity — a config with every faultlab field at
        its default IS the pre-faultlab config (same dataclass, same
        hash), so re-running it must be bit-identical in the science
        fields AND cost zero new backend compiles (the jit cache hits);
      * the rounds-vs-drop_prob curve through the batched engine with
        drop_prob riding DynParams — the whole curve in ONE bucket
        executable (compile count pinned) — plus the churn curve;
      * witnessed crash_recover (amnesia churn) and partition points
        audited CLEAN under the new invariants (down_silence + the
        partition-epoch quorum-evidence bound, benor_tpu/audit.py).

    Cross-field facts (stall threshold, row ordering, the recomputed ok
    verdict) are pinned by check_metrics_schema.check_faults_manifest.
    """
    from benor_tpu import audit, results
    from benor_tpu.config import SimConfig
    from benor_tpu.faults.report import faults_manifest
    from benor_tpu.sweep import run_point
    from benor_tpu.utils.compile_counter import count_backend_compiles

    n_f, trials, max_rounds = 64, 16, 24
    base = SimConfig(n_nodes=n_f, n_faulty=8, trials=trials,
                     max_rounds=max_rounds, seed=seed, delivery="quorum",
                     scheduler="uniform", path="histogram")
    pt0 = run_point(base)
    with count_backend_compiles() as cc:
        pt1 = run_point(base.replace(drop_prob=0.0, recovery=None,
                                     partition=None))
    identity = {
        "bit_equal": bool(
            pt0.rounds_executed == pt1.rounds_executed
            and pt0.decided_frac == pt1.decided_frac
            and pt0.mean_k == pt1.mean_k
            and pt0.ones_frac == pt1.ones_frac
            and pt0.disagree_frac == pt1.disagree_frac
            and (pt0.k_hist == pt1.k_hist).all()),
        "extra_compiles": cc.count,
    }

    curves = results.faults_curves(n_f, trials, seed=seed,
                                   max_rounds=max_rounds)

    from benor_tpu.state import FaultSpec

    audits = {}
    # crash at round 1 so the down intervals BIND (full delivery decides
    # in round ~1; a later crash would witness an already-settled net)
    churn_cfg = SimConfig(
        n_nodes=n_f, n_faulty=8, trials=trials, max_rounds=max_rounds,
        seed=seed, fault_model="crash_recover",
        recovery="stagger:1:4:amnesia", witness_trials=(0, 1),
        witness_nodes=12)
    rep, _ = audit.audit_point(churn_cfg, label="bench churn amnesia")
    audits["crash_recover"] = {"ok": bool(rep.ok),
                               "checks": sum(rep.checks.values()),
                               "violations": len(rep.violations)}
    part_cfg = SimConfig(
        n_nodes=n_f, n_faulty=8, trials=trials, max_rounds=max_rounds,
        seed=seed, partition="halves:4", witness_trials=(0, 1),
        witness_nodes=12)
    rep2, _ = audit.audit_point(part_cfg, label="bench partition halves")
    audits["partition"] = {"ok": bool(rep2.ok),
                           "checks": sum(rep2.checks.values()),
                           "violations": len(rep2.violations)}
    # zero crashes: the quorum slack F is what absorbs the thinning
    # (crash faults would pin the live population to N - F exactly and
    # every receiver would stall — the stall cliff, not omission)
    drop_cfg = SimConfig(
        n_nodes=n_f, n_faulty=16, trials=trials, max_rounds=max_rounds,
        seed=seed, drop_prob=0.05, witness_trials=(0, 1),
        witness_nodes=12)
    rep3, _ = audit.audit_point(drop_cfg,
                                faults=FaultSpec.none(trials, n_f),
                                label="bench omission")
    audits["omission"] = {"ok": bool(rep3.ok),
                          "checks": sum(rep3.checks.values()),
                          "violations": len(rep3.violations)}
    blob = faults_manifest(identity, curves, audits)
    blob.update(n=n_f, trials=trials)
    return blob


def _sweepscope_check() -> dict:
    """The batched sweep plane's observability acceptance (PR 13,
    benor_tpu/sweepscope) at the fixed CPU-safe capture scale the
    committed SWEEP_BASELINE.json was taken at (two buckets: one dyn
    CF-regime bucket + one quorum-specialized static bucket):

      * journal OFF vs ON must be bit-identical in the science fields
        AND backend compile counts (the journal is host-side only);
      * a resume from the completed journal must reassemble every point
        bit-identically with ZERO compiles (the preemption-survival
        contract; the SIGKILL-mid-bucket variant lives in
        tests/test_sweepscope.py);
      * the ``kind: sweep_manifest`` document must be schema-valid
        (tools/sweep_manifest_schema.json, loaded by file path — the
        checker must not drift from CI's) with the overlap-headroom
        attribution present;
      * the same gate CI runs (sweepscope/gate.compare_sweep behind
        tools/check_sweep_regression.py) must be in-band vs the
        committed SWEEP_BASELINE.json when comparable (an accelerator
        capture vs the CPU baseline is honestly reported incomparable,
        not silently passed).
    """
    import importlib.util
    import tempfile

    from benor_tpu.sweepscope import (IncomparableSweep,
                                      build_sweep_manifest,
                                      capture_base_config,
                                      compare_sweep)
    from benor_tpu.sweep import run_curve_batched

    # the ONE capture workload definition, shared with the committed
    # SWEEP_BASELINE.json regeneration (capture_sweep_manifest) so this
    # gate and CI always price the same sweep
    base, fs = capture_base_config()

    def science(p):
        return (p.rounds_executed, p.decided_frac, p.mean_k,
                p.ones_frac, p.disagree_frac, tuple(p.k_hist.tolist()))

    cb_off = run_curve_batched(base, fs)
    with tempfile.TemporaryDirectory() as td:
        jp = os.path.join(td, "sweep_journal.jsonl")
        cb_on = run_curve_batched(base, fs, journal_path=jp)
        cb_res = run_curve_batched(base, fs, journal_path=jp,
                                   resume=True)
    cb_pipe = run_curve_batched(base, fs, pipeline=True)
    bit_equal = all(science(a) == science(b)
                    for a, b in zip(cb_off.points, cb_on.points))
    compile_parity = cb_off.compile_count == cb_on.compile_count
    resume_bit_equal = all(science(a) == science(b)
                           for a, b in zip(cb_off.points, cb_res.points))
    # PR 16: compile-ahead/execute-behind dispatch must change neither
    # the science nor the per-bucket compile counts — only the wall
    pipeline_bit_equal = all(science(a) == science(b)
                             for a, b in zip(cb_off.points,
                                             cb_pipe.points))
    pipeline_compile_parity = (cb_pipe.bucket_compile_counts
                               == cb_off.bucket_compile_counts)

    manifest = build_sweep_manifest(cb_off, base)
    spec = importlib.util.spec_from_file_location(
        "_check_metrics_schema",
        os.path.join(HERE, "tools", "check_metrics_schema.py"))
    cms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cms)
    schema_errors = cms.check_sweep_manifest(manifest)
    headroom_present = isinstance(manifest.get("overlap_headroom_s"),
                                  (int, float))
    blob = {
        "manifest": manifest,
        "schema_errors": schema_errors,
        "bit_equal_journal_off_on": bit_equal,
        "journal_compile_parity": compile_parity,
        "resume_bit_equal": resume_bit_equal,
        "resume_compiles": cb_res.compile_count,
        "resume_buckets_reused": sum(cb_res.bucket_reused),
        "headroom_present": headroom_present,
        "pipeline_bit_equal": pipeline_bit_equal,
        "pipeline_compile_parity": pipeline_compile_parity,
        "pipeline_span_s": round(cb_pipe.span_s, 6),
        "pipeline_headroom_reclaimed_s": round(
            cb_pipe.headroom_reclaimed_s, 6),
    }
    regressions = []
    comparable = None
    baseline_path = os.path.join(HERE, "SWEEP_BASELINE.json")
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as fh:
                baseline = json.load(fh)
            regressions = [f.to_dict()
                           for f in compare_sweep(manifest, baseline)]
            comparable = True
        except (IncomparableSweep, ValueError) as e:
            comparable = False
            blob["baseline_note"] = f"{e}"
    else:
        blob["baseline_note"] = "no committed SWEEP_BASELINE.json"
    blob["baseline_comparable"] = comparable
    blob["regressions"] = regressions
    blob["ok"] = (not schema_errors and bit_equal and compile_parity
                  and resume_bit_equal and cb_res.compile_count == 0
                  and headroom_present and pipeline_bit_equal
                  and pipeline_compile_parity and not regressions)
    return blob


def _kernelscope_check() -> dict:
    """The pallas kernel interior's observability acceptance (PR 14,
    benor_tpu/kernelscope) at the fixed CPU-safe capture scale the
    committed KERNEL_BASELINE.json was taken at (both fused dispatches:
    the single-pass kernel + the two-kernel plane pipeline):

      * telemetry OFF vs ON must be bit-identical in the science fields
        (recorded per kernel by the capture) AND cost the same NUMBER
        of backend compiles — the house rule, measured here with the
        jax.monitoring hook on fresh seeds so the jit cache cannot
        fake it;
      * the ``kind: kernel_manifest`` document must be schema-valid
        (tools/kernel_manifest_schema.json via the file-path-loaded
        checker — cross-field recomputation of pad waste, predicted
        bytes and the byte ratio included) with the predicted-vs-
        measured byte telescoping PRESENT for every kernel;
      * the same gate CI runs (kernelscope/gate.compare_kernels behind
        tools/check_kernel_regression.py) must be in-band vs the
        committed KERNEL_BASELINE.json when comparable (an accelerator
        capture vs the CPU baseline is honestly reported incomparable,
        not silently passed).
    """
    import importlib.util

    from benor_tpu.kernelscope import (IncomparableKernels,
                                       capture_kernels, compare_kernels,
                                       load_kernel_manifest)
    from benor_tpu.kernelscope.capture import _inputs, _two_kernel_cfg
    from benor_tpu.utils.compile_counter import count_backend_compiles

    manifest = capture_kernels()
    spec = importlib.util.spec_from_file_location(
        "_check_metrics_schema",
        os.path.join(HERE, "tools", "check_metrics_schema.py"))
    cms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cms)
    schema_errors = cms.check_kernel_manifest(manifest)
    bit_equal = all(k.get("bit_equal_off_on")
                    for k in manifest["kernels"].values())
    telescoping = all(k.get("byte_ratio") is not None
                      for k in manifest["kernels"].values()
                      if k.get("measured_bytes_per_round"))

    # compile-count parity, fresh seeds so the jit cache cannot hide a
    # recompile (the same discipline as test_fused_compile_counts_*)
    from benor_tpu.sim import run_consensus
    counts = []
    for telem, seed in ((False, 7101), (True, 7103)):
        cfg = _two_kernel_cfg(256, 8, 12, seed,
                              kernel_telemetry=telem)
        state, faults, key = _inputs(cfg)
        with count_backend_compiles() as cc:
            out = run_consensus(cfg, state, faults, key)
            int(out[0])
        counts.append(cc.count)
    compile_parity = counts[0] == counts[1]

    blob = {
        "manifest": manifest,
        "schema_errors": schema_errors,
        "bit_equal_off_on": bool(bit_equal),
        "compile_parity": bool(compile_parity),
        "compile_counts_off_on": counts,
        "telescoping_present": bool(telescoping),
    }
    regressions = []
    comparable = None
    baseline_path = os.path.join(HERE, "KERNEL_BASELINE.json")
    if os.path.exists(baseline_path):
        try:
            regressions = [f.to_dict() for f in compare_kernels(
                manifest, load_kernel_manifest(baseline_path))]
            comparable = True
        except (IncomparableKernels, ValueError) as e:
            comparable = False
            blob["baseline_note"] = f"{e}"
    else:
        blob["baseline_note"] = "no committed KERNEL_BASELINE.json"
    blob["baseline_comparable"] = comparable
    blob["regressions"] = regressions
    blob["ok"] = (not schema_errors and bit_equal and compile_parity
                  and telescoping and not regressions)
    return blob


def _atlas_check() -> dict:
    """The phase-boundary observatory's acceptance (PR 20,
    benor_tpu/atlas) at the fixed CPU-safe capture scale the committed
    ATLAS_BASELINE.json was taken at (all three shipped searches:
    omission stall cliff, partition liveness boundary, F >= N/2 quorum
    cliff):

      * search OFF vs ON must be bit-identical: driving the quorum
        search's coarse generation-0 grid through run_points_batched
        DIRECTLY must reproduce the search's recorded probes exactly
        (science fields) at the same compile count — the atlas driver
        adds no execution semantics of its own;
      * every omission-search refinement generation must have run as
        ONE dyn bucket with ONE compile (the whole drop_prob axis
        shares a traced-DynParams executable — the probe cost model
        the manifest's per-cliff compile accounting is built on);
      * the ``kind: atlas_manifest`` document must be schema-valid
        with all cross-field recomputes (tools/atlas_manifest_schema
        .json via the file-path-loaded checker), every cliff's shrunk
        repro must have replayed bit-identically at capture time, and
        the stalled partition boundary must have audited CLEAN
        (liveness-not-safety, machine-checked);
      * the same gate CI runs (atlas/gate.compare_atlas behind
        tools/check_atlas_regression.py) must be in-band vs the
        committed ATLAS_BASELINE.json when comparable (an accelerator
        capture vs the CPU baseline is honestly reported incomparable,
        not silently passed).
    """
    import importlib.util

    import numpy as np

    from benor_tpu.atlas import manifest as amanifest
    from benor_tpu.atlas.gate import IncomparableAtlas, compare_atlas
    from benor_tpu.atlas.scenario import parse_axis
    from benor_tpu.config import SimConfig
    from benor_tpu.sweep import run_points_batched

    manifest = amanifest.capture_atlas(forensics=True)

    spec = importlib.util.spec_from_file_location(
        "_check_metrics_schema",
        os.path.join(HERE, "tools", "check_metrics_schema.py"))
    cms = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cms)
    schema_errors = cms.check_atlas_manifest(manifest)

    # search-off identity: the quorum search's generation-0 grid,
    # driven through the sweep engine directly (no atlas driver)
    qspec = amanifest._search_specs()["quorum"]
    qcfg = SimConfig(**qspec["cfg"])
    axis = parse_axis(qspec["axis"])
    grid = axis.grid(qspec["coarse"])
    ones = np.ones((qcfg.trials, qcfg.n_nodes), np.int8)
    cb = run_points_batched(qcfg, [axis.apply(qcfg, v) for v in grid],
                            initial_values=ones)
    qsearch = next(s for s in manifest["searches"]
                   if s["name"] == "quorum")
    gen0 = [p for p in qsearch["probes"] if p["generation"] == 0]
    off_identity = (len(gen0) == len(cb.points) and all(
        p["rounds_executed"] == int(pt.rounds_executed)
        and p["decided_frac"] == float(pt.decided_frac)
        and p["mean_k"] == float(pt.mean_k)
        and p["disagree_frac"] == float(pt.disagree_frac)
        for p, pt in zip(gen0, cb.points)))
    off_compile_parity = (
        cb.compile_count == qsearch["generations"][0]["compile_count"])

    # one-bucket-per-generation pin: the whole drop_prob axis is one
    # traced-DynParams executable, every generation of it
    osearch = next(s for s in manifest["searches"]
                   if s["name"] == "omission")
    one_bucket = all(g["n_buckets"] == 1 and g["compile_count"] == 1
                     for g in osearch["generations"])

    cliffs = [c for s in manifest["searches"] for c in s["cliffs"]]
    repro_ok = bool(cliffs) and all(c.get("repro_reproduced") is True
                                    for c in cliffs)
    psearch = next(s for s in manifest["searches"]
                   if s["name"] == "partition")
    liveness_clean = all(
        c.get("safety", {}).get("audit_ok") is True
        for c in psearch["cliffs"])

    blob = {
        "manifest": manifest,
        "schema_errors": schema_errors,
        "off_identity": off_identity,
        "off_compile_parity": off_compile_parity,
        "omission_one_bucket_per_generation": one_bucket,
        "cliff_count": manifest["cliff_count"],
        "repro_replayed": repro_ok,
        "partition_audit_clean": liveness_clean,
    }
    regressions = []
    comparable = None
    baseline_path = os.path.join(HERE, "ATLAS_BASELINE.json")
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as fh:
                baseline = json.load(fh)
            regressions = [f.to_dict()
                           for f in compare_atlas(manifest, baseline)]
            comparable = True
        except (IncomparableAtlas, ValueError) as e:
            comparable = False
            blob["baseline_note"] = f"{e}"
    else:
        blob["baseline_note"] = "no committed ATLAS_BASELINE.json"
    blob["baseline_comparable"] = comparable
    blob["regressions"] = regressions
    blob["ok"] = (not schema_errors and off_identity
                  and off_compile_parity and one_bucket
                  and manifest["cliff_count"] >= 2 and repro_ok
                  and liveness_clean and not regressions)
    return blob


def _lint_check() -> dict:
    """benorlint over the shipped package (benor_tpu/analysis): the lint
    verdict rides every sweep-mode bench artifact, so a capture taken
    from a tree with tracer-hygiene / layout / config-parity findings is
    visibly dirty (``lint_ok`` headline bool; full accounting in the
    sidecar's ``lint`` blob)."""
    from benor_tpu.analysis import run_lint

    rep = run_lint()
    return {
        "ok": rep.ok,
        "findings": len(rep.findings),
        "counts": rep.counts(),
        "suppressed": dict(rep.suppressed),
        "suppressed_total": sum(rep.suppressed.values()),
        "files": rep.files,
        "elapsed_s": round(rep.elapsed_s, 3),
        # enough of each finding to act on without re-running the linter
        "first": [f"{f.location()}: [{f.rule}] {f.message}"
                  for f in rep.findings[:5]],
    }


def main() -> None:
    mode = os.environ.get("BENCH_MODE", "sweep")
    platform, fallback = acquire_platform()
    if platform == "cpu":
        _force_cpu()
    _enable_compile_cache()
    try:
        if mode == "pallas":
            out = bench_pallas(platform, fallback)
        else:
            out = bench_sweep(platform, fallback)
    except Exception as e:  # noqa: BLE001 — the contract is ONE JSON line
        import traceback
        traceback.print_exc(file=sys.stderr)
        metric, unit = _labels(mode, platform)
        out = {
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "platform": platform,
            "fallback_cpu": fallback,
            "error": f"{type(e).__name__}: {e}",
        }
    if "curve" in out:
        # sweep-mode success: attach the static-analysis gate (error and
        # pallas-mode records carry no sidecar, so no lint blob either)
        try:
            out["lint"] = _lint_check()
        except Exception as e:  # noqa: BLE001 — the gate must not kill the run
            out["lint"] = {"ok": False, "findings": -1,
                           "error": f"{type(e).__name__}: {e}"}
        out["lint_ok"] = bool(out["lint"].get("ok"))
        log(f"bench: lint check {out['lint']}")
    # BENCH_METRICS_PATH: dump the unified metrics registry (compile
    # counts/durations, probe accounting, timed spans) as JSON-lines —
    # best-effort, off by default so driver artifacts don't grow
    metrics_path = os.environ.get("BENCH_METRICS_PATH")
    if metrics_path:
        try:
            from benor_tpu.utils.metrics import export_jsonl
            n_rec = export_jsonl(metrics_path)
            log(f"bench: {n_rec} metrics records -> {metrics_path}")
        except Exception as e:  # noqa: BLE001 — observability is optional
            log(f"bench: metrics export failed: {e}")
    if any(k in out for k in _DETAIL_KEYS):
        headline, detail = _split_headline(out)
        # BENCH_DETAIL_PATH: redirect the sidecar (ad-hoc smoke runs must
        # not clobber a committed on-chip capture at the default path)
        detail_path = os.environ.get(
            "BENCH_DETAIL_PATH", os.path.join(HERE, "BENCH_DETAIL.json"))
        try:
            with open(detail_path, "w") as fh:
                json.dump({**headline, **detail}, fh, indent=1)
            log(f"bench: full detail (curve + kernel checks) -> {detail_path}")
        except OSError as e:  # noqa: BLE001 — sidecar is best-effort
            log(f"bench: could not write sidecar {detail_path}: {e}")
        log("bench: detail json: " + json.dumps(detail))
        out = headline
    emit(out)


if __name__ == "__main__":
    main()
