"""The fused vote-phase kernel (ops/pallas_round.py, r3 VERDICT item 2).

The kernel folds the CF vote sampler + coin + decide/adopt/commit chain
into one VMEM pass.  Because it reuses the EXACT streams of the unfused
pallas path (cf_counts_pallas's PHASE_VOTE key, the _COIN_SALT coin
block), a use_pallas_round=True run must be BIT-IDENTICAL to the
use_pallas_hist=True run — which makes these interpret-mode CPU tests
exact pins, not statistical gates.
"""

import jax
import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.ops import sampling, tally
from benor_tpu.sim import run_consensus
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import balanced_inputs

N, T = 96, 8


def _run(use_round, table_max=4, **kw):
    """Full consensus run in the forced CF regime (quorum > table_max)."""
    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = table_max
    try:
        cfg = SimConfig(n_nodes=N, trials=T, delivery="quorum",
                        scheduler="uniform", path="histogram",
                        use_pallas_hist=True, use_pallas_round=use_round,
                        max_rounds=24, **kw)
        if use_round:
            assert tally.pallas_round_active(cfg)
        cr = (np.where(np.arange(N) < cfg.n_faulty, 3, 0)
              if cfg.fault_model == "crash_at_round" else None)
        faults = (FaultSpec.first_f(cfg, crash_rounds=cr) if cfg.n_faulty
                  else FaultSpec.none(T, N))
        state = init_state(cfg, balanced_inputs(T, N), faults)
        r, fin = run_consensus(cfg, state, faults, jax.random.key(cfg.seed))
        return int(r), fin
    finally:
        sampling.EXACT_TABLE_MAX = old


def _assert_same(a, b):
    (ra, fa), (rb, fb) = a, b
    assert ra == rb
    np.testing.assert_array_equal(np.asarray(fa.x), np.asarray(fb.x))
    np.testing.assert_array_equal(np.asarray(fa.decided),
                                  np.asarray(fb.decided))
    np.testing.assert_array_equal(np.asarray(fa.k), np.asarray(fb.k))


@pytest.mark.parametrize("kw", [
    dict(n_faulty=24, seed=3),                             # crash faults
    dict(n_faulty=30, seed=5, rule="textbook"),
    dict(n_faulty=24, seed=7, coin_mode="common"),
    dict(n_faulty=24, seed=9, coin_mode="weak_common", coin_eps=0.5),
    dict(n_faulty=24, seed=11, freeze_decided=False),
    dict(n_faulty=0, seed=13),                             # fault-free
    dict(n_faulty=20, seed=15, fault_model="byzantine"),
    dict(n_faulty=20, seed=17, fault_model="crash_at_round"),
    dict(n_faulty=20, seed=19, fault_model="equivocate"),
    dict(n_faulty=20, seed=21, fault_model="equivocate",
         coin_mode="common"),
    dict(n_faulty=20, seed=23, fault_model="equivocate",
         coin_mode="weak_common", coin_eps=0.5),
], ids=["crash", "textbook", "common", "weak", "nofreeze", "faultfree",
        "byzantine", "crash-at-round", "equivocate", "equiv-common",
        "equiv-weak"])
@pytest.mark.slow
def test_fused_bit_identical_to_unfused_pallas(kw):
    _assert_same(_run(False, **kw), _run(True, **kw))


@pytest.mark.slow
def test_fused_bit_identical_zero_crash_multiround():
    """Balanced inputs + zero crashes + F > N/3: the genuinely multi-round
    flagship regime (sampling noise random-walk, several coin rounds)."""
    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        outs = []
        for use_round in (False, True):
            cfg = SimConfig(n_nodes=N, n_faulty=40, trials=T,
                            delivery="quorum", scheduler="uniform",
                            path="histogram", use_pallas_hist=True,
                            use_pallas_round=use_round, max_rounds=32,
                            seed=2)
            faults = FaultSpec.none(T, N)
            state = init_state(cfg, balanced_inputs(T, N), faults)
            r, fin = run_consensus(cfg, state, faults,
                                   jax.random.key(cfg.seed))
            outs.append((int(r), fin))
        _assert_same(*outs)
        assert outs[0][0] > 1, "regime must be multi-round to be a real pin"
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
def test_fused_bit_identical_stalled_quorum():
    """A trial with fewer live senders than the quorum stalls forever on
    both paths (quorum_ok gating inside the kernel)."""
    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        outs = []
        for use_round in (False, True):
            cfg = SimConfig(n_nodes=N, n_faulty=24, trials=T,
                            delivery="quorum", scheduler="uniform",
                            path="histogram", use_pallas_hist=True,
                            use_pallas_round=use_round, max_rounds=8,
                            seed=4)
            # kill MORE than F lanes: alive < quorum in every trial
            faulty = np.zeros(N, bool)
            faulty[:24] = True
            faults = FaultSpec.from_faulty_list(cfg, faulty)
            state = init_state(cfg, balanced_inputs(T, N), faults)
            state = state.__class__(
                x=state.x, decided=state.decided, k=state.k,
                killed=state.killed.at[:, :30].set(True))
            r, fin = run_consensus(cfg, state, faults,
                                   jax.random.key(cfg.seed))
            outs.append((int(r), fin))
        _assert_same(*outs)
        assert not np.asarray(outs[0][1].decided).any()
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
@pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2)])
def test_fused_sharded_bit_identical(mesh_shape):
    from benor_tpu.parallel import make_mesh, run_consensus_sharded

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        cfg = SimConfig(n_nodes=32, n_faulty=12, trials=8,
                        delivery="quorum", scheduler="uniform",
                        path="histogram", use_pallas_hist=True,
                        use_pallas_round=True, max_rounds=16, seed=6)
        faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
        state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes),
                           faults)
        key = jax.random.key(cfg.seed)
        r1, f1 = run_consensus(cfg, state, faults, key)
        r2, f2 = run_consensus_sharded(cfg, state, faults, key,
                                       make_mesh(*mesh_shape))
        assert int(r1) == int(r2)
        np.testing.assert_array_equal(np.asarray(f1.x), np.asarray(f2.x))
        np.testing.assert_array_equal(np.asarray(f1.decided),
                                      np.asarray(f2.decided))
        np.testing.assert_array_equal(np.asarray(f1.k), np.asarray(f2.k))
    finally:
        sampling.EXACT_TABLE_MAX = old


def test_gating():
    base = dict(n_nodes=N, n_faulty=24, trials=T, delivery="quorum",
                scheduler="uniform", path="histogram",
                use_pallas_hist=True, use_pallas_round=True)
    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        assert tally.pallas_round_active(SimConfig(**base))
        # byzantine / crash_at_round ride the flip sentinel + per-round
        # killed mask; equivocate fuses the mixed-population sampler (r5)
        assert tally.pallas_round_active(
            SimConfig(**{**base, "fault_model": "byzantine"}))
        assert tally.pallas_round_active(
            SimConfig(**{**base, "fault_model": "crash_at_round"}))
        assert tally.pallas_round_active(
            SimConfig(**{**base, "fault_model": "equivocate"}))
        # off without the flag, or (in the uniform regime) the hist kernel
        assert not tally.pallas_round_active(
            SimConfig(**{**base, "use_pallas_round": False}))
        assert not tally.pallas_round_active(
            SimConfig(**{**base, "use_pallas_hist": False}))
        # the count-controlling adversaries ARE served (closed-form
        # delivered counts, counts_mode='delivered'/'camps' — full
        # battery in tests/test_pallas_round_adv.py); biased has no
        # closed form and stays unfused
        assert tally.pallas_round_active(
            SimConfig(**{**base, "scheduler": "adversarial"}))
        assert not tally.pallas_round_active(
            SimConfig(**{**base, "scheduler": "biased"}))
        # weak-coin endpoints short-circuit to plain streams (XLA side)
        assert not tally.pallas_round_active(SimConfig(
            **{**base, "coin_mode": "weak_common", "coin_eps": 0.0}))
        assert tally.pallas_round_active(SimConfig(
            **{**base, "coin_mode": "weak_common", "coin_eps": 0.4}))
    finally:
        sampling.EXACT_TABLE_MAX = old


def test_packed_k_field_overflow_rejected():
    """ADVICE r4 (re-anchored on the PR 8 plane layout, and again on the
    PR 15 down-plane relayout — the k cap paid one plane for the
    crash-recovery down bit, 26 -> 25): max_rounds must fit the
    PACK_LAYOUT k field's declared 25-plane cap (k reaches
    max_rounds + 1)."""
    SimConfig(n_nodes=4, n_faulty=0, use_pallas_round=True,
              max_rounds=(1 << 25) - 2)          # largest legal value
    with pytest.raises(ValueError, match="25 bit-planes"):
        SimConfig(n_nodes=4, n_faulty=0, use_pallas_round=True,
                  max_rounds=(1 << 25) - 1)
    SimConfig(n_nodes=4, n_faulty=0, max_rounds=1 << 25)  # unfused: fine


@pytest.mark.slow
def test_fused_equivocate_multiround():
    """Equivocators (alive, per-receiver random values) + balanced honest
    inputs: a genuinely multi-round equivocate run, fused == unfused
    bit-for-bit — including the fused next-round histogram partials the
    loop carries (valid because killed/faulty are static under this fault
    model)."""
    outs = {}
    for use_round in (False, True):
        r, fin = _run(use_round, n_faulty=30, seed=25,
                      fault_model="equivocate")
        outs[use_round] = (r, fin)
    _assert_same(outs[False], outs[True])
    assert outs[True][0] > 1, "scenario decided too fast to exercise the loop"


@pytest.mark.slow
@pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2)])
def test_fused_equivocate_sharded_bit_identical(mesh_shape):
    """The fused equivocate round under a mesh: the honest-histogram and
    n_equiv psums + global-id streams keep any mesh shape bit-identical
    to the single device (equivocators stay ALIVE, so the draws are not
    clamped — the identity is not vacuous)."""
    from benor_tpu.parallel import make_mesh, run_consensus_sharded

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        cfg = SimConfig(n_nodes=32, n_faulty=10, trials=8,
                        delivery="quorum", scheduler="uniform",
                        path="histogram", fault_model="equivocate",
                        use_pallas_hist=True, use_pallas_round=True,
                        max_rounds=16, seed=8)
        assert tally.pallas_round_active(cfg)
        faults = FaultSpec.first_f(cfg)
        state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes),
                           faults)
        key = jax.random.key(cfg.seed)
        r1, f1 = run_consensus(cfg, state, faults, key)
        r2, f2 = run_consensus_sharded(cfg, state, faults, key,
                                       make_mesh(*mesh_shape))
        assert int(r1) == int(r2)
        np.testing.assert_array_equal(np.asarray(f1.x), np.asarray(f2.x))
        np.testing.assert_array_equal(np.asarray(f1.decided),
                                      np.asarray(f2.decided))
        np.testing.assert_array_equal(np.asarray(f1.k), np.asarray(f2.k))
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
@pytest.mark.parametrize("fault_model", ["crash", "equivocate",
                                         "crash_at_round"])
def test_fused_sharded_slice_and_resume_bit_identical(fault_model):
    """The fused packed loop under a mesh with NON-trivial round bounds:
    2-round slices (the poll_rounds path) and a cut@2 + resume must both
    equal the uninterrupted single-device fused run — including
    crash_at_round, whose hist1 must be recomputed at the re-entry
    round."""
    from benor_tpu.parallel import (make_mesh, resume_consensus_sharded,
                                    run_consensus_slice_sharded)
    from benor_tpu.sim import start_state

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        n, f, T = 32, 10, 8
        cfg = SimConfig(n_nodes=n, n_faulty=f, trials=T, delivery="quorum",
                        scheduler="uniform", path="histogram",
                        fault_model=fault_model, use_pallas_hist=True,
                        use_pallas_round=True, max_rounds=16, seed=12)
        assert tally.pallas_round_active(cfg)
        if fault_model == "crash":
            faults = FaultSpec.none(T, n)          # draws not clamped
        else:
            cr = (np.where(np.arange(n) < f, 3, 0)
                  if fault_model == "crash_at_round" else None)
            faults = FaultSpec.first_f(cfg, crash_rounds=cr)
        state = init_state(cfg, balanced_inputs(T, n), faults)
        key = jax.random.key(cfg.seed)
        r1, f1 = run_consensus(cfg, state, faults, key)
        assert int(r1) > 1, "need a multi-round scenario"
        mesh = make_mesh(2, 4)

        # 2-round slices to termination
        st, r = start_state(cfg, state), 1
        while True:
            r_next, st = run_consensus_slice_sharded(
                cfg, st, faults, key, mesh, r, r + 2)
            rn = int(r_next)
            if rn == r or rn > cfg.max_rounds or bool(np.asarray(
                    (st.decided | st.killed).all())):
                break
            r = rn
        assert rn - 1 == int(r1)
        _assert_same((int(r1), f1), (rn - 1, st))

        # cut@2 + resume
        rc, fc = run_consensus(cfg.replace(max_rounds=2), state, faults, key)
        rr, fr = resume_consensus_sharded(cfg, fc, faults, key, mesh,
                                          from_round=int(rc) + 1)
        assert int(rr) == int(r1)
        _assert_same((int(r1), f1), (int(rr), fr))
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
def test_fused_single_device_slice_and_resume_bit_identical():
    """The single-device poll (run_consensus_slice) and checkpoint
    (resume_consensus) paths dispatch to the SAME packed loop as
    run_consensus — sliced / cut-and-resumed fused runs equal the
    uninterrupted one bitwise."""
    from benor_tpu.sim import (resume_consensus, run_consensus_slice,
                               start_state)

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        n, f, T = 32, 10, 8
        cfg = SimConfig(n_nodes=n, n_faulty=f, trials=T, delivery="quorum",
                        scheduler="uniform", path="histogram",
                        use_pallas_hist=True, use_pallas_round=True,
                        max_rounds=16, seed=12)
        faults = FaultSpec.none(T, n)
        state = init_state(cfg, balanced_inputs(T, n), faults)
        key = jax.random.key(cfg.seed)
        r1, f1 = run_consensus(cfg, state, faults, key)
        assert int(r1) > 1

        st, r = start_state(cfg, state), 1
        while True:
            r_next, st = run_consensus_slice(cfg, st, faults, key,
                                             jax.numpy.int32(r),
                                             jax.numpy.int32(r + 2))
            rn = int(r_next)
            if rn == r or rn > cfg.max_rounds or bool(np.asarray(
                    (st.decided | st.killed).all())):
                break
            r = rn
        _assert_same((int(r1), f1), (rn - 1, st))

        rc, fc = run_consensus(cfg.replace(max_rounds=2), state, faults, key)
        rr, fr = resume_consensus(cfg, fc, faults, key,
                                  from_round=int(rc) + 1)
        _assert_same((int(r1), f1), (int(rr), fr))
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
@pytest.mark.parametrize("kw", [
    dict(scheduler="uniform", use_pallas_hist=True, table_max=4),
    dict(scheduler="adversarial", coin_mode="common", table_max=None),
], ids=["sampled", "delivered"])
def test_record_trajectory_fused_matches_endpoint(kw):
    """results.trajectory_study runs record_trajectory with the flagship
    flags on the accelerator — the per-round benor_round wrapper
    (packed_round: pack/unpack at the round boundary) must agree with
    the packed while-loop's endpoint for BOTH counts sources.  Under
    the common-coin delivered mode the fused scan additionally equals
    the unfused XLA scan bit-for-bit (shared streams)."""
    from benor_tpu.sweep import record_trajectory

    kw = dict(kw)                      # parametrize dicts must stay pristine
    table_max = kw.pop("table_max")
    old = sampling.EXACT_TABLE_MAX
    if table_max is not None:
        sampling.EXACT_TABLE_MAX = table_max
    try:
        def run(use_round):
            cfg = SimConfig(n_nodes=N, n_faulty=24, trials=T,
                            delivery="quorum", path="histogram",
                            use_pallas_round=use_round, max_rounds=16,
                            seed=8, **kw)
            faults = FaultSpec.none(T, N)
            state = init_state(cfg, balanced_inputs(T, N), faults)
            key = jax.random.key(cfg.seed)
            if use_round:
                assert tally.pallas_round_active(cfg)
            r_end, fin_end = run_consensus(cfg, state, faults, key)
            fin_sc, traj = record_trajectory(cfg, state, faults, key,
                                             n_rounds=int(r_end) + 1)
            return fin_end, fin_sc, {k: np.asarray(v)
                                     for k, v in traj.items()}

        fin_end, fin_sc, traj = run(True)
        # scan endpoint == while-loop endpoint (fused path vs itself)
        np.testing.assert_array_equal(np.asarray(fin_sc.x),
                                      np.asarray(fin_end.x))
        np.testing.assert_array_equal(np.asarray(fin_sc.decided),
                                      np.asarray(fin_end.decided))
        assert traj["decided"][-1] == 1.0

        if kw.get("scheduler") == "adversarial":
            # common coin: fused trajectory == unfused XLA trajectory
            _, _, traj_x = run(False)
            for name in traj:
                np.testing.assert_array_equal(traj[name], traj_x[name])
    finally:
        sampling.EXACT_TABLE_MAX = old
