"""faultlab (PR 15, benor_tpu/faults): the dynamic fault-injection plane.

The acceptance pins:

  * injection OFF is bit-identical in results AND compile counts across
    all five regimes — a config with every faultlab field at its default
    IS the pre-faultlab config (same dataclass, same hash), so a rerun
    must hit the jit cache with zero new backend compiles;
  * a full rounds-vs-drop_prob curve executes with exactly ONE backend
    compile (drop_prob rides DynParams) and is bit-equal to the
    per-point oracle;
  * seeded down-interval-decide and cross-partition-quorum forgeries are
    caught by the auditor with exact (trial, node, round) witnesses;
    clean runs across all fault families audit green.

Runs on the 8-device virtual CPU mesh forced by tests/conftest.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from benor_tpu.audit import (WitnessBundle, audit_point, audit_witness)
from benor_tpu.config import SimConfig
from benor_tpu.faults.partitions import (group_of, group_size_of,
                                         parse_partition)
from benor_tpu.faults.recovery import (crash_recover_faults,
                                       parse_recovery, rejoin_mode)
from benor_tpu.ops import sampling, tally
from benor_tpu.sim import run_consensus, run_consensus_slice, start_state
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import (balanced_inputs, default_crash_faults,
                             random_inputs, run_point, run_points_batched)
from benor_tpu.state import (WIT_DECIDED, WIT_V0, WIT_V1, WIT_WRITTEN,
                             WIT_X)
from benor_tpu.utils.compile_counter import count_backend_compiles


def _assert_state_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.x), np.asarray(b.x))
    np.testing.assert_array_equal(np.asarray(a.decided),
                                  np.asarray(b.decided))
    np.testing.assert_array_equal(np.asarray(a.k), np.asarray(b.k))
    np.testing.assert_array_equal(np.asarray(a.killed),
                                  np.asarray(b.killed))


def _points_equal(a, b):
    assert a.rounds_executed == b.rounds_executed
    assert a.mean_k == b.mean_k
    assert a.decided_frac == b.decided_frac
    assert a.ones_frac == b.ones_frac
    assert a.disagree_frac == b.disagree_frac
    assert (a.k_hist == b.k_hist).all()


# --------------------------------------------------------------------------
# spec grammars + config validation
# --------------------------------------------------------------------------


def test_recovery_spec_grammar():
    s = parse_recovery("at:3:4")
    assert (s.kind, s.crash, s.down, s.rejoin) == ("at", 3, 4, "durable")
    assert s.rounds(3) == ([3, 3, 3], [7, 7, 7])
    s = parse_recovery("stagger:2:3:amnesia")
    assert (s.kind, s.crash, s.down, s.rejoin) == ("stagger", 2, 3,
                                                   "amnesia")
    assert s.rounds(3) == ([2, 3, 4], [5, 6, 7])
    assert parse_recovery("at:5:0").rounds(2) == ([5, 5], [0, 0])
    assert parse_recovery(None) is None
    assert rejoin_mode(None) == "durable"
    assert rejoin_mode("at:2:2:amnesia") == "amnesia"
    for bad in ("foo:1:2", "at:1", "at:x:2", "at:0:2", "at:1:-1",
                "stagger:1:2:3", "at:1:2:sometimes"):
        with pytest.raises(ValueError):
            parse_recovery(bad)


def test_partition_spec_grammar():
    s = parse_partition("halves:6")
    assert (s.groups, s.heal_round) == (2, 6)
    assert s.group_sizes(10) == [5, 5]
    s = parse_partition("groups:3:4")
    assert (s.groups, s.heal_round) == (3, 4)
    assert sum(s.group_sizes(10)) == 10
    assert parse_partition(None) is None
    # contiguous assignment: group ids monotone, sizes match group_of
    n, g = 13, 3
    ids = np.arange(n)
    grp = np.asarray(group_of(ids, n, g))
    sizes = parse_partition(f"groups:{g}:2").group_sizes(n)
    assert [int((grp == k).sum()) for k in range(g)] == sizes
    assert group_size_of(0, n, parse_partition(f"groups:{g}:2")) == sizes[0]
    for bad in ("halves", "halves:0", "groups:1:4", "groups:2",
                "thirds:3", "groups:x:4"):
        with pytest.raises(ValueError):
            parse_partition(bad)


def test_config_validation_matrix():
    ok = SimConfig(n_nodes=16, n_faulty=2, drop_prob=0.1)
    assert ok.drop_prob == 0.1
    SimConfig(n_nodes=16, n_faulty=2, partition="halves:4")
    SimConfig(n_nodes=16, n_faulty=2, fault_model="crash_recover",
              recovery="at:2:3")
    with pytest.raises(ValueError, match=r"\[0, 1\)"):
        SimConfig(n_nodes=16, n_faulty=2, drop_prob=1.0)
    with pytest.raises(ValueError, match="delivery='all'"):
        SimConfig(n_nodes=16, n_faulty=2, drop_prob=0.1,
                  delivery="quorum")
    with pytest.raises(ValueError, match="equivocate"):
        SimConfig(n_nodes=16, n_faulty=2, drop_prob=0.1,
                  fault_model="equivocate")
    with pytest.raises(ValueError, match="complete graph"):
        SimConfig(n_nodes=16, n_faulty=2, drop_prob=0.1,
                  topology="ring:2")
    with pytest.raises(ValueError, match="crash_recover"):
        SimConfig(n_nodes=16, n_faulty=2, recovery="at:2:3")
    with pytest.raises(ValueError, match="backend='tpu'"):
        SimConfig(n_nodes=16, n_faulty=2, fault_model="crash_recover",
                  recovery="at:2:3", backend="express")
    with pytest.raises(ValueError, match="mutually exclusive"):
        SimConfig(n_nodes=16, n_faulty=2, partition="halves:4",
                  committee_cap=4, committee_count=2, committee_size=4)
    with pytest.raises(ValueError, match="equivocate"):
        SimConfig(n_nodes=16, n_faulty=2, partition="halves:4",
                  fault_model="equivocate")
    # partition composes with topology
    SimConfig(n_nodes=16, n_faulty=1, partition="halves:4",
              topology="ring:4")


# --------------------------------------------------------------------------
# injection-off bit-identity: results AND compile counts, five regimes
# --------------------------------------------------------------------------


def _off(cfg):
    """The injection-off twin — MUST be the identical config object."""
    off = cfg.replace(drop_prob=0.0, recovery=None, partition=None)
    assert off == cfg and hash(off) == hash(cfg)
    return off


def test_injection_off_identity_traced_and_batched():
    cfg = SimConfig(n_nodes=32, n_faulty=4, trials=8, max_rounds=16,
                    seed=3, delivery="quorum", scheduler="uniform",
                    path="histogram")
    pt = run_point(cfg)
    with count_backend_compiles() as cc:
        pt2 = run_point(_off(cfg))
    assert cc.count == 0
    _points_equal(pt, pt2)

    # the batched engine AOT-compiles its bucket executable every
    # invocation by design (compile accounting is measured, not
    # inferred) — the identity pin is therefore EQUAL compile counts
    # plus bit-equal points, not a cache hit
    cb = run_points_batched(cfg, [cfg, cfg.replace(n_faulty=6)])
    cb2 = run_points_batched(_off(cfg),
                             [_off(cfg), _off(cfg).replace(n_faulty=6)])
    assert cb2.compile_count == cb.compile_count
    assert cb2.n_buckets == cb.n_buckets
    for a, b in zip(cb.points, cb2.points):
        _points_equal(a, b)


def test_injection_off_identity_sliced():
    cfg = SimConfig(n_nodes=24, n_faulty=3, trials=4, max_rounds=16,
                    seed=4)
    faults = default_crash_faults(cfg)
    state = init_state(cfg, random_inputs(4, 4, 24), faults)
    key = jax.random.key(cfg.seed)
    st = start_state(cfg, state)
    r1, s1 = run_consensus_slice(cfg, st, faults, key, jnp.int32(1),
                                 jnp.int32(cfg.max_rounds + 2))
    with count_backend_compiles() as cc:
        r2, s2 = run_consensus_slice(_off(cfg), st, faults, key,
                                     jnp.int32(1),
                                     jnp.int32(cfg.max_rounds + 2))
    assert cc.count == 0
    assert int(r1) == int(r2)
    _assert_state_equal(s1, s2)


def test_injection_off_identity_fused_pallas():
    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        cfg = SimConfig(n_nodes=96, n_faulty=24, trials=4, max_rounds=16,
                        seed=5, delivery="quorum", scheduler="uniform",
                        path="histogram", use_pallas_hist=True,
                        use_pallas_round=True)
        assert tally.pallas_round_active(cfg)
        faults = default_crash_faults(cfg)
        state = init_state(cfg, balanced_inputs(4, 96), faults)
        key = jax.random.key(cfg.seed)
        r1, s1 = run_consensus(cfg, state, faults, key)
        with count_backend_compiles() as cc:
            r2, s2 = run_consensus(_off(cfg), state, faults, key)
        assert cc.count == 0
        assert int(r1) == int(r2)
        _assert_state_equal(s1, s2)
    finally:
        sampling.EXACT_TABLE_MAX = old


def test_injection_off_identity_sharded():
    from benor_tpu.parallel import make_mesh, run_consensus_sharded

    cfg = SimConfig(n_nodes=32, n_faulty=4, trials=8, max_rounds=16,
                    seed=6, delivery="quorum", scheduler="uniform",
                    path="histogram")
    faults = default_crash_faults(cfg)
    state = init_state(cfg, random_inputs(6, 8, 32), faults)
    key = jax.random.key(cfg.seed)
    mesh = make_mesh(2, 4)
    r1, s1 = run_consensus_sharded(cfg, state, faults, key, mesh)
    with count_backend_compiles() as cc:
        r2, s2 = run_consensus_sharded(_off(cfg), state, faults, key,
                                       mesh)
    assert cc.count == 0
    assert int(r1) == int(r2)
    _assert_state_equal(s1, s2)


# --------------------------------------------------------------------------
# omission: the one-bucket drop curve (acceptance) + path duality
# --------------------------------------------------------------------------


def test_drop_curve_single_compile_and_oracle_bit_equal():
    """The acceptance pin: a whole rounds-vs-drop_prob curve is ONE
    bucket executable (compile_counter-measured), bit-equal per point to
    the run_point oracle.  Zero-crash faults (faults/curves.drop_curve's
    policy): the quorum slack F is what absorbs the thinning."""
    from benor_tpu.faults.curves import drop_curve

    base = SimConfig(n_nodes=64, n_faulty=16, trials=8, max_rounds=24,
                     seed=7, path="histogram")
    ps = (0.02, 0.05, 0.1, 0.15)
    rows, cb = drop_curve(base, ps)      # warm the eager input helpers
    assert cb.n_buckets == 1
    assert cb.compile_count == 1
    assert cb.bucket_kinds == ["dyn"]
    # the whole-scope pin: with the eager helpers warm, re-running the
    # ENTIRE curve costs exactly the one bucket executable build (the
    # batched engine AOT-compiles per invocation by design)
    with count_backend_compiles() as cc:
        rows, cb = drop_curve(base, ps)
    assert cb.compile_count == 1
    assert cc.count == 1
    none = FaultSpec.none(base.trials, base.n_nodes)
    for p, pt in zip(ps, cb.points):
        _points_equal(run_point(base.replace(drop_prob=p), faults=none),
                      pt)
    assert [r["drop_prob"] for r in rows] == list(ps)


def test_drop_slows_convergence_both_paths():
    """Omission is really injected on BOTH compute paths: with p in the
    live regime (p < F/N) rounds-to-decide is no faster than lossless
    delivery, and the dense per-edge mask and the histogram binomial
    thinning agree on full termination (fixed seeds — deterministic).
    Zero crashes: with the live population pinned to the quorum exactly,
    any drop stalls every receiver (the cliff the curve policy avoids)."""
    base = SimConfig(n_nodes=48, n_faulty=12, trials=16, max_rounds=32,
                     seed=8, path="histogram")
    none = FaultSpec.none(base.trials, base.n_nodes)
    p0 = run_point(base, faults=none)
    ph = run_point(base.replace(drop_prob=0.08), faults=none)
    pd = run_point(base.replace(drop_prob=0.08, path="dense"),
                   faults=none)
    assert ph.decided_frac == 1.0 and pd.decided_frac == 1.0
    # near the threshold the per-lane stalls dominate: strictly slower
    # than lossless delivery (fixed seed — deterministic, not flaky)
    near = run_point(base.replace(drop_prob=0.2), faults=none)
    assert near.mean_k > p0.mean_k
    # past the stall threshold (p >= F/N) the network effectively
    # stalls to the round cap (a rare lucky lane may still clear the
    # thinning's tail — hence < 5%, not == 0)
    stall = run_point(base.replace(drop_prob=0.4), faults=none)
    assert stall.decided_frac < 0.05
    assert stall.rounds_executed == base.max_rounds
    # and crash-from-birth faults + ANY drop is the stall cliff: live
    # population == quorum exactly, no slack to absorb thinning
    cliff = run_point(base.replace(drop_prob=0.08, max_rounds=8))
    assert cliff.decided_frac < 0.2


# --------------------------------------------------------------------------
# crash-recovery churn
# --------------------------------------------------------------------------


def test_crash_recover_never_rejoin_equals_crash_at_round():
    """recovery down=0 (never rejoins) IS crash_at_round: same killed
    derivation, same streams, bit-identical results."""
    cfg_cr = SimConfig(n_nodes=32, n_faulty=6, trials=8, max_rounds=20,
                       seed=9, fault_model="crash_recover",
                       recovery="at:3:0")
    cfg_car = cfg_cr.replace(fault_model="crash_at_round", recovery=None)
    iv = random_inputs(9, 8, 32)
    f_cr = default_crash_faults(cfg_cr)
    f_car = FaultSpec.first_f(cfg_car,
                              crash_rounds=np.where(np.arange(32) < 6,
                                                    3, 0))
    key = jax.random.key(9)
    r1, s1 = run_consensus(cfg_cr, init_state(cfg_cr, iv, f_cr), f_cr,
                           key)
    r2, s2 = run_consensus(cfg_car, init_state(cfg_car, iv, f_car),
                           f_car, key)
    assert int(r1) == int(r2)
    _assert_state_equal(s1, s2)


def test_crash_recover_down_interval_freezes_then_rejoins():
    """A down lane's witnessed (x, decided, k ~ participation) freeze
    for the whole interval, and it participates again after rejoin —
    the clean-run semantics the down_silence invariant audits.  The
    crash is at round 1 so the interval BINDS: full delivery converges
    in ~1 round, and a later crash would watch an already-settled
    network."""
    cfg = SimConfig(n_nodes=32, n_faulty=4, trials=4, max_rounds=24,
                    seed=10, fault_model="crash_recover",
                    recovery="at:1:5", witness_trials=(0,),
                    witness_nodes=8)
    report, bundle = audit_point(cfg)
    assert report.ok
    buf = np.asarray(bundle.buffer)
    # watched node 0 is faulty (first-F) with interval [1, 6)
    assert int(bundle.down_crash[0, 0]) == 1
    assert int(bundle.down_recover[0, 0]) == 6
    written = np.nonzero(buf[:, 0, 0, WIT_WRITTEN] > 0)[0]
    inside = [r for r in written if 1 <= r < 6]
    assert inside, "run must outlast the down interval"
    for r in inside:
        assert buf[r, 0, 0, WIT_X] == buf[0, 0, 0, WIT_X]
        assert buf[r, 0, 0, WIT_DECIDED] == buf[0, 0, 0, WIT_DECIDED]
        assert buf[r, 0, 0, WIT_DECIDED] == 0
    # the trial cannot settle while the lane is down: the loop ran to
    # the rejoin round, where the lane finally decides
    assert written[-1] >= 6
    assert buf[written[-1], 0, 0, WIT_DECIDED] == 1


@pytest.mark.parametrize("rejoin", ["durable", "amnesia"])
def test_crash_recover_packed_bit_identical_to_unfused(rejoin):
    """The packed pallas path re-derives down-intervals from the round
    bounds in-kernel: use_pallas_round is bit-identical to the unfused
    pallas-hist path under churn, durable AND amnesia rejoins."""
    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        # F = 40 keeps the CF-sampled tallies (~ m/2 = 28 per class)
        # under the decide bar for the first rounds, so the run outlasts
        # the churn window instead of deciding before anyone crashes
        base = dict(n_nodes=96, trials=8, n_faulty=40, max_rounds=24,
                    seed=11, delivery="quorum", scheduler="uniform",
                    path="histogram", fault_model="crash_recover",
                    recovery=f"at:2:4:{rejoin}")
        c_hist = SimConfig(use_pallas_hist=True, **base)
        c_round = SimConfig(use_pallas_hist=True, use_pallas_round=True,
                            **base)
        assert tally.pallas_round_active(c_round)
        fl = default_crash_faults(c_round)
        iv = balanced_inputs(8, 96)
        key = jax.random.key(11)
        ra, fa = run_consensus(c_hist, init_state(c_hist, iv, fl), fl,
                               key)
        rb, fb = run_consensus(c_round, init_state(c_round, iv, fl), fl,
                               key)
        # the run must actually cross the churn window, or the pin is
        # vacuous (the faulty lanes are down for rounds [2, 6))
        assert int(ra) >= 6
        assert int(ra) == int(rb)
        _assert_state_equal(fa, fb)
    finally:
        sampling.EXACT_TABLE_MAX = old


def test_crash_recover_sliced_sharded_batched_bit_identical():
    from benor_tpu.parallel import make_mesh, run_consensus_sharded

    cfg = SimConfig(n_nodes=32, n_faulty=6, trials=8, max_rounds=20,
                    seed=12, fault_model="crash_recover",
                    recovery="stagger:2:4:amnesia")
    iv = random_inputs(12, 8, 32)
    faults = default_crash_faults(cfg)
    state = init_state(cfg, iv, faults)
    key = jax.random.key(12)
    r0, fin0 = run_consensus(cfg, state, faults, key)

    # sliced
    cur, r = start_state(cfg, state), jnp.int32(1)
    while True:
        nr, cur = run_consensus_slice(cfg, cur, faults, key, r, r + 3)
        if int(nr) == int(r):
            break
        r = nr
    _assert_state_equal(fin0, cur)

    # sharded (trials x nodes mesh)
    r2, fin2 = run_consensus_sharded(cfg, state, faults, key,
                                     make_mesh(2, 4))
    assert int(r2) == int(r0)
    _assert_state_equal(fin0, fin2)

    # batched engine (dyn bucket; fault spec built by the same policy)
    cb = run_points_batched(cfg, [cfg])
    _points_equal(run_point(cfg), cb.points[0])


# --------------------------------------------------------------------------
# partitions
# --------------------------------------------------------------------------


def test_partition_stalls_until_heal():
    """halves:<h> with F < N/2 is a clean liveness attack: no group can
    muster the quorum N - F, every lane stalls (k frozen), and the run
    converges only after the heal — every decided lane's k exceeds the
    heal round."""
    heal = 6
    cfg = SimConfig(n_nodes=32, n_faulty=4, trials=8, max_rounds=24,
                    seed=13, partition=f"halves:{heal}")
    pt = run_point(cfg)
    base = run_point(cfg.replace(partition=None))
    assert pt.rounds_executed >= heal
    assert pt.decided_frac == 1.0
    # k histogram: no decided lane with k <= heal (k = r + 1, r >= heal)
    assert pt.k_hist[:heal + 1].sum() == 0
    assert pt.mean_k > base.mean_k


def test_partition_cannot_split_brain():
    """The quorum N - F spans EVERY minority group (a group holds at
    most ~N/2 < N - F members for any F < N/2), so a partition can
    starve liveness but never manufacture split-brain: even with
    per-group UNANIMOUS opposing inputs — the textbook partition
    nightmare — nothing decides inside the epoch, and after the heal
    the merged network agrees."""
    n, heal = 32, 6
    cfg = SimConfig(n_nodes=n, n_faulty=4, trials=8, max_rounds=24,
                    seed=14, partition=f"halves:{heal}")
    iv = np.concatenate([np.zeros(n // 2, np.int8),
                         np.ones(n // 2, np.int8)])
    pt = run_point(cfg, initial_values=np.tile(iv, (8, 1)),
                   faults=FaultSpec.none(8, n))
    assert pt.k_hist[:heal + 1].sum() == 0     # no in-epoch decide
    assert pt.disagree_frac == 0.0             # no split-brain, ever
    assert pt.decided_frac == 1.0              # heals, then agrees


def test_partition_composes_with_topology():
    cfg = SimConfig(n_nodes=32, n_faulty=1, trials=4, max_rounds=24,
                    seed=15, topology="ring:4", partition="halves:4",
                    witness_trials=(0,), witness_nodes=6)
    report, bundle = audit_point(
        cfg, initial_values=np.ones((4, 32), np.int8),
        faults=FaultSpec.none(4, 32), unanimous=1)
    assert bundle.tally_bound == 5          # d + 1
    assert bundle.partition == "halves:4"
    assert report.ok


# --------------------------------------------------------------------------
# audits: clean across families, forgeries pinpointed (acceptance)
# --------------------------------------------------------------------------


def test_audit_clean_across_fault_families():
    common = dict(n_nodes=32, trials=4, max_rounds=24, seed=16,
                  witness_trials=(0, 1), witness_nodes=8)
    fams = [
        (SimConfig(n_faulty=4, fault_model="crash_recover",
                   recovery="at:1:4", **common), None),
        (SimConfig(n_faulty=4, fault_model="crash_recover",
                   recovery="stagger:1:3:amnesia", **common), None),
        # zero crashes for the omission point (the quorum slack absorbs
        # the thinning; crash faults would stall every receiver)
        (SimConfig(n_faulty=8, drop_prob=0.05, **common),
         FaultSpec.none(4, 32)),
        (SimConfig(n_faulty=4, partition="halves:4", **common), None),
    ]
    for cfg, faults in fams:
        report, _ = audit_point(cfg, faults=faults,
                                label=f"clean {cfg.fault_model}")
        assert report.ok, (cfg, report.summary())
        if cfg.fault_model == "crash_recover":
            assert report.checks["down_silence"] >= 1


def test_audit_flags_forged_decide_in_down_interval():
    """The acceptance forgery: a decide written inside a down interval
    is caught with its exact (trial, node, round)."""
    cfg = SimConfig(n_nodes=32, n_faulty=4, trials=4, max_rounds=24,
                    seed=10, fault_model="crash_recover",
                    recovery="at:1:5", witness_trials=(0,),
                    witness_nodes=8)
    report, bundle = audit_point(cfg)
    assert report.ok
    forged = np.array(bundle.buffer)
    rd = 3                                # inside [1, 6)
    assert forged[rd, 0, 0, WIT_WRITTEN] > 0
    forged[rd, 0, 0, WIT_DECIDED] = 1
    forged[rd, 0, 0, WIT_X] = 1
    forged[rd, 0, 0, WIT_V1] = cfg.n_faulty + 1
    rep = audit_witness(WitnessBundle(
        buffer=forged, trial_ids=bundle.trial_ids,
        node_ids=bundle.node_ids, rule=cfg.rule, n_faulty=cfg.n_faulty,
        n_nodes=cfg.n_nodes, down_crash=bundle.down_crash,
        down_recover=bundle.down_recover))
    hits = [v for v in rep.violations if v.invariant == "down_silence"]
    assert hits
    v = hits[0]
    assert (v.trial, v.round, v.nodes) == (0, rd, [0])
    assert v.detail["crash_round"] == 1
    assert v.detail["recover_round"] == 6


def test_audit_flags_forged_cross_partition_quorum():
    """The other acceptance forgery: a tally no partition group could
    deliver during the epoch is flagged as forged evidence, pinpointed
    to (trial, node, round)."""
    heal = 6
    cfg = SimConfig(n_nodes=32, n_faulty=4, trials=4, max_rounds=24,
                    seed=13, partition=f"halves:{heal}",
                    witness_trials=(0,), witness_nodes=8)
    report, bundle = audit_point(cfg)
    assert report.ok
    forged = np.array(bundle.buffer)
    rd = 3                                # inside the epoch (< heal)
    assert forged[rd, 0, 0, WIT_WRITTEN] > 0
    gsize = group_size_of(int(bundle.node_ids[0]), cfg.n_nodes,
                          parse_partition(cfg.partition))
    forged[rd, 0, 0, WIT_V0] = gsize + 5  # beyond the group
    forged[rd, 0, 0, WIT_V1] = 0
    rep = audit_witness(WitnessBundle(
        buffer=forged, trial_ids=bundle.trial_ids,
        node_ids=bundle.node_ids, rule=cfg.rule, n_faulty=cfg.n_faulty,
        n_nodes=cfg.n_nodes, partition=cfg.partition))
    hits = [v for v in rep.violations
            if v.invariant == "quorum_evidence"
            and v.detail.get("group_size") == gsize]
    assert hits
    v = hits[0]
    assert (v.trial, v.round, v.nodes) == (0, rd, [0])
    # the SAME tally after the heal is legal (whole network again)
    healed = np.array(bundle.buffer)
    post = [r for r in
            np.nonzero(healed[:, 0, 0, WIT_WRITTEN] > 0)[0]
            if r >= heal]
    assert post, "run must outlast the epoch"
    healed[post[0], 0, 0, WIT_V0] = gsize + 5
    rep2 = audit_witness(WitnessBundle(
        buffer=healed, trial_ids=bundle.trial_ids,
        node_ids=bundle.node_ids, rule=cfg.rule, n_faulty=cfg.n_faulty,
        n_nodes=cfg.n_nodes, partition=cfg.partition))
    assert not any(v.detail.get("group_size") == gsize
                   for v in rep2.violations)


def test_bundle_roundtrip_with_faultlab_fields(tmp_path):
    import json
    import sys, os
    from benor_tpu.audit import load_bundle, save_bundle

    cfg = SimConfig(n_nodes=24, n_faulty=3, trials=2, max_rounds=16,
                    seed=17, fault_model="crash_recover",
                    recovery="at:2:3", witness_trials=(0,),
                    witness_nodes=4)
    report, bundle = audit_point(cfg, label="roundtrip")
    path = tmp_path / "bundle.json"
    save_bundle(str(path), bundle, report)
    back = load_bundle(str(path))
    assert back.partition is None
    np.testing.assert_array_equal(back.down_crash, bundle.down_crash)
    np.testing.assert_array_equal(back.down_recover,
                                  bundle.down_recover)
    assert audit_witness(back).ok
    # schema-valid (tools/witness_bundle_schema.json)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import check_metrics_schema as cms
        assert cms.check_witness_bundle(
            json.loads(path.read_text())) == []
    finally:
        sys.path.pop(0)


# --------------------------------------------------------------------------
# structural pallas demotion
# --------------------------------------------------------------------------


def test_faults_demotion_warns_and_counts():
    import benor_tpu.sim as sim
    from benor_tpu.utils.metrics import REGISTRY

    sim._faults_demotion_warned = False
    cfg = SimConfig(n_nodes=16, n_faulty=2, trials=2, drop_prob=0.05,
                    use_pallas_round=True, use_pallas_hist=True)
    before = REGISTRY.counter("sim.demotion.faults").value
    with pytest.warns(UserWarning, match="fault plane armed"):
        run_point(cfg)
    assert REGISTRY.counter("sim.demotion.faults").value > before
    sim._faults_demotion_warned = True


# --------------------------------------------------------------------------
# serve satellites: CONFIG_FIELDS, 400s, bucket keys, end-to-end
# --------------------------------------------------------------------------


def test_serve_jobspec_faultlab_fields():
    from benor_tpu.serve.jobs import JobSpec

    spec = JobSpec.from_dict({"n_nodes": 32, "n_faulty": 4, "trials": 4,
                              "drop_prob": 0.05})
    assert spec.to_config().drop_prob == 0.05
    spec = JobSpec.from_dict({"n_nodes": 32, "n_faulty": 4, "trials": 4,
                              "fault_model": "crash_recover",
                              "recovery": "stagger:2:3:amnesia"})
    assert spec.to_config().recovery == "stagger:2:3:amnesia"
    spec = JobSpec.from_dict({"n_nodes": 32, "n_faulty": 4, "trials": 4,
                              "partition": "halves:5"})
    assert spec.to_config().partition == "halves:5"


def test_serve_jobspec_faultlab_structured_400s():
    from benor_tpu.serve.jobs import JobError, JobSpec

    cases = [
        ({"drop_prob": "lots"}, "drop_prob"),
        ({"recovery": 7}, "recovery"),
        ({"partition": ["halves", 5]}, "partition"),
        # SimConfig-level rejections surface on the 'config' field
        ({"drop_prob": 0.2, "delivery": "quorum"}, "config"),
        ({"recovery": "at:2:3"}, "config"),          # needs crash_recover
        ({"partition": "halves:0"}, "config"),       # bad heal round
        ({"fault_model": "crash_recover",
          "recovery": "sometimes:1:2"}, "config"),   # bad grammar
    ]
    base = {"n_nodes": 32, "n_faulty": 4, "trials": 4}
    for doc, field in cases:
        with pytest.raises(JobError) as ei:
            JobSpec.from_dict({**base, **doc})
        assert ei.value.body["field"] == field, (doc, ei.value.body)


def test_serve_bucket_key_drop_coalesces_specs_separate():
    from benor_tpu.serve.batcher import serve_bucket_key

    base = SimConfig(n_nodes=32, n_faulty=4, trials=4, seed=0)
    a = serve_bucket_key(base.replace(drop_prob=0.05))
    b = serve_bucket_key(base.replace(drop_prob=0.2, seed=9))
    assert a == b                       # dyn axis + seed erased
    assert serve_bucket_key(base.replace(drop_prob=0.05)) != \
        serve_bucket_key(base)          # armed never coalesces with off
    p1 = serve_bucket_key(base.replace(partition="halves:4"))
    p2 = serve_bucket_key(base.replace(partition="halves:8"))
    assert p1 != p2                     # partition specs bucket apart
    r1 = serve_bucket_key(base.replace(fault_model="crash_recover",
                                       recovery="at:2:3"))
    r2 = serve_bucket_key(base.replace(fault_model="crash_recover",
                                       recovery="at:2:5"))
    assert r1 != r2                     # churn schedules bucket apart


def test_serve_end_to_end_faultlab_jobs_bit_equal_run_point():
    """Faultlab jobs through the REAL batcher equal the oracle — the
    serve house rule extended to the new planes."""
    from benor_tpu.serve.batcher import Batcher

    b = Batcher(start=False)
    try:
        docs = [
            {"n_nodes": 32, "n_faulty": 8, "trials": 4, "max_rounds": 16,
             "seed": 6, "drop_prob": 0.05},
            {"n_nodes": 32, "n_faulty": 4, "trials": 4, "max_rounds": 16,
             "seed": 6, "fault_model": "crash_recover",
             "recovery": "stagger:2:3:amnesia"},
        ]
        for doc in docs:
            jobs = b.submit_dict(doc)
            assert b.step() >= 1
            job = jobs[0]
            assert job.state == "done", job.error
            pt = run_point(job.cfg)
            assert job.result["mean_k"] == pt.mean_k
            assert job.result["decided_frac"] == pt.decided_frac
            assert job.result["k_hist"] == pt.k_hist.tolist()
    finally:
        b.close()


# --------------------------------------------------------------------------
# the faults manifest checker: tamper matrix
# --------------------------------------------------------------------------


def _good_faults_blob():
    from benor_tpu.faults.report import faults_manifest

    identity = {"bit_equal": True, "extra_compiles": 0}
    curves = {
        "drop_curve": [
            {"drop_prob": 0.02, "n_nodes": 64, "n_faulty": 16,
             "trials": 8, "mean_k": 2.5, "decided_frac": 1.0,
             "rounds_executed": 4},
            {"drop_prob": 0.1, "n_nodes": 64, "n_faulty": 16,
             "trials": 8, "mean_k": 3.5, "decided_frac": 1.0,
             "rounds_executed": 6},
        ],
        "drop_compile_count": 1, "drop_buckets": 1,
        "churn_curve": [
            {"down_rounds": 3, "recovery": "stagger:2:3", "n_nodes": 64,
             "n_faulty": 8, "trials": 8, "mean_k": 4.0,
             "decided_frac": 1.0, "rounds_executed": 8},
        ],
        "churn_compile_count": 1,
    }
    audits = {"crash_recover": {"ok": True, "checks": 10,
                                "violations": 0}}
    return faults_manifest(identity, curves, audits)


def test_check_faults_manifest_tamper_matrix():
    import os, sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import check_metrics_schema as cms
    finally:
        sys.path.pop(0)

    assert cms.check_faults_manifest(_good_faults_blob()) == []

    blob = _good_faults_blob()
    blob["ok"] = False                      # contradicts its parts
    assert any("contradicts" in e
               for e in cms.check_faults_manifest(blob))

    blob = _good_faults_blob()
    blob["drop_curve"][1]["drop_prob"] = 0.3    # >= F/N stall threshold
    assert any("stall threshold" in e
               for e in cms.check_faults_manifest(blob))

    blob = _good_faults_blob()
    blob["drop_curve"].reverse()
    assert any("not sorted" in e
               for e in cms.check_faults_manifest(blob))

    blob = _good_faults_blob()
    blob["drop_compile_count"] = 2
    assert any("one-bucket" in e
               for e in cms.check_faults_manifest(blob))

    blob = _good_faults_blob()
    blob["churn_curve"][0]["down_rounds"] = 5   # != the parsed spec
    assert any("down length" in e
               for e in cms.check_faults_manifest(blob))

    blob = _good_faults_blob()
    blob["churn_curve"][0]["recovery"] = "sometimes:1:2"
    assert any("unparseable" in e
               for e in cms.check_faults_manifest(blob))

    blob = _good_faults_blob()
    blob["audits"]["crash_recover"]["violations"] = 2
    assert any("claims ok" in e
               for e in cms.check_faults_manifest(blob))

    degraded = {"ok": True, "error": "boom"}
    assert any("carries an 'error'" in e
               for e in cms.check_faults_manifest(degraded))
