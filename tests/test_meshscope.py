"""meshscope (ISSUE 6): live runtime & multichip scaling observatory.

Acceptance contract:
  * meshscope off is bit-identical in results AND compile counts for
    the sharded, multihost, sliced and batched regimes (the heartbeat
    knob is host-side only; pinned via utils/compile_counter);
  * `python -m benor_tpu scale` emits a schema-valid scaling manifest
    with per-shape throughput, efficiency and straggler ratio; the
    committed SCALING_BASELINE.json passes the gate (exit 0) and an
    injected 2x step-time straggler fixture both trips the imbalance
    detector and drives the gate to exit 2;
  * `watch` tails a live heartbeat file end-to-end.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from benor_tpu.config import SimConfig
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import balanced_inputs
from benor_tpu.utils.compile_counter import count_backend_compiles
from benor_tpu.utils.metrics import REGISTRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
BASELINE = os.path.join(REPO, "SCALING_BASELINE.json")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"_tool_{name}", os.path.join(TOOLS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _livelock_cfg(**kw):
    """Private-coin count-controlling adversary: forced ties livelock
    every trial to the round cap — deterministic multi-round work, so
    heartbeats genuinely fire and bit-identity pins aren't vacuous."""
    base = dict(n_nodes=24, n_faulty=4, trials=8, delivery="quorum",
                scheduler="adversarial", coin_mode="private",
                path="histogram", max_rounds=8, seed=3)
    base.update(kw)
    return SimConfig(**base)


def _inputs(cfg):
    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes),
                       faults)
    return state, faults, jax.random.key(cfg.seed)


def _assert_state_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.x), np.asarray(b.x))
    np.testing.assert_array_equal(np.asarray(a.decided),
                                  np.asarray(b.decided))
    np.testing.assert_array_equal(np.asarray(a.k), np.asarray(b.k))
    np.testing.assert_array_equal(np.asarray(a.killed),
                                  np.asarray(b.killed))


# --------------------------------------------------------------------------
# Off-path bit-identity + compile counts, per regime
# --------------------------------------------------------------------------


def test_heartbeat_off_on_bit_identical_sharded():
    """Sharded regime: heartbeat on publishes (gauges move) but results,
    recorder AND compile counts match the off run exactly."""
    from benor_tpu.parallel import make_mesh
    from benor_tpu.parallel.sharded import run_consensus_slice_sharded
    from benor_tpu.sim import start_state

    mesh = make_mesh(2, 2)
    outs, compiles = {}, {}
    for hb in (0, 2):
        cfg = _livelock_cfg(record=True, heartbeat_rounds=hb)
        state, faults, key = _inputs(cfg)
        st = start_state(cfg, state)
        args = (cfg, st, faults, key, mesh, 1, cfg.max_rounds + 2)
        before = REGISTRY.counter("heartbeat.published").value
        int(run_consensus_slice_sharded(*args)[0])        # warm-up
        with count_backend_compiles() as cc:
            out = run_consensus_slice_sharded(*args)
            int(out[0])
        outs[hb] = out
        compiles[hb] = cc.count
        if hb:
            assert REGISTRY.counter("heartbeat.published").value > before
    assert int(outs[0][0]) == int(outs[2][0])
    _assert_state_equal(outs[0][1], outs[2][1])
    np.testing.assert_array_equal(np.asarray(outs[0][2]),
                                  np.asarray(outs[2][2]))
    # steady state: publishing compiles NOTHING — both paths hit the
    # jit cache identically
    assert compiles[0] == compiles[2] == 0


def test_heartbeat_off_on_bit_identical_multihost():
    """Multihost slice wrapper (single-process (1, 2) mesh — the same
    compiled executable a pod run uses): heartbeat on/off bit-identical
    in results and compile counts."""
    from benor_tpu.parallel import make_mesh
    from benor_tpu.parallel.multihost import run_consensus_slice_multihost
    from benor_tpu.parallel.sharded import shard_inputs
    from benor_tpu.sim import start_state

    mesh = make_mesh(1, 2)
    outs, compiles = {}, {}
    for hb in (0, 3):
        cfg = _livelock_cfg(record=True, heartbeat_rounds=hb)
        state, faults, key = _inputs(cfg)
        st, fl = shard_inputs(start_state(cfg, state), faults, mesh)
        args = (cfg, st, fl, key, mesh, 1, cfg.max_rounds + 2)
        int(run_consensus_slice_multihost(*args)[0])      # warm-up
        with count_backend_compiles() as cc:
            out = run_consensus_slice_multihost(*args)
            int(out[0])
        outs[hb] = out
        compiles[hb] = cc.count
    assert int(outs[0][0]) == int(outs[3][0])
    _assert_state_equal(outs[0][1], outs[3][1])
    np.testing.assert_array_equal(np.asarray(outs[0][2]),
                                  np.asarray(outs[3][2]))
    assert compiles[0] == compiles[3] == 0


def test_heartbeat_off_on_bit_identical_sliced_network(tmp_path):
    """Sliced regime (TpuNetwork poll loop): heartbeat on writes the
    JSON-lines plane and closes with done=true, while final state,
    rounds and compile counts match the off run."""
    from benor_tpu.api import launch_network
    from benor_tpu.meshscope.heartbeat import read_heartbeats

    n, f = 10, 5
    vals = [1, 1, 0, 0, 1, 1, 0, 0, 1, 1]
    faulty = [True] * f + [False] * (n - f)
    nets, compiles = {}, {}
    hb_path = str(tmp_path / "hb.jsonl")
    for hb in (0, 2):
        def mk():
            return launch_network(n, f, vals, faulty, backend="tpu",
                                  seed=0, delivery="quorum",
                                  max_rounds=12, poll_rounds=2,
                                  record=True, heartbeat_rounds=hb)
        mk().start()                  # warm-up: compile the slice
        net = mk()
        if hb:
            net.heartbeat_path = hb_path
        with count_backend_compiles() as cc:
            net.start()
        nets[hb] = net
        compiles[hb] = cc.count
    assert nets[0].rounds_executed == nets[2].rounds_executed
    assert nets[0].get_states() == nets[2].get_states()
    assert nets[0].get_round_history() == nets[2].get_round_history()
    assert compiles[0] == compiles[2] == 0
    beats = read_heartbeats(hb_path)
    assert beats and beats[-1]["done"] is True
    assert beats[-1]["round"] == nets[2].rounds_executed
    # the livelock never decides: the recorder-derived fraction says so
    assert beats[-1]["decided_frac"] == 0.0
    assert any(b["rounds_per_sec"] is not None for b in beats)


def test_one_shot_network_heartbeat_publishes_final_beat(tmp_path):
    """poll_rounds=0 (one-shot run_consensus) has no slice boundaries,
    but an armed heartbeat must not be a silent no-op — `watch` would
    block on an empty file forever.  The run publishes its one honest
    record: the final state, done=true."""
    from benor_tpu.api import launch_network
    from benor_tpu.meshscope.heartbeat import read_heartbeats

    n, f = 10, 5
    vals = [1, 1, 0, 0, 1, 1, 0, 0, 1, 1]
    faulty = [True] * f + [False] * (n - f)
    hb_path = str(tmp_path / "hb.jsonl")
    net = launch_network(n, f, vals, faulty, backend="tpu", seed=0,
                         delivery="quorum", max_rounds=12,
                         poll_rounds=0, record=True, heartbeat_rounds=2)
    net.heartbeat_path = hb_path
    net.start()
    beats = read_heartbeats(hb_path)
    assert len(beats) == 1
    assert beats[0]["done"] is True
    assert beats[0]["round"] == net.rounds_executed


def test_sharded_network_heartbeat_not_double_published(tmp_path):
    """TpuNetwork.start on a mesh runs its OWN publisher (it owns the
    file plane); the sharded slice wrapper must not publish the same
    beat a second time into the shared heartbeat.* gauges — every
    registry publish has exactly one JSON-lines record."""
    from benor_tpu.api import launch_network
    from benor_tpu.meshscope.heartbeat import read_heartbeats

    n, f = 10, 5
    vals = [1, 1, 0, 0, 1, 1, 0, 0, 1, 1]
    faulty = [True] * f + [False] * (n - f)
    hb_path = str(tmp_path / "hb.jsonl")
    net = launch_network(n, f, vals, faulty, backend="tpu", seed=0,
                         delivery="quorum", max_rounds=12,
                         poll_rounds=2, record=True, heartbeat_rounds=2,
                         mesh_shape=(1, 2))
    net.heartbeat_path = hb_path
    before = REGISTRY.counter("heartbeat.published").value
    net.start()
    published = REGISTRY.counter("heartbeat.published").value - before
    beats = read_heartbeats(hb_path)
    assert beats and beats[-1]["done"] is True
    assert published == len(beats)


def test_heartbeat_off_on_bit_identical_batched_sweep():
    """Batched dynamic-F sweep: per-bucket heartbeats (progress plane)
    leave every point summary and the compile count untouched."""
    from benor_tpu.sweep import run_curve_batched

    f_values = [2, 4]
    curves, compiles = {}, {}
    for hb in (0, 2):
        cfg = _livelock_cfg(heartbeat_rounds=hb)
        before = REGISTRY.counter("heartbeat.published").value
        cb = run_curve_batched(cfg, f_values)
        curves[hb] = cb
        compiles[hb] = cb.compile_count
        if hb:
            assert REGISTRY.counter("heartbeat.published").value > before
            assert REGISTRY.gauge("heartbeat.progress").value == 1.0
    for p0, p1 in zip(curves[0].points, curves[2].points):
        d0, d1 = p0.to_dict(), p1.to_dict()
        for volatile in ("seconds", "trials_per_sec"):
            d0.pop(volatile), d1.pop(volatile)
        assert d0 == d1
    assert compiles[0] == compiles[2]


# --------------------------------------------------------------------------
# Telemetry: collective attribution, memory, stragglers, shard tracks
# --------------------------------------------------------------------------


def test_collective_bytes_derive_from_layout_tables():
    from benor_tpu.meshscope import collective_bytes
    from benor_tpu.ops.pallas_round import PARTIAL_COLS
    from benor_tpu.state import REC_WIDTH, WIT_WIDTH

    cfg = _livelock_cfg(record=True, witness_trials=(0, 1),
                        witness_nodes=4)
    fam = collective_bytes(cfg)
    assert fam["recorder_psum"] == REC_WIDTH * 4
    assert fam["witness_psum"] == 2 * 4 * WIT_WIDTH * 4
    assert fam["tally_psum"] == 2 * cfg.trials * 3 * 4
    assert fam["total"] == sum(v for k, v in fam.items() if k != "total")
    assert REGISTRY.gauge(
        "meshscope.collective.recorder_psum_bytes").value == REC_WIDTH * 4

    # dense path swaps the psum family for the all-gather family
    dense = collective_bytes(_livelock_cfg(path="dense"))
    assert "tally_allgather" in dense and "tally_psum" not in dense

    # the fused round's only traffic is the partial-column rows
    fused = SimConfig(n_nodes=128, n_faulty=26, trials=4,
                      delivery="quorum", scheduler="adversarial",
                      coin_mode="common", path="histogram",
                      use_pallas_round=True, record=True, max_rounds=8)
    from benor_tpu.ops.tally import pallas_round_active
    assert pallas_round_active(fused)
    fp = collective_bytes(fused)
    assert fp["pallas_partials"] == 2 * 4 * PARTIAL_COLS * 4
    assert "recorder_psum" not in fp      # rides the partial columns


def test_straggler_detector_trips_on_2x_step_time():
    from benor_tpu.meshscope import STRAGGLER_TRIP, detect_stragglers

    before = REGISTRY.counter("meshscope.straggler_detected").value
    ok = detect_stragglers([1.0, 1.0, 1.0, 1.1])
    assert not ok.tripped and ok.stragglers == []
    assert REGISTRY.counter("meshscope.straggler_detected").value == before

    # the acceptance fixture: one shard at 2x the median step time
    bad = detect_stragglers([1.0, 1.0, 1.0, 2.0])
    assert bad.tripped and bad.ratio == pytest.approx(2.0)
    assert bad.stragglers == [3]
    assert bad.ratio >= STRAGGLER_TRIP
    assert REGISTRY.counter(
        "meshscope.straggler_detected").value == before + 1
    assert REGISTRY.gauge(
        "meshscope.straggler_ratio").value == pytest.approx(2.0)


def test_device_memory_watermarks_and_probe():
    from benor_tpu.meshscope import probe_shard_step_times, \
        sample_device_memory
    from benor_tpu.parallel import make_mesh

    keep = jnp.ones((64, 64), jnp.float32) + 0    # a live buffer to see
    rows = sample_device_memory()
    assert len(rows) == len(jax.local_devices())
    assert any(r["live_bytes"] > 0 for r in rows)
    assert REGISTRY.gauge("meshscope.mem.live_bytes.d0").value >= 0
    del keep

    mesh = make_mesh(1, 4)
    times = probe_shard_step_times(mesh=mesh, reps=2, size=64)
    assert len(times) == 4 and all(t > 0 for t in times)


def test_export_shard_trace_renders_per_shard_tracks(tmp_path):
    from benor_tpu.meshscope import export_shard_trace

    path = str(tmp_path / "shards.trace.json")
    n = export_shard_trace(path, [[0.1, 0.1], [0.2, 0.2]])
    assert n == 4
    doc = json.load(open(path))
    tids = {e["tid"] for e in doc["traceEvents"]}
    assert tids == {"shard 0", "shard 1"}
    slow = [e for e in doc["traceEvents"] if e["tid"] == "shard 1"]
    assert all(e["dur"] == pytest.approx(0.2e6) for e in slow)


# --------------------------------------------------------------------------
# Scaling ladder, manifest schema, gate exit codes
# --------------------------------------------------------------------------


def _small_ladder():
    from benor_tpu.meshscope import (build_scaling_manifest,
                                     run_scaling_ladder)
    rows, scale = run_scaling_ladder([1, 2], n_nodes=64, trials=4,
                                     max_rounds=4, reps=1)
    return build_scaling_manifest(rows, "weak", "nodes", scale)


def test_scaling_ladder_manifest_schema_valid():
    cms = _load_tool("check_metrics_schema")
    manifest = _small_ladder()
    assert cms.check_scaling_manifest(manifest) == []
    rows = manifest["rows"]
    assert [r["devices"] for r in rows] == [1, 2]
    assert rows[0]["efficiency"] == 1.0
    # weak mode: the node axis grew with the rung; the livelock shape
    # makes the round count the full cap on every rung
    assert rows[1]["n_nodes"] == 2 * rows[0]["n_nodes"]
    assert all(r["rounds"] == 4 for r in rows)
    assert all(r["node_rounds_per_sec"] > 0 for r in rows)
    assert all(len(r["shard_probe_s"]) == r["devices"] for r in rows)


def test_scaling_manifest_cross_field_validation():
    cms = _load_tool("check_metrics_schema")
    manifest = _small_ladder()
    tampered = json.loads(json.dumps(manifest))
    tampered["rows"][1]["efficiency"] = 0.123456
    errs = cms.check_scaling_manifest(tampered)
    assert any("throughput ratio" in e for e in errs)

    no_anchor = json.loads(json.dumps(manifest))
    no_anchor["rows"] = [r for r in no_anchor["rows"]
                         if r["devices"] != 1]
    errs = cms.check_scaling_manifest(no_anchor)
    assert any("1-device rung" in e for e in errs)

    bad_mesh = json.loads(json.dumps(manifest))
    bad_mesh["rows"][1]["mesh_shape"] = [1, 3]
    errs = cms.check_scaling_manifest(bad_mesh)
    assert any("mesh_shape" in e for e in errs)


def test_scale_cli_emits_schema_valid_manifest(tmp_path):
    """`python -m benor_tpu scale --mesh 1,2 --profile-out ...` on CPU:
    the acceptance surface, end to end in-process."""
    from benor_tpu.__main__ import main

    out = str(tmp_path / "scaling.json")
    rc = main(["scale", "--mesh", "1,2", "--n", "64", "--trials", "4",
               "--max-rounds", "4", "--reps", "1",
               "--profile-out", out,
               "--baseline", str(tmp_path / "missing.json")])
    assert rc == 0
    manifest = json.load(open(out))
    assert manifest["kind"] == "scaling_manifest"
    cms = _load_tool("check_metrics_schema")
    assert cms.check_scaling_manifest(manifest) == []
    assert {r["devices"] for r in manifest["rows"]} == {1, 2}


def test_committed_baseline_passes_gate_and_straggler_fixture_exits_2(
        tmp_path):
    """Acceptance: SCALING_BASELINE.json passes the gate (exit 0); an
    injected 2x step-time straggler drives it to exit 2; a different
    platform is refused with exit 3.  Runs the real tool as a
    subprocess — the no-jax stdlib path CI takes."""
    assert os.path.exists(BASELINE)
    tool = os.path.join(TOOLS, "check_scaling_regression.py")
    r = subprocess.run([sys.executable, tool, BASELINE],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr

    fixture = json.load(open(BASELINE))
    fixture["rows"][-1]["straggler_ratio"] = 2.0
    fx_path = str(tmp_path / "straggler.json")
    json.dump(fixture, open(fx_path, "w"))
    r = subprocess.run([sys.executable, tool, fx_path],
                       capture_output=True, text=True)
    assert r.returncode == 2
    assert "straggler_ratio" in r.stdout

    other = json.load(open(BASELINE))
    other["platform"] = "tpu"
    ot_path = str(tmp_path / "other.json")
    json.dump(other, open(ot_path, "w"))
    r = subprocess.run([sys.executable, tool, ot_path],
                       capture_output=True, text=True)
    assert r.returncode == 3


def test_scalegate_efficiency_collapse_rules():
    from benor_tpu.meshscope import compare_scaling

    base = json.load(open(BASELINE))
    assert compare_scaling(base, base) == []

    # efficiency under the band
    worse = json.loads(json.dumps(base))
    worse["rows"][1]["efficiency"] = base["rows"][1]["efficiency"] * 0.5
    findings = compare_scaling(worse, base)
    assert any(f.metric == "efficiency" for f in findings)

    # missing/zero efficiency = the worst collapse
    zero = json.loads(json.dumps(base))
    zero["rows"][1]["efficiency"] = 0.0
    findings = compare_scaling(zero, base)
    assert any("worst possible collapse" in f.message for f in findings)

    # a vanished rung is a finding on its own
    gone = json.loads(json.dumps(base))
    gone["rows"] = gone["rows"][:-1]
    findings = compare_scaling(gone, base)
    assert any(f.metric == "row" for f in findings)

    # the straggler trip is ABSOLUTE: it fires even on a manifest rung
    # the baseline never captured (`scale --mesh 1,2,4` vs a d=1,2
    # baseline must not silently skip the d=4 health check)
    wider = json.loads(json.dumps(base))
    extra = dict(wider["rows"][-1])
    extra["devices"] *= 2
    extra["straggler_ratio"] = 2.0
    wider["rows"].append(extra)
    findings = compare_scaling(wider, base)
    assert [f.metric for f in findings] == ["straggler_ratio"]
    assert findings[0].devices == extra["devices"]


# --------------------------------------------------------------------------
# Satellite: the MULTICHIP_r*.json trajectory walk
# --------------------------------------------------------------------------


def test_multichip_trajectory_missing_or_zero_is_worst_collapse(tmp_path):
    from benor_tpu.perfscope.baseline import check_multichip_trajectory

    def rec(name, **kw):
        path = str(tmp_path / name)
        json.dump(kw, open(path, "w"))
        return path

    paths = [
        rec("MULTICHIP_r01.json", n_devices=8, ok=False, rc=124),
        rec("MULTICHIP_r02.json", n_devices=8, ok=True,
            scaling_efficiency=0.9),
        rec("MULTICHIP_r03.json", n_devices=8, ok=True),    # missing
        rec("MULTICHIP_r04.json", n_devices=8, ok=True,
            scaling_efficiency=0.0),                        # zero
        rec("MULTICHIP_r05.json", n_devices=4, ok=True),    # other key
    ]
    findings = check_multichip_trajectory(paths)
    regressions = [f for f in findings if f.startswith("REGRESSION")]
    # r03 (missing) and r04 (zero) both collapse vs r02's 0.9; r05 has
    # no same-device-count bar so it only notes
    assert len(regressions) == 2
    assert "r03" in regressions[0] and "r04" in regressions[1]
    assert any("treated as 0.0" in f for f in findings)
    assert any("skipped/failed" in f for f in findings)

    # the committed repo records predate the metric: notes only, no
    # regression (nothing ever set an efficiency bar)
    import glob
    committed = sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    assert committed
    assert not any(f.startswith("REGRESSION")
                   for f in check_multichip_trajectory(committed))


# --------------------------------------------------------------------------
# Heartbeat plane + watch CLI
# --------------------------------------------------------------------------


def test_heartbeat_publisher_records_and_gauges(tmp_path):
    from benor_tpu.meshscope import HeartbeatPublisher, read_heartbeats

    cfg = _livelock_cfg(heartbeat_rounds=1)
    path = str(tmp_path / "hb.jsonl")
    pub = HeartbeatPublisher(cfg, path=path, label="t")
    pub.publish(2, decided_frac=0.25)
    time.sleep(0.01)
    pub.publish(4, decided_frac=0.5)
    pub.close(8)
    recs = read_heartbeats(path)
    assert [r["round"] for r in recs] == [2, 4, 8]
    assert recs[1]["rounds_per_sec"] > 0
    assert recs[1]["eta_s"] is not None and recs[1]["eta_s"] >= 0
    assert recs[-1]["done"] is True and recs[-1]["progress"] == 1.0
    assert REGISTRY.gauge("heartbeat.round").value == 8.0
    for r in recs:
        assert r["kind"] == "heartbeat" and "ts" in r


def test_slice_publisher_resets_between_runs():
    """The per-label slice publisher is only reused when a slice picks
    up exactly where the previous one stopped; a NEW run (from_round=1)
    gets fresh rate state even when its boundary round is past the old
    run's — otherwise its first beat's rounds/sec would span the idle
    and compile gap between the two runs."""
    from benor_tpu.meshscope import heartbeat as hb

    cfg = _livelock_cfg(heartbeat_rounds=2)
    label = "test.slice.reset"
    hb.publish_slice_heartbeat(cfg, 5, label=label, from_round=1)
    pub1 = hb._SLICE_PUBS[label][0]
    # continuation: next slice of the same run keeps the publisher
    hb.publish_slice_heartbeat(cfg, 9, label=label, from_round=5)
    assert hb._SLICE_PUBS[label][0] is pub1
    # fresh run whose first boundary lands PAST the old cursor: the
    # from_round=1 restart is the only signal a new run began
    hb.publish_slice_heartbeat(cfg, 11, label=label, from_round=1)
    assert hb._SLICE_PUBS[label][0] is not pub1


def test_watch_cli_tails_live_heartbeat_end_to_end(tmp_path, capsys):
    """A writer thread appends beats while `watch` tails the file — the
    full live-progress loop, two actors, one file."""
    from benor_tpu.__main__ import main
    from benor_tpu.meshscope import HeartbeatPublisher

    cfg = _livelock_cfg(heartbeat_rounds=1)
    path = str(tmp_path / "hb.jsonl")

    def writer():
        pub = HeartbeatPublisher(cfg, path=path, label="sweep")
        for r in (2, 4, 6):
            pub.publish(r, decided_frac=r / 8)
            time.sleep(0.05)
        pub.close(8)

    t = threading.Thread(target=writer)
    t.start()
    try:
        rc = main(["watch", path, "--poll", "0.02", "--timeout", "20"])
    finally:
        t.join()
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 4
    assert "round=2/8" in lines[0]
    assert lines[-1].endswith("DONE")


def test_watch_cli_times_out_on_silent_file(tmp_path, capsys):
    from benor_tpu.__main__ import main

    path = str(tmp_path / "empty.jsonl")
    open(path, "w").close()
    rc = main(["watch", path, "--poll", "0.02", "--timeout", "0.1"])
    assert rc == 1
