"""Test environment: force an 8-device virtual CPU mesh BEFORE jax imports.

Benches run on the real TPU chip; tests run on CPU with 8 virtual devices so
the multi-chip sharding paths (parallel/) are exercised without hardware.
"""

import os

# HARD set (not setdefault): the ambient environment ships
# JAX_PLATFORMS=axon, and the CLI's _honor_platform_env re-asserts the env
# value — a setdefault would let an isolated CLI test re-select the axon
# backend and hang on an unreachable chip (test runs must never need TPU).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin overrides JAX_PLATFORMS at import; the config update
# below wins regardless, so tests really run on the 8-device virtual CPU.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE: the persistent compile cache is deliberately NOT enabled here.
# XLA:CPU cache entries are machine-profile AOT artifacts and their
# (de)serializer segfaulted three consecutive full-suite runs on a
# migrated workspace (2026-07-31) — benor_tpu/utils/cache.py no-ops on
# the CPU backend for exactly this reason, and calling it here would
# just document a false dependency.  The accelerator paths (bench,
# recapture, CLI on TPU) still use .jax_cache/.
