"""benorlint (benor_tpu/analysis) — the static analyzer's own tests.

Three layers, mirroring the analyzer's contract:

  * FIXTURE tests: one seeded violation per rule in a synthetic package
    tree, asserting the rule fires with the right file:line (including
    an overlapping-column layout and a SimConfig field missing from the
    sharded regime).
  * MUTATION tests: copies of the REAL state.py / ops/pallas_round.py /
    sharded.py with one layout column removed (every recorder column,
    every witness field) or one config reference dropped — proving the
    acceptance property that any single hand-edit of the kind PR 2/3
    made by hand now fails the linter.
  * SELF-CHECK: the shipped benor_tpu/ tree lints CLEAN (exit 0 via the
    CLI), with exactly the documented pragma suppressions counted.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

import benor_tpu
from benor_tpu.analysis import Project, run_lint, run_rules
from benor_tpu.analysis.cli import main as lint_main

PKG_DIR = os.path.dirname(os.path.abspath(benor_tpu.__file__))
REPO = os.path.dirname(PKG_DIR)
sys.path.insert(0, os.path.join(REPO, "tools"))
import check_metrics_schema  # noqa: E402


def _write_pkg(tmp_path, files: dict) -> str:
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(root)


def _findings(root, rules=None):
    active, suppressed = run_rules(Project(root), names=rules)
    return active, suppressed


def _line_of(src: str, marker: str) -> int:
    for i, line in enumerate(textwrap.dedent(src).splitlines(), start=1):
        if marker in line:
            return i
    raise AssertionError(f"marker {marker!r} not in fixture")


# --------------------------------------------------------------------------
# fixture tests: one seeded violation per tracer rule, with file:line
# --------------------------------------------------------------------------


HOST_SYNC_SRC = """\
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np


    @functools.partial(jax.jit, static_argnums=0)
    def round_loop(cfg, state):
        n = jnp.sum(state).item()      # MARK-item
        host = np.asarray(state)       # MARK-asarray
        return n + int(state)          # MARK-int
"""


def test_host_sync_fixture(tmp_path):
    root = _write_pkg(tmp_path, {"mod.py": HOST_SYNC_SRC})
    active, _ = _findings(root, rules=["host-sync"])
    got = {(f.path, f.line) for f in active}
    assert ("mod.py", _line_of(HOST_SYNC_SRC, "MARK-item")) in got
    assert ("mod.py", _line_of(HOST_SYNC_SRC, "MARK-asarray")) in got
    assert ("mod.py", _line_of(HOST_SYNC_SRC, "MARK-int")) in got
    assert all(f.rule == "host-sync" for f in active)


def test_host_sync_only_fires_in_traced_functions(tmp_path):
    # the SAME .item() in plain harness code is a completion barrier,
    # not a bug — reachability is what makes the rule usable
    root = _write_pkg(tmp_path, {"mod.py": """\
        import numpy as np

        def harness(out):
            return int(out[0]), np.asarray(out[1]).item()
    """})
    active, _ = _findings(root, rules=["host-sync"])
    assert active == []


HOST_RNG_SRC = """\
    import numpy as np

    def inputs(trials, n):
        return np.random.default_rng(0).integers(   # MARK-rng
            0, 2, size=(trials, n))
"""


def test_host_rng_fixture(tmp_path):
    root = _write_pkg(tmp_path, {"gen.py": HOST_RNG_SRC})
    active, _ = _findings(root, rules=["host-rng"])
    assert [(f.path, f.line) for f in active] == \
        [("gen.py", _line_of(HOST_RNG_SRC, "MARK-rng"))]


TRACED_BRANCH_SRC = """\
    import jax
    import jax.numpy as jnp


    @jax.jit
    def step(x):
        if jnp.any(x > 0):             # MARK-if
            x = x - 1
        while jnp.sum(x) > 0:          # MARK-while
            x = x - 1
        if x.shape[0] > 2:             # static shape branch: fine
            x = x + 0
        return x
"""


def test_traced_branch_fixture(tmp_path):
    root = _write_pkg(tmp_path, {"mod.py": TRACED_BRANCH_SRC})
    active, _ = _findings(root, rules=["traced-branch"])
    got = sorted((f.path, f.line) for f in active)
    assert got == [
        ("mod.py", _line_of(TRACED_BRANCH_SRC, "MARK-if")),
        ("mod.py", _line_of(TRACED_BRANCH_SRC, "MARK-while")),
    ]


DTYPE_SRC = """\
    import jax
    import jax.numpy as jnp


    @jax.jit
    def widen(x):
        return x.astype(jnp.int64)     # MARK-64
"""


def test_dtype_drift_fixture(tmp_path):
    root = _write_pkg(tmp_path, {"mod.py": DTYPE_SRC})
    active, _ = _findings(root, rules=["dtype-drift"])
    assert [(f.path, f.line) for f in active] == \
        [("mod.py", _line_of(DTYPE_SRC, "MARK-64"))]


DONATE_SRC = """\
    import functools

    import jax


    @functools.partial(jax.jit, static_argnums=0)   # MARK-jit
    def run(cfg, state):
        return state


    @functools.partial(jax.jit, static_argnums=0,
                       donate_argnums=(1,))
    def run_donated(cfg, state):
        return state
"""


def test_donate_argnums_fixture(tmp_path):
    root = _write_pkg(tmp_path, {"mod.py": DONATE_SRC})
    active, _ = _findings(root, rules=["donate-argnums"])
    assert [(f.path, f.line) for f in active] == \
        [("mod.py", _line_of(DONATE_SRC, "MARK-jit"))]


RNG_FOLD_SRC = """\
    import jax


    @jax.jit
    def draws(base_key, trial, node, n):
        k = jax.random.fold_in(base_key, trial * n + node)   # MARK-flat
        u = jax.random.uniform(base_key)                     # MARK-raw
        return k, u
"""


def test_rng_fold_fixture(tmp_path):
    root = _write_pkg(tmp_path, {"mod.py": RNG_FOLD_SRC})
    active, _ = _findings(root, rules=["rng-fold"])
    got = sorted((f.path, f.line) for f in active)
    assert got == [
        ("mod.py", _line_of(RNG_FOLD_SRC, "MARK-flat")),
        ("mod.py", _line_of(RNG_FOLD_SRC, "MARK-raw")),
    ]


BROAD_EXCEPT_SRC = """\
    def best_effort():
        try:
            return 1
        except Exception:              # MARK-broad
            return None
"""


def test_broad_except_fixture(tmp_path):
    root = _write_pkg(tmp_path, {"mod.py": BROAD_EXCEPT_SRC})
    active, _ = _findings(root, rules=["broad-except"])
    assert [(f.path, f.line) for f in active] == \
        [("mod.py", _line_of(BROAD_EXCEPT_SRC, "MARK-broad"))]


def test_nested_traced_def_reports_once(tmp_path):
    # nested defs are walked under their own FuncInfo AND the parent's;
    # run_rules dedups so one violation is one finding (and one pragma
    # suppression counts once)
    root = _write_pkg(tmp_path, {"mod.py": """\
        import jax
        import jax.numpy as jnp


        @jax.jit
        def outer(x):
            def body(y):
                return jnp.sum(y).item()
            return body(x)
    """})
    active, _ = _findings(root, rules=["host-sync"])
    assert len(active) == 1


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    broken_root = _write_pkg(tmp_path, {"broken.py": "def f(:\n"})
    active, _ = _findings(broken_root)
    assert [f.rule for f in active] == ["parse-error"]
    assert active[0].path == "broken.py"

    class Args:
        root = broken_root
        format = "json"
        out = None
        metrics_out = None

    assert lint_main(Args()) == 2       # the 0/2 contract holds


def test_pragma_suppresses_and_is_counted(tmp_path):
    root = _write_pkg(tmp_path, {"gen.py": """\
        import numpy as np

        def inputs(n):
            # benorlint: allow-host-rng — seeded host-side input generation
            return np.random.default_rng(0).integers(0, 2, size=n)
    """})
    active, suppressed = _findings(root, rules=["host-rng"])
    assert active == []
    assert suppressed == {"host-rng": 1}


# --------------------------------------------------------------------------
# layout rules: fixtures + mutations of the REAL tables
# --------------------------------------------------------------------------


def _layout_tree(tmp_path) -> str:
    """A minimal package tree holding the real layout-bearing files."""
    root = tmp_path / "pkg"
    (root / "ops").mkdir(parents=True)
    for rel in ("state.py", "config.py"):
        shutil.copy(os.path.join(PKG_DIR, rel), root / rel)
    shutil.copy(os.path.join(PKG_DIR, "ops", "pallas_round.py"),
                root / "ops" / "pallas_round.py")
    return str(root)


_LAYOUT_RULES = ["layout-overlap", "layout-parity", "layout-outspec"]


def _edit(root, rel, old, new, count=None):
    p = os.path.join(root, rel)
    with open(p) as fh:
        text = fh.read()
    assert old in text, f"{old!r} not found in {rel}"
    with open(p, "w") as fh:
        fh.write(text.replace(old, new) if count is None
                 else text.replace(old, new, count))


def test_layout_rules_clean_on_shipped_tables(tmp_path):
    root = _layout_tree(tmp_path)
    active, _ = _findings(root, rules=_LAYOUT_RULES)
    assert active == []


def test_layout_overlap_fixture(tmp_path):
    # the seeded violation the issue asks for: two recorder partials on
    # the same kernel column
    root = _layout_tree(tmp_path)
    _edit(root, "ops/pallas_round.py",
          '"killed": (6, 1),', '"killed": (5, 1),')
    active, _ = _findings(root, rules=["layout-overlap"])
    assert any(f.rule == "layout-overlap"
               and f.path == "ops/pallas_round.py"
               and "overlaps" in f.message for f in active)


@pytest.mark.parametrize("column", ["decided", "killed", "undecided_0",
                                    "undecided_1", "undecided_q",
                                    "coin_flips", "tally_margin"])
def test_removing_any_recorder_column_fails(tmp_path, column):
    # acceptance: removing any single _RP_-era column from
    # VOTE_RECORD_LAYOUT must fail the linter
    root = _layout_tree(tmp_path)
    idx = {"decided": 5, "killed": 6, "undecided_0": 7, "undecided_1": 8,
           "undecided_q": 9, "coin_flips": 10, "tally_margin": 11}[column]
    _edit(root, "ops/pallas_round.py",
          f'    "{column}": ({idx}, 1),\n', "", count=1)
    active, _ = _findings(root, rules=_LAYOUT_RULES)
    assert any(f.rule in ("layout-overlap", "layout-parity")
               for f in active), f"dropping {column} went unnoticed"


@pytest.mark.parametrize("field", ["p0", "p1", "x", "decided", "killed",
                                   "coined", "v0", "v1"])
def test_removing_any_witness_field_fails(tmp_path, field):
    # acceptance: dropping a witness column from either kernel field
    # tuple must fail the linter
    root = _layout_tree(tmp_path)
    old = f', "{field}"' if field in ("p1", "v1") else f'"{field}", '
    _edit(root, "ops/pallas_round.py", old, "", count=1)
    active, _ = _findings(root, rules=["layout-parity"])
    assert any(f.rule == "layout-parity" and field in f.message
               for f in active), \
        f"dropping witness field {field} went unnoticed"


def test_removing_wit_layout_row_fails(tmp_path):
    root = _layout_tree(tmp_path)
    _edit(root, "state.py", '    "v0": (6, 1),', "", count=1)
    active, _ = _findings(root, rules=_LAYOUT_RULES)
    assert any(f.path == "state.py" for f in active)


def test_deleting_a_table_is_itself_a_finding(tmp_path):
    root = _layout_tree(tmp_path)
    _edit(root, "ops/pallas_round.py", "VOTE_RECORD_LAYOUT = {",
          "VOTE_RECORD_LAYOUT_RENAMED = {", count=1)
    active, _ = _findings(root, rules=["layout-overlap"])
    assert any("missing" in f.message for f in active)


def test_layout_outspec_fixture(tmp_path):
    root = _layout_tree(tmp_path)
    _edit(root, "ops/pallas_round.py",
          "return pl.BlockSpec((1, t, PARTIAL_COLS)",
          "return pl.BlockSpec((1, t, 128)", count=1)
    active, _ = _findings(root, rules=["layout-outspec"])
    assert len(active) == 1
    assert active[0].path == "ops/pallas_round.py"
    assert "PARTIAL_COLS" in active[0].hint


def test_witness_budget_pinned_to_partial_cols(tmp_path):
    # config.WITNESS_MAX_NODES is sized so the vote kernel's witness
    # blocks fit PARTIAL_COLS; growing it past the budget must fail
    root = _layout_tree(tmp_path)
    _edit(root, "config.py", "WITNESS_MAX_NODES = 16",
          "WITNESS_MAX_NODES = 32", count=1)
    active, _ = _findings(root, rules=["layout-parity"])
    assert any("PARTIAL_COLS" in f.message for f in active)


# --------------------------------------------------------------------------
# telem-layout: mutations of the REAL TELEM_COLS table (PR 14)
# --------------------------------------------------------------------------


_TELEM_COLUMNS = ["active_lanes", "pad_lanes", "sampler_draws",
                  "hist_visits", "quorum_passes", "coin_draws",
                  "plane_hops"]


def test_telem_layout_clean_on_shipped_table(tmp_path):
    root = _layout_tree(tmp_path)
    active, _ = _findings(root, rules=["telem-layout"])
    assert active == []


@pytest.mark.parametrize("column", _TELEM_COLUMNS)
def test_removing_any_telem_column_fails(tmp_path, column):
    # acceptance: removing ANY single column from TELEM_COLS (including
    # the last, which density alone cannot see — the emission-dict
    # parity catches it) must fail the linter
    root = _layout_tree(tmp_path)
    idx = _TELEM_COLUMNS.index(column)
    _edit(root, "ops/pallas_round.py",
          f'    "{column}": ({idx}, 1),\n', "", count=1)
    active, _ = _findings(root, rules=["telem-layout"])
    assert any(f.rule == "telem-layout" for f in active), \
        f"dropping telemetry column {column} went unnoticed"


def test_telem_overlap_fails(tmp_path):
    root = _layout_tree(tmp_path)
    _edit(root, "ops/pallas_round.py",
          '    "pad_lanes": (1, 1),', '    "pad_lanes": (0, 1),',
          count=1)
    active, _ = _findings(root, rules=["telem-layout"])
    assert any("overlaps" in f.message for f in active)


def test_telem_emission_without_declaration_fails(tmp_path):
    # a column emitted by _telem_cols but missing from the table is
    # "emitted but undeclared" even when the table stays dense
    root = _layout_tree(tmp_path)
    _edit(root, "ops/pallas_round.py",
          '        "plane_hops": jnp.full((t,), hops, jnp.int32),',
          '        "plane_hops": jnp.full((t,), hops, jnp.int32),\n'
          '        "rogue_counter": zeros,', count=1)
    active, _ = _findings(root, rules=["telem-layout"])
    assert any("rogue_counter" in f.message for f in active)


def test_telem_budget_pinned_to_partial_cols(tmp_path):
    # widening the telemetry block past the worst-case witness budget
    # must fail: 108 base+record+witness columns leave only 20
    root = _layout_tree(tmp_path)
    _edit(root, "ops/pallas_round.py",
          '    "plane_hops": (6, 1),', '    "plane_hops": (6, 40),',
          count=1)
    active, _ = _findings(root, rules=["telem-layout"])
    assert any("PARTIAL_COLS" in f.message and "telemetry" in f.message
               for f in active)


def test_telem_hand_constant_is_a_finding(tmp_path):
    root = _layout_tree(tmp_path)
    _edit(root, "ops/pallas_round.py",
          "TELEM_STAGES = (\"proposal\", \"vote\")",
          "TELEM_STAGES = (\"proposal\", \"vote\")\n_TELEM_PAD_COL = 1",
          count=1)
    active, _ = _findings(root, rules=["telem-layout"])
    assert any("hand-numbered" in f.message
               and "_TELEM_PAD_COL" in f.message for f in active)


def test_deleting_telem_table_is_itself_a_finding(tmp_path):
    root = _layout_tree(tmp_path)
    _edit(root, "ops/pallas_round.py", "TELEM_COLS = {",
          "TELEM_COLS_RENAMED = {", count=1)
    active, _ = _findings(root, rules=["telem-layout"])
    assert any("missing" in f.message for f in active)


# --------------------------------------------------------------------------
# pack rules: mutations of the REAL bit-field layout table (PR 8)
# --------------------------------------------------------------------------


_PACK_RULES = ["pack-layout", "pack-parity"]


def test_pack_rules_clean_on_shipped_table(tmp_path):
    root = _layout_tree(tmp_path)
    active, _ = _findings(root, rules=_PACK_RULES)
    assert active == []


@pytest.mark.parametrize("field", ["x", "decided", "killed", "coined",
                                   "faulty", "down", "k"])
def test_removing_any_pack_field_fails(tmp_path, field):
    # acceptance: removing ANY single bit-field from PACK_LAYOUT must
    # fail lint — NetState fields via pack-parity, the extra fields via
    # parity-or-density (coined/faulty/down leave a plane gap AND break
    # the PACK_EXTRA_FIELDS set)
    root = _layout_tree(tmp_path)
    base = {"x": "(0, 2)", "decided": "(2, 1)", "killed": "(3, 1)",
            "coined": "(4, 1)", "faulty": "(5, 1)", "down": "(6, 1)",
            "k": "(7, 25)"}[field]
    _edit(root, "state.py", f'    "{field}": {base},', "", count=1)
    active, _ = _findings(root, rules=_PACK_RULES)
    assert any(f.path == "state.py" for f in active), \
        f"dropping packed field {field} went unnoticed"


def test_pack_overlap_fails(tmp_path):
    root = _layout_tree(tmp_path)
    _edit(root, "state.py", '    "killed": (3, 1),',
          '    "killed": (2, 1),', count=1)
    active, _ = _findings(root, rules=["pack-layout"])
    assert any("overlaps" in f.message for f in active)


def test_pack_width_must_fit_word(tmp_path):
    # widening k past the uint32 word budget must fail — the declared
    # cap is what config.py's max_rounds validation enforces at runtime
    root = _layout_tree(tmp_path)
    _edit(root, "state.py", '    "k": (7, 25),', '    "k": (7, 30),',
          count=1)
    active, _ = _findings(root, rules=["pack-layout"])
    assert any("word" in f.message for f in active)


def test_pack_undeclared_extra_field_fails(tmp_path):
    # a packed field that is neither a NetState leaf nor declared in
    # PACK_EXTRA_FIELDS rides the stack undocumented -> pack-parity
    root = _layout_tree(tmp_path)
    _edit(root, "state.py",
          'PACK_EXTRA_FIELDS = ("faulty", "coined", "down")',
          'PACK_EXTRA_FIELDS = ("faulty", "down")', count=1)
    active, _ = _findings(root, rules=["pack-parity"])
    assert any("coined" in f.message for f in active)


def test_deleting_pack_table_is_itself_a_finding(tmp_path):
    root = _layout_tree(tmp_path)
    _edit(root, "state.py", "PACK_LAYOUT = {", "PACK_LAYOUT_RENAMED = {",
          count=1)
    active, _ = _findings(root, rules=["pack-layout"])
    assert any("missing" in f.message for f in active)


# --------------------------------------------------------------------------
# config parity: fixture + mutation of the real sharded regime
# --------------------------------------------------------------------------


def _parity_tree(tmp_path) -> str:
    root = tmp_path / "pkg"
    (root / "ops").mkdir(parents=True)
    (root / "parallel").mkdir()
    for rel in ("config.py", "sim.py", "sweep.py"):
        shutil.copy(os.path.join(PKG_DIR, rel), root / rel)
    for rel in ("ops/pallas_round.py", "parallel/sharded.py",
                "parallel/multihost.py", "parallel/grid.py"):
        shutil.copy(os.path.join(PKG_DIR, rel), os.path.join(root, rel))
    return str(root)


def test_config_parity_clean_on_shipped_tree(tmp_path):
    active, _ = _findings(_parity_tree(tmp_path),
                          rules=["config-parity"])
    assert active == []


def test_config_parity_field_missing_from_sharded(tmp_path):
    # the issue's seeded violation: a SimConfig field the driver consumes
    # vanishes from the sharded regime — the next recorder-style feature
    # silently skipping a mesh
    root = _parity_tree(tmp_path)
    _edit(root, "parallel/sharded.py", "cfg.max_rounds", "(1 << 20)")
    active, _ = _findings(root, rules=["config-parity"])
    assert len(active) == 1
    f = active[0]
    assert f.rule == "config-parity" and f.path == "sim.py"
    assert "max_rounds" in f.message and "parallel/sharded.py" in f.message


def test_config_parity_new_consumed_field_fires_everywhere(tmp_path):
    # a field sim.py starts consuming without threading it anywhere
    root = _parity_tree(tmp_path)
    _edit(root, "sim.py", "if cfg.record or cfg.witness:",
          "if (cfg.record or cfg.witness) and not cfg.poll_rounds:",
          count=1)
    active, _ = _findings(root, rules=["config-parity"])
    hits = [f for f in active if "poll_rounds" in f.message]
    assert len(hits) == 5      # one per regime file, none allowlisted


def test_config_parity_heartbeat_field_clean_and_mutation_fails(tmp_path):
    """ISSUE 6 satellite: heartbeat_rounds is consumed by the driver
    (sim.heartbeat_due) and must stay visible in every regime — the
    shipped tree passes (sweep/sharded/multihost reference it, the
    fused kernels carry a reasoned PARITY_ALLOWLIST entry), and
    removing the reference from ONE regime fails lint."""
    root = _parity_tree(tmp_path)
    active, _ = _findings(root, rules=["config-parity"])
    assert active == []        # clean as shipped (allowlist included)

    # mutation: the sharded slice wrapper stops honoring the cadence
    _edit(root, "parallel/sharded.py",
          "if heartbeat and cfg.heartbeat_rounds:",
          "if False:", count=1)
    active, _ = _findings(root, rules=["config-parity"])
    assert len(active) == 1
    f = active[0]
    assert f.rule == "config-parity" and f.path == "sim.py"
    assert "heartbeat_rounds" in f.message
    assert "parallel/sharded.py" in f.message

    # same mutation against the sweep engine, independently
    root2 = _parity_tree(tmp_path.joinpath("second"))
    _edit(root2, "sweep.py", "if base_cfg.heartbeat_rounds:",
          "if False:", count=1)
    active, _ = _findings(root2, rules=["config-parity"])
    assert any("heartbeat_rounds" in f.message and "sweep.py"
               in f.message for f in active)


def test_config_parity_topology_fields_clean_and_mutation_fails(tmp_path):
    """ISSUE 12 satellite: the structured-delivery fields (topology,
    committee_cap) are consumed by the driver (sim.delivery_plane) and
    policed across the five regimes — the shipped tree passes (sweep.py
    references both; pallas_round/sharded/multihost carry reasoned
    PARITY_ALLOWLIST delegation entries), and removing the reference
    from ONE regime fails lint."""
    root = _parity_tree(tmp_path)
    active, _ = _findings(root, rules=["config-parity"])
    assert active == []        # clean as shipped (allowlist included)

    # mutation: the sweep engine's bucketing stops seeing the topology
    # axis — two different adjacency specs would silently share a
    # compiled executable
    _edit(root, "sweep.py", "and cfg.topology is None", "", count=1)
    active, _ = _findings(root, rules=["config-parity"])
    hits = [f for f in active if "topology" in f.message]
    assert len(hits) == 1
    f = hits[0]
    assert f.rule == "config-parity" and f.path == "sim.py"
    assert "sweep.py" in f.message

    # committee_cap mutation, independently: erase the committee-knob
    # bucketing from sweep_bucket_key
    root2 = _parity_tree(tmp_path.joinpath("second"))
    _edit(root2, "sweep.py", "if cfg.committee_cap:", "if False:",
          count=1)
    active, _ = _findings(root2, rules=["config-parity"])
    assert any("committee_cap" in f.message and "sweep.py" in f.message
               for f in active)


def test_config_parity_faultlab_fields_clean_and_mutation_fails(tmp_path):
    """ISSUE 15 satellite: the faultlab fields (drop_prob, partition,
    recovery, plus fault_model now that sim.injection_plane consumes it)
    are policed across the five regimes — the shipped tree passes
    (sweep.py references them in quorum_specialized / sweep_bucket_key /
    default_crash_faults, ops/pallas_round.py reads fault_model and the
    recovery rejoin mode itself; the remaining regime cells carry
    reasoned PARITY_ALLOWLIST delegations), and removing the reference
    from ONE regime fails lint."""
    root = _parity_tree(tmp_path)
    active, _ = _findings(root, rules=["config-parity"])
    assert active == []        # clean as shipped (allowlist included)

    # mutation: the sweep engine's bucketing stops seeing the omission
    # axis — armed and off drop configs would silently share a bucket
    _edit(root, "sweep.py", "if cfg.drop_prob or cfg.partition is not "
          "None:", "if cfg.partition is not None:", count=1)
    _edit(root, "sweep.py", "if cfg.drop_prob:", "if False:", count=1)
    active, _ = _findings(root, rules=["config-parity"])
    hits = [f for f in active if "drop_prob" in f.message]
    assert len(hits) == 1
    f = hits[0]
    assert f.rule == "config-parity" and f.path == "sim.py"
    assert "sweep.py" in f.message

    # recovery mutation, independently: the default fault policy stops
    # realizing the schedule
    root2 = _parity_tree(tmp_path.joinpath("second"))
    _edit(root2, "sweep.py", "if cfg.recovery is None:", "if False:",
          count=1)
    active, _ = _findings(root2, rules=["config-parity"])
    assert any("recovery" in f.message and "sweep.py" in f.message
               for f in active)


def test_config_parity_grid_regime_clean_and_mutation_fails(tmp_path):
    """ISSUE 16 satellite: parallel/grid.py is the sixth policed regime
    — the shipped tree passes (grid references the placement-shaping
    fields itself; the delegated fields carry reasoned PARITY_ALLOWLIST
    entries), and removing ONE placement-relevant reference (the
    recorder's partition rule) fails lint with a single finding."""
    root = _parity_tree(tmp_path)
    active, _ = _findings(root, rules=["config-parity"])
    assert active == []        # clean as shipped (allowlist included)

    # mutation: placement stops seeing the recorder arm — a recorded 2D
    # run would device_put the state but leave the recorder rule out of
    # partition_rules, exactly the recorder-style regime skip the rule
    # owns
    _edit(root, "parallel/grid.py", "if cfg.record:", "if False:",
          count=1)
    active, _ = _findings(root, rules=["config-parity"])
    hits = [f for f in active if "record" in f.message
            and "parallel/grid.py" in f.message]
    assert len(hits) == 1
    f = hits[0]
    assert f.rule == "config-parity" and f.path == "sim.py"

    # partition mutation, independently: the bucketing predicate stops
    # seeing the partition plane (its spec would still ride the key,
    # but quorum_specialized is the reviewed consumption point)
    root3 = _parity_tree(tmp_path.joinpath("third"))
    _edit(root3, "sweep.py", "if cfg.drop_prob or cfg.partition is not "
          "None:", "if cfg.drop_prob:", count=1)
    active, _ = _findings(root3, rules=["config-parity"])
    assert any("partition" in f.message and "sweep.py" in f.message
               for f in active)


# --------------------------------------------------------------------------
# perf observability: raw jits off the perfscope funnel (ISSUE 5)
# --------------------------------------------------------------------------


PERF_JIT_SRC = """\
    import functools

    import jax


    @functools.partial(jax.jit, static_argnums=0)   # MARK-decorator
    def raw_entry(cfg, state):
        return state


    def build(fn, args):
        jitted = jax.jit(fn)                        # MARK-callsite
        return jitted.lower(*args).compile()        # MARK-chain
"""


def test_perf_unregistered_jit_fixture(tmp_path):
    # no perfscope/instrument.py in the tree: every raw jit spelling is
    # unregistered by definition
    root = _write_pkg(tmp_path, {"mod.py": PERF_JIT_SRC})
    active, _ = _findings(root, rules=["perf-unregistered-jit"])
    got = sorted((f.path, f.line) for f in active)
    assert got == [
        ("mod.py", _line_of(PERF_JIT_SRC, "MARK-decorator")),
        ("mod.py", _line_of(PERF_JIT_SRC, "MARK-callsite")),
        ("mod.py", _line_of(PERF_JIT_SRC, "MARK-chain")),
    ]
    assert all(f.rule == "perf-unregistered-jit" for f in active)


def test_perf_rule_pragma_for_test_trees(tmp_path):
    # the sanctioned escape hatch for throwaway fixture jits
    root = _write_pkg(tmp_path, {"mod.py": """\
        import jax

        def fixture(fn):
            # benorlint: allow-perf-unregistered-jit — throwaway test jit
            return jax.jit(fn)
    """})
    active, suppressed = _findings(root,
                                   rules=["perf-unregistered-jit"])
    assert active == []
    assert suppressed == {"perf-unregistered-jit": 1}


def _perf_tree(tmp_path) -> str:
    """The real funnel + the real registered entry points."""
    root = tmp_path / "pkg"
    (root / "perfscope").mkdir(parents=True)
    shutil.copy(os.path.join(PKG_DIR, "sim.py"), root / "sim.py")
    shutil.copy(os.path.join(PKG_DIR, "sweep.py"), root / "sweep.py")
    shutil.copy(os.path.join(PKG_DIR, "perfscope", "instrument.py"),
                root / "perfscope" / "instrument.py")
    return str(root)


def test_perf_rule_clean_on_shipped_registry(tmp_path):
    # the shipped raw-jit entry points are exactly the JIT_REGISTRY
    # roster, and the funnel module itself is exempt
    active, _ = _findings(_perf_tree(tmp_path),
                          rules=["perf-unregistered-jit"])
    assert active == []


def test_removing_a_jit_registry_entry_fails(tmp_path):
    # the mutation the issue asks for: un-rostering one entry point
    # makes its (unchanged) raw jit an unregistered executable
    root = _perf_tree(tmp_path)
    _edit(root, "perfscope/instrument.py",
          '    "sim.run_consensus",\n', "", count=1)
    active, _ = _findings(root, rules=["perf-unregistered-jit"])
    assert len(active) == 1
    f = active[0]
    assert f.path == "sim.py" and "'sim.run_consensus'" in f.message


def test_stale_registry_entry_is_a_finding(tmp_path):
    # a roster row that resolves to nothing allow-lists nothing — and
    # must say so rather than rot silently
    root = _perf_tree(tmp_path)
    _edit(root, "perfscope/instrument.py",
          '"sweep.summarize_final"', '"sweep.summarize_gone"', count=1)
    active, _ = _findings(root, rules=["perf-unregistered-jit"])
    paths = {f.path for f in active}
    # the stale row fires on the roster, and the now-unrostered real
    # function fires at its decorator
    assert paths == {"perfscope/instrument.py", "sweep.py"}
    assert any("stale" in f.message for f in active)


SERVE_BLOCKING_SRC = """\
    import asyncio
    import socket
    import time


    async def handler(reader, writer, arr):
        time.sleep(0.1)                            # MARK-sleep
        n = arr.item()                             # MARK-item
        s = socket.create_connection(("x", 80))    # MARK-socket

        def helper():
            time.sleep(0.2)                        # MARK-nested
        helper()
        await asyncio.sleep(0)                     # fine: awaitable
        return n, s


    def sync_worker():
        time.sleep(1.0)       # fine: not on the event loop
"""


def test_serve_blocking_fixture(tmp_path):
    root = _write_pkg(tmp_path, {"srv.py": SERVE_BLOCKING_SRC})
    active, _ = _findings(root, rules=["serve-blocking-call"])
    got = sorted((f.path, f.line) for f in active)
    assert got == [
        ("srv.py", _line_of(SERVE_BLOCKING_SRC, "MARK-sleep")),
        ("srv.py", _line_of(SERVE_BLOCKING_SRC, "MARK-item")),
        ("srv.py", _line_of(SERVE_BLOCKING_SRC, "MARK-socket")),
        ("srv.py", _line_of(SERVE_BLOCKING_SRC, "MARK-nested")),
    ]
    assert all(f.rule == "serve-blocking-call" for f in active)
    assert any("event loop" in f.message for f in active)


SERVE_PRAGMA_SRC = """\
    import time as t
    from urllib.request import urlopen


    async def handler():
        # benorlint: allow-serve-blocking-call — startup-only path
        t.sleep(0.1)
        return urlopen("http://x")       # MARK-urlopen
"""


def test_serve_blocking_pragma_and_aliases(tmp_path):
    # alias-resolved spellings fire; the pragma is the escape hatch
    root = _write_pkg(tmp_path, {"srv.py": SERVE_PRAGMA_SRC})
    active, suppressed = _findings(root, rules=["serve-blocking-call"])
    assert suppressed == {"serve-blocking-call": 1}
    assert [f.line for f in active] == [
        _line_of(SERVE_PRAGMA_SRC, "MARK-urlopen")]
    assert "urllib.request.urlopen" in active[0].message


def test_serve_blocking_mutation_of_real_server(tmp_path):
    """The acceptance mutation: the SHIPPED server.py is clean, and
    swapping ONE awaited drain for a blocking sleep fails lint — the
    exact hand-edit that would freeze every SSE client."""
    root = tmp_path / "pkg"
    (root / "serve").mkdir(parents=True)
    shutil.copy(os.path.join(PKG_DIR, "serve", "server.py"),
                root / "serve" / "server.py")
    active, _ = _findings(str(root), rules=["serve-blocking-call"])
    assert active == []
    _edit(str(root), "serve/server.py",
          "await writer.drain()", "time.sleep(0.001)", count=1)
    _edit(str(root), "serve/server.py",
          "import asyncio\n", "import asyncio\nimport time\n", count=1)
    active, _ = _findings(str(root), rules=["serve-blocking-call"])
    assert len(active) == 1
    assert active[0].path == "serve/server.py"
    assert "time.sleep" in active[0].message


def test_registry_module_gone_is_also_stale(tmp_path):
    # a roster row whose whole MODULE left the tree (rename/delete) is
    # as stale as a vanished function — both sweep.* rows must fire
    root = _perf_tree(tmp_path)
    os.remove(os.path.join(root, "sweep.py"))
    active, _ = _findings(root, rules=["perf-unregistered-jit"])
    assert {f.path for f in active} == {"perfscope/instrument.py"}
    stale = [f for f in active if "stale" in f.message]
    assert len(stale) == 2
    assert all("sweep" in f.message for f in stale)


# --------------------------------------------------------------------------
# manifest-kind-parity: emitted manifest kinds need registered checkers
# --------------------------------------------------------------------------


MANIFEST_EMIT_SRC = """\
    FOO_MANIFEST_KIND = "foo_manifest"        # MARK-const


    def build():
        return {"kind": "bar_manifest"}       # MARK-dict
"""


def test_manifest_kind_fixture_without_tools(tmp_path):
    # no tools/check_metrics_schema.py next to the tree: every emitted
    # kind is unregistered by definition (the missing-funnel behavior
    # of perf-unregistered-jit)
    root = _write_pkg(tmp_path, {"emit.py": MANIFEST_EMIT_SRC})
    active, _ = _findings(root, rules=["manifest-kind-parity"])
    got = sorted((f.path, f.line) for f in active)
    assert got == [
        ("emit.py", _line_of(MANIFEST_EMIT_SRC, "MARK-const")),
        ("emit.py", _line_of(MANIFEST_EMIT_SRC, "MARK-dict")),
    ]
    assert all(f.rule == "manifest-kind-parity" for f in active)
    assert all("not in the tree" in f.message for f in active)


def test_manifest_kind_identifier_strings_do_not_count(tmp_path):
    # __all__ rosters of *_manifest function NAMES and comparison-site
    # consumers are not emissions — only the dict-entry and *_KIND
    # constant spellings count
    root = _write_pkg(tmp_path, {"mod.py": """\
        __all__ = ["save_sweep_manifest", "build_scaling_manifest"]


        def compare(doc):
            return doc.get("kind") == "nonexistent_manifest"
    """})
    active, _ = _findings(root, rules=["manifest-kind-parity"])
    assert active == []


def _manifest_tree(tmp_path) -> str:
    """The real sweepscope manifest builder + the real checker registry
    in the sibling tools/ dir (the rule resolves the registry relative
    to the lint root's PARENT, mirroring the repo layout)."""
    root = tmp_path / "pkg"
    (root / "sweepscope").mkdir(parents=True)
    shutil.copy(os.path.join(PKG_DIR, "sweepscope", "manifest.py"),
                root / "sweepscope" / "manifest.py")
    (tmp_path / "tools").mkdir()
    shutil.copy(os.path.join(REPO, "tools", "check_metrics_schema.py"),
                tmp_path / "tools" / "check_metrics_schema.py")
    return str(root)


def test_manifest_kind_clean_on_shipped_registry(tmp_path):
    active, _ = _findings(_manifest_tree(tmp_path),
                          rules=["manifest-kind-parity"])
    assert active == []


def test_removing_sweep_checker_registration_fails(tmp_path):
    """The acceptance mutation: un-registering check_sweep_manifest
    makes the (unchanged) sweepscope emission an unvalidated kind."""
    root = _manifest_tree(tmp_path)
    _edit(str(tmp_path), "tools/check_metrics_schema.py",
          '    "sweep_manifest": "check_sweep_manifest",\n', "",
          count=1)
    active, _ = _findings(root, rules=["manifest-kind-parity"])
    assert len(active) == 1
    f = active[0]
    assert f.path == "sweepscope/manifest.py"
    assert "'sweep_manifest'" in f.message


def _kernel_manifest_tree(tmp_path) -> str:
    """The real kernelscope manifest builder + the real checker registry
    in the sibling tools/ dir (the PR-14 twin of _manifest_tree)."""
    root = tmp_path / "pkg"
    (root / "kernelscope").mkdir(parents=True)
    shutil.copy(os.path.join(PKG_DIR, "kernelscope", "manifest.py"),
                root / "kernelscope" / "manifest.py")
    (tmp_path / "tools").mkdir()
    shutil.copy(os.path.join(REPO, "tools", "check_metrics_schema.py"),
                tmp_path / "tools" / "check_metrics_schema.py")
    return str(root)


def test_kernel_manifest_kind_clean_on_shipped_registry(tmp_path):
    active, _ = _findings(_kernel_manifest_tree(tmp_path),
                          rules=["manifest-kind-parity"])
    assert active == []


def test_removing_kernel_checker_registration_fails(tmp_path):
    """The PR-14 acceptance mutation: un-registering
    check_kernel_manifest makes the (unchanged) kernelscope emission an
    unvalidated kind — the manifest-kind-parity lint is what turns the
    satellite requirement 'register the new kind' into a hard
    failure."""
    root = _kernel_manifest_tree(tmp_path)
    _edit(str(tmp_path), "tools/check_metrics_schema.py",
          '    "kernel_manifest": "check_kernel_manifest",\n', "",
          count=1)
    active, _ = _findings(root, rules=["manifest-kind-parity"])
    assert len(active) == 1
    f = active[0]
    assert f.path == "kernelscope/manifest.py"
    assert "'kernel_manifest'" in f.message


def _atlas_manifest_tree(tmp_path) -> str:
    """The real atlas manifest builder + the real checker registry in
    the sibling tools/ dir (the PR-20 sibling of _manifest_tree)."""
    root = tmp_path / "pkg"
    (root / "atlas").mkdir(parents=True)
    shutil.copy(os.path.join(PKG_DIR, "atlas", "manifest.py"),
                root / "atlas" / "manifest.py")
    (tmp_path / "tools").mkdir()
    shutil.copy(os.path.join(REPO, "tools", "check_metrics_schema.py"),
                tmp_path / "tools" / "check_metrics_schema.py")
    return str(root)


def test_atlas_manifest_kind_clean_on_shipped_registry(tmp_path):
    active, _ = _findings(_atlas_manifest_tree(tmp_path),
                          rules=["manifest-kind-parity"])
    assert active == []


def test_removing_atlas_checker_registration_fails(tmp_path):
    """The PR-20 acceptance mutation: un-registering
    check_atlas_manifest makes the (unchanged) atlas emission an
    unvalidated kind."""
    root = _atlas_manifest_tree(tmp_path)
    _edit(str(tmp_path), "tools/check_metrics_schema.py",
          '    "atlas_manifest": "check_atlas_manifest",\n', "",
          count=1)
    active, _ = _findings(root, rules=["manifest-kind-parity"])
    assert len(active) == 1
    f = active[0]
    assert f.path == "atlas/manifest.py"
    assert "'atlas_manifest'" in f.message


def test_stale_manifest_checker_row_is_a_finding(tmp_path):
    # a registry row whose checker function left the tool validates
    # nothing — the JIT_REGISTRY staleness discipline
    root = _manifest_tree(tmp_path)
    _edit(str(tmp_path), "tools/check_metrics_schema.py",
          '"check_sweep_manifest"', '"check_sweep_gone"', count=1)
    active, _ = _findings(root, rules=["manifest-kind-parity"])
    stale = [f for f in active if "stale" in f.message]
    assert len(stale) == 1
    assert "check_sweep_gone" in stale[0].message


# --------------------------------------------------------------------------
# self-check: the shipped tree is lint-clean, suppressions accounted
# --------------------------------------------------------------------------


def test_shipped_tree_lints_clean():
    rep = run_lint()
    assert rep.findings == [], rep.to_text()
    # the documented intentional exceptions, and nothing else (the third
    # broad-except is perfscope.instrument.cost_of's best-effort
    # accounting boundary; the fourth through sixth are the serve
    # plane's multi-tenant isolation boundaries — batcher step/run and
    # the request handler's 500 path; the seventh is sweep_async's
    # cross-thread exception relay, which re-raises verbatim on the
    # consumer; the second host-rng is the topo plane's seeded static
    # graph-table construction, a trace-time constant —
    # topo/graphs.build_neighbor_table)
    assert rep.suppressed == {"host-sync": 1, "host-rng": 2,
                              "donate-argnums": 3, "broad-except": 7}
    assert rep.files >= 40


def test_report_schema_and_cli_exit_codes(tmp_path):
    class Args:
        root = None
        format = "json"
        out = str(tmp_path / "report.json")
        metrics_out = None

    assert lint_main(Args()) == 0
    with open(Args.out) as fh:
        doc = json.load(fh)
    assert check_metrics_schema.check_lint_report(doc) == []
    assert doc["ok"] is True and doc["suppressed_total"] == 13

    # a dirty tree exits 2 through the same entry point
    dirty = _write_pkg(tmp_path, {"gen.py": HOST_RNG_SRC})

    class Dirty(Args):
        root = dirty
        out = str(tmp_path / "dirty.json")

    assert lint_main(Dirty()) == 2
    with open(Dirty.out) as fh:
        doc = json.load(fh)
    assert check_metrics_schema.check_lint_report(doc) == []
    assert doc["ok"] is False and doc["counts"] == {"host-rng": 1}


def test_cli_subprocess_exit_0():
    # the acceptance command, end to end: `python -m benor_tpu lint`
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "benor_tpu", "lint", "--format", "json"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True
    assert check_metrics_schema.check_lint_report(doc) == []


def test_lint_feeds_metrics_registry():
    from benor_tpu.utils.metrics import REGISTRY
    before = REGISTRY.counter("analysis.runs").value
    rep = run_lint()
    assert REGISTRY.counter("analysis.runs").value == before + 1
    assert REGISTRY.counter("analysis.files").value >= rep.files
    assert REGISTRY.counter("analysis.suppressed").value >= 8
