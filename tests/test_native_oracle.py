"""Native C++ oracle: bit-exact differential testing vs the Python oracle.

The native oracle replays the exact event-loop semantics (same FIFO, same
quirks, same CPython-MT19937 coin stream), so for any (seed, scenario) the
two oracles must produce IDENTICAL final states — not just statistically
similar ones.
"""

import numpy as np
import pytest

from benor_tpu.api import launch_network
from benor_tpu.backends.native_oracle import native_available
from benor_tpu.config import SimConfig

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++ unavailable; native oracle not built")


def _mt_reference_check():
    """CPython-MT19937 parity spot check, independent of the oracle."""
    import ctypes
    import random
    # drive the C++ stream indirectly through a 1-node run is awkward;
    # instead check Python's stream has the documented first value for
    # seed 42 (guards against interpreter changes breaking the contract)
    r = random.Random(42)
    assert abs(r.random() - 0.6394267984578837) < 1e-15


SCENARIOS = [
    # (n, f, seed, values, faulty) — the §4 matrix shapes + stress shapes
    (5, 0, 0, [1] * 5, [False] * 5),
    (5, 1, 1, [1, 1, 1, 0, 0], [False] * 4 + [True]),
    (9, 4, 2, [1, 0, 1, 0, 1, 0, 1, 1, 0],
     [True, True, False, False, True, False, False, False, True]),
    (10, 5, 3, [1, 0] * 5, [True] * 5 + [False] * 5),   # livelock F=N/2
    (7, 2, 4, [0, 1, 1, 0, 1, 0, 1],
     [True, False, True, False, False, False, False]),
    (1, 0, 5, [1], [False]),                            # N=1
    (30, 9, 6, [i % 2 for i in range(30)],
     [True] * 9 + [False] * 21),
]


@pytest.mark.parametrize("order", ["fifo", "shuffle"])
@pytest.mark.parametrize("n,f,seed,values,faulty", SCENARIOS)
def test_native_matches_python_oracle_exactly(n, f, seed, values, faulty,
                                              order):
    _mt_reference_check()
    nets = {}
    for backend in ("express", "native"):
        net = launch_network(n, f, values, faulty, backend=backend,
                             seed=seed, max_rounds=12, oracle_order=order)
        net.start()
        nets[backend] = net.get_states()
    assert nets["express"] == nets["native"]


def test_shuffle_changes_delivery_order():
    """The oracle_order flag must actually change the execution.

    Final states alone cannot distinguish orders here: tally multisets are
    permutation-invariant, so once plurality-adopt re-unanimizes x the
    endpoint coincides.  The *delivery trace* is the honest observable —
    record each (dest, k, x, phase) delivery and assert the interleavings
    differ while both traces carry the same message multiset."""
    from collections import Counter

    from benor_tpu.backends.express import _ExpressNode

    n, f = 9, 5                   # healthy = quorum = 4: ties -> coins
    values = [1, 0, 1, 0, 1, 0, 0, 1, 1]
    faulty = [True] * 5 + [False] * 4
    traces = {}
    orig = _ExpressNode.on_message
    try:
        for order in ("fifo", "shuffle"):
            net = launch_network(n, f, values, faulty, backend="express",
                                 seed=0, max_rounds=3, oracle_order=order)
            trace = []

            def rec(self, k, x, mt, _t=trace):
                _t.append((self.node_id, k, x, mt))
                return orig(self, k, x, mt)

            _ExpressNode.on_message = rec
            net.start()
            _ExpressNode.on_message = orig
            traces[order] = trace
    finally:
        _ExpressNode.on_message = orig
    assert traces["fifo"] != traces["shuffle"]
    # same deliveries, different interleaving (shuffle loses no messages)
    assert Counter(t[:2] + t[3:] for t in traces["fifo"]) == \
        Counter(t[:2] + t[3:] for t in traces["shuffle"])


def test_native_pre_start_stop_matches_python():
    """A healthy node stopped BEFORE /start must not participate (it keeps
    its state but never broadcasts) — identically in both oracles.  With
    node 4 (the only 0-holder among quorum members) silenced, the outcome
    shifts, so divergence here is observable."""
    n, f = 5, 1
    values = [1, 1, 1, 0, 0]
    faulty = [False, False, False, False, True]
    finals = {}
    for backend in ("express", "native"):
        net = launch_network(n, f, values, faulty, backend=backend,
                             seed=7, max_rounds=12)
        net.stop_node(3)          # pre-start kill of a healthy node
        net.start()
        finals[backend] = net.get_states()
    assert finals["express"] == finals["native"]
    # the stopped node kept its state but was killed and never advanced
    st = finals["native"][3]
    assert st["killed"] is True and st["k"] == 0 and st["decided"] is False


def test_native_large_n_runs_fast():
    """N=300: ~1e5+ messages/round — impractical interpreted, fast native."""
    import time
    n, f = 300, 90
    values = [i % 2 for i in range(n)]
    faulty = [True] * f + [False] * (n - f)
    net = launch_network(n, f, values, faulty, backend="native", seed=9,
                         max_rounds=12)
    t0 = time.perf_counter()
    net.start()
    dt = time.perf_counter() - t0
    states = net.get_states()
    healthy = [s for s in states if s["decided"] is not None]
    assert all(s["decided"] for s in healthy)
    vals = {s["x"] for s in healthy}
    assert len(vals) == 1, f"disagreement: {vals}"
    assert dt < 30, f"native oracle too slow: {dt:.1f}s"


def test_native_step_cap_raises():
    net = launch_network(5, 0, [1] * 5, [False] * 5, backend="native",
                         seed=0)
    net._step_cap = 3
    with pytest.raises(RuntimeError, match="step cap"):
        net.start()


def test_native_parity_api_surface():
    net = launch_network(3, 1, [1, 1, 0], [True, False, False],
                         backend="native", seed=0)
    assert net.status(0) == ("faulty", 500)
    assert net.status(1) == ("live", 200)
    assert net.get_state(0) == {"killed": True, "x": None, "decided": None,
                                "k": None}
    net.start()
    net.stop_node(1)
    assert net.status(1) == ("faulty", 500)
    net.stop()
    assert net.status(2) == ("faulty", 500)


def test_run_batch_surfaces_tripped_count():
    """ADVICE r4: capped seeds must be countable (and refusable) without
    every caller remembering to scan steps < 0."""
    from benor_tpu.backends import native_oracle
    cfg = SimConfig(n_nodes=5, n_faulty=0, backend="native", max_rounds=12)
    vals, faulty = [1] * 5, [False] * 5
    seeds = np.arange(8, dtype=np.uint32)
    ok = native_oracle.run_batch(cfg, vals, faulty, seeds)
    assert ok["n_tripped"] == 0
    capped = native_oracle.run_batch(cfg, vals, faulty, seeds, step_cap=3)
    assert capped["n_tripped"] == len(seeds)
    assert (capped["steps"] < 0).all()
    with pytest.raises(RuntimeError, match="step cap"):
        native_oracle.run_batch(cfg, vals, faulty, seeds, step_cap=3,
                                raise_on_cap=True)


# --- POST /message injection parity (r5) --------------------------------

INJ = ([(0, 1, 1, "proposal phase")] * 3 + [(1, 1, 1, "proposal phase")] * 3
       + [(2, 1, 1, "proposal phase")] * 3 + [(1, 2, "?", "voting phase")]
       # hostile wire values: an unknown type still occupies a queue slot
       # (shuffle permutation parity) and a non-canonical x classes by
       # Python == semantics on BOTH engines (0.5 -> the neither class)
       + [(2, 1, 1, "gossip"), (0, 2, 0.5, "voting phase"),
          (1, 1, True, "proposal phase")])


@pytest.mark.parametrize("order", ["fifo", "shuffle"])
def test_injected_runs_bit_equal_across_oracles(order):
    """Pre-start injections land ahead of the /start fan-out in BOTH
    engines, so injected traces are bit-equal across languages — the
    cross-language differential contract now covers the injection
    surface too."""
    states = {}
    for backend in ("express", "native"):
        net = launch_network(4, 1, [0, 0, 0, 0],
                             [False, False, False, True], backend=backend,
                             seed=7, max_rounds=12, oracle_order=order)
        for nid, k, x, mt in INJ:
            assert net.inject_message(nid, k, x, mt) is True
        # killed target: no enqueue, reference's no-response contract
        assert net.inject_message(3, 1, 1, "proposal phase") is False
        net.start()
        states[backend] = net.get_states()
    assert states["express"] == states["native"]
    # the forged all-1 proposals flip the unanimous-0 network (efficacy)
    healthy = states["native"][:3]
    assert all(s["decided"] for s in healthy)


def test_native_injection_contracts():
    net = launch_network(3, 0, [1, 1, 1], [False] * 3, backend="native",
                         seed=0, max_rounds=12)
    # out-of-range k would silently diverge from the Python oracle's
    # dict-keyed buffers (C++ sizes its tallies max_rounds + 2)
    with pytest.raises(ValueError, match="max_rounds"):
        net.inject_message(0, 13 + 1, 1, "proposal phase")
    with pytest.raises(ValueError, match="max_rounds"):
        net.inject_message(0, -1, 1, "proposal phase")
    # unknown message types are silent no-ops in the reference handler:
    # accepted, delivered, ignored
    assert net.inject_message(0, 1, 1, "gossip") is True
    net.start()
    assert all(s["decided"] for s in net.get_states())
    # post-start: the batched C++ engine has no live queue
    with pytest.raises(NotImplementedError, match="express"):
        net.inject_message(0, 1, 1, "proposal phase")


def test_native_injection_over_http():
    """The wire surface: POST /message on a native-backed listener
    delivers (200), and the injected run matches the express-backed run
    driven through the same HTTP flow."""
    import json
    import urllib.request
    from benor_tpu.backends.http_api import NodeHttpCluster

    finals = {}
    for backend, base in (("express", 3250), ("native", 3260)):
        net = launch_network(4, 1, [0, 0, 0, 0],
                             [False, False, False, True], backend=backend,
                             seed=7, max_rounds=12)
        with NodeHttpCluster(net, base):
            for nid in range(3):
                for _ in range(3):
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{base + nid}/message",
                        method="POST",
                        data=json.dumps({"k": 1, "x": 1, "messageType":
                                         "proposal phase"}).encode())
                    with urllib.request.urlopen(req, timeout=10) as r:
                        assert r.status == 200
            urllib.request.urlopen(
                f"http://127.0.0.1:{base}/start", timeout=30).read()
            finals[backend] = [json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{base + i}/getState", timeout=10).read())
                for i in range(4)]
        net.close()
    assert finals["express"] == finals["native"]
    assert all(s["x"] == 1 for s in finals["native"][:3])


def test_negative_node_id_normalizes_like_python_lists():
    """The Python oracle's nodes[node_id] accepts negative indices; the
    native wrapper normalizes them so the SAME node receives the
    injection in both engines (raw negatives would be dropped C++-side,
    silently forking the traces)."""
    states = {}
    for backend in ("express", "native"):
        net = launch_network(3, 0, [0, 0, 0], [False] * 3, backend=backend,
                             seed=1, max_rounds=12)
        for _ in range(3):
            assert net.inject_message(-1, 1, 1, "proposal phase") is True
        net.start()
        states[backend] = net.get_states()
        net.close()
    assert states["express"] == states["native"]
    net = launch_network(3, 0, [0, 0, 0], [False] * 3, backend="native",
                         seed=1)
    with pytest.raises(IndexError):
        net.inject_message(3, 1, 1, "proposal phase")
    net.close()
