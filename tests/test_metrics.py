"""Unified metrics layer (utils/metrics.py): registry + exporters."""

import json

import numpy as np
import pytest

from benor_tpu.state import REC_COLUMNS, REC_WIDTH
from benor_tpu.utils import metrics


@pytest.fixture
def registry():
    reg = metrics.MetricsRegistry()
    return reg


def test_registry_types_and_snapshot(registry):
    registry.counter("a.count").inc()
    registry.counter("a.count").inc(2.5)
    registry.gauge("b.gauge").set(7)
    with registry.timer("c.timer").time():
        pass
    snap = {m["name"]: m for m in registry.snapshot()}
    assert snap["a.count"]["value"] == 3.5
    assert snap["b.gauge"]["value"] == 7.0
    assert snap["c.timer"]["count"] == 1
    assert snap["c.timer"]["total_s"] >= 0
    # one name, one type — a re-registration under another type is loud
    with pytest.raises(TypeError):
        registry.gauge("a.count")


def test_exporters_roundtrip(tmp_path, registry):
    registry.counter("compiles").inc(4)
    registry.gauge("hbm.util").set(0.33)
    with registry.timer("sweep.run").time():
        pass

    p = tmp_path / "m.jsonl"
    n = metrics.export_jsonl(str(p), registry)
    lines = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert n == len(lines) == 3
    assert {ln["name"] for ln in lines} == {"compiles", "hbm.util",
                                            "sweep.run"}
    assert all("ts" in ln for ln in lines)

    p = tmp_path / "m.prom"
    metrics.export_prometheus(str(p), registry)
    text = p.read_text()
    assert "# TYPE benor_tpu_compiles counter" in text
    assert "benor_tpu_compiles 4.0" in text
    assert "benor_tpu_hbm_util 0.33" in text          # name sanitized
    assert "benor_tpu_sweep_run_count 1" in text

    p = tmp_path / "t.json"
    n_ev = metrics.export_chrome_trace(str(p), registry)
    trace = json.loads(p.read_text())
    assert len(trace["traceEvents"]) == n_ev
    phases = {e["ph"] for e in trace["traceEvents"]}
    assert "X" in phases and "C" in phases


def _synthetic_recorder(rows):
    """Recorder buffer from (decided, killed, u0, u1, uq, coins, margin)
    tuples, padded with unwritten (all-zero) tail rows."""
    rec = np.zeros((10, REC_WIDTH), np.int32)
    for i, row in enumerate(rows):
        rec[i] = row
    return rec


def test_round_history_rows_and_summary():
    rec = _synthetic_recorder([
        (0, 2, 10, 10, 0, 0, 0),      # row 0: snapshot
        (8, 2, 5, 5, 2, 12, 3),       # round 1
        (20, 2, 0, 0, 0, 0, 9),       # round 2: quiesced
    ])
    rows = metrics.round_history_rows(rec)
    assert len(rows) == 3                       # zero tail rows trimmed
    assert rows[0] == {"round": 0, **dict(zip(REC_COLUMNS,
                                              (0, 2, 10, 10, 0, 0, 0)))}
    summ = metrics.round_history_summary(rec)
    assert summ["rounds_executed"] == 2
    assert summ["rounds_to_quiescence"] == 2
    assert summ["decide_velocity"] == [8, 12]
    assert summ["rounds_to_quiescence_hist"] == [8, 12]
    assert summ["final"]["decided"] == 20

    # a never-quiescing (livelock) history reports None
    live = _synthetic_recorder([(0, 0, 10, 12, 0, 0, 0),
                                (0, 0, 8, 8, 6, 22, 0)])
    assert metrics.round_history_summary(live)["rounds_to_quiescence"] is None


def test_gapped_resume_buffer_renders_by_round_index():
    """A resume_consensus(..., recorder=None) buffer has unwritten rows
    between the re-entry snapshot (row 0) and from_round: renderers must
    key written rows by their TRUE round index, not drop the history at
    the first gap."""
    rec = np.zeros((8, REC_WIDTH), np.int32)
    rec[0] = (6, 2, 6, 6, 2, 0, 0)      # re-entry snapshot
    rec[4] = (12, 2, 3, 3, 2, 5, 1)     # resumed round 4
    rec[5] = (20, 2, 0, 0, 0, 0, 4)     # round 5: quiesced
    rows = metrics.round_history_rows(rec)
    assert [r["round"] for r in rows] == [0, 4, 5]
    summ = metrics.round_history_summary(rec)
    assert summ["rounds_executed"] == 2
    assert summ["rounds_to_quiescence"] == 5
    assert summ["decide_velocity"] == [6, 8]    # gap entry aggregates
    assert summ["final"]["decided"] == 20


def test_chrome_trace_renders_rounds(tmp_path, registry):
    rec = _synthetic_recorder([(0, 0, 4, 4, 0, 0, 0),
                               (8, 0, 0, 0, 0, 0, 2)])
    p = tmp_path / "t.json"
    metrics.export_chrome_trace(str(p), registry, round_history=rec,
                                rounds_label="unit")
    evs = json.loads(p.read_text())["traceEvents"]
    rounds = [e for e in evs if e["tid"] == "rounds"]
    assert len(rounds) == 2
    assert rounds[0]["name"] == "unit start"
    assert rounds[1]["args"]["decided"] == 8


def test_timed_feeds_registry():
    """Satellite: utils/tracing.timed now also records into the unified
    registry (same label), so ad-hoc timings reach every exporter."""
    from benor_tpu.utils import tracing

    name = "unit.timed_feeds_registry"
    before = metrics.REGISTRY.timer(name).count
    with tracing.timed(name, sink=lambda m: None):
        pass
    assert metrics.REGISTRY.timer(name).count == before + 1


def test_compile_counter_feeds_registry():
    """utils/compile_counter's process-lifetime listener mirrors every
    backend compile into the registry counters."""
    import jax
    import jax.numpy as jnp

    from benor_tpu.utils.compile_counter import count_backend_compiles

    c = metrics.REGISTRY.counter("jax.backend_compiles")
    before = c.value
    with count_backend_compiles() as cc:
        jax.jit(lambda x: x * 3 + 1)(jnp.arange(17, dtype=jnp.int32) % 5
                                     ).block_until_ready()
    assert cc.count >= 1
    assert c.value >= before + cc.count


def test_concurrent_writers_exports_never_tear(tmp_path, registry):
    """ISSUE 6 satellite: the heartbeat publisher (appending JSON lines)
    and the main loop (snapshot exports) run on different threads; every
    intermediate file must parse as clean JSON-lines and the final
    counts must be exact — no interleaved bytes, no torn snapshots."""
    import threading

    snap_path = str(tmp_path / "snap.jsonl")
    hb_path = str(tmp_path / "hb.jsonl")
    writers, incs_each, beats_each = 4, 200, 50
    stop = threading.Event()
    torn = []

    def hammer(i):
        c = registry.counter("w.count")
        t = registry.timer("w.timer")
        for j in range(incs_each):
            c.inc()
            if j % (incs_each // beats_each) == 0:
                with t.time():
                    pass
                metrics.append_jsonl(hb_path, {"kind": "heartbeat",
                                               "writer": i, "beat": j})

    def exporter():
        while not stop.is_set():
            metrics.export_jsonl(snap_path, registry)
            try:
                with open(snap_path) as fh:
                    for line in fh:
                        json.loads(line)
            except ValueError as e:   # a torn export would land here
                torn.append(str(e))

    threads = [threading.Thread(target=hammer, args=(i,))
               for i in range(writers)]
    exp = threading.Thread(target=exporter)
    exp.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    exp.join()
    assert torn == []
    # exact totals: no lost increments under contention
    metrics.export_jsonl(snap_path, registry)
    snap = {m["name"]: m for m in registry.snapshot()}
    assert snap["w.count"]["value"] == writers * incs_each
    assert snap["w.timer"]["count"] == writers * beats_each
    # every heartbeat line is whole and attributable
    lines = [json.loads(ln) for ln in open(hb_path)]
    assert len(lines) == writers * beats_each
    per_writer = {i: 0 for i in range(writers)}
    for rec in lines:
        assert rec["kind"] == "heartbeat" and "ts" in rec
        per_writer[rec["writer"]] += 1
    assert all(n == beats_each for n in per_writer.values())


def test_export_jsonl_is_atomic_replace(tmp_path, registry):
    """export_jsonl rewrites via temp-file + os.replace: no .tmp
    leftovers and the target always holds one complete snapshot."""
    import os

    registry.counter("x").inc()
    path = str(tmp_path / "m.jsonl")
    for _ in range(3):
        metrics.export_jsonl(path, registry)
    assert [p for p in os.listdir(tmp_path) if ".tmp." in p] == []
    recs = [json.loads(ln) for ln in open(path)]
    assert any(r["name"] == "x" for r in recs)
