"""Multi-chip sharding tests (SURVEY.md N7, §7 stage 6 + hard-part 5).

Runs on the 8-device virtual CPU mesh forced by conftest.py.  The core
contract: the shard_map'd runner is BIT-IDENTICAL to the single-device run
for every mesh shape, every compute path, every scheduler — because RNG keys
derive from global (trial, node, round) ids, never shard-local order.
"""

import jax
import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.parallel import make_mesh, run_consensus_sharded
from benor_tpu.sim import run_consensus
from benor_tpu.state import FaultSpec, init_state

N, F, T = 16, 4, 8
FAULTY = [True] * F + [False] * (N - F)
VALS = [i % 2 for i in range(N)]
MESH_SHAPES = [(1, 8), (2, 4), (4, 2), (8, 1), (1, 1), (2, 2)]


def _run_pair(cfg, mesh_shape):
    faults = FaultSpec.from_faulty_list(cfg, FAULTY)
    state = init_state(cfg, VALS, faults)
    key = jax.random.key(cfg.seed)
    r1, s1 = run_consensus(cfg, state, faults, key)
    mesh = make_mesh(*mesh_shape)
    r2, s2 = run_consensus_sharded(cfg, state, faults, key, mesh)
    return (r1, s1), (r2, s2)


def _assert_identical(a, b):
    (r1, s1), (r2, s2) = a, b
    assert int(r1) == int(r2)
    np.testing.assert_array_equal(np.asarray(s1.x), np.asarray(s2.x))
    np.testing.assert_array_equal(np.asarray(s1.decided),
                                  np.asarray(s2.decided))
    np.testing.assert_array_equal(np.asarray(s1.k), np.asarray(s2.k))
    np.testing.assert_array_equal(np.asarray(s1.killed), np.asarray(s2.killed))


@pytest.mark.parametrize("mesh_shape", MESH_SHAPES)
@pytest.mark.parametrize("path", ["dense", "histogram"])
@pytest.mark.slow
def test_sharded_bit_identical_quorum_uniform(mesh_shape, path):
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=T, delivery="quorum",
                    scheduler="uniform", path=path, seed=7)
    a, b = _run_pair(cfg, mesh_shape)
    _assert_identical(a, b)


@pytest.mark.parametrize("mesh_shape", [(2, 4), (8, 1)])
@pytest.mark.slow
def test_sharded_bit_identical_all_delivery(mesh_shape):
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=T, delivery="all", seed=1)
    a, b = _run_pair(cfg, mesh_shape)
    _assert_identical(a, b)


@pytest.mark.parametrize("mesh_shape", [(1, 8), (4, 2)])
@pytest.mark.slow
def test_sharded_bit_identical_common_coin_adversarial(mesh_shape):
    # The adversarial scheduler forces livelock under private coins; the
    # common coin must still converge identically on every mesh shape.
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=T, delivery="quorum",
                    scheduler="adversarial", coin_mode="common", seed=5)
    a, b = _run_pair(cfg, mesh_shape)
    _assert_identical(a, b)


@pytest.mark.parametrize("mesh_shape", [(2, 4)])
@pytest.mark.slow
def test_sharded_bit_identical_byzantine(mesh_shape):
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=T, delivery="quorum",
                    scheduler="uniform", fault_model="byzantine", seed=11)
    a, b = _run_pair(cfg, mesh_shape)
    _assert_identical(a, b)


def test_mesh_divisibility_validated():
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=4, delivery="all")
    faults = FaultSpec.from_faulty_list(cfg, FAULTY)
    state = init_state(cfg, VALS, faults)
    with pytest.raises(ValueError, match="evenly divide"):
        run_consensus_sharded(cfg, state, faults, jax.random.key(0),
                              make_mesh(8, 1))


@pytest.mark.slow
def test_backend_mesh_shape_switch():
    """TpuNetwork honors cfg.mesh_shape end-to-end via the parity API."""
    from benor_tpu.api import launch_network, start_consensus

    net_single = launch_network(N, F, VALS, FAULTY, delivery="quorum",
                                trials=T, seed=7)
    net_mesh = launch_network(N, F, VALS, FAULTY, delivery="quorum",
                              trials=T, seed=7, mesh_shape=(2, 4))
    start_consensus(net_single)
    start_consensus(net_mesh)
    assert net_single.get_states() == net_mesh.get_states()


# --- sliced mid-run observability under a mesh (r4 VERDICT task 5) -----

def _poll_net(mesh_shape, poll_rounds, **kw):
    from benor_tpu.api import launch_network
    n, f = 12, 6                                  # F = N/2 livelock
    vals = [1, 1, 0, 0] * 3
    faulty = [True] * f + [False] * (n - f)
    return launch_network(n, f, vals, faulty, backend="tpu", seed=5,
                          delivery="quorum", trials=2, max_rounds=12,
                          mesh_shape=mesh_shape, poll_rounds=poll_rounds,
                          **kw)


@pytest.mark.parametrize("mesh_shape", [(2, 2), (1, 4)])
def test_poll_rounds_sharded_bit_identical(mesh_shape):
    """cfg.poll_rounds now composes with mesh_shape: the sliced sharded
    run's final state and rounds_executed match BOTH the one-shot sharded
    run and the single-device run exactly."""
    nets = {}
    for label, ms, pr in (("sliced", mesh_shape, 2),
                          ("oneshot", mesh_shape, 0),
                          ("single", None, 0)):
        net = _poll_net(ms, pr)
        net.start()
        nets[label] = net
    assert (nets["sliced"].rounds_executed == nets["oneshot"].rounds_executed
            == nets["single"].rounds_executed)
    for trial in (0, 1):
        assert (nets["sliced"].get_states(trial)
                == nets["oneshot"].get_states(trial)
                == nets["single"].get_states(trial))


def test_poll_rounds_sharded_observes_live_network():
    """Mid-run snapshots under a 4-device mesh show a live undecided
    network with k growing across slices (the reference's poll-during-run
    contract, benorconsensus.test.ts:149-160, now off the single device)."""
    net = _poll_net((2, 2), 1)
    snaps = []
    net.start(on_slice=lambda: snaps.append(net.get_state(7)))
    assert len(snaps) >= 10
    ks = [s["k"] for s in snaps]
    assert all(s["decided"] is False for s in snaps)
    assert ks == sorted(ks) and len(set(ks)) >= 10
    assert net.get_state(7)["k"] > 10             # livelock parity (:341)
