"""Multi-chip sharding tests (SURVEY.md N7, §7 stage 6 + hard-part 5).

Runs on the 8-device virtual CPU mesh forced by conftest.py.  The core
contract: the shard_map'd runner is BIT-IDENTICAL to the single-device run
for every mesh shape, every compute path, every scheduler — because RNG keys
derive from global (trial, node, round) ids, never shard-local order.
"""

import jax
import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.parallel import make_mesh, run_consensus_sharded
from benor_tpu.sim import run_consensus
from benor_tpu.state import FaultSpec, init_state

N, F, T = 16, 4, 8
FAULTY = [True] * F + [False] * (N - F)
VALS = [i % 2 for i in range(N)]
MESH_SHAPES = [(1, 8), (2, 4), (4, 2), (8, 1), (1, 1), (2, 2)]


def _run_pair(cfg, mesh_shape):
    faults = FaultSpec.from_faulty_list(cfg, FAULTY)
    state = init_state(cfg, VALS, faults)
    key = jax.random.key(cfg.seed)
    r1, s1 = run_consensus(cfg, state, faults, key)
    mesh = make_mesh(*mesh_shape)
    r2, s2 = run_consensus_sharded(cfg, state, faults, key, mesh)
    return (r1, s1), (r2, s2)


def _assert_identical(a, b):
    (r1, s1), (r2, s2) = a, b
    assert int(r1) == int(r2)
    np.testing.assert_array_equal(np.asarray(s1.x), np.asarray(s2.x))
    np.testing.assert_array_equal(np.asarray(s1.decided),
                                  np.asarray(s2.decided))
    np.testing.assert_array_equal(np.asarray(s1.k), np.asarray(s2.k))
    np.testing.assert_array_equal(np.asarray(s1.killed), np.asarray(s2.killed))


@pytest.mark.parametrize("mesh_shape", MESH_SHAPES)
@pytest.mark.parametrize("path", ["dense", "histogram"])
@pytest.mark.slow
def test_sharded_bit_identical_quorum_uniform(mesh_shape, path):
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=T, delivery="quorum",
                    scheduler="uniform", path=path, seed=7)
    a, b = _run_pair(cfg, mesh_shape)
    _assert_identical(a, b)


@pytest.mark.parametrize("mesh_shape", [(2, 4), (8, 1)])
@pytest.mark.slow
def test_sharded_bit_identical_all_delivery(mesh_shape):
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=T, delivery="all", seed=1)
    a, b = _run_pair(cfg, mesh_shape)
    _assert_identical(a, b)


@pytest.mark.parametrize("mesh_shape", [(1, 8), (4, 2)])
@pytest.mark.slow
def test_sharded_bit_identical_common_coin_adversarial(mesh_shape):
    # The adversarial scheduler forces livelock under private coins; the
    # common coin must still converge identically on every mesh shape.
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=T, delivery="quorum",
                    scheduler="adversarial", coin_mode="common", seed=5)
    a, b = _run_pair(cfg, mesh_shape)
    _assert_identical(a, b)


@pytest.mark.parametrize("mesh_shape", [(2, 4)])
@pytest.mark.slow
def test_sharded_bit_identical_byzantine(mesh_shape):
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=T, delivery="quorum",
                    scheduler="uniform", fault_model="byzantine", seed=11)
    a, b = _run_pair(cfg, mesh_shape)
    _assert_identical(a, b)


def test_mesh_divisibility_validated():
    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=4, delivery="all")
    faults = FaultSpec.from_faulty_list(cfg, FAULTY)
    state = init_state(cfg, VALS, faults)
    with pytest.raises(ValueError, match="evenly divide"):
        run_consensus_sharded(cfg, state, faults, jax.random.key(0),
                              make_mesh(8, 1))


@pytest.mark.slow
def test_backend_mesh_shape_switch():
    """TpuNetwork honors cfg.mesh_shape end-to-end via the parity API."""
    from benor_tpu.api import launch_network, start_consensus

    net_single = launch_network(N, F, VALS, FAULTY, delivery="quorum",
                                trials=T, seed=7)
    net_mesh = launch_network(N, F, VALS, FAULTY, delivery="quorum",
                              trials=T, seed=7, mesh_shape=(2, 4))
    start_consensus(net_single)
    start_consensus(net_mesh)
    assert net_single.get_states() == net_mesh.get_states()
