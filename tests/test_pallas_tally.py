"""Pallas dense-tally kernel: bit-parity with the XLA einsum path.

Runs in interpreter mode (tests are on CPU); the kernel itself is
TPU-shaped (128-lane one-hot, MXU matmul per receiver tile).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from benor_tpu.config import SimConfig
from benor_tpu.ops.pallas_tally import dense_counts_pallas
from benor_tpu.ops.tally import dense_counts
from benor_tpu.sim import simulate


@pytest.mark.parametrize("shape", [(2, 64, 64), (1, 128, 128),
                                   (3, 120, 120), (2, 200, 200)])
@pytest.mark.slow
def test_kernel_matches_xla_dense_counts(shape):
    T, R, S = shape
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    mask = jax.random.bernoulli(k1, 0.7, (T, R, S))
    sent = jax.random.randint(k2, (T, S), 0, 3).astype(jnp.int8)
    alive = jax.random.bernoulli(k3, 0.9, (T, S))
    ref = np.asarray(dense_counts(mask, sent, alive))
    out = np.asarray(dense_counts_pallas(mask, sent, alive, interpret=True))
    np.testing.assert_array_equal(out, ref)


def test_counts_respect_alive_and_mask():
    T, R, S = 1, 8, 16
    mask = jnp.ones((T, R, S), bool)
    sent = jnp.zeros((T, S), jnp.int8).at[0, :5].set(1)
    alive = jnp.ones((T, S), bool).at[0, 0].set(False)  # a dead 1-sender
    out = np.asarray(dense_counts_pallas(mask, sent, alive, interpret=True))
    assert (out[0, :, 1] == 4).all()      # 5 ones minus the dead one
    assert (out[0, :, 0] == 11).all()
    assert (out[0, :, 2] == 0).all()


@pytest.mark.slow
def test_end_to_end_pallas_equals_xla():
    """Full consensus runs produce identical results with/without pallas."""
    n, f, trials = 60, 15, 16
    vals = np.random.default_rng(3).integers(0, 2, (trials, n), np.int8)
    faulty = [True] * f + [False] * (n - f)
    base = SimConfig(n_nodes=n, n_faulty=f, trials=trials, max_rounds=48,
                     delivery="quorum", scheduler="uniform", path="dense",
                     seed=3)
    r1, f1, _ = simulate(base, vals, faulty)
    r2, f2, _ = simulate(base.replace(use_pallas=True), vals, faulty)
    assert int(r1) == int(r2)
    np.testing.assert_array_equal(np.asarray(f1.x), np.asarray(f2.x))
    np.testing.assert_array_equal(np.asarray(f1.k), np.asarray(f2.k))
    np.testing.assert_array_equal(np.asarray(f1.decided),
                                  np.asarray(f2.decided))
