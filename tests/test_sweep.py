"""Science-harness tests: sweep points, curves, coin comparison, CLI."""

import json

import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.sweep import (balanced_inputs, baseline_configs,
                             coin_comparison, record_trajectory, rounds_vs_f,
                             run_point, save_points)


@pytest.mark.slow
def test_run_point_summary_consistency():
    cfg = SimConfig(n_nodes=50, n_faulty=10, trials=64, max_rounds=32,
                    delivery="quorum", scheduler="uniform", seed=5)
    pt = run_point(cfg)
    assert pt.decided_frac == pytest.approx(1.0)
    assert 2.0 <= pt.mean_k <= 10.0
    # histogram mass equals number of decided healthy lanes
    assert pt.k_hist.sum() == 64 * 40
    # histogram mean matches mean_k
    ks = np.arange(len(pt.k_hist))
    assert (ks * pt.k_hist).sum() / pt.k_hist.sum() == pytest.approx(
        pt.mean_k, abs=1e-3)
    assert pt.trials_per_sec > 0


@pytest.mark.slow
def test_rounds_vs_f_monotone_ish():
    """More faults -> fewer live senders -> never *faster* on average."""
    cfg = SimConfig(n_nodes=40, n_faulty=0, trials=96, max_rounds=48,
                    delivery="quorum", scheduler="uniform", seed=6)
    pts = rounds_vs_f(cfg, [0, 8, 16], verbose=False)
    assert [p.n_faulty for p in pts] == [0, 8, 16]
    assert all(p.decided_frac == pytest.approx(1.0) for p in pts)
    assert pts[0].mean_k <= pts[-1].mean_k + 0.5  # noise tolerance


@pytest.mark.slow
def test_coin_comparison_adversarial_contrast():
    """Count-controlling adversary: private coin livelocks, common escapes.

    F must be >> sqrt(N) for a durable livelock (see coin_comparison
    docstring): N=100, F=40 gives a per-round escape chance of
    ~2*Phi(-4) ~ 6e-5, so 24 rounds decide with prob < 0.2%.
    """
    cfg = SimConfig(n_nodes=100, n_faulty=40, trials=64, max_rounds=24,
                    seed=7)
    res = coin_comparison(cfg, verbose=False)
    assert res["private"][0].decided_frac < 0.05
    assert res["common"][0].decided_frac == pytest.approx(1.0)
    assert res["common"][0].mean_k <= 6.0


def test_coin_comparison_rejects_odd_quorum():
    cfg = SimConfig(n_nodes=21, n_faulty=6, trials=4)
    with pytest.raises(ValueError, match="even quorum"):
        coin_comparison(cfg, verbose=False)


@pytest.mark.slow
def test_trajectory_endpoint_matches_run_consensus():
    """Fixed-round scan == early-exit while_loop once everything settled
    (decided lanes freeze; settled rounds are state no-ops)."""
    import jax

    from benor_tpu.sim import run_consensus
    from benor_tpu.state import FaultSpec, init_state

    cfg = SimConfig(n_nodes=48, n_faulty=18, trials=16, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=64,
                    seed=3)
    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes), faults)
    key = jax.random.key(cfg.seed)
    rounds, final = run_consensus(cfg, state, faults, key)
    n_rounds = int(rounds) + 3                # strictly past termination
    final_t, traj = record_trajectory(cfg, state, faults, key, n_rounds)
    np.testing.assert_array_equal(np.asarray(final_t.x), np.asarray(final.x))
    np.testing.assert_array_equal(np.asarray(final_t.decided),
                                  np.asarray(final.decided))
    np.testing.assert_array_equal(np.asarray(final_t.k), np.asarray(final.k))
    dec = np.asarray(traj["decided"])
    assert dec.shape == (n_rounds,)
    assert (np.diff(dec) >= -1e-6).all()      # decided fraction is monotone
    assert dec[-1] == 1.0
    shares = (np.asarray(traj["zeros"]) + np.asarray(traj["ones"])
              + np.asarray(traj["qs"]))
    np.testing.assert_allclose(shares, 1.0, atol=1e-5)


def test_trajectory_shows_adversarial_q_flood():
    """Under the tie-forcing adversary the round-resolved signature is a
    standing '?' majority and decided == 0 — visible ONLY in a trajectory
    (the endpoint alone cannot distinguish livelock shapes)."""
    import jax

    from benor_tpu.state import FaultSpec, init_state

    cfg = SimConfig(n_nodes=100, n_faulty=40, trials=8, delivery="quorum",
                    scheduler="adversarial", coin_mode="private",
                    path="histogram", max_rounds=8, seed=5)
    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes), faults)
    _, traj = record_trajectory(cfg, state, faults, jax.random.key(5), 6)
    assert (np.asarray(traj["decided"]) == 0.0).all()
    # after round 1's tied proposal tally every live lane votes "?" and
    # then coins; the standing x-share of "?" stays 0 (x is post-coin) but
    # the adversary keeps decided flat — contrast with the uniform run
    assert (np.asarray(traj["disagree"]) == 0.0).all()


class TestWeakCommonCoin:
    """coin_mode='weak_common': the eps-interpolation between shared and
    private coins, against the count-controlling adversary (N=100, F=40 —
    F >> sqrt(N) so the private limit's livelock persists)."""

    def _run(self, eps, max_rounds=24, trials=64, seed=3):
        import jax

        from benor_tpu.sim import run_consensus
        from benor_tpu.state import FaultSpec, init_state
        from benor_tpu.sweep import balanced_inputs

        cfg = SimConfig(n_nodes=100, n_faulty=40, trials=trials,
                        delivery="quorum", scheduler="adversarial",
                        coin_mode="weak_common", coin_eps=eps,
                        max_rounds=max_rounds, seed=seed)
        faults = FaultSpec.none(trials, 100)
        state = init_state(cfg, balanced_inputs(trials, 100), faults)
        r, final = run_consensus(cfg, state, faults, jax.random.key(seed))
        return cfg, int(r), np.asarray(final.decided)

    @pytest.mark.slow
    def test_limits_and_transition(self):
        # eps=0 ~ common: O(1) rounds; eps=1 ~ private: livelock;
        # decided fraction is monotone non-increasing across the grid
        _, r0, d0 = self._run(0.0)
        assert d0.all() and r0 <= 4
        _, r1, d1 = self._run(1.0)
        assert not d1.any() and r1 == 24
        fracs = [self._run(e)[2].mean() for e in (0.2, 0.5, 0.7, 0.9)]
        assert all(a >= b - 1e-9 for a, b in zip(fracs, fracs[1:])), fracs
        # the transition brackets the predicted eps* = 1 - f = 0.6
        assert fracs[1] > 0.9 and fracs[-1] < 0.5, fracs

    @pytest.mark.slow
    def test_mesh_bit_identity(self):
        import jax

        from benor_tpu.parallel import make_mesh, run_consensus_sharded
        from benor_tpu.sim import run_consensus
        from benor_tpu.state import FaultSpec, init_state
        from benor_tpu.sweep import balanced_inputs

        cfg = SimConfig(n_nodes=32, n_faulty=12, trials=4,
                        delivery="quorum", scheduler="adversarial",
                        coin_mode="weak_common", coin_eps=0.75,
                        max_rounds=12, seed=5, path="histogram")
        faults = FaultSpec.none(4, 32)
        state = init_state(cfg, balanced_inputs(4, 32), faults)
        key = jax.random.key(5)
        r1, f1 = run_consensus(cfg, state, faults, key)
        r2, f2 = run_consensus_sharded(cfg, state, faults, key,
                                       make_mesh(2, 4))
        assert int(r1) == int(r2)
        np.testing.assert_array_equal(np.asarray(f1.x), np.asarray(f2.x))
        np.testing.assert_array_equal(np.asarray(f1.k), np.asarray(f2.k))

    def test_config_validation(self):
        with pytest.raises(ValueError, match="coin_eps"):
            SimConfig(n_nodes=4, n_faulty=0, coin_eps=1.5,
                      coin_mode="weak_common")
        with pytest.raises(ValueError, match="weak_common"):
            SimConfig(n_nodes=4, n_faulty=0, coin_eps=0.5)

    @pytest.mark.slow
    def test_critical_line_shifts_under_equivocation(self):
        """Weak coins vs EQUIVOCATING adversaries compose predictably: the
        adversary ties iff deviating-minority + free pool reach the tie
        target, so the critical deviation moves to
        eps*(f) = 1 - 2F/(N-F) — below the crash-free eps* = 1 - f.
        At N=99, F=21: eps* ~ 0.46; straddle it."""
        import jax

        from benor_tpu.sim import run_consensus
        from benor_tpu.state import FaultSpec, init_state
        from benor_tpu.sweep import balanced_inputs

        n, f, trials = 99, 21, 48
        for eps, decides in ((0.2, True), (0.8, False)):
            cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials,
                            delivery="quorum", scheduler="adversarial",
                            fault_model="equivocate",
                            coin_mode="weak_common", coin_eps=eps,
                            max_rounds=20, seed=7)
            faults = FaultSpec.first_f(cfg)
            state = init_state(cfg, balanced_inputs(trials, n), faults)
            r, final = run_consensus(cfg, state, faults, jax.random.key(7))
            dec = np.asarray(final.decided)[:, f:]
            if decides:
                assert dec.mean() > 0.95, (eps, dec.mean())
            else:
                assert dec.mean() < 0.2, (eps, dec.mean())


@pytest.mark.slow
def test_results_generator_end_to_end(tmp_path):
    """The science-deliverable generator (benor_tpu.results.generate) runs
    every study end-to-end at toy scale and writes both artifacts; the
    committed RESULTS/ is this exact pipeline at N=1M on the real chip."""
    from benor_tpu.results import generate

    out = generate(out_dir=str(tmp_path), n_large=400, trials_large=4,
                   presets=False)
    for key in ("balanced_curve", "margin_sweep", "coin_contrast",
                "disagreement", "safety_violation", "equivocation",
                "trajectory", "scaling", "rule_comparison", "weak_coin",
                "oracle_parity"):
        assert key in out, key
    op = out["oracle_parity"]
    assert op["order_invariant_decided_runs"] is True
    assert op["ks_pvalue"] > 0.01
    # targeted adversary: 0/1 safety curve — violated strictly inside
    # (0, 1/2), intact at the edges, livelock past 1/2, and the
    # one-equivocator row always violated
    sv = out["safety_violation"]
    for row in sv:
        if row["fault_model"] == "equivocate":
            assert row["disagree_frac"] == 1.0
        elif "odd" in row["fault_model"]:
            # the parity-weakened attack: violated iff N <= 3F + 1
            assert (row["disagree_frac"] == 1.0) is \
                ("N<3F+1" in row["fault_model"]), row
        elif row["f"] == 0 or row["f"] > 200:     # f=0 / past N/2 at N=400
            assert row["disagree_frac"] == 0.0
        else:
            assert row["disagree_frac"] == 1.0, row
    # the N//3 threshold rows must disagree about decidability (N=400:
    # F=133 has 3F<N, F=134 has 3F>N)
    eq = {r["label"]: r for r in out["equivocation"]}
    assert eq["N//3"]["decided_frac"] == 1.0
    assert eq["N//3+1"]["decided_frac"] == 0.0
    # plurality adoption must converge faster than textbook
    rules = {r["rule"]: r for r in out["rule_comparison"]}
    assert rules["reference"]["mean_k"] < rules["textbook"]["mean_k"]
    # the scaling study must include the requested top point even when it
    # is below the usual 10^3..10^6 ladder
    assert [r["n"] for r in out["scaling"]] == [400]
    md = (tmp_path / "RESULTS.md").read_text()
    assert "N > 3F" in md and "trajectory" in md.lower()
    assert (tmp_path / "results.json").exists()


@pytest.mark.slow
def test_save_points_roundtrip(tmp_path):
    cfg = SimConfig(n_nodes=10, n_faulty=2, trials=8, delivery="quorum",
                    scheduler="uniform", seed=8)
    pts = rounds_vs_f(cfg, [2], verbose=False)
    path = str(tmp_path / "pts.json")
    save_points(path, pts)
    data = json.load(open(path))
    assert data[0]["n_faulty"] == 2
    assert isinstance(data[0]["k_hist"], list)


def test_baseline_presets_valid():
    cfgs = baseline_configs()
    assert set(cfgs) == {"n5_faultfree", "n10k_crash", "n100k_byzantine",
                         "n1m_coin_sweep", "n1m_adversarial"}
    # constructing them validates all fields via __post_init__
    for cfg in cfgs.values():
        assert cfg.n_nodes >= 5


class TestCli:
    def test_demo_default(self, capsys):
        from benor_tpu.__main__ import main
        assert main(["demo", "-n", "6", "-f", "2", "--backend", "tpu"]) == 0
        out = capsys.readouterr().out
        assert out.count("node ") == 6
        assert "'decided': True" in out

    def test_demo_express(self, capsys):
        from benor_tpu.__main__ import main
        assert main(["demo", "-n", "5", "-f", "1",
                     "--backend", "express"]) == 0
        assert "'decided': True" in capsys.readouterr().out

    def test_demo_too_many_faulty(self, capsys):
        from benor_tpu.__main__ import main
        assert main(["demo", "-n", "4", "-f", "3"]) == 1  # start.ts:25-29

    @pytest.mark.slow
    def test_sweep_cli(self, tmp_path, capsys):
        from benor_tpu.__main__ import main
        out = str(tmp_path / "s.json")
        assert main(["sweep", "--n", "12", "--f-values", "0,3",
                     "--trials", "16", "--out", out]) == 0
        assert len(json.load(open(out))) == 2

    def test_results_cli_arg_plumbing(self, monkeypatch):
        """`results` flags reach the generator verbatim (the real generator
        runs in test_results_generator_end_to_end; here only the argparse
        plumbing is under test)."""
        import benor_tpu.results as results_mod
        from benor_tpu.__main__ import main
        called = {}
        monkeypatch.setattr(results_mod, "generate",
                            lambda **kw: called.update(kw))
        assert main(["results", "--out", "X", "--n", "123",
                     "--trials", "4", "--no-presets"]) == 0
        assert called == {"out_dir": "X", "n_large": 123,
                          "trials_large": 4, "seed": 0, "presets": False}
        # no --n/--trials on a CPU backend: the platform-aware smoke
        # defaults (shared constants with bench.py)
        called.clear()
        assert main(["results", "--no-presets"]) == 0
        assert called["n_large"] == 50_000 and called["trials_large"] == 8

    def test_ensure_live_backend_falls_back_on_hang(self, monkeypatch,
                                                    capsys):
        """The axon plugin hangs indefinitely when the chip is
        unreachable; the CLI probes via the shared helper and pins CPU on
        failure instead of hanging the user's terminal — announcing the
        fallback on stdout so captured output stays honest."""
        import benor_tpu.utils.backend as backend_mod

        import benor_tpu.__main__ as cli

        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setattr(backend_mod, "probe_with_retries",
                            lambda *a, **kw: None)
        monkeypatch.setattr(cli, "FELL_BACK", False)
        calls = []
        import jax
        monkeypatch.setattr(jax.config, "update",
                            lambda *a: calls.append(a))
        cli._ensure_live_backend(retries=1, timeout_s=1)
        assert calls == [("jax_platforms", "cpu")]
        assert cli.FELL_BACK
        out = capsys.readouterr()
        assert "falling back to CPU" in out.err
        assert out.out == ""       # stdout stays clean (JSON subcommands)
        # live backend: probe succeeds, nothing overridden
        monkeypatch.setattr(backend_mod, "probe_with_retries",
                            lambda *a, **kw: "axon")
        calls.clear()
        cli._ensure_live_backend(retries=1, timeout_s=1)
        assert calls == []
        # explicit non-axon pins skip the probe entirely (the hang-at-init
        # failure mode is axon-specific; a healthy TPU pays no overhead)
        monkeypatch.setattr(backend_mod, "probe_with_retries",
                            lambda *a, **kw: pytest.fail("probed"))
        for plat in ("cpu", "tpu"):
            monkeypatch.setenv("JAX_PLATFORMS", plat)
            cli._ensure_live_backend(retries=1, timeout_s=1)
        assert calls == []
        # UNSET env still probes when the axon plugin is importable: the
        # plugin self-registers as the ambient default backend, so the
        # hang risk is identical to an explicit JAX_PLATFORMS=axon
        # (ADVICE r3); with the plugin absent, no probe.
        monkeypatch.setenv("JAX_PLATFORMS", "")
        probed = []
        monkeypatch.setattr(backend_mod, "probe_with_retries",
                            lambda *a, **kw: probed.append(1) or "axon")
        import importlib.util
        if importlib.util.find_spec("axon") is not None:
            cli._ensure_live_backend(retries=1, timeout_s=1)
            assert probed == [1]
        real_find_spec = importlib.util.find_spec
        monkeypatch.setattr(importlib.util, "find_spec",
                            lambda name, *a: None if name == "axon"
                            else real_find_spec(name, *a))
        probed.clear()
        cli._ensure_live_backend(retries=1, timeout_s=1)
        assert probed == []
        assert calls == []

    @pytest.mark.slow
    def test_coins_cli_weak_rows(self, capsys):
        from benor_tpu.__main__ import main
        assert main(["coins", "--n", "20", "--f", "6", "--trials", "8",
                     "--max-rounds", "8", "--eps", "0.1"]) == 0
        out = capsys.readouterr().out
        assert "weak_common(eps=0.1):" in out

    @pytest.mark.slow
    def test_sweep_cli_balanced(self, tmp_path, capsys):
        """--balanced: zero crashes + balanced inputs (the science regime);
        points carry the disagree_frac field."""
        from benor_tpu.__main__ import main
        out = str(tmp_path / "sb.json")
        assert main(["sweep", "--n", "24", "--f-values", "4,9",
                     "--trials", "16", "--balanced", "--out", out]) == 0
        pts = json.load(open(out))
        assert len(pts) == 2 and all("disagree_frac" in p for p in pts)
        assert "balanced/no-crash" in capsys.readouterr().out

    @pytest.mark.slow
    def test_sweep_cli_pallas_flag(self, tmp_path, capsys):
        """--pallas on engages the fused flagship flags (adversarial =
        counts_mode 'delivered', active at ANY quorum) and says so in the
        header; --pallas auto on CPU stays off.  Same seed, same closed
        forms + shared common-coin stream => identical points."""
        from benor_tpu.__main__ import main
        outs = {}
        for choice in ("on", "auto"):
            out = str(tmp_path / f"p_{choice}.json")
            assert main(["sweep", "--n", "24", "--f-values", "6",
                         "--trials", "8", "--balanced", "--scheduler",
                         "adversarial", "--coin", "common",
                         "--max-rounds", "8", "--pallas", choice,
                         "--out", out]) == 0
            header = capsys.readouterr().out
            assert (", pallas" in header) == (choice == "on")
            outs[choice] = [
                {k: v for k, v in p.items()
                 if k not in ("seconds", "trials_per_sec")}
                for p in json.load(open(out))]
        assert outs["on"] == outs["auto"]

    @pytest.mark.slow
    def test_coins_cli_pallas_flag(self, capsys):
        from benor_tpu.__main__ import main
        assert main(["coins", "--n", "20", "--f", "6", "--trials", "8",
                     "--max-rounds", "8", "--pallas", "on"]) == 0
        out = capsys.readouterr().out
        assert "private:" in out and "common:" in out


class TestFlagshipFlags:
    def test_cpu_returns_empty(self, monkeypatch):
        import jax

        from benor_tpu import results
        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        assert results._flagship_flags() == {}

    def test_probe_outcome_gates_flags(self, monkeypatch):
        """generate() records the probe outcome in _PROBE_OK; False must
        demote every study's flags to the XLA path, None (no probe — the
        CLI case) and True must return the flagship set."""
        import jax

        from benor_tpu import results
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        for ok, want in ((None, results.FLAGSHIP_FLAGS),
                         (True, results.FLAGSHIP_FLAGS), (False, {})):
            monkeypatch.setattr(results, "_PROBE_OK", ok)
            assert results._flagship_flags() == want

    def test_probe_demotes_only_on_kernel_errors(self, monkeypatch, capsys):
        """Mirror of bench.py's demotion policy: a Mosaic/pallas failure
        returns False (demote); anything else re-raises with correct
        attribution (it would hit the XLA path too)."""
        import benor_tpu.sim as sim
        from benor_tpu import results

        def boom_mosaic(*a, **kw):
            raise RuntimeError("Mosaic lowering failed (simulated)")

        def boom_other(*a, **kw):
            raise RuntimeError("something unrelated")

        n = 20000                      # quorum above the CF gate
        monkeypatch.setattr(sim, "run_consensus", boom_mosaic)
        results._flagship_probe.cache_clear()
        try:
            assert results._flagship_probe(n) is False
            assert "probe failed" in capsys.readouterr().out
            results._flagship_probe.cache_clear()
            monkeypatch.setattr(sim, "run_consensus", boom_other)
            with pytest.raises(RuntimeError, match="unrelated"):
                results._flagship_probe(n)
            # below the CF regime the flags are inert: no compile at all
            results._flagship_probe.cache_clear()
            assert results._flagship_probe(64) is True
        finally:
            results._flagship_probe.cache_clear()

    @pytest.mark.slow
    def test_probe_passes_in_interpret_mode(self):
        """The probe itself runs the fused round (interpret mode on this
        CPU suite) at a CF-regime N and succeeds."""
        from benor_tpu import results
        from benor_tpu.ops import sampling
        results._flagship_probe.cache_clear()
        try:
            assert results._flagship_probe(
                2 * sampling.EXACT_TABLE_MAX) is True
        finally:
            results._flagship_probe.cache_clear()
