"""The 'targeted' partitioned count-controlling adversary (r3 VERDICT item 3).

Pins every claim in ops/tally.py:targeted_counts and config.py:

  * AGREEMENT VIOLATION for every 1 <= F < N/2 (even quorum, balanced
    inputs, no crashes): the healthy network decides BOTH values — the
    sharpest possible safety threshold, sitting exactly at the
    fault-tolerance boundary F = N/2 where the run flips to livelock.
  * The odd-quorum weakening (no phase-1 ties can be manufactured; the
    attack then needs N <= 3F + 1) — a parity effect born of quirk 4.
  * ONE equivocator violates agreement at any N (fault_model='equivocate'
    lets the adversary repair quorum parity and substitute camp members).
  * F = 0 leaves the adversary powerless (quorum N = full delivery).
  * Dense and histogram paths are bit-identical (closed form on both).
  * The closed-form counts are REALIZABLE as an explicit delivery schedule
    (scheduler.realize_counts_mask -> dense_counts reproduces them).
  * The sharded runner is bit-identical to single-device for this
    scheduler (mesh-shape independence).

The contrast the RESULTS 'safety_violation' study records: the
delay-bounded 'biased' scheduler produces a soft probabilistic
disagreement curve (results.py:disagreement_sweep); this adversary's curve
is exactly 0/1 with a step at each boundary.
"""

import jax
import numpy as np
import pytest

from benor_tpu.config import SimConfig, VAL0, VAL1, VALQ
from benor_tpu.ops import scheduler, tally
from benor_tpu.sim import run_consensus
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import balanced_inputs, summarize_final


def _run(n, f, path="histogram", fault_model="crash", trials=4, seed=0,
         max_rounds=16):
    cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials, delivery="quorum",
                    scheduler="targeted", path=path, fault_model=fault_model,
                    max_rounds=max_rounds, seed=seed)
    faults = (FaultSpec.first_f(cfg) if fault_model == "equivocate"
              else FaultSpec.none(trials, n))
    state = init_state(cfg, balanced_inputs(trials, n), faults)
    r, final = run_consensus(cfg, state, faults, jax.random.key(seed))
    dec, _, _, _, disagree = summarize_final(final, faults.faulty,
                                             cfg.max_rounds)
    return int(r), float(dec), float(disagree), final, faults


@pytest.mark.parametrize("n,f", [(100, 2), (100, 10), (100, 26), (100, 48),
                                 (1000, 400)])
@pytest.mark.slow
def test_agreement_violated_below_half_even_quorum(n, f):
    assert (n - f) % 2 == 0, "cases must have an even quorum"
    _, dec, disagree, final, faults = _run(n, f)
    assert disagree == 1.0, "every trial must decide both values"
    # both camps really decided (not a ?-value artifact)
    hd = np.asarray(final.decided) & ~np.asarray(faults.faulty)
    x = np.asarray(final.x)
    assert ((x == VAL0) & hd).any(axis=-1).all()
    assert ((x == VAL1) & hd).any(axis=-1).all()


@pytest.mark.parametrize("n,f", [(100, 50), (100, 60), (99, 50)])
def test_livelock_at_and_above_half(n, f):
    r, dec, disagree, _, _ = _run(n, f)
    assert dec == 0.0 and disagree == 0.0
    assert r == 16, "must run to the cap"


def test_powerless_at_f_zero():
    _, dec, disagree, final, faults = _run(100, 0)
    assert dec == 1.0 and disagree == 0.0
    hd = np.asarray(final.decided)
    x = np.asarray(final.x)
    # agreement is PER TRIAL: with F=0 the tie-broken coin decides each
    # trial independently, so different trials may legitimately land on
    # different values — only a within-trial split would mean adversary
    # power survived F=0
    for t in range(x.shape[0]):
        assert len(np.unique(x[t][hd[t]])) == 1


@pytest.mark.parametrize("n,f,violates", [(100, 5, False), (100, 35, True)])
def test_odd_quorum_weakening(n, f, violates):
    """No "?" can be manufactured (no perfect phase-1 ties), so the attack
    needs the starved fill itself to stay under the bar: N <= 3F + 1."""
    assert (n - f) % 2 == 1
    _, _, disagree, _, _ = _run(n, f)
    assert (disagree == 1.0) is violates


@pytest.mark.parametrize("n", [10, 100, 999])
def test_single_equivocator_splits_any_n(n):
    _, dec, disagree, _, _ = _run(n, 1, fault_model="equivocate")
    assert disagree == 1.0


@pytest.mark.parametrize("n,f,fault_model", [
    (64, 16, "crash"), (64, 31, "crash"), (65, 16, "crash"),
    (64, 4, "equivocate")])
@pytest.mark.slow
def test_dense_histogram_bit_identical(n, f, fault_model):
    r1, _, _, fin1, _ = _run(n, f, "dense", fault_model)
    r2, _, _, fin2, _ = _run(n, f, "histogram", fault_model)
    assert r1 == r2
    np.testing.assert_array_equal(np.asarray(fin1.x), np.asarray(fin2.x))
    np.testing.assert_array_equal(np.asarray(fin1.decided),
                                  np.asarray(fin2.decided))
    np.testing.assert_array_equal(np.asarray(fin1.k), np.asarray(fin2.k))


@pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2)])
@pytest.mark.slow
def test_sharded_bit_identical(mesh_shape):
    from benor_tpu.parallel import make_mesh, run_consensus_sharded
    cfg = SimConfig(n_nodes=16, n_faulty=4, trials=8, delivery="quorum",
                    scheduler="targeted", path="histogram", max_rounds=16,
                    seed=3)
    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes), faults)
    key = jax.random.key(cfg.seed)
    r1, s1 = run_consensus(cfg, state, faults, key)
    r2, s2 = run_consensus_sharded(cfg, state, faults, key,
                                   make_mesh(*mesh_shape))
    assert int(r1) == int(r2)
    np.testing.assert_array_equal(np.asarray(s1.x), np.asarray(s2.x))
    np.testing.assert_array_equal(np.asarray(s1.decided),
                                  np.asarray(s2.decided))
    np.testing.assert_array_equal(np.asarray(s1.k), np.asarray(s2.k))


class TestRealizability:
    """The closed forms describe deliveries an asynchronous network could
    actually exhibit: realize_counts_mask builds an explicit per-edge
    schedule whose dense_counts reproduce the counts bit-for-bit."""

    def _random_population(self, key, trials, n):
        k1, k2 = jax.random.split(key)
        sent = jax.random.randint(k1, (trials, n), 0, 3).astype(np.int8)
        alive = np.array(jax.random.bernoulli(k2, 0.9, (trials, n)))
        return np.array(sent), alive

    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.slow
    def test_targeted_counts_realizable(self, seed):
        trials, n, f = 8, 64, 20
        cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials,
                        delivery="quorum", scheduler="targeted",
                        path="dense", seed=seed)
        sent, alive = self._random_population(jax.random.key(seed), trials, n)
        # live population must cover the quorum for the sum-to-m contract
        alive[:, : cfg.quorum] = True
        import jax.numpy as jnp
        hist = tally.class_histogram(jnp.asarray(sent), jnp.asarray(alive))
        counts = tally.targeted_counts(cfg, hist, np.arange(n))
        mask = scheduler.realize_counts_mask(counts, jnp.asarray(sent),
                                             jnp.asarray(alive))
        realized = tally.dense_counts(mask, jnp.asarray(sent),
                                      jnp.asarray(alive))
        np.testing.assert_array_equal(np.asarray(realized),
                                      np.asarray(counts))
        assert (np.asarray(counts).sum(-1) == cfg.quorum).all()

    @pytest.mark.parametrize("seed", [0, 3])
    def test_adversarial_counts_realizable(self, seed):
        """The tie-forcing adversary's counts are realizable too — the
        witness covers both count-controlling schedulers."""
        trials, n, m = 8, 64, 44
        sent, alive = self._random_population(jax.random.key(seed), trials, n)
        alive[:, :m] = True
        import jax.numpy as jnp
        hist = tally.class_histogram(jnp.asarray(sent), jnp.asarray(alive))
        counts = jnp.broadcast_to(
            tally.adversarial_counts(hist, m)[:, None, :], (trials, n, 3))
        mask = scheduler.realize_counts_mask(counts, jnp.asarray(sent),
                                             jnp.asarray(alive))
        realized = tally.dense_counts(mask, jnp.asarray(sent),
                                      jnp.asarray(alive))
        np.testing.assert_array_equal(np.asarray(realized),
                                      np.asarray(counts))


def test_oracle_backends_reject_targeted():
    """The event-loop oracles replicate the reference exactly; the
    framework-only adversary must fail loudly there (api.py guard)."""
    from benor_tpu.api import launch_network
    for backend in ("express", "native"):
        with pytest.raises(ValueError, match="scheduler='uniform'"):
            launch_network(6, 2, [1] * 6, [True] * 2 + [False] * 4,
                           backend=backend, scheduler="targeted",
                           delivery="quorum")


def test_camp_sizes():
    cfg = SimConfig(n_nodes=100, n_faulty=10, delivery="quorum",
                    scheduler="targeted")
    assert tally.targeted_camp_sizes(cfg) == (11, 0)
    cfg = cfg.replace(fault_model="equivocate")
    assert tally.targeted_camp_sizes(cfg) == (1, 10)
    cfg = cfg.replace(n_faulty=3)
    assert tally.targeted_camp_sizes(cfg) == (1, 3)
