"""Promotion guards of the on-chip recapture daemon (recapture.py).

The daemon's whole value is unattended honesty: it must promote
BENCH_TPU.json / RESULTS/ ONLY for genuine on-chip runs and never let a
CPU fallback or a garbled bench overwrite captured artifacts (two such
bugs were caught in review — these are their regression pins).  The
subprocess layer is stubbed; the worktree/probe plumbing is driven for
real by the round workflow itself.
"""

import importlib.util
import json
import os
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def recap(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "recap_under_test", os.path.join(ROOT, "recapture.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    here = tmp_path / "repo"
    cap = here / ".capture"
    wt = cap / "wt"
    for d in (here, cap, wt):
        d.mkdir(parents=True)
    monkeypatch.setattr(m, "HERE", str(here))
    monkeypatch.setattr(m, "CAP", str(cap))
    monkeypatch.setattr(m, "WT", str(wt))
    monkeypatch.setattr(m, "STATE", str(cap / "state.json"))
    monkeypatch.setattr(m, "LOGF", str(cap / "recapture.log"))
    return m


def _stub_run(monkeypatch, m, stdout="", rc=0, detail=None, results_meta=...):
    """Swap the MODULE's subprocess binding for a canned-run namespace.

    Patching ``m.subprocess.run`` directly would stub the stdlib
    singleton for every subprocess user (git helpers included); replacing
    the module attribute confines the stub to recapture.py."""
    from types import SimpleNamespace

    def fake_run(cmd, **kw):
        if detail is not None:
            with open(os.path.join(m.WT, "BENCH_DETAIL.json"), "w") as fh:
                json.dump(detail, fh)
        if results_meta is not ...:
            out_dir = [c for c in cmd if "RESULTS" in str(c)][-1]
            os.makedirs(out_dir, exist_ok=True)
            with open(os.path.join(out_dir, "results.json"), "w") as fh:
                json.dump({"meta": results_meta}, fh)
        return subprocess.CompletedProcess(cmd, rc, stdout=stdout, stderr="")

    monkeypatch.setattr(m, "subprocess", SimpleNamespace(
        run=fake_run, CompletedProcess=subprocess.CompletedProcess,
        TimeoutExpired=subprocess.TimeoutExpired,
        CalledProcessError=subprocess.CalledProcessError))


GOOD = {"metric": "mc_trials_per_sec_n1e6", "value": 950.0,
        "unit": "trials/s", "vs_baseline": 63.2, "platform": "tpu",
        "fallback_cpu": False}


def test_bench_promotes_genuine_on_chip_run(recap, monkeypatch):
    _stub_run(monkeypatch, recap, stdout=json.dumps(GOOD) + "\n",
              detail={"curve": []})
    assert recap.run_bench("abc123def") is True
    out = json.load(open(os.path.join(recap.HERE, "BENCH_TPU.json")))
    assert out["platform"] == "tpu" and out["capture"]["sha"] == "abc123def"
    assert os.path.exists(os.path.join(recap.HERE, "BENCH_DETAIL.json"))


@pytest.mark.parametrize("stdout", [
    "",                                         # no JSON line at all
    "bench: something went sideways\n",         # non-JSON final line
    json.dumps({"capture": "no-metric"}),       # JSON but not emit()'s
    json.dumps({**GOOD, "platform": "cpu"}),    # ran on CPU
    json.dumps({**GOOD, "fallback_cpu": True}),  # mid-run fallback
    json.dumps({**GOOD, "error": "boom"}),      # bench-internal error
], ids=["empty", "nonjson", "not-emit", "cpu", "fallback", "error"])
def test_bench_never_promotes_dishonest_runs(recap, monkeypatch, stdout):
    _stub_run(monkeypatch, recap, stdout=stdout)
    assert recap.run_bench("abc") is False
    assert not os.path.exists(os.path.join(recap.HERE, "BENCH_TPU.json"))


def test_bench_rc_failure_not_promoted(recap, monkeypatch):
    _stub_run(monkeypatch, recap, stdout=json.dumps(GOOD), rc=3)
    assert recap.run_bench("abc") is False


def test_results_promotes_only_on_chip_and_stages_first(recap, monkeypatch):
    # CPU-fallback artifact: staged, checked, NOT promoted — the main
    # repo's RESULTS/ (here: pre-existing on-chip capture) must survive
    out_dir = os.path.join(recap.HERE, "RESULTS")
    os.makedirs(out_dir)
    with open(os.path.join(out_dir, "results.json"), "w") as fh:
        json.dump({"meta": {"platform": "tpu", "n_large": 1_000_000}}, fh)
    _stub_run(monkeypatch, recap, results_meta={"platform": "cpu"})
    assert recap.run_results("abc") is False
    kept = json.load(open(os.path.join(out_dir, "results.json")))
    assert kept["meta"]["platform"] == "tpu"        # untouched

    # genuine on-chip artifact: promoted atomically from the staging dir
    _stub_run(monkeypatch, recap,
              results_meta={"platform": "TPU v5 lite", "n_large": 1_000_000})
    assert recap.run_results("abc") is True
    got = json.load(open(os.path.join(out_dir, "results.json")))
    assert got["meta"]["n_large"] == 1_000_000
    assert not os.path.exists(os.path.join(recap.CAP, "RESULTS.stage"))


def test_state_roundtrip(recap):
    recap.save_state({"bench_sha": "x"})
    assert recap.load_state() == {"bench_sha": "x"}


@pytest.mark.parametrize("meta", [{}, {"platform": ""}, {"n_large": 5}],
                         ids=["empty-meta", "empty-platform", "no-platform"])
def test_results_fails_closed_on_unverifiable_artifact(recap, monkeypatch,
                                                       meta):
    """An artifact that cannot AFFIRM an accelerator (corrupt/missing
    meta.platform) must not be promoted — absence of 'cpu' is not
    evidence of 'tpu'."""
    out_dir = os.path.join(recap.HERE, "RESULTS")
    os.makedirs(out_dir)
    with open(os.path.join(out_dir, "results.json"), "w") as fh:
        json.dump({"meta": {"platform": "tpu"}}, fh)
    _stub_run(monkeypatch, recap, results_meta=meta)
    assert recap.run_results("abc") is False
    kept = json.load(open(os.path.join(out_dir, "results.json")))
    assert kept["meta"]["platform"] == "tpu"


@pytest.mark.parametrize("raw", ['[1, 2]', '{"meta": "tpu"}', '{corrupt'],
                         ids=["list-top", "string-meta", "invalid-json"])
def test_results_fails_closed_on_structurally_corrupt_artifact(
        recap, monkeypatch, raw):
    """Corruption that isn't even a meta-dict must log-and-return-False,
    not kill the retry-forever daemon with an AttributeError."""
    from types import SimpleNamespace

    def fake_run(cmd, **kw):
        out_dir = [c for c in cmd if "RESULTS" in str(c)][-1]
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "results.json"), "w") as fh:
            fh.write(raw)
        return subprocess.CompletedProcess(cmd, 0, stdout="", stderr="")

    monkeypatch.setattr(recap, "subprocess", SimpleNamespace(
        run=fake_run, TimeoutExpired=subprocess.TimeoutExpired))
    assert recap.run_results("abc") is False
