"""The reference's integration-test contract, ported scenario-for-scenario.

Source: /root/reference/__test__/tests/benorconsensus.test.ts (SURVEY.md §4
scenario matrix).  Every scenario runs on BOTH backends — the TPU
device-array simulator and the express-style event-loop oracle — and must
produce the same observable verdicts; this is the differential-parity
harness the reference's grading suite becomes.
"""

import numpy as np
import pytest

from benor_tpu.api import (get_nodes_state, launch_network, reached_finality,
                           start_consensus, stop_consensus)

BACKENDS = ["tpu", "express"]
# The express oracle runs every scenario under BOTH legal delivery
# serializations (cfg.oracle_order — the reference's fire-and-forget fetches
# make any interleaving legal, SURVEY §5.8).  The tpu backend has no event
# loop; its delivery model is the N9 scheduler, so order is moot there.
BACKEND_ORDERS = [("tpu", "fifo"), ("express", "fifo"),
                  ("express", "shuffle")]


def _launch(faulty, values, backend, **kw):
    return launch_network(len(faulty), sum(faulty), values, faulty,
                          backend=backend, **kw)


def _run_to_finality(net):
    """The tests' poll loop (benorconsensus.test.ts:149-160) collapsed:
    start() returns with the network already settled or at its round cap."""
    start_consensus(net)
    return get_nodes_state(net)


@pytest.mark.parametrize("backend", BACKENDS)
class TestSetup:
    """'Project is setup correctly' — status codes (test.ts:45-118)."""

    def test_status_2_healthy_1_faulty(self, backend):
        net = _launch([True, False, False], [1, 1, 1], backend)
        for i, faulty in enumerate([True, False, False]):
            body, code = net.status(i)
            if faulty:
                assert (body, code) == ("faulty", 500)
            else:
                assert (body, code) == ("live", 200)
        net.close()

    def test_status_8_healthy_2_faulty(self, backend):
        faulty = [True, False, False, False, False, True, False, False,
                  False, False]
        net = _launch(faulty, [1] * 10, backend)
        for i, f in enumerate(faulty):
            body, code = net.status(i)
            assert (body, code) == (("faulty", 500) if f else ("live", 200))
        net.close()


@pytest.mark.parametrize("backend,order", BACKEND_ORDERS)
class TestBenOr:
    """'Testing Ben-Or implementation' (test.ts:120-492)."""

    def _assert_faulty_null(self, state):
        # faulty fields are all null (e.g. test.ts:164-167)
        assert state["decided"] is None
        assert state["x"] is None
        assert state["k"] is None

    def test_unanimous_agreement(self, backend, order):
        # test.ts:133-175: N=5, F=0, all 1 -> all decide 1, k <= 2
        faulty = [False] * 5
        net = _launch(faulty, [1] * 5, backend, oracle_order=order)
        states = _run_to_finality(net)
        assert reached_finality(states)
        for st in states:
            assert st["decided"] is True
            assert st["x"] == 1
            assert st["k"] <= 2
        net.close()

    def test_simple_majority(self, backend, order):
        # test.ts:179-223: N=5, F=1, vals 1,1,1,0,(0 faulty) -> decide 1, k <= 2
        faulty = [False, False, False, False, True]
        net = _launch(faulty, [1, 1, 1, 0, 0], backend, oracle_order=order)
        states = _run_to_finality(net)
        for st, f in zip(states, faulty):
            if f:
                self._assert_faulty_null(st)
            else:
                assert st["decided"] is True
                assert st["x"] == 1
                assert st["k"] <= 2
        net.close()

    def test_fault_tolerance_threshold(self, backend, order):
        # test.ts:227-286: N=9, F=4, mixed -> all healthy decide, same value
        faulty = [True] * 4 + [False] * 5
        net = _launch(faulty, [0, 0, 1, 1, 1, 0, 0, 1, 1], backend, oracle_order=order)
        states = _run_to_finality(net)
        consensus = []
        for st, f in zip(states, faulty):
            if f:
                self._assert_faulty_null(st)
            else:
                assert st["decided"] is True
                assert st["k"] is not None
                assert st["x"] is not None
                consensus.append(st["x"])
        assert all(v == consensus[0] for v in consensus)
        net.close()

    def test_exceeding_fault_tolerance_livelock(self, backend, order):
        # test.ts:292-345: N=10, F=5 -> healthy never decide, k > 10
        faulty = [True] * 5 + [False] * 5
        net = _launch(faulty, [0, 0, 1, 1, 1, 0, 0, 1, 1, 0], backend,
                      max_rounds=15, oracle_order=order)
        states = _run_to_finality(net)
        for st, f in zip(states, faulty):
            if f:
                self._assert_faulty_null(st)
            else:
                assert st["decided"] is not True
                assert st["k"] > 10
                assert st["x"] is not None
        net.close()

    def test_no_faulty_nodes(self, backend, order):
        # test.ts:351-393: N=5, F=0, vals 0,1,0,1,1 -> all decide 1, k <= 2
        faulty = [False] * 5
        net = _launch(faulty, [0, 1, 0, 1, 1], backend, oracle_order=order)
        states = _run_to_finality(net)
        for st in states:
            assert st["decided"] is True
            assert st["x"] == 1
            assert st["k"] <= 2
        net.close()

    def test_randomized(self, backend, order):
        # test.ts:399-450: N=7, F=2, random bits -> healthy all decide,
        # identical value
        rng = np.random.default_rng(42)
        faulty = [False, False, True, False, True, False, False]
        values = [int(v) for v in rng.integers(0, 2, size=7)]
        net = _launch(faulty, values, backend, oracle_order=order)
        states = _run_to_finality(net)
        consensus = []
        for st, f in zip(states, faulty):
            if f:
                self._assert_faulty_null(st)
            else:
                assert st["decided"] is True
                assert st["x"] is not None
                consensus.append(st["x"])
        assert all(v == consensus[0] for v in consensus)
        net.close()

    def test_one_node(self, backend, order):
        # test.ts:454-486: N=1 decides its own value (self-broadcast,
        # quirk 6, makes the quorum of 1 reachable)
        net = _launch([False], [1], backend, oracle_order=order)
        states = _run_to_finality(net)
        assert len(states) == 1
        assert states[0]["decided"] is True
        assert states[0]["x"] == 1
        net.close()

    def test_stop_consensus_kills_all(self, backend, order):
        # consensus.ts:10-15 + node.ts:191-194: /stop flips killed
        faulty = [False] * 3
        net = _launch(faulty, [1, 1, 1], backend, oracle_order=order)
        start_consensus(net)
        stop_consensus(net)
        for i in range(3):
            assert net.status(i) == ("faulty", 500)
        # state survives the kill (reference /getState after /stop)
        st = net.get_state(0)
        assert st["killed"] is True
        assert st["x"] is not None
        net.close()


class TestBackendAgreement:
    """Differential check: both backends reach the same verdict per scenario."""

    @pytest.mark.parametrize("faulty,values", [
        ([False] * 5, [1] * 5),
        ([False, False, False, False, True], [1, 1, 1, 0, 0]),
        ([True] * 4 + [False] * 5, [0, 0, 1, 1, 1, 0, 0, 1, 1]),
        ([False] * 5, [0, 1, 0, 1, 1]),
        ([False], [1]),
    ])
    def test_same_decision(self, faulty, values):
        outcomes = {}
        for backend in BACKENDS:
            net = _launch(faulty, values, backend)
            states = _run_to_finality(net)
            live = [s for s, f in zip(states, faulty) if not f]
            outcomes[backend] = (
                all(s["decided"] is True for s in live),
                {s["x"] for s in live},
            )
            net.close()
        assert outcomes["tpu"] == outcomes["express"]
