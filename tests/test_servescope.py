"""servescope (PR 11) — span tracing + stage-latency attribution for
the serve plane.

Five layers:

  * SPAN PLANE: the utils/metrics Span API (explicit stamps, parent/
    child, flow links), its disabled-by-default contract and the
    Chrome-trace/Perfetto rendering (flow start/finish pairs resolve,
    stage spans nest inside their job span in stage order).
  * THE HOUSE RULE, host edition: tracing off vs on is bit-identical
    in results AND adds zero backend compiles at steady state — the
    flight-recorder discipline applied to the host-side span plane.
  * STAGE MODEL: the nine stamps land on every served job, the stage
    durations telescope (sum == done - accepted), and the
    ``/v1/jobs/<id>/timing`` route serves them over real sockets with
    the X-Request-Id echo.
  * SATELLITES: the batcher worker loop's structured last-error
    snapshot + serve.batch_errors counter, the paired sse_opened/
    sse_closed counters around the client gauge, queue depth sampled
    at drain.
  * ARTIFACTS: the v2 manifest's stage/attribution cross-field checks
    and the regression gate's exit-2 verdict on an injected queue-wait
    regression (the acceptance fixture).
"""

from __future__ import annotations

import copy
import json
import os
import socket
import sys
import time

import pytest

from benor_tpu.serve import (Batcher, ServeApp, compare_serve,
                             stage_durations, timing_dict)
from benor_tpu.serve.jobs import STAGE_NAMES, STAGE_STAMPS, STAGES
from benor_tpu.sweep import run_point
from benor_tpu.config import SimConfig
from benor_tpu.utils.compile_counter import count_backend_compiles
from benor_tpu.utils.metrics import (REGISTRY, SPANS, SpanLog,
                                     export_chrome_trace, perf_to_epoch)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import check_metrics_schema  # noqa: E402
import check_serve_regression  # noqa: E402

SPEC = {"kind": "simulate", "n_nodes": 16, "n_faulty": 2, "trials": 4,
        "max_rounds": 8, "delivery": "all", "seed": 3}


def _drain(batcher, deadline_s: float = 30.0) -> int:
    n = 0
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        got = batcher.step()
        if not got:
            break
        n += got
    return n


@pytest.fixture
def spans_off():
    """Leave the process-wide span log exactly as found (disabled and
    empty — the default every other test relies on)."""
    yield
    SPANS.disable()
    SPANS.clear()


# --------------------------------------------------------------------------
# span plane: the API itself
# --------------------------------------------------------------------------


def test_spanlog_disabled_is_a_noop():
    log = SpanLog()
    assert log.add("x", 0.0, 1.0) == 0
    assert len(log) == 0


def test_spanlog_records_and_caps():
    log = SpanLog(cap=2).enable()
    a = log.add("a", 10.0, 1.0, track="t")
    b = log.add("b", 11.0, 1.0, parent_id=a, flow_in=7, flow_out=(8, 9))
    assert a and b and b == a + 1
    assert log.add("c", 12.0, 1.0) == 0          # over cap: dropped
    assert log.dropped == 1
    spans = log.snapshot()
    assert [s.name for s in spans] == ["a", "b"]
    assert spans[1].parent_id == a
    assert spans[1].flow_in == (7,) and spans[1].flow_out == (8, 9)
    log.clear()
    assert len(log) == 0 and log.dropped == 0


def test_spanlog_flow_ids_are_unique():
    log = SpanLog().enable()
    ids = [log.new_flow() for _ in range(10)]
    assert len(set(ids)) == 10


def test_chrome_trace_renders_spans_and_flows(tmp_path):
    from benor_tpu.utils.metrics import Span
    spans = [
        Span("parent", 100.0, 2.0, track="demo", span_id=1,
             flow_out=(5,)),
        Span("child", 100.5, 0.5, track="demo", span_id=2, parent_id=1,
             flow_in=(5,)),
    ]
    path = str(tmp_path / "trace.json")
    export_chrome_trace(path, spans=spans)
    ev = json.load(open(path))["traceEvents"]
    xs = {e["name"]: e for e in ev if e.get("ph") == "X"}
    assert xs["child"]["args"]["parent_id"] == 1
    s_ids = {e["id"] for e in ev if e.get("ph") == "s"}
    f_ids = {e["id"] for e in ev if e.get("ph") == "f"}
    assert f_ids == s_ids == {5}
    # nesting by time containment: child inside parent on the same tid
    assert xs["child"]["tid"] == xs["parent"]["tid"]
    assert xs["child"]["ts"] >= xs["parent"]["ts"]
    assert (xs["child"]["ts"] + xs["child"]["dur"]
            <= xs["parent"]["ts"] + xs["parent"]["dur"] + 1e-6)


# --------------------------------------------------------------------------
# stage model: stamps + telescoping attribution
# --------------------------------------------------------------------------


def test_stage_durations_telescope_and_clamp():
    stamps = {name: float(i) for i, name in enumerate(STAGE_STAMPS)}
    stages = stage_durations(stamps)
    assert set(stages) == set(STAGE_NAMES)
    # consecutive-stamp deltas telescope to done - accepted exactly
    assert sum(stages.values()) == pytest.approx(
        stamps["done"] - stamps["accepted"])
    # a raced stamp pair clamps to zero, never negative attribution
    stamps_bad = dict(stamps)
    stamps_bad["result_sliced"] = stamps["done"] + 5.0
    assert stage_durations(stamps_bad)["stream_out"] == 0.0
    # missing stamps: the stage is absent, not fabricated
    partial = {"accepted": 0.0, "validated": 1.0}
    assert stage_durations(partial) == {"validate": 1.0}


def test_timing_dict_shape():
    stamps = {name: float(i) for i, name in enumerate(STAGE_STAMPS)}
    doc = timing_dict(stamps)
    assert doc["total_s"] == pytest.approx(8.0)
    assert doc["stamps_rel_s"]["accepted"] == 0.0
    assert doc["stamps_rel_s"]["done"] == pytest.approx(8.0)
    assert doc["sub_stages_s"]["stream_wait"] == pytest.approx(1.0)
    assert doc["sub_stages_s"]["stream_flush"] == pytest.approx(1.0)
    # the sub-stages subdivide stream_out exactly
    assert (doc["sub_stages_s"]["stream_wait"]
            + doc["sub_stages_s"]["stream_flush"]
            == pytest.approx(doc["stages_s"]["stream_out"]))


def test_batcher_stamps_every_transition():
    b = Batcher(start=False)
    job = b.submit_dict(dict(SPEC))[0]
    _drain(b)
    # every batcher-owned stamp, in STAGE_STAMPS order (first_sse is
    # the HTTP stream leg's, absent on a directly-driven batcher)
    want = [s for s in STAGE_STAMPS if s != "first_sse"]
    assert [s for s in STAGE_STAMPS if s in job.stamps] == want
    times = [job.stamps[s] for s in want]
    assert times == sorted(times)
    stages = stage_durations(job.stamps)
    assert sum(stages.values()) == pytest.approx(
        job.stamps["done"] - job.stamps["accepted"])


def test_queue_depth_gauge_sampled_at_drain():
    b = Batcher(max_batch_jobs=2, start=False)
    for s in range(3):
        b.submit_dict({**SPEC, "seed": 70 + s})
    assert REGISTRY.gauge("serve.queue_depth").value == 3.0
    b.step()                                    # pops a batch of 2
    assert REGISTRY.gauge("serve.queue_depth").value == 1.0
    b.step()
    assert REGISTRY.gauge("serve.queue_depth").value == 0.0


# --------------------------------------------------------------------------
# the house rule: tracing off is bit-identical + zero new compiles
# --------------------------------------------------------------------------


def test_tracing_off_bit_identical_and_zero_compiles(spans_off):
    """Steady-state serving with the span plane armed must add ZERO
    backend compiles and return results bit-equal to the untraced run
    of the identical spec — the flight-recorder house rule, applied to
    the host-side tracing layer."""
    spec = {**SPEC, "seed": 41}
    b = Batcher(start=False)
    job_off = b.submit_dict(dict(spec))[0]      # warm + tracing off
    _drain(b)
    SPANS.enable()
    with count_backend_compiles() as cc:
        job_on = b.submit_dict(dict(spec))[0]
        _drain(b)
    SPANS.disable()
    assert cc.count == 0, "armed tracing must not trigger compiles"
    assert len(SPANS) > 0, "armed tracing must record spans"
    r_off = {k: v for k, v in job_off.result.items() if k != "job"}
    r_on = {k: v for k, v in job_on.result.items() if k != "job"}
    assert r_off.pop("seconds") >= 0.0 and r_on.pop("seconds") >= 0.0
    assert r_on == r_off                         # floats ==, not approx


def test_batch_and_job_spans_flow_link_and_nest(spans_off):
    SPANS.enable()
    b = Batcher(start=False)
    jobs = [b.submit_dict({**SPEC, "seed": 80 + s})[0] for s in range(3)]
    _drain(b)
    spans = SPANS.snapshot()
    batches = [s for s in spans if s.track == "serve.batcher"]
    assert len(batches) == 1 and batches[0].args["jobs"] == 3
    assert batches[0].args["capacity"] == 4      # next pow2 rung
    assert batches[0].args["pad"] == 1
    flow_out = set(batches[0].flow_out)
    assert len(flow_out) == 3
    flow_in = set()
    for job in jobs:
        track = [s for s in spans if s.track == f"job {job.id}"]
        parent = [s for s in track if s.parent_id is None]
        assert len(parent) == 1
        stage_spans = [s for s in track if s.parent_id is not None]
        assert all(s.parent_id == parent[0].span_id
                   for s in stage_spans)
        # nesting matches stage order: starts ascending, inside parent
        want_order = [n for n, _, _ in STAGES
                      if n in [s.name for s in stage_spans]]
        assert [s.name for s in stage_spans] == want_order
        starts = [s.start for s in stage_spans]
        assert starts == sorted(starts)
        p0, p1 = parent[0].start, parent[0].start + parent[0].dur_s
        for s in stage_spans:
            assert s.start >= p0 - 1e-6
            assert s.start + s.dur_s <= p1 + 1e-6
        launch = [s for s in stage_spans if s.name == "launch"]
        flow_in |= set(launch[0].flow_in)
    assert flow_in == flow_out                   # links resolve 1:1


def test_perfetto_export_of_serve_spans_resolves_flows(tmp_path,
                                                       spans_off):
    SPANS.enable()
    b = Batcher(start=False)
    for s in range(2):
        b.submit_dict({**SPEC, "seed": 90 + s})
    _drain(b)
    path = str(tmp_path / "serve_trace.json")
    export_chrome_trace(path, spans=True)
    ev = json.load(open(path))["traceEvents"]
    s_ids = {e["id"] for e in ev if e.get("ph") == "s"}
    f_ids = {e["id"] for e in ev if e.get("ph") == "f"}
    assert f_ids and f_ids <= s_ids              # every finish has a start
    names = {e["name"] for e in ev if e.get("ph") == "X"}
    assert any(n.startswith("batch dyn") for n in names)
    assert "launch" in names and "queue_wait" in names


# --------------------------------------------------------------------------
# satellites: batch-error snapshot, sse gauge pairing
# --------------------------------------------------------------------------


def test_batch_error_counter_and_snapshot_in_stats(monkeypatch):
    """The worker loop's bare print_exc is gone: a failed batch ticks
    serve.batch_errors, stores a structured last-error snapshot that
    /v1/stats surfaces, and the loop survives to serve the next job."""
    before = REGISTRY.counter("serve.batch_errors").value
    b = Batcher(start=True)
    try:
        def boom(key, jobs):
            raise RuntimeError("injected batch failure")
        monkeypatch.setattr(b, "_execute", boom)
        job = b.submit_dict(dict(SPEC))[0]
        assert job.wait(timeout=30)
        assert job.state == "error"
        deadline = time.time() + 10
        while time.time() < deadline and b.batch_errors < 1:
            time.sleep(0.02)
        st = b.stats()
        assert st["batch_errors"] == 1
        assert "RuntimeError: injected batch failure" \
            in st["last_error"]["error"]
        assert "traceback" in st["last_error"]
        assert st["last_error"]["ts"] > 0
        assert REGISTRY.counter("serve.batch_errors").value == before + 1
        # the loop survived: the next (healthy) job completes
        monkeypatch.undo()
        ok_job = b.submit_dict({**SPEC, "seed": 55})[0]
        assert ok_job.wait(timeout=60) and ok_job.state == "done"
    finally:
        b.close()


@pytest.fixture(scope="module")
def app():
    with ServeApp(max_batch_jobs=8) as a:
        yield a


def _request(app, payload: bytes, read_until=None,
             timeout: float = 60.0) -> bytes:
    s = socket.create_connection((app.host, app.port), timeout=timeout)
    try:
        s.sendall(payload)
        chunks = b""
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks += b
            if read_until and read_until in chunks:
                break
    finally:
        s.close()
    return chunks


def _get(app, path: str, headers: str = "") -> bytes:
    return _request(app, f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                         f"{headers}\r\n".encode())


def _status_and_json(resp: bytes):
    head, _, body = resp.partition(b"\r\n\r\n")
    return int(head.split()[1]), json.loads(body)


def test_sse_gauge_pairs_and_returns_to_rest(app):
    g0 = REGISTRY.gauge("serve.sse_clients").value
    opened0 = REGISTRY.counter("serve.sse_opened").value
    closed0 = REGISTRY.counter("serve.sse_closed").value
    body = json.dumps({**SPEC, "seed": 61}).encode()
    resp = _request(
        app,
        b"POST /v1/jobs?stream=sse HTTP/1.1\r\nHost: x\r\n"
        + f"Content-Length: {len(body)}\r\n\r\n".encode() + body,
        read_until=b"event: done")
    assert b"event: result" in resp
    deadline = time.time() + 10
    while time.time() < deadline and \
            REGISTRY.counter("serve.sse_closed").value < closed0 + 1:
        time.sleep(0.02)
    assert REGISTRY.counter("serve.sse_opened").value == opened0 + 1
    assert REGISTRY.counter("serve.sse_closed").value == closed0 + 1
    assert REGISTRY.gauge("serve.sse_clients").value == g0
    # the paired counters audit the gauge: opened - closed == in-flight
    assert (REGISTRY.counter("serve.sse_opened").value
            - REGISTRY.counter("serve.sse_closed").value) == g0


def test_stats_surfaces_batch_error_fields(app):
    code, stats = _status_and_json(_get(app, "/v1/stats"))
    assert code == 200
    assert "batch_errors" in stats and "last_error" in stats


def test_request_id_echo_and_minting(app):
    resp = _get(app, "/healthz", headers="X-Request-Id: my.id-42\r\n")
    assert b"X-Request-Id: my.id-42" in resp
    resp = _get(app, "/healthz",
                headers="X-Request-Id: bad id with spaces\r\n")
    head = resp.partition(b"\r\n\r\n")[0]
    assert b"X-Request-Id: r-" in head           # minted, not echoed
    resp = _get(app, "/healthz")
    assert b"X-Request-Id: r-" in resp
    # a rejection raised INSIDE request parsing (413 on the header
    # alone) still carries the client's correlation id — errors are
    # where correlation matters most
    resp = _request(
        app, b"POST /v1/jobs HTTP/1.1\r\nHost: x\r\n"
             b"X-Request-Id: too-big-7\r\n"
             b"Content-Length: 99999999\r\n\r\n")
    head = resp.partition(b"\r\n\r\n")[0]
    assert head.startswith(b"HTTP/1.1 413")
    assert b"X-Request-Id: too-big-7" in head


def test_http_timing_route_over_sockets(app):
    code, sub = _status_and_json(_request(
        app, b"POST /v1/jobs HTTP/1.1\r\nHost: x\r\n"
             b"Content-Length: %d\r\n\r\n"
             % len(json.dumps({**SPEC, "seed": 62}).encode())
             + json.dumps({**SPEC, "seed": 62}).encode()))
    assert code == 202
    job_id = sub["jobs"][0]
    deadline = time.time() + 30
    while time.time() < deadline:
        code, snap = _status_and_json(_get(app, f"/v1/jobs/{job_id}"))
        if snap["state"] == "done":
            break
        time.sleep(0.05)
    code, doc = _status_and_json(_get(app, f"/v1/jobs/{job_id}/timing"))
    assert code == 200
    assert doc["job"] == job_id and doc["state"] == "done"
    assert set(doc["stages_s"]) == set(STAGE_NAMES) - {"stream_out"} \
        or set(doc["stages_s"]) == set(STAGE_NAMES)
    # the payload rounds each stage to 6 dp independently: allow the
    # documented N*0.5e-6 rounding slack on the telescoping identity
    assert doc["total_s"] >= sum(doc["stages_s"].values()) - 5e-6
    assert doc["stamps_rel_s"]["accepted"] == 0.0
    # oracle cross-check: the timing route's job is still bit-equal
    cfg = SimConfig(n_nodes=16, n_faulty=2, trials=4, max_rounds=8,
                    delivery="all", seed=62)
    assert snap["result"]["mean_k"] == run_point(cfg).mean_k
    code, _ = _status_and_json(_get(app, "/v1/jobs/nope/timing"))
    assert code == 404


# --------------------------------------------------------------------------
# artifacts: v2 schema cross-fields + the injected-regression gate
# --------------------------------------------------------------------------


def _baseline() -> dict:
    with open(os.path.join(REPO, "SERVE_BASELINE.json")) as fh:
        return json.load(fh)


def test_v2_schema_rejects_v1_and_broken_stage_blocks(tmp_path):
    base = _baseline()
    v1 = copy.deepcopy(base)
    v1["schema_version"] = 1
    assert any("schema_version" in e
               for e in check_metrics_schema.check_serve_manifest(v1))
    bad = copy.deepcopy(base)
    bad["stages"]["queue_wait"]["p50"] = \
        bad["stages"]["queue_wait"]["p99"] + 1.0
    assert any("percentiles out of order" in e
               for e in check_metrics_schema.check_serve_manifest(bad))
    missing = copy.deepcopy(base)
    del missing["stages"]["launch"]
    assert any("launch" in e
               for e in check_metrics_schema.check_serve_manifest(missing))


def test_attribution_cross_fields_are_pinned():
    base = _baseline()
    # a drifted sum
    bad = copy.deepcopy(base)
    bad["attribution"]["stage_mean_sum_ms"] += 100.0
    errs = check_metrics_schema.check_serve_manifest(bad)
    assert any("stage_mean_sum_ms" in e for e in errs)
    # a hand-edited ok over a broken coverage
    lie = copy.deepcopy(base)
    lie["attribution"]["coverage"] = 0.2
    lie["attribution"]["client_mean_ms"] = \
        lie["attribution"]["stage_mean_sum_ms"] / 0.2
    lie["latency_ms"]["mean"] = lie["attribution"]["client_mean_ms"]
    errs = check_metrics_schema.check_serve_manifest(lie)
    assert any("$.attribution.ok" in e for e in errs)


def test_gate_exits_2_on_injected_queue_wait_regression(tmp_path):
    """The acceptance fixture: a manifest whose queue-wait p99 blew past
    the stage band must exit 2 through the real CLI; the same fixture
    passes under a lifted --stage-band, and the committed baseline
    self-gates at 0."""
    base = _baseline()
    bad = copy.deepcopy(base)
    bad["stages"]["queue_wait"]["p99"] = \
        round(base["stages"]["queue_wait"]["p99"] * 3.0 + 500.0, 3)
    mp, bp = str(tmp_path / "m.json"), str(tmp_path / "b.json")
    with open(bp, "w") as fh:
        json.dump(base, fh)
    with open(mp, "w") as fh:
        json.dump(bad, fh)
    assert check_serve_regression.main([mp, bp]) == 2
    findings = compare_serve(bad, base)
    assert any(f.metric == "stages.queue_wait.p99" for f in findings)
    # a lifted band clears it (the ratio is ~3.4x < 10x)
    assert check_serve_regression.main([mp, bp, "--stage-band",
                                        "10.0"]) == 0
    # launch p99 gates the same way
    bad2 = copy.deepcopy(base)
    bad2["stages"]["launch"]["p99"] = \
        round(base["stages"]["launch"]["p99"] * 3.0 + 500.0, 3)
    with open(mp, "w") as fh:
        json.dump(bad2, fh)
    assert check_serve_regression.main([mp, bp]) == 2
    # sub-noise-floor blowups are ignored (2x of ~nothing is noise)
    tiny = copy.deepcopy(base)
    tiny["stages"]["launch"]["p99"] = \
        round(base["stages"]["launch"]["p99"] * 3.0, 3)
    ok = tiny["stages"]["launch"]["p99"] \
        - base["stages"]["launch"]["p99"] < 50.0
    if ok:
        with open(mp, "w") as fh:
            json.dump(tiny, fh)
        assert check_serve_regression.main([mp, bp]) == 0


def test_gate_flags_broken_attribution():
    base = _baseline()
    bad = copy.deepcopy(base)
    bad["attribution"]["ok"] = False
    bad["attribution"]["coverage"] = 0.4
    findings = compare_serve(bad, base)
    assert any(f.metric == "attribution" for f in findings)


def test_committed_baseline_attribution_is_complete():
    base = _baseline()
    assert base["schema_version"] == 2
    assert base["attribution"]["ok"] is True
    assert base["attribution"]["jobs_timed"] >= 1000
    assert set(base["stages"]) == set(STAGE_NAMES)
