"""Batched dynamic-F sweep engine (sweep.run_curve_batched).

Pins the tentpole contract of the compile-amortized curve engine:

  * bit-identical per-f summaries (decided_frac, mean_k, k_hist,
    ones_frac, disagree_frac, rounds_executed) between the batched
    executable and the per-point ``run_point`` oracle, across the uniform
    and adversarial/targeted schedulers and both coin modes;
  * exactly ONE XLA backend compile per static-shape bucket, measured by
    the jax.monitoring hook (utils/compile_counter.py), for a >= 5-point
    curve;
  * bucketing: quorum-specialized regimes (exact-table quorums, dense
    top-k masks, pallas kernels) are split into their own static buckets
    while the CF regime shares one.
"""

import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.ops import sampling
from benor_tpu.state import FaultSpec
from benor_tpu.sweep import (balanced_inputs, coin_comparison,
                             coin_comparison_batched, quorum_specialized,
                             rounds_vs_f, rounds_vs_f_batched,
                             run_curve_batched, run_point, sweep_bucket_key)

#: Smallest CF-regime geometry that keeps every quorum above
#: sampling.EXACT_TABLE_MAX (= 4096) for the f grid below.
CF_N = 9000
CF_FS = [600, 1200, 1800, 2400, 3000]


def assert_points_bit_identical(a, b):
    assert a.n_faulty == b.n_faulty and a.n_nodes == b.n_nodes
    assert a.rounds_executed == b.rounds_executed, a.n_faulty
    assert a.decided_frac == b.decided_frac, a.n_faulty
    assert a.mean_k == b.mean_k, a.n_faulty
    assert a.ones_frac == b.ones_frac, a.n_faulty
    assert a.disagree_frac == b.disagree_frac, a.n_faulty
    np.testing.assert_array_equal(a.k_hist, b.k_hist)


def test_cf_uniform_bit_identity_and_one_compile():
    """The north-star shape: >= 5 f values in the CF regime — one bucket,
    one measured backend compile, summaries bit-equal to the per-point
    oracle."""
    cfg = SimConfig(n_nodes=CF_N, n_faulty=0, trials=4, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=16,
                    seed=3)
    pp = rounds_vs_f(cfg, CF_FS, verbose=False)
    cb = run_curve_batched(cfg, CF_FS)
    assert cb.n_buckets == 1
    assert cb.bucket_sizes == [len(CF_FS)]
    # the acceptance gate: exactly 1 XLA compile per static-shape bucket,
    # asserted via the jax.monitoring backend-compile hook the engine
    # scopes over its compile+execute phase
    assert cb.compile_count == cb.n_buckets == 1
    for a, b in zip(pp, cb.points):
        assert_points_bit_identical(a, b)


def test_wrapper_matches_rounds_vs_f():
    cfg = SimConfig(n_nodes=CF_N, n_faulty=0, trials=4, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=16,
                    seed=5)
    pp = rounds_vs_f(cfg, CF_FS[:3], verbose=False)
    bb = rounds_vs_f_batched(cfg, CF_FS[:3], verbose=False)
    for a, b in zip(pp, bb):
        assert_points_bit_identical(a, b)


@pytest.mark.parametrize("scheduler,coin", [
    ("adversarial", "private"),      # livelock regime (tie-forcing)
    ("adversarial", "common"),       # O(1) escape
    ("targeted", "private"),         # agreement attack (disagree > 0)
])
def test_adversarial_schedulers_bit_identity(scheduler, coin):
    """The closed-form count adversaries have no quorum-specialized
    shapes, so even small-N points batch dynamically — balanced inputs,
    zero crashes (the adversary's strongest setting)."""
    n, trials = 100, 8
    cfg = SimConfig(n_nodes=n, n_faulty=0, trials=trials, delivery="quorum",
                    scheduler=scheduler, coin_mode=coin, path="histogram",
                    max_rounds=8, seed=7)
    fs = [20, 30, 40]
    bal = balanced_inputs(trials, n)

    def no_crash(c):
        return FaultSpec.none(trials, n)

    cb = run_curve_batched(cfg, fs, initial_values=bal, faults_for=no_crash)
    assert cb.n_buckets == 1 and cb.compile_count == 1
    for f, b in zip(fs, cb.points):
        a = run_point(cfg.replace(n_faulty=f), initial_values=bal,
                      faults=FaultSpec.none(trials, n))
        assert_points_bit_identical(a, b)
    if scheduler == "targeted":
        # sanity that the regime is non-trivial: the partitioned
        # adversary violates agreement at every even-quorum point
        assert any(p.disagree_frac > 0 for p in cb.points)


def test_uniform_common_coin_bit_identity():
    """Both coin modes covered on the uniform scheduler too."""
    cfg = SimConfig(n_nodes=CF_N, n_faulty=0, trials=4, delivery="quorum",
                    scheduler="uniform", coin_mode="common",
                    path="histogram", max_rounds=16, seed=11)
    fs = CF_FS[:3]
    cb = run_curve_batched(cfg, fs)
    assert cb.compile_count == cb.n_buckets == 1
    for f, b in zip(fs, cb.points):
        a = run_point(cfg.replace(n_faulty=f))
        assert_points_bit_identical(a, b)


def test_mixed_regimes_split_buckets():
    """An f past the CF boundary (quorum <= EXACT_TABLE_MAX) cannot share
    the traced executable — it gets a static bucket of its own, still
    bit-identical to the oracle."""
    cfg = SimConfig(n_nodes=CF_N, n_faulty=0, trials=4, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=16,
                    seed=3)
    f_exact = CF_N - sampling.EXACT_TABLE_MAX + 500   # quorum 3596 <= 4096
    fs = CF_FS + [f_exact]
    cb = run_curve_batched(cfg, fs)
    assert cb.n_buckets == 2
    assert cb.compile_count == 2
    assert sorted(cb.bucket_sizes) == [1, len(CF_FS)]
    a = run_point(cfg.replace(n_faulty=f_exact))
    assert_points_bit_identical(a, cb.points[-1])


def test_coin_comparison_batched_matches_per_point():
    cfg = SimConfig(n_nodes=100, n_faulty=40, trials=16, max_rounds=8,
                    seed=7)
    per_point = coin_comparison(cfg, verbose=False)
    batched = coin_comparison_batched(cfg, [40], verbose=False)
    for coin in ("private", "common"):
        assert_points_bit_identical(per_point[coin][0], batched[coin][0])


def test_coin_comparison_batched_rejects_odd_quorum():
    cfg = SimConfig(n_nodes=21, n_faulty=0, trials=4)
    with pytest.raises(ValueError, match="even quorum"):
        coin_comparison_batched(cfg, [6], verbose=False)


class TestBucketing:
    def test_cf_points_share_a_key(self):
        cfg = SimConfig(n_nodes=CF_N, n_faulty=0, trials=4,
                        delivery="quorum", scheduler="uniform",
                        path="histogram")
        keys = {sweep_bucket_key(cfg.replace(n_faulty=f)) for f in CF_FS}
        assert len(keys) == 1

    def test_exact_regime_specializes(self):
        cfg = SimConfig(n_nodes=100, n_faulty=20, trials=4,
                        delivery="quorum", scheduler="uniform",
                        path="histogram")
        assert quorum_specialized(cfg)       # quorum 80 <= EXACT_TABLE_MAX
        k1 = sweep_bucket_key(cfg)
        k2 = sweep_bucket_key(cfg.replace(n_faulty=30))
        assert k1 != k2                      # one bucket per exact quorum

    def test_dense_path_specializes_but_closed_forms_do_not(self):
        dense = SimConfig(n_nodes=100, n_faulty=20, trials=4,
                          delivery="quorum", scheduler="uniform",
                          path="dense")
        assert quorum_specialized(dense)     # top-k mask shape = m
        adv = dense.replace(scheduler="adversarial")
        assert not quorum_specialized(adv)   # closed form, any path

    def test_pallas_flags_specialize(self):
        cfg = SimConfig(n_nodes=CF_N, n_faulty=600, trials=4,
                        delivery="quorum", scheduler="uniform",
                        path="histogram", use_pallas_hist=True)
        assert quorum_specialized(cfg)       # kernel bakes the quorum
        assert not quorum_specialized(cfg.replace(use_pallas_hist=False))

    def test_schedulers_never_share_buckets(self):
        cfg = SimConfig(n_nodes=CF_N, n_faulty=600, trials=4,
                        delivery="quorum", scheduler="uniform",
                        path="histogram")
        assert sweep_bucket_key(cfg) != sweep_bucket_key(
            cfg.replace(scheduler="adversarial"))


@pytest.mark.slow
def test_sweep_cli_batched(tmp_path, capsys):
    """`sweep --batched` routes through the engine (bucket banner printed)
    and writes the same point schema as the per-point path."""
    import json

    from benor_tpu.__main__ import main
    out = str(tmp_path / "b.json")
    assert main(["sweep", "--n", "24", "--f-values", "4,9", "--trials", "8",
                 "--max-rounds", "8", "--balanced", "--batched",
                 "--out", out]) == 0
    pts = json.load(open(out))
    assert len(pts) == 2 and all("disagree_frac" in p for p in pts)
    assert "batched curve:" in capsys.readouterr().out


def test_compile_counter_hook_counts_fresh_compiles():
    """The measurement primitive itself: AOT lower+compile emits exactly
    one backend-compile event per executable, and scopes nest."""
    import jax
    import jax.numpy as jnp

    from benor_tpu.utils.compile_counter import count_backend_compiles

    x = jnp.arange(8.0)          # built OUTSIDE the counting scopes
    y = jnp.arange(16.0)         # distinct shape: jax dedupes identical
    f = lambda v: v * 3 + 1      # noqa: E731    HLO across AOT compiles
    with count_backend_compiles() as outer:
        with count_backend_compiles() as inner:
            jax.jit(f).lower(x).compile()
        jax.jit(f).lower(y).compile()
    assert inner.count == 1
    assert outer.count == 2
    assert outer.seconds > 0
