"""Property tests: the consensus invariants, over Monte-Carlo batches.

The reference's suite checks single scenarios; these check the protocol
PROPERTIES — agreement, validity, termination — over many random trials,
schedulers and both compute paths (the kind of testing SURVEY §4 notes the
reference lacks).
"""

import numpy as np
import pytest

from benor_tpu.config import SimConfig, VALQ
from benor_tpu.sim import simulate


def _run(n, f, trials, seed, *, vals=None, faulty=None, faults=None,
         **overrides):
    kw = dict(delivery="quorum", scheduler="uniform", max_rounds=64)
    kw.update(overrides)
    cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials, seed=seed, **kw)
    if vals is None:
        vals = np.random.default_rng(seed).integers(
            0, 2, size=(trials, n), dtype=np.int8)
    if faulty is None and faults is None:
        faulty = [True] * f + [False] * (n - f)
    rounds, final, faults = simulate(cfg, vals, faulty, faults=faults)
    healthy = ~np.asarray(faults.faulty)
    return (np.asarray(final.x), np.asarray(final.decided),
            np.asarray(final.k), healthy)


@pytest.mark.parametrize("path", ["dense", "histogram"])
@pytest.mark.parametrize("seed", [1, 2, 3])
@pytest.mark.slow
def test_agreement(path, seed):
    """No two healthy decided lanes of a trial hold different values."""
    x, decided, _, healthy = _run(60, 15, 64, seed, path=path)
    for t in range(x.shape[0]):
        vals = x[t][healthy[t] & decided[t]]
        assert vals.size > 0
        assert (vals == vals[0]).all(), f"trial {t} disagrees"


@pytest.mark.parametrize("path", ["dense", "histogram"])
@pytest.mark.parametrize("v", [0, 1])
@pytest.mark.slow
def test_validity_unanimous(path, v):
    """If every healthy node starts with v, every decision is v."""
    n, f, trials = 40, 10, 32
    vals = np.full((trials, n), v, np.int8)
    x, decided, k, healthy = _run(n, f, trials, 11, vals=vals, path=path)
    assert (decided | ~healthy).all()
    assert (x[healthy & decided] == v).all()
    # unanimous inputs decide in the first round (k snapshot = 2)
    assert (k[healthy & decided] == 2).all()


@pytest.mark.parametrize("scheduler", ["uniform", "biased"])
@pytest.mark.slow
def test_termination_under_threshold(scheduler):
    """F < N/2 with a fair/bounded scheduler: every trial terminates."""
    x, decided, k, healthy = _run(
        30, 14, 64, 13, scheduler=scheduler, path="dense",
        adversary_strength=0.75 if scheduler == "biased" else 0.0)
    assert (decided | ~healthy).all()


@pytest.mark.parametrize("path", ["dense", "histogram"])
@pytest.mark.slow
def test_textbook_rule_agreement_and_termination(path):
    """rule='textbook' (coin whenever no value has > F votes — classic
    Ben-Or, no plurality-adopt) still satisfies agreement and terminates
    under the crash model; only the kernel's decision-rule flag differs
    from the reference-mode runs above."""
    x, decided, _, healthy = _run(60, 15, 64, 5, path=path,
                                  rule="textbook")
    hd = healthy & decided
    assert (hd | ~healthy).all(), "healthy lanes must all decide"
    for t in range(x.shape[0]):
        vals = x[t][hd[t]]
        assert (vals == vals[0]).all(), f"trial {t} disagrees"


def test_textbook_coin_contrast_under_adversary():
    """Textbook mode preserves the classic contrast: the count-controlling
    adversary livelocks private coins but not the shared common coin."""
    from benor_tpu.state import FaultSpec
    n, trials = 100, 16
    from benor_tpu.sweep import balanced_inputs
    vals = balanced_inputs(trials, n)
    # zero crashes (FaultSpec.none — the launch validation pins list-born
    # faults to exactly F), leaving the adversary its full delivery slack
    base = dict(n=n, f=40, trials=trials, seed=6, vals=vals,
                scheduler="adversarial", rule="textbook",
                faults=FaultSpec.none(trials, n))
    x, dec, _, healthy = _run(**{**base}, coin_mode="private",
                              max_rounds=24)
    assert not dec[healthy.astype(bool)].any(), "private coin must livelock"
    x, dec, k, healthy = _run(**{**base}, coin_mode="common")
    assert dec[healthy.astype(bool)].all(), "common coin must converge"


@pytest.mark.slow
def test_no_decision_value_is_question_mark():
    """Decided lanes never hold "?" — decisions are on 0/1 only."""
    x, decided, _, healthy = _run(25, 8, 64, 17)
    assert (x[decided & healthy] != VALQ).all()


def test_byzantine_agreement_full_delivery():
    """Byzantine flips with delivery='all': every receiver tallies the same
    multiset, so decisions are identical -> agreement holds exactly."""
    n, f, trials = 50, 9, 64
    x, decided, _, healthy = _run(n, f, trials, 19, fault_model="byzantine",
                                  delivery="all")
    for t in range(trials):
        vals = x[t][healthy[t] & decided[t]]
        if vals.size:
            assert (vals == vals[0]).all(), f"trial {t} safety violation"
    assert (decided & healthy).any(axis=1).mean() > 0.9


@pytest.mark.slow
def test_byzantine_quorum_sampling_breaks_reference_rule():
    """A *finding* the simulator must reproduce: the reference's decide rule
    (plurality-adopt + decide on count > F, node.ts:99-112) is NOT safe once
    receivers tally different N-F subsets and all N nodes stay alive
    (Byzantine keeps faulty senders alive, unlike crash).  With a split vote
    (a zeros, b ones), a 41-of-50 sample can put count(0) on either side of
    F=9, so different receivers decide different values.  The reference
    never sees this because its crash model pins alive == quorum (zero
    sampling slack).  BFT-safe Ben-Or needs the (N+F)/2 vote threshold,
    which the reference (and hence our reference-mode) lacks."""
    n, f, trials = 50, 9, 64
    x, decided, _, healthy = _run(n, f, trials, 19, fault_model="byzantine",
                                  delivery="quorum")
    violations = 0
    for t in range(trials):
        vals = x[t][healthy[t] & decided[t]]
        if vals.size and not (vals == vals[0]).all():
            violations += 1
    assert violations > 0, (
        "expected the simulator to surface reference-rule safety violations "
        "under Byzantine faults + quorum sampling")


@pytest.mark.slow
def test_crash_at_round_kills_and_network_survives():
    """crash_at_round: faulty lanes die at their round; with quorum still
    available the healthy majority terminates."""
    n, f, trials = 30, 5, 32
    crash_rounds = np.zeros(n, np.int32)
    crash_rounds[:f] = [1, 2, 2, 3, 4]
    cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials, max_rounds=64,
                    delivery="quorum", scheduler="uniform", seed=23,
                    fault_model="crash_at_round")
    vals = np.random.default_rng(23).integers(0, 2, (trials, n), np.int8)
    rounds, final, faults = simulate(
        cfg, vals, [True] * f + [False] * (n - f), crash_rounds=crash_rounds)
    killed = np.asarray(final.killed)
    decided = np.asarray(final.decided)
    faulty = np.asarray(faults.faulty)
    # a lane dies iff the run reached its crash round (a trial that settles
    # early never executes the later crash rounds — like the reference
    # network being torn down before a node would have failed)
    executed = int(rounds)
    for i in range(f):
        if crash_rounds[i] <= executed:
            assert killed[:, i].all(), f"lane {i} should have crashed"
    assert killed[:, 0].all(), "round-1 crash always precedes settling"
    assert (decided | faulty).all(), "healthy lanes must still decide"


@pytest.mark.slow
def test_mesh_shape_invariance_of_results():
    """SURVEY §7 hard-part 5: same seed, different mesh shapes -> identical
    results (RNG keyed on global ids, not shard layout)."""
    import jax
    from benor_tpu.parallel import make_mesh, run_consensus_sharded
    from benor_tpu.sim import run_consensus
    from benor_tpu.state import FaultSpec, init_state

    cfg = SimConfig(n_nodes=32, n_faulty=8, trials=8, max_rounds=48,
                    delivery="quorum", scheduler="uniform", seed=29,
                    path="dense")
    vals = np.random.default_rng(29).integers(0, 2, (8, 32), np.int8)
    faults = FaultSpec.from_faulty_list(cfg, [True] * 8 + [False] * 24)
    state = init_state(cfg, vals, faults)
    key = jax.random.key(cfg.seed)
    _, ref = run_consensus(cfg, state, faults, key)
    for shape in [(1, 8), (2, 4), (4, 2), (8, 1)]:
        mesh = make_mesh(*shape)
        _, out = run_consensus_sharded(cfg, state, faults, key, mesh)
        np.testing.assert_array_equal(np.asarray(out.x), np.asarray(ref.x))
        np.testing.assert_array_equal(np.asarray(out.k), np.asarray(ref.k))
