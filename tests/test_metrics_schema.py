"""Bench artifact contract (tools/check_metrics_schema.py): the stdout
headline must stay under the driver's truncation horizon and
BENCH_DETAIL.json must match the checked-in schema — so new recorder/
metrics keys can never re-trigger the round-3 parsed-null failure."""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_metrics_schema.py")
DETAIL = os.path.join(REPO, "BENCH_DETAIL.json")

spec = importlib.util.spec_from_file_location("check_metrics_schema", TOOL)
tool = importlib.util.module_from_spec(spec)
spec.loader.exec_module(tool)


@pytest.fixture(scope="module")
def committed_detail():
    with open(DETAIL) as fh:
        return json.load(fh)


def test_committed_detail_passes_schema(committed_detail):
    assert tool.check_schema(committed_detail) == []


def test_committed_detail_headline_under_budget(committed_detail):
    assert tool.check_headline(committed_detail) == []
    assert tool.headline_bytes(committed_detail) <= tool.HEADLINE_BUDGET


def test_headline_budget_catches_inflation(committed_detail):
    """A key that bench._split_headline would keep on stdout (i.e. not in
    _DETAIL_KEYS) must trip the budget check once it is large — the exact
    round-3 failure shape."""
    bloated = dict(committed_detail)
    bloated["giant_new_headline_key"] = ["x" * 40] * 60
    errs = tool.check_headline(bloated)
    assert errs and "sidecar" in errs[0]


def test_detail_keys_stay_off_headline(committed_detail):
    """The series-sized keys (curve, kernel checks, flight recorder) must
    be routed to the sidecar by bench._split_headline."""
    import sys
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    blob = dict(committed_detail)
    blob["flight_recorder"] = {"bit_equal_record_off_on": True,
                               "decide_velocity": list(range(64))}
    head, detail = bench._split_headline(blob)
    for key in bench._DETAIL_KEYS:
        assert key not in head
    assert "flight_recorder" in detail
    assert head.get("recorder_ok") is True


def test_schema_catches_missing_required(committed_detail):
    broken = {k: v for k, v in committed_detail.items() if k != "curve"}
    errs = tool.check_schema(broken)
    assert any("curve" in e for e in errs)


def test_schema_catches_type_drift(committed_detail):
    broken = dict(committed_detail)
    broken["n_regimes"] = "seventeen"
    errs = tool.check_schema(broken)
    assert any("n_regimes" in e for e in errs)


def test_tool_main_passes_on_committed_artifact(capsys):
    assert tool.main([DETAIL]) == 0
    assert "schema OK" in capsys.readouterr().out
