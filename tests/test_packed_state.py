"""Bit-plane packed node state (PR 8: state.PACK_LAYOUT +
ops/pallas_round.py pack_state/unpack_state/fused_round_pallas).

Three contracts:

  1. pack/unpack round-trip: property-style over random [T, N] states —
     every NetState leaf survives the plane transpose bit-for-bit, pad
     lanes carry the killed bit + inert "?" value, and the stack's plane
     count follows state.pack_width(cfg).
  2. packed-vs-unpacked BIT-IDENTITY in results AND compile counts
     across the compiled regimes: the fused dispatch (one-pass kernel or
     two-kernel plane pipeline) must equal the unfused pallas path,
     whether entered via run_consensus (traced/fused), the slice
     primitive, the batched sweep's static bucket, or the sharded
     runner.
  3. pad-lane masking for the word layout (the PR 3 witness-aliasing bug
     class): node-sharded pads alias the next shard's global id range,
     so an unmasked pad bit inside the last plane words would
     double-count tallies/witness columns after the psum — sharded
     witness rows must equal single-device rows exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.ops import sampling, tally
from benor_tpu.ops.pallas_round import (FUSED_ONE_PASS_MAX_NODES,
                                        pack_state, partial_dtype,
                                        plane_field, unpack_state)
from benor_tpu.sim import run_consensus
from benor_tpu.state import (PACK_COINED, PACK_FAULTY, PACK_KILLED,
                             PACK_LAYOUT, PACK_STATIC_WIDTH, FaultSpec,
                             NetState, init_state, pack_k_bits,
                             pack_width)
from benor_tpu.sweep import balanced_inputs


def _random_state(rng, t, n, max_k):
    return NetState(
        x=jnp.asarray(rng.integers(0, 3, size=(t, n)), jnp.int8),
        decided=jnp.asarray(rng.integers(0, 2, size=(t, n)), bool),
        k=jnp.asarray(rng.integers(0, max_k + 1, size=(t, n)), jnp.int32),
        killed=jnp.asarray(rng.integers(0, 2, size=(t, n)), bool),
    )


@pytest.mark.parametrize("t,n", [(1, 1), (3, 31), (2, 32), (4, 96),
                                 (2, 512), (1, 513)])
def test_pack_unpack_round_trip(t, n):
    """Property-style: random states (every (t, n) crossing word and
    tile boundaries, so pad lanes exist in most cases) round-trip
    bit-for-bit, faulty mask included."""
    rng = np.random.default_rng(1234 + t * 1000 + n)
    cfg = SimConfig(n_nodes=n, n_faulty=0, trials=t, max_rounds=37)
    for trial in range(3):
        state = _random_state(rng, t, n, cfg.max_rounds + 1)
        faulty = jnp.asarray(rng.integers(0, 2, size=(t, n)), bool)
        pack = pack_state(cfg, state, faulty)
        assert pack.dtype == jnp.uint32
        assert pack.shape[1] == pack_width(cfg)
        back = unpack_state(pack, n)
        np.testing.assert_array_equal(np.asarray(back.x),
                                      np.asarray(state.x))
        np.testing.assert_array_equal(np.asarray(back.decided),
                                      np.asarray(state.decided))
        np.testing.assert_array_equal(np.asarray(back.k),
                                      np.asarray(state.k))
        np.testing.assert_array_equal(np.asarray(back.killed),
                                      np.asarray(state.killed))
        # the faulty mask rides its declared plane
        fb = plane_field(pack, PACK_FAULTY, 1)[:, :n]
        np.testing.assert_array_equal(np.asarray(fb).astype(bool),
                                      np.asarray(faulty))


def test_pad_lanes_killed_and_inert():
    """Pad lanes (both in-word and whole pad words) carry the killed bit
    and x = "?", with zero k/faulty/coined — the invariant every
    histogram, alive count and settled count relies on."""
    from benor_tpu.config import VALQ

    t, n = 2, 70                     # pads 70..511 inside the plane words
    cfg = SimConfig(n_nodes=n, n_faulty=0, trials=t, max_rounds=5)
    rng = np.random.default_rng(7)
    state = _random_state(rng, t, n, cfg.max_rounds)
    pack = pack_state(cfg, state, jnp.zeros((t, n), bool))
    np_total = pack.shape[2] * 32
    assert np_total >= n
    killed = plane_field(pack, PACK_KILLED, 1)
    x = plane_field(pack, 0, PACK_LAYOUT["x"][1])
    coined = plane_field(pack, PACK_COINED, 1)
    assert bool((killed[:, n:] == 1).all())
    assert bool((x[:, n:] == VALQ).all())
    assert bool((coined == 0).all())  # no round has run anywhere


def test_k_planes_follow_max_rounds():
    """The k field materializes only the planes this config's round cap
    needs — the whole point of the variable-width relayout."""
    for mr, bits in ((1, 2), (6, 3), (12, 4), (200, 8), (40000, 16)):
        cfg = SimConfig(n_nodes=8, n_faulty=0, max_rounds=mr)
        assert pack_k_bits(cfg) == bits, mr
        assert pack_width(cfg) == PACK_STATIC_WIDTH + bits
    assert pack_k_bits(SimConfig(n_nodes=8, n_faulty=0, max_rounds=12)) \
        <= PACK_LAYOUT["k"][1]


def test_partial_dtype_quorum_bounds():
    """The tally-partial narrowing follows the N-F quorum bound: int16
    whenever the quorum and tile fit 15 bits, int32 past that, int8 for
    genuinely tiny tiles."""
    assert partial_dtype(72, 512) == jnp.int16
    assert partial_dtype(20000, 512) == jnp.int16
    assert partial_dtype(40000, 512) == jnp.int32
    assert partial_dtype(500, 40000) == jnp.int32
    assert partial_dtype(60, 100) == jnp.int8


def _fused_cfg(n, t, seed, **kw):
    kw.setdefault("n_faulty", n // 4)
    kw.setdefault("max_rounds", 16)
    return SimConfig(n_nodes=n, trials=t, delivery="quorum",
                     scheduler="uniform", path="histogram",
                     use_pallas_hist=True, use_pallas_round=True,
                     seed=seed, **kw)


def _run_pair(cfg_fused, faults, state, key):
    """(unfused pallas run, fused run) final tuples for one config."""
    outs = []
    for use_round in (False, True):
        cfg = cfg_fused.replace(use_pallas_round=use_round)
        r, fin = run_consensus(cfg, state, faults, key)
        outs.append((int(r), np.asarray(fin.x), np.asarray(fin.decided),
                     np.asarray(fin.k), np.asarray(fin.killed)))
    return outs


def test_packed_vs_unpacked_bit_identity_smoke():
    """Tier-1 (non-slow) pin of the PR-8 acceptance: a fused
    (plane-packed, one-pass kernel) run equals the unfused pallas run
    bit-for-bit at a compact geometry.  The full battery (all fault
    models / coins / regimes) lives in the slow marks here and in
    tests/test_pallas_round.py."""
    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        n, t = 64, 4
        cfg = _fused_cfg(n, t, seed=2, n_faulty=26, max_rounds=6)
        assert tally.pallas_round_active(cfg)
        faults = FaultSpec.none(t, n)
        state = init_state(cfg, balanced_inputs(t, n), faults)
        outs = _run_pair(cfg, faults, state, jax.random.key(cfg.seed))
        (r0, *a), (r1, *b) = outs
        assert r0 == r1
        for x, y, name in zip(a, b, ("x", "decided", "k", "killed")):
            np.testing.assert_array_equal(x, y, err_msg=name)
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
def test_one_pass_vs_two_kernel_bit_identity():
    """The single-pass kernel (within the FUSED_ONE_PASS caps) and the
    two-kernel plane pipeline must agree bit-for-bit: force the
    two-kernel path by dropping the cap, then compare against the
    default dispatch on the same config."""
    from benor_tpu.ops import pallas_round as pr

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        n, t = 96, 8
        cfg = _fused_cfg(n, t, seed=2, n_faulty=40)
        assert tally.pallas_round_active(cfg)
        faults = FaultSpec.none(t, n)
        state = init_state(cfg, balanced_inputs(t, n), faults)
        key = jax.random.key(cfg.seed)
        r1, f1 = run_consensus(cfg, state, faults, key)

        old_cap = pr.FUSED_ONE_PASS_MAX_NODES
        pr.FUSED_ONE_PASS_MAX_NODES = 0          # demote to two-kernel
        try:
            # run the packed loop EAGERLY (run_packed is the function
            # run_consensus jits): an equal-hash cfg through the jitted
            # entry would be served the cached one-pass executable and
            # the comparison would be vacuous
            out = pr.run_packed(cfg, state, faults,
                                jax.random.key(cfg.seed))
            r2, f2 = out[0], out[1]
        finally:
            pr.FUSED_ONE_PASS_MAX_NODES = old_cap
        assert int(r1) == int(r2)
        for name in ("x", "decided", "k", "killed"):
            np.testing.assert_array_equal(
                np.asarray(getattr(f1, name)),
                np.asarray(getattr(f2, name)), err_msg=name)
        assert int(r1) > 1, "needs a multi-round scenario to pin anything"
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
def test_fused_compile_counts_match_unfused():
    """Regime discipline: the plane relayout must not change HOW MANY
    backend compiles a fused run costs vs the unfused pallas path (one
    jit entry per config either way)."""
    from benor_tpu.utils.compile_counter import count_backend_compiles

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        n, t = 96, 4
        counts = []
        for use_round, seed in ((False, 51), (True, 53)):
            cfg = _fused_cfg(n, t, seed=seed, n_faulty=24,
                             max_rounds=8).replace(
                                 use_pallas_round=use_round)
            faults = FaultSpec.none(t, n)
            state = init_state(cfg, balanced_inputs(t, n), faults)
            with count_backend_compiles() as cc:
                r, _ = run_consensus(cfg, state, faults,
                                     jax.random.key(seed))
                int(r)
            counts.append(cc.count)
        assert counts[0] == counts[1] == 1, counts
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
def test_packed_bit_identity_sliced_and_batched():
    """The slice primitive and the batched sweep's static bucket both
    dispatch onto the plane loop; both must equal the one-shot fused
    run (and hence, transitively, the unfused path)."""
    from benor_tpu.sim import run_consensus_slice, start_state

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        n, t = 96, 8
        cfg = _fused_cfg(n, t, seed=2, n_faulty=40)
        faults = FaultSpec.none(t, n)
        state = init_state(cfg, balanced_inputs(t, n), faults)
        key = jax.random.key(cfg.seed)
        r1, f1 = run_consensus(cfg, state, faults, key)
        assert int(r1) > 1

        st, r = start_state(cfg, state), 1
        while True:
            r_next, st = run_consensus_slice(cfg, st, faults, key,
                                             jnp.int32(r),
                                             jnp.int32(r + 3))
            rn = int(r_next)
            if rn == r or rn > cfg.max_rounds or bool(np.asarray(
                    (st.decided | st.killed).all())):
                break
            r = rn
        assert rn - 1 == int(r1)
        for name in ("x", "decided", "k", "killed"):
            np.testing.assert_array_equal(
                np.asarray(getattr(f1, name)),
                np.asarray(getattr(st, name)), err_msg=name)

        # the batched sweep buckets pallas configs statically
        # (quorum_specialized): the static bucket runs the SAME fused
        # loop — its per-point summary must match the one-shot run's
        from benor_tpu.sweep import run_curve_batched, summarize_final
        # faults_for must match the one-shot run's zero-crash spec (the
        # default is the first-F-faulty crash mask, a different network)
        curve = run_curve_batched(cfg, [cfg.n_faulty],
                                  balanced_inputs(t, n),
                                  faults_for=lambda c: faults)
        pt = curve.points[0]
        dec, mk, ones, _khist, dis = summarize_final(
            f1, faults.faulty, cfg.max_rounds)
        assert pt.rounds_executed == int(r1)
        assert pt.decided_frac == pytest.approx(float(dec))
        assert pt.mean_k == pytest.approx(float(mk))
        assert pt.ones_frac == pytest.approx(float(ones))
        assert pt.disagree_frac == pytest.approx(float(dis))
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
def test_per_round_packed_branch_crash_at_round():
    """benor_round's packed branch (pack/unpack at the round boundary —
    the trajectory/per-round callers) under crash_at_round: the caller
    must pad crash_round to the padded NODE total, not the plane count
    (the PR-8 relayout moved the node axis to pack.shape[2] * 32; a
    review caught the stale shape[1] crashing this exact path)."""
    from benor_tpu.models.benor import benor_round
    from benor_tpu.sim import start_state

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        n, t = 96, 4
        cr = np.where(np.arange(n) < 20, 2, 0)
        outs = {}
        for fused in (False, True):
            cfg = _fused_cfg(n, t, seed=17, n_faulty=20,
                             fault_model="crash_at_round").replace(
                                 use_pallas_round=fused)
            faults = FaultSpec.first_f(cfg, crash_rounds=cr)
            state = start_state(cfg, init_state(
                cfg, balanced_inputs(t, n), faults))
            st = state
            for r in (1, 2, 3):
                st = benor_round(cfg, st, faults, jax.random.key(cfg.seed),
                                 jnp.int32(r))
            outs[fused] = st
        for name in ("x", "decided", "k", "killed"):
            np.testing.assert_array_equal(
                np.asarray(getattr(outs[False], name)),
                np.asarray(getattr(outs[True], name)), err_msg=name)
        assert bool(np.asarray(outs[True].killed)[:, :20].all())
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
def test_pad_alias_no_double_count_sharded_witness():
    """The pad-lane masking audit for the word layout (satellite: the
    PR 3 witness bug class).  On a (1, 4) node-sharded mesh each shard
    pads its 24 local nodes to a full tile whose pad ids ALIAS the next
    shard's real range; if a pad bit inside the plane words leaked into
    the witness partials, the psum would double every aliased watched
    node's columns.  Sharded witness rows must equal the single-device
    rows bit-for-bit."""
    from benor_tpu.parallel import make_mesh, run_consensus_sharded

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        n, t = 96, 8
        cfg = _fused_cfg(n, t, seed=35, n_faulty=24).replace(
            witness_trials=(0, 3), witness_nodes=4)
        assert tally.pallas_round_active(cfg)
        faults = FaultSpec.none(t, n)
        state = init_state(cfg, balanced_inputs(t, n), faults)
        key = jax.random.key(cfg.seed)
        r1, f1, w1 = run_consensus(cfg, state, faults, key)
        r2, f2, w2 = run_consensus_sharded(cfg, state, faults, key,
                                           make_mesh(1, 4))
        assert int(r1) == int(r2)
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
        np.testing.assert_array_equal(np.asarray(f1.x), np.asarray(f2.x))
        # non-vacuous: some witnessed tally column must be non-zero
        assert np.asarray(w1).max() > 0
    finally:
        sampling.EXACT_TABLE_MAX = old
