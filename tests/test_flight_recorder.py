"""Flight recorder (SimConfig.record): the on-device round-history buffer.

Acceptance contract (ISSUE 2):
  * identical per-round (decided, killed) series across the traced,
    fused-pallas, sliced (poll_rounds), batched-sweep and sharded regimes
    on the same seed;
  * record=False leaves compile counts and results bit-identical
    (asserted via utils/compile_counter).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from benor_tpu.config import SimConfig
from benor_tpu.sim import (run_consensus, run_consensus_slice, simulate,
                           start_state)
from benor_tpu.state import (REC_COINS, REC_DECIDED, REC_KILLED, REC_MARGIN,
                             REC_UNDEC0, REC_UNDEC1, REC_UNDECQ, REC_WIDTH,
                             FaultSpec, init_state)
from benor_tpu.sweep import balanced_inputs

T, N = 8, 24

#: The cross-path fixture: count-controlling adversary + common coin.
#: Every regime — the XLA loop, the fused pallas round (counts_mode
#: 'delivered', interpret-mode on CPU), slices, the batched dynamic-F
#: engine and the sharded mesh — shares EVERY random bit here (closed-form
#: counts, one per-trial shared coin), so the full recorder buffers must
#: be bit-identical, not just the (decided, killed) series.
ADV = dict(n_nodes=N, n_faulty=4, trials=T, delivery="quorum",
           scheduler="adversarial", coin_mode="common", path="histogram",
           max_rounds=12, seed=3, record=True)


def _adv_inputs():
    cfg = SimConfig(**ADV)
    faults = FaultSpec.none(T, N)
    state = init_state(cfg, balanced_inputs(T, N), faults)
    return cfg, state, faults, jax.random.key(ADV["seed"])


def _slice_all(cfg, state, faults, key, chunk):
    """Drive run_consensus_slice to termination in ``chunk``-round steps,
    threading one recorder across slices — the poll_rounds shape."""
    st = start_state(cfg, state)
    r, rec = jnp.int32(1), None
    while True:
        r_next, st, rec = run_consensus_slice(cfg, st, faults, key, r,
                                              r + chunk, rec)
        if int(r_next) == int(r) or int(r_next) > cfg.max_rounds:
            break
        r = r_next
    return st, rec


def test_series_identical_across_all_regimes():
    """The acceptance pin: one seed, five regimes, one recorder."""
    from benor_tpu.parallel import make_mesh, run_consensus_sharded
    from benor_tpu.sweep import run_curve_batched

    cfg, state, faults, key = _adv_inputs()
    r, fin, rec = run_consensus(cfg, state, faults, key)
    rec = np.asarray(rec)
    assert int(r) >= 2                      # multi-round, or the pin is vacuous

    # fused pallas round (bit-identical here: delivered counts + common coin)
    cfg_p = cfg.replace(use_pallas_round=True)
    from benor_tpu.ops.tally import pallas_round_active
    assert pallas_round_active(cfg_p)
    rp, finp, recp = run_consensus(cfg_p, state, faults, key)
    assert int(rp) == int(r)
    np.testing.assert_array_equal(rec, np.asarray(recp))
    np.testing.assert_array_equal(np.asarray(fin.x), np.asarray(finp.x))

    # sliced (poll_rounds shape), both compute paths
    for c, chunk in ((cfg, 3), (cfg_p, 2)):
        fin_s, rec_s = _slice_all(c, state, faults, key, chunk)
        np.testing.assert_array_equal(rec, np.asarray(rec_s))
        np.testing.assert_array_equal(np.asarray(fin.x),
                                      np.asarray(fin_s.x))

    # batched dynamic-F sweep (the adversarial curve is a dyn bucket)
    cb = run_curve_batched(cfg.replace(n_faulty=0), [4, 6],
                           initial_values=balanced_inputs(T, N),
                           faults_for=lambda c: FaultSpec.none(T, N))
    np.testing.assert_array_equal(rec, cb.points[0].round_history)

    # sharded mesh (multiple shapes; counts psum'd before the row write)
    for shape in ((2, 4), (1, 8), (4, 1)):
        rs, fs, rec_m = run_consensus_sharded(cfg, state, faults, key,
                                              make_mesh(*shape))
        assert int(rs) == int(r)
        np.testing.assert_array_equal(rec, np.asarray(rec_m))


def test_uniform_dense_regimes_match():
    """Same pin on the uniform scheduler's dense path (per-lane sampled
    deliveries): traced vs sliced vs sharded share streams by the RNG
    global-id contract, so recorders must agree bit-for-bit."""
    from benor_tpu.parallel import make_mesh, run_consensus_sharded

    cfg = SimConfig(n_nodes=16, n_faulty=4, trials=4, delivery="quorum",
                    scheduler="uniform", max_rounds=16, seed=11,
                    record=True)
    faults = FaultSpec.from_faulty_list(cfg, [True] * 4 + [False] * 12)
    state = init_state(cfg, [i % 2 for i in range(16)], faults)
    key = jax.random.key(cfg.seed)
    r, fin, rec = run_consensus(cfg, state, faults, key)
    rec = np.asarray(rec)

    fin_s, rec_s = _slice_all(cfg, state, faults, key, 2)
    np.testing.assert_array_equal(rec, np.asarray(rec_s))

    rs, fs, rec_m = run_consensus_sharded(cfg, state, faults, key,
                                          make_mesh(2, 2))
    np.testing.assert_array_equal(rec, np.asarray(rec_m))


def test_record_off_results_and_compile_count():
    """record=False must be indistinguishable from a build without the
    feature: bit-identical results to record=True, and exactly ONE
    backend compile for the run (the flag is static — no hidden extra
    executables), measured by the jax.monitoring hook."""
    from benor_tpu.utils.compile_counter import count_backend_compiles

    base = dict(n_nodes=26, n_faulty=5, trials=5, delivery="quorum",
                scheduler="uniform", max_rounds=16, seed=77)
    cfg_off = SimConfig(**base)
    cfg_on = SimConfig(record=True, **base)
    faults = FaultSpec.from_faulty_list(
        cfg_off, [True] * 5 + [False] * 21)
    state = init_state(cfg_off, [i % 2 for i in range(26)], faults)
    key = jax.random.key(cfg_off.seed)

    with count_backend_compiles() as cc:
        r0, fin0 = run_consensus(cfg_off, state, faults, key)
        int(r0)
    assert cc.count == 1, cc.count

    r1, fin1, _rec = run_consensus(cfg_on, state, faults, key)
    assert int(r0) == int(r1)
    for leaf in ("x", "decided", "k", "killed"):
        np.testing.assert_array_equal(np.asarray(getattr(fin0, leaf)),
                                      np.asarray(getattr(fin1, leaf)))


def test_row_semantics():
    """Row invariants: the class columns partition the lane population,
    row 0 is the pre-round snapshot, the decided column is cumulative and
    ends at the final decided count, margins/coins behave per regime."""
    cfg, state, faults, key = _adv_inputs()
    r, fin, rec = run_consensus(cfg, state, faults, key)
    rec, rounds = np.asarray(rec), int(r)

    written = rec[:rounds + 1]
    # decided + killed + the three undecided classes == T*N on every row
    assert (written[:, :5].sum(axis=1) == T * N).all()
    # row 0: nothing decided yet, balanced inputs split the histogram
    assert written[0, REC_DECIDED] == 0 and written[0, REC_KILLED] == 0
    assert written[0, REC_UNDEC0] == written[0, REC_UNDEC1] == T * N // 2
    assert written[0, [REC_COINS, REC_MARGIN]].sum() == 0
    # cumulative decided, ending at the final state's count
    assert (np.diff(written[:, REC_DECIDED]) >= 0).all()
    assert written[-1, REC_DECIDED] == int(np.asarray(fin.decided).sum())
    # unwritten tail rows stay zero
    assert (rec[rounds + 1:] == 0).all()
    # the forced-tie round: every live lane flips, margin 0; the common
    # coin then aligns values, so a later round shows a positive margin
    assert written[1, REC_COINS] == T * N
    assert written[1, REC_MARGIN] == 0
    assert written[rounds, REC_MARGIN] > 0


def test_recorder_vs_debug_and_simulate_arity():
    """simulate() appends the recorder under cfg.record; cfg.record is
    rejected on the oracle backends (no device loop to fill)."""
    cfg = SimConfig(n_nodes=10, n_faulty=2, trials=2, delivery="quorum",
                    scheduler="uniform", seed=9, record=True)
    rounds, final, faults, rec = simulate(
        cfg, [1] * 10, [True] * 2 + [False] * 8)
    assert np.asarray(rec).shape == (cfg.max_rounds + 1, REC_WIDTH)
    with pytest.raises(ValueError, match="record"):
        SimConfig(n_nodes=4, n_faulty=0, backend="express", record=True)


def test_tpu_network_round_history():
    """TpuNetwork.get_round_history(): the parity-API surface, live under
    poll_rounds slicing and loud when record is off."""
    from benor_tpu.backends.tpu import TpuNetwork

    cfg = SimConfig(n_nodes=10, n_faulty=2, trials=4, delivery="quorum",
                    scheduler="uniform", seed=1, max_rounds=16,
                    record=True, poll_rounds=2)
    net = TpuNetwork(cfg, [1] * 10, [True] * 2 + [False] * 8)
    seen = []
    net.start(on_slice=lambda: seen.append(len(net.get_round_history())))
    hist = net.get_round_history()
    assert len(hist) == net.rounds_executed + 1
    assert hist[0]["round"] == 0
    # recorder counts are global over ALL trials
    assert hist[-1]["decided"] == int(np.asarray(net.state.decided).sum())
    assert seen and seen[0] <= len(hist)    # grew live between slices

    # one-shot (no poll) path fills it too; record off raises
    cfg1 = cfg.replace(poll_rounds=0)
    net1 = TpuNetwork(cfg1, [1] * 10, [True] * 2 + [False] * 8)
    net1.start()
    assert net1.get_round_history() == hist
    net0 = TpuNetwork(cfg1.replace(record=False), [1] * 10,
                      [True] * 2 + [False] * 8)
    net0.start()
    with pytest.raises(ValueError, match="record=True"):
        net0.get_round_history()


def test_resume_threads_recorder():
    """resume_consensus keeps filling a checkpointed run's buffer: cut at
    round c, resume with the partial recorder, get the one-shot buffer."""
    from benor_tpu.sim import resume_consensus

    cfg, state, faults, key = _adv_inputs()
    r, fin, rec = run_consensus(cfg, state, faults, key)

    st = start_state(cfg, state)
    r_cut, st_cut, rec_cut = run_consensus_slice(
        cfg, st, faults, key, jnp.int32(1), jnp.int32(2), None)
    rr, fr, rec_res = resume_consensus(cfg, st_cut, faults, key,
                                       int(r_cut), recorder=rec_cut)
    assert int(rr) == int(r)
    np.testing.assert_array_equal(np.asarray(rec), np.asarray(rec_res))
    np.testing.assert_array_equal(np.asarray(fin.x), np.asarray(fr.x))
