"""Oracle <-> scheduler DISTRIBUTION parity (r3 VERDICT items 4 + 7).

SURVEY §7 ranks "faithful asynchrony that still exhibits textbook Ben-Or
round distributions" as hard-part #1.  These tests settle it with a sharper
statement than a statistical match — a structural theorem about the
reference contract itself:

  Within the reference's expressible scenario space, crash-from-birth
  faults are pinned to exactly F (launchNodes.ts:12-13), so the live
  population equals the quorum N-F.  Every tally therefore contains the
  FULL live population in ANY delivery order — the event-loop asynchrony
  is tally-invisible:

  (1) Decisions/adoptions depend only on shared counts, and coin draws
      matter only through their per-round multiset (the same shared-stream
      segment in any order).  Every run that DECIDES has a final trace
      that is bit-identical across delivery orders (fifo == shuffle).
  (2) Order-dependence survives only in runs CAPPED immediately after a
      coin phase: the final x of undecided lanes is the raw coin
      assignment, which permutes with delivery order while its per-trial
      multiset stays invariant.
  (3) Consequently the rounds-to-decide law has a single stochastic
      driver — iid fair coins — and matches the tpu backend's
      uniform-quorum scheduler law (two-sample KS over ~10^3 per-trial
      samples).  The asynchrony-model gap the round-3 VERDICT hypothesized
      ("event-loop delivery is not uniform-without-replacement") is
      vacuous inside the reference contract: there is no delivery slack
      for the schedulers to disagree over.  (Slack exists only in
      framework extensions — alive > quorum via FaultSpec.none — which
      the oracles, faithfully, cannot express.)

The engine is the batched native oracle (one ctypes call per [S] seed
vector, native/express_oracle.cpp:benor_express_run_batch).
"""

import numpy as np
import pytest

from benor_tpu.backends import native_oracle
from benor_tpu.config import SimConfig

pytestmark = pytest.mark.skipif(not native_oracle.native_available(),
                                reason="g++ unavailable")

N, F = 100, 40
FAULTY = [True] * F + [False] * (N - F)
# balanced healthy inputs: phase-1 ties -> "?" votes -> every round coins
VALS = [0] * F + [i % 2 for i in range(N - F)]
HEALTHY = slice(F, N)


def _batch(order, max_rounds=64, n_seeds=200):
    cfg = SimConfig(n_nodes=N, n_faulty=F, backend="native",
                    max_rounds=max_rounds, oracle_order=order)
    return native_oracle.run_batch(cfg, VALS, FAULTY,
                                   np.arange(n_seeds, dtype=np.uint32))


def test_batch_matches_single_runs():
    """The [S]-seed batch entry is bit-identical to S single-seed calls."""
    n, f = 20, 6
    vals = [i % 2 for i in range(n)]
    faulty = [True] * f + [False] * (n - f)
    for order in ("fifo", "shuffle"):
        cfg = SimConfig(n_nodes=n, n_faulty=f, backend="native",
                        max_rounds=24, oracle_order=order)
        seeds = np.arange(12, dtype=np.uint32)
        out = native_oracle.run_batch(cfg, vals, faulty, seeds)
        assert (out["steps"] >= 0).all()
        for i, sd in enumerate(seeds):
            net = native_oracle.NativeExpressNetwork(
                cfg.replace(seed=int(sd)), vals, faulty)
            net.start()
            np.testing.assert_array_equal(net._x, out["x"][i])
            np.testing.assert_array_equal(net._k, out["k"][i])
            np.testing.assert_array_equal(net._decided.astype(bool),
                                          out["decided"][i])


def test_ks_helper_matches_scipy():
    """results.ks_two_sample (scipy-free, used by the RESULTS study) agrees
    with scipy's asymptotic two-sample KS."""
    scipy_stats = pytest.importorskip("scipy.stats")
    from benor_tpu.results import ks_two_sample

    rng = np.random.default_rng(0)
    a = rng.integers(2, 7, 400)
    b = rng.integers(2, 7, 500) + (rng.random(500) < 0.15)
    d, p = ks_two_sample(a, b)
    ref = scipy_stats.ks_2samp(a, b, method="asymp")
    assert d == pytest.approx(ref.statistic, abs=1e-12)
    assert p == pytest.approx(ref.pvalue, abs=0.02)


@pytest.mark.slow
def test_decided_runs_are_delivery_order_invariant():
    """Theorem (1): every decided run's final trace is BIT-IDENTICAL
    between fifo and shuffle delivery — the asynchrony is tally-invisible
    under the reference contract (alive == quorum)."""
    a = _batch("fifo")
    b = _batch("shuffle")
    assert a["decided"][:, HEALTHY].all(), "scenario must decide"
    np.testing.assert_array_equal(a["x"], b["x"])
    np.testing.assert_array_equal(a["k"], b["k"])
    np.testing.assert_array_equal(a["decided"], b["decided"])


@pytest.mark.slow
def test_capped_coin_phase_permutes_assignment_only():
    """Theorem (2): cap the run right after the round-1 coin phase — the
    one window where delivery order is observable.  Per-node coin values
    permute; the per-trial multiset is invariant."""
    a = _batch("fifo", max_rounds=1, n_seeds=40)
    b = _batch("shuffle", max_rounds=1, n_seeds=40)
    ax, bx = a["x"][:, HEALTHY], b["x"][:, HEALTHY]
    assert not a["decided"][:, HEALTHY].any()
    # some seed shows a different per-node assignment...
    assert (ax != bx).any(axis=1).all(), \
        "every capped-after-coin seed should permute some assignment"
    # ...but the multiset of coin values never changes
    np.testing.assert_array_equal(np.sort(ax, axis=1), np.sort(bx, axis=1))


@pytest.mark.slow
def test_rounds_to_decide_law_matches_tpu_uniform_scheduler():
    """Theorem (3): the oracle's per-trial rounds-to-decide law equals the
    tpu backend's under the uniform-quorum scheduler — two-sample KS on
    ~500 independent per-trial samples (lanes are lockstep-correlated, so
    the honest unit is the trial)."""
    import jax

    from benor_tpu.sim import run_consensus
    from benor_tpu.state import FaultSpec, init_state

    S = 500
    out = _batch("shuffle", n_seeds=S)
    k_oracle = out["k"][:, HEALTHY].max(axis=1) - 1

    cfg = SimConfig(n_nodes=N, n_faulty=F, trials=S, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=64,
                    seed=11)
    faults = FaultSpec.from_faulty_list(cfg, FAULTY)
    state = init_state(cfg, np.tile(np.asarray(VALS, np.int8), (S, 1)),
                       faults)
    _, fin = run_consensus(cfg, state, faults, jax.random.key(11))
    k_tpu = np.asarray(fin.k)[:, HEALTHY].max(axis=1) - 1

    from benor_tpu.results import ks_two_sample
    stat, pvalue = ks_two_sample(k_oracle, k_tpu)
    assert pvalue > 0.01, (stat, pvalue, np.bincount(k_oracle),
                           np.bincount(k_tpu))
    # both laws live where textbook Ben-Or puts them: almost everything
    # decides within a few coin rounds
    assert abs(k_oracle.mean() - k_tpu.mean()) < 0.2
