"""gridpipe (PR 16) — 2D (trials x nodes) grid placement + the
compile-ahead/execute-behind sweep pipeline.

Pins the PR 16 house rules:

  * ``run_consensus_grid`` is bit-identical at EVERY mesh shape —
    (1, 1) falls through to the traced loop, (1, d) is exactly
    ``run_consensus_sharded``, and (t, n) with t > 1 multiplies the
    node-axis psum tallies with trials-axis data parallelism (verified
    against a NumPy oracle and the flagship ladder regime);
  * recorder / witness / heartbeat planes survive 2D placement
    unchanged (the partition-rule table replicates the round-major
    observation buffers);
  * ``run_points_batched(pipeline=True)`` is bit-identical to serial
    dispatch in the science fields AND the per-bucket backend compile
    counts, reports ``headroom_reclaimed_s`` against the serial
    overlap model, and keeps heartbeat/verbose output ordered by
    bucket completion (bucket_index attached, no torn lines);
  * a pipelined journaled sweep SIGKILLed mid-flight resumes
    bit-identically on a DIFFERENT mesh shape with exactly
    n_remaining_buckets compiles (fingerprints exclude the mesh —
    results are mesh-independent — while the v2 record stamp pins
    mesh/pipeline provenance so in-place edits rerun);
  * the sweep gate's reclaimed-headroom checks fire when a pipelined
    manifest reports reclaimed ~ 0 against a substantive serial model,
    and stay silent below the CPU-smoke noise floor.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.ops import sampling
from benor_tpu.parallel import (auto_factor, make_grid_mesh, make_mesh,
                                partition_rules, run_consensus_grid,
                                run_consensus_sharded)
from benor_tpu.parallel.mesh import AXIS_NODES, AXIS_TRIALS
from benor_tpu.sim import run_consensus
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import run_curve_batched, run_points_batched
from benor_tpu.sweepscope import read_journal
from benor_tpu.sweepscope.gate import (RECLAIM_MODEL_FLOOR_S,
                                       compare_sweep)
from benor_tpu.sweepscope.journal import BUCKET_KIND

try:
    from jax import shard_map as shard_map
except ImportError:                                    # 0.4.x
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, F, T = 16, 4, 8
FAULTY = [True] * F + [False] * (N - F)
VALS = [i % 2 for i in range(N)]

#: Mixed-bucket sweep geometry (mirrors test_sweepscope): two CF-regime
#: points share a dyn bucket, one exact-table point gets a static
#: bucket — the smallest sweep exercising BOTH bucket kinds under the
#: pipeline and the grid.
CF_N = 9000
MIXED_FS = [600, 1200, CF_N - sampling.EXACT_TABLE_MAX + 500]


def _cfg(**kw):
    base = dict(n_nodes=N, n_faulty=F, trials=T, delivery="quorum",
                scheduler="uniform", path="histogram", max_rounds=8,
                seed=7)
    base.update(kw)
    return SimConfig(**base)


def _sweep_cfg(**kw):
    base = dict(n_nodes=CF_N, n_faulty=0, trials=4, delivery="quorum",
                scheduler="uniform", path="histogram", max_rounds=8,
                seed=3)
    base.update(kw)
    return SimConfig(**base)


def _inputs(cfg):
    faults = FaultSpec.from_faulty_list(cfg, FAULTY)
    state = init_state(cfg, VALS, faults)
    return state, faults, jax.random.key(cfg.seed)


def _assert_state_equal(s1, s2):
    for f in ("x", "decided", "k", "killed"):
        np.testing.assert_array_equal(np.asarray(getattr(s1, f)),
                                      np.asarray(getattr(s2, f)))


def science(p):
    return (p.rounds_executed, p.decided_frac, p.mean_k, p.ones_frac,
            p.disagree_frac, tuple(p.k_hist.tolist()))


def assert_bit_equal(pa, pb):
    assert len(pa) == len(pb)
    for a, b in zip(pa, pb):
        assert science(a) == science(b), (a.n_faulty, b.n_faulty)


# --------------------------------------------------------------------------
# 2D mesh: bit-identity at every shape, vs the traced AND sharded oracles
# --------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(1, 1), (1, 4), (2, 2), (2, 4)])
def test_grid_bit_identical_to_traced_loop(shape):
    cfg = _cfg()
    state, faults, key = _inputs(cfg)
    r1, s1 = run_consensus(cfg, state, faults, key)
    mesh = make_grid_mesh(trial_shards=shape[0], node_shards=shape[1])
    r2, s2 = run_consensus_grid(cfg, state, faults, key, mesh=mesh)
    assert int(r1) == int(r2)
    _assert_state_equal(s1, s2)


def test_grid_1xd_is_exactly_the_sharded_runner():
    """(1, d) must reproduce run_consensus_sharded verbatim — the grid
    entry point adds placement, never a second code path."""
    cfg = _cfg()
    state, faults, key = _inputs(cfg)
    mesh = make_mesh(1, 4)
    r_sh, s_sh = run_consensus_sharded(cfg, state, faults, key, mesh)
    r_gr, s_gr = run_consensus_grid(
        cfg, state, faults, key,
        mesh=make_grid_mesh(trial_shards=1, node_shards=4))
    assert int(r_sh) == int(r_gr)
    _assert_state_equal(s_sh, s_gr)


def test_grid_auto_mesh_uses_available_devices():
    cfg = _cfg()
    state, faults, key = _inputs(cfg)
    r1, s1 = run_consensus(cfg, state, faults, key)
    mesh = make_grid_mesh(cfg)
    assert mesh.size > 1               # conftest forces 8 CPU devices
    r2, s2 = run_consensus_grid(cfg, state, faults, key)
    assert int(r1) == int(r2)
    _assert_state_equal(s1, s2)


def test_auto_factor_properties():
    # prefers (devices used, node shards): 8 devices, N divisible by 8
    assert auto_factor(8, 8, 16) == (1, 8)
    # N=6: node axis tops out at 6... but (4, 2) uses all 8 devices
    assert auto_factor(8, 4, 6) == (4, 2)
    # odd extents: best full-device factoring wins, else largest usable
    assert auto_factor(8, 3, 5) == (1, 5)
    assert auto_factor(1, 64, 4096) == (1, 1)
    for d, t, n in [(8, 4, 6), (8, 8, 16), (6, 2, 9), (8, 3, 5)]:
        ts, ns = auto_factor(d, t, n)
        assert ts * ns <= d and t % ts == 0 and n % ns == 0


def test_partition_rules_observation_entries_follow_cfg():
    plain = partition_rules(_cfg())
    assert "recorder" not in plain and "witness" not in plain
    for leaf in ("x", "decided", "k", "killed", "faulty", "crash_round",
                 "recover_round"):
        assert plain[leaf] == P(AXIS_TRIALS, AXIS_NODES)
    assert plain["base_key"] == P()
    rec = partition_rules(_cfg(record=True, witness_trials=(0, 1),
                               witness_nodes=2))
    assert rec["recorder"] == P() and rec["witness"] == P()


def test_grid_recorder_witness_parity():
    """The observation planes must survive 2D placement bit-identically
    (the round-major buffers are psum-reduced in-kernel, replicated on
    exit)."""
    cfg = _cfg(record=True, witness_trials=(0, 1), witness_nodes=2)
    state, faults, key = _inputs(cfg)
    out1 = run_consensus(cfg, state, faults, key)
    out2 = run_consensus_grid(
        cfg, state, faults, key,
        mesh=make_grid_mesh(trial_shards=2, node_shards=2))
    assert len(out1) == len(out2) == 4
    assert int(out1[0]) == int(out2[0])
    _assert_state_equal(out1[1], out2[1])
    for a, b in zip(jax.tree_util.tree_leaves(out1[2:]),
                    jax.tree_util.tree_leaves(out2[2:])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grid_psum_tallies_match_numpy_oracle():
    """The 2D contract in one shard_map: trials-axis data parallelism
    multiplying node-axis psum tallies, checked against np.sum /
    np.bincount on the unsharded operand."""
    mesh = make_mesh(2, 2)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 2, size=(4, 64)).astype(np.int32)

    def tally(xs):
        ones = jnp.sum(xs, axis=1, keepdims=True)
        return jax.lax.psum(ones, AXIS_NODES)

    out = shard_map(tally, mesh=mesh,
                    in_specs=P(AXIS_TRIALS, AXIS_NODES),
                    out_specs=P(AXIS_TRIALS, None))(x)
    np.testing.assert_array_equal(np.asarray(out)[:, 0],
                                  x.sum(axis=1))

    def hist(xs):
        oh = (xs[..., None] == jnp.arange(2)[None, None, :])
        return jax.lax.psum(jnp.sum(oh, axis=1), AXIS_NODES)

    h = shard_map(hist, mesh=mesh,
                  in_specs=P(AXIS_TRIALS, AXIS_NODES),
                  out_specs=P(AXIS_TRIALS, None))(x)
    want = np.stack([np.bincount(row, minlength=2) for row in x])
    np.testing.assert_array_equal(np.asarray(h), want)


def test_grid_flagship_regime_2d():
    """The scaling ladder's flagship regime (forced-tie adversarial,
    histogram psums) on a t>1 grid == the traced loop — the small-scale
    twin of the committed MULTICHIP_r06 capture."""
    from benor_tpu.meshscope.scaling import _ladder_cfg
    from benor_tpu.sweep import balanced_inputs
    cfg = _ladder_cfg(64, 4, 4, 0)
    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes),
                       faults)
    key = jax.random.key(cfg.seed)
    r1, s1 = run_consensus(cfg, state, faults, key)
    r2, s2 = run_consensus_grid(
        cfg, state, faults, key,
        mesh=make_grid_mesh(trial_shards=2, node_shards=2))
    assert int(r1) == int(r2) == cfg.max_rounds   # forced tie: runs capped
    _assert_state_equal(s1, s2)


# --------------------------------------------------------------------------
# pipelined dispatch: bit-identity, compile parity, ordered heartbeat
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def pipe_runs(tmp_path_factory):
    """One mixed dyn+static curve run three ways — serial (the oracle),
    pipelined+journaled, and pipelined on a (2, 2) grid — paying the
    CF-regime compiles once for the whole module."""
    td = tmp_path_factory.mktemp("gridpipe")
    jp = str(td / "journal.jsonl")
    hb = str(td / "heartbeat.jsonl")
    cfg = _sweep_cfg(heartbeat_rounds=4)
    oracle = run_curve_batched(cfg, MIXED_FS)
    piped = run_curve_batched(cfg, MIXED_FS, pipeline=True,
                              journal_path=jp, heartbeat_path=hb)
    meshed = run_curve_batched(
        cfg, MIXED_FS, pipeline=True,
        mesh=make_grid_mesh(trial_shards=2, node_shards=2))
    return cfg, jp, hb, oracle, piped, meshed


def test_pipeline_bit_identical_and_compile_parity(pipe_runs):
    _, _, _, oracle, piped, _ = pipe_runs
    assert set(oracle.bucket_kinds) == {"dyn", "static"}
    assert_bit_equal(oracle.points, piped.points)
    assert piped.bucket_kinds == oracle.bucket_kinds
    assert piped.bucket_point_indices == oracle.bucket_point_indices
    # the pipeline moves WHERE compiles happen (the compile-ahead
    # thread), never HOW MANY — per-bucket counts must match serial
    assert piped.bucket_compile_counts == oracle.bucket_compile_counts
    assert piped.compile_count == oracle.compile_count
    assert piped.pipelined and not oracle.pipelined
    assert piped.span_s > 0.0
    assert piped.headroom_reclaimed_s >= 0.0


def test_pipeline_on_2d_mesh_bit_identical(pipe_runs):
    cfg, _, _, oracle, _, meshed = pipe_runs
    assert_bit_equal(oracle.points, meshed.points)
    assert meshed.mesh_shape == [2, 2]
    assert meshed.bucket_compile_counts == oracle.bucket_compile_counts


def test_pipeline_journal_carries_mesh_and_pipeline_provenance(pipe_runs):
    _, jp, _, _, piped, _ = pipe_runs
    recs = [r for r in read_journal(jp) if r.get("kind") == BUCKET_KIND]
    assert len(recs) == piped.n_buckets
    for rec in recs:
        assert rec["pipelined"] is True
        assert rec["mesh_shape"] is None          # no mesh on this run
        assert rec["stamp_sha256"]


def test_heartbeat_ordered_bucket_completion_no_torn_lines(pipe_runs):
    """The watch-tail pin: under async dispatch every heartbeat line
    parses whole (one writer — the ordered main thread), carries the
    completing bucket's index, and arrives in completion order."""
    _, _, hb, _, piped, _ = pipe_runs
    with open(hb) as fh:
        lines = fh.read().splitlines()
    assert lines
    recs = [json.loads(ln) for ln in lines]       # no torn lines
    sweep_beats = [r for r in recs
                   if r.get("label") == "sweep" and "bucket_index" in r]
    assert len(sweep_beats) == piped.n_buckets
    idx = [r["bucket_index"] for r in sweep_beats]
    assert idx == sorted(idx) == list(range(piped.n_buckets))
    done = [r["points_done"] for r in sweep_beats]
    assert done == sorted(done)                   # monotone progress
    assert done[-1] == len(MIXED_FS)
    assert sweep_beats[-1]["done"] is True


def test_pipeline_verbose_lines_whole_and_ordered(capsys):
    """Verbose output under the compile-ahead thread: one whole line
    per bucket, in bucket order (the worker never writes stdout)."""
    cfg = SimConfig(n_nodes=64, n_faulty=0, trials=8,
                    delivery="quorum", scheduler="uniform",
                    path="histogram", max_rounds=8, seed=5)
    cfgs = [cfg.replace(n_faulty=f) for f in (8, 12, 16)]
    run_points_batched(cfg, cfgs, pipeline=True, verbose=True)
    out = capsys.readouterr().out
    marks = [ln for ln in out.splitlines() if ln.startswith("  bucket ")]
    assert [ln.split("/")[0] for ln in marks] == \
        [f"  bucket {i + 1}" for i in range(3)]


# --------------------------------------------------------------------------
# SIGKILL mid-pipeline: resume bit-equal on a DIFFERENT mesh shape
# --------------------------------------------------------------------------


_CHILD_SRC = """\
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, sys.argv[2])
from benor_tpu.config import SimConfig
from benor_tpu.sweep import default_crash_faults, run_points_batched

base = SimConfig(n_nodes=64, n_faulty=0, trials=8, delivery="quorum",
                 scheduler="uniform", path="histogram", max_rounds=8,
                 seed=5)
cfgs = [base.replace(n_faulty=f) for f in (8, 12, 16)]


def slow_faults(c):
    # widen the kill window (masks identical to the default policy, so
    # the fingerprints match the parent's cross-mesh resume)
    time.sleep(1.0)
    return default_crash_faults(c)


run_points_batched(base, cfgs, faults_for=slow_faults,
                   journal_path=sys.argv[1], pipeline=True)
"""


def test_sigkill_mid_pipeline_resumes_on_different_mesh(tmp_path):
    """The elastic-sweep acceptance: SIGKILL a PIPELINED journaled
    sweep mid-bucket, resume on a different mesh shape, pin
    bit-equality vs the uninterrupted oracle AND exactly
    n_remaining_buckets compiles — journal fingerprints exclude the
    mesh because the results are mesh-independent."""
    jp = str(tmp_path / "kill_journal.jsonl")
    script = tmp_path / "child.py"
    script.write_text(_CHILD_SRC)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, str(script), jp, REPO],
                            env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            done = [r for r in read_journal(jp)
                    if r.get("kind") == BUCKET_KIND]
            if done:
                break
            time.sleep(0.05)
        assert proc.poll() is None, \
            "child exited before the kill — the sweep ran to completion"
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    recs = [r for r in read_journal(jp) if r.get("kind") == BUCKET_KIND]
    n_done = len(recs)
    assert 1 <= n_done < 3, n_done
    assert all(r["pipelined"] for r in recs)

    base = SimConfig(n_nodes=64, n_faulty=0, trials=8,
                     delivery="quorum", scheduler="uniform",
                     path="histogram", max_rounds=8, seed=5)
    cfgs = [base.replace(n_faulty=f) for f in (8, 12, 16)]
    oracle = run_points_batched(base, cfgs)
    resumed = run_points_batched(
        base, cfgs, journal_path=jp, resume=True, pipeline=True,
        mesh=make_grid_mesh(trial_shards=1, node_shards=8))
    assert resumed.compile_count == 3 - n_done
    assert sum(resumed.bucket_reused) == n_done
    assert resumed.mesh_shape == [1, 8]
    assert_bit_equal(oracle.points, resumed.points)


def test_journal_mesh_provenance_tamper_reruns(tmp_path):
    """The v2 stamp matrix: editing a record's mesh_shape or pipelined
    field IN PLACE breaks stamp_sha256 — the bucket reruns instead of
    reusing a record whose provenance was rewritten."""
    base = SimConfig(n_nodes=64, n_faulty=0, trials=8,
                     delivery="quorum", scheduler="uniform",
                     path="histogram", max_rounds=8, seed=5)
    cfgs = [base.replace(n_faulty=f) for f in (8, 12, 16)]
    jp = str(tmp_path / "journal.jsonl")
    clean = run_points_batched(base, cfgs, journal_path=jp,
                               pipeline=True)
    for field, value in (("mesh_shape", [4, 2]), ("pipelined", False)):
        tampered = tmp_path / f"tamper_{field}.jsonl"
        lines = []
        with open(jp) as fh:
            for i, ln in enumerate(fh):
                rec = json.loads(ln)
                if i == 0 and rec.get("kind") == BUCKET_KIND:
                    rec[field] = value
                lines.append(json.dumps(rec))
        tampered.write_text("\n".join(lines) + "\n")
        cb = run_points_batched(base, cfgs, journal_path=str(tampered),
                                resume=True)
        assert cb.bucket_reused.count(True) == 2, field
        assert cb.compile_count == 1, field
        assert_bit_equal(clean.points, cb.points)


# --------------------------------------------------------------------------
# checkpoint: grid provenance + auto-mesh resume
# --------------------------------------------------------------------------


def test_checkpoint_mesh_shape_roundtrip_and_auto_resume(tmp_path):
    from benor_tpu.utils.checkpoint import (resume_from,
                                            save_checkpoint,
                                            saved_mesh_shape)
    cfg = _cfg(max_rounds=12)
    state, faults, key = _inputs(cfg)
    rounds_full, final_full = run_consensus(cfg, state, faults, key)
    r_cap, mid = run_consensus(cfg.replace(max_rounds=2), state, faults,
                               key)
    plain = str(tmp_path / "plain.npz")
    save_checkpoint(plain, cfg, mid, faults, next_round=int(r_cap) + 1)
    assert saved_mesh_shape(plain) is None      # byte layout unchanged
    gridded = str(tmp_path / "grid.npz")
    save_checkpoint(gridded, cfg, mid, faults,
                    next_round=int(r_cap) + 1, mesh_shape=(2, 4))
    assert saved_mesh_shape(gridded) == (2, 4)
    rounds_res, final_res, _ = resume_from(gridded, mesh="auto")
    assert int(rounds_res) == int(rounds_full)
    _assert_state_equal(final_full, final_res)


# --------------------------------------------------------------------------
# gate: reclaimed-headroom findings
# --------------------------------------------------------------------------


def _pipe_manifest(pipelined, model, reclaimed, base=None):
    """A minimal comparable manifest pair for the pipeline checks."""
    buckets = [
        {"index": 0, "kind": "dyn", "size": 2, "point_indices": [0, 1],
         "prepare_s": 0.1, "compile_s": model, "run_s": model,
         "fetch_s": 0.05, "compile_count": 1},
        {"index": 1, "kind": "static", "size": 1, "point_indices": [2],
         "prepare_s": 0.1, "compile_s": model, "run_s": model,
         "fetch_s": 0.05, "compile_count": 1},
    ]
    from benor_tpu.sweepscope.gate import (ideal_pipeline_s,
                                           overlap_headroom_s, serial_s)
    ser = serial_s(buckets)
    span = ser - reclaimed
    doc = {
        "kind": "sweep_manifest", "schema_version": 2,
        "platform": "cpu", "device_kind": "cpu",
        "scale": {"n_nodes": 64, "trials": 8, "max_rounds": 8,
                  "seed": 5, "n_points": 3, "f_values": [8, 12, 16]},
        "n_buckets": 2, "compile_count": 2, "wall_s": ser,
        "buckets": buckets,
        "stage_totals": {"prepare_s": 0.2, "compile_s": 2 * model,
                         "run_s": 2 * model, "fetch_s": 0.1},
        "serial_s": ser,
        "ideal_pipeline_s": ideal_pipeline_s(buckets),
        "overlap_headroom_s": overlap_headroom_s(buckets),
        "overlap_headroom_frac": overlap_headroom_s(buckets) / ser,
        "pipeline": {
            "pipelined": pipelined, "span_s": span,
            "headroom_model_s": overlap_headroom_s(buckets),
            "headroom_reclaimed_s": reclaimed,
            "headroom_reclaimed_frac":
                (reclaimed / overlap_headroom_s(buckets)
                 if overlap_headroom_s(buckets) > 0 else 0.0)},
        "telescoping": {"stage_sum_s": ser, "wall_s": ser,
                        "coverage": 1.0},
    }
    return doc


def test_gate_fires_when_pipeline_reclaims_nothing():
    """reclaimed ~ 0 where the serial model shows substantive headroom
    == the compile-ahead thread serialized; the gate must say so."""
    base = _pipe_manifest(True, model=2.0, reclaimed=1.5)
    dead = _pipe_manifest(True, model=2.0, reclaimed=0.0)
    findings = compare_sweep(dead, base)
    assert any(f.metric == "pipeline.headroom_reclaimed_frac"
               for f in findings)
    assert compare_sweep(base, base) == []


def test_gate_reclaim_floor_disarms_cpu_smoke_noise():
    """Below RECLAIM_MODEL_FLOOR_S the serial model is timer noise —
    reclaimed ~ 0 must NOT gate (the committed CPU baseline relies on
    this)."""
    tiny = _pipe_manifest(True, model=RECLAIM_MODEL_FLOOR_S / 10,
                          reclaimed=0.0)
    assert compare_sweep(tiny, tiny) == []


def test_gate_missing_pipeline_block_is_a_finding():
    base = _pipe_manifest(True, model=2.0, reclaimed=1.5)
    broken = dict(base)
    broken["pipeline"] = None
    findings = compare_sweep(broken, base)
    assert any(f.metric == "pipeline" for f in findings)
