"""benor-topo (benor_tpu/topo) — the structured-delivery plane's tests.

The ISSUE 12 acceptance pins, in tier-1:

  * ``topology='complete'`` is the IDENTITY spec: bit-identical to the
    pre-topology path in results AND compile counts, across the traced,
    batched and sharded regimes (the spec normalizes to ``None`` at the
    SimConfig boundary, so the configs hash equal and the jit cache
    simply hits).
  * ring/torus neighbor indices match a tiny NumPy oracle; the
    random-regular table is reproducible, self-loop-free and
    duplicate-free; NO dense N x N adjacency tensor exists anywhere on
    the compiled path (asserted on the jaxpr's intermediate shapes).
  * committee membership is bit-reproducible under a fixed seed and
    the committee-size sweep runs as ONE bucket executable whose
    points are bit-identical to the per-point oracle.
  * a witnessed torus run audits CLEAN under the relaxed neighborhood
    invariants, and a seeded violation (a tally no d+1 neighborhood
    could deliver) is pinpointed to its (trial, node, round).
  * the serve plane accepts/validates the new CONFIG_FIELDS with
    structured 400s and never coalesces mismatched topologies.
"""

import copy
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from benor_tpu import audit
from benor_tpu.config import SimConfig
from benor_tpu.ops.collectives import SINGLE
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import (run_curve_batched, run_point,
                             run_points_batched, sweep_bucket_key)
from benor_tpu.topo import TopologySpec, build_neighbor_table, parse_topology
from benor_tpu.topo.curves import (committee_curve, degree_curve,
                                   unanimity_fault)
from benor_tpu.topo.deliver import neighbor_ids, neighborhood_counts
from benor_tpu.topo import committees
from benor_tpu.utils.compile_counter import count_backend_compiles

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import check_metrics_schema  # noqa: E402


# --------------------------------------------------------------------------
# spec grammar + metadata
# --------------------------------------------------------------------------


def test_parse_grammar_and_normalization():
    assert parse_topology(None) is None
    assert parse_topology("complete") is None
    assert parse_topology("ring:4") == TopologySpec("ring", 4)
    assert parse_topology("torus2d:8x4") == TopologySpec(
        "torus2d", 4, rows=8, cols=4)
    assert parse_topology("expander:6") == TopologySpec("expander", 6)
    assert parse_topology("random_regular:5:9") == TopologySpec(
        "random_regular", 5, graph_seed=9)
    # canonical round-trip
    for s in ("ring:4", "torus2d:8x4", "expander:6", "random_regular:5:9"):
        assert parse_topology(s).spec_string() == s


@pytest.mark.parametrize("bad", [
    "ring", "ring:x", "ring:3", "torus2d:8", "torus2d:axb",
    "moebius:4", "random_regular:", "ring:4:5", "torus2d:2x8",
])
def test_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        spec = parse_topology(bad)
        spec.validate(16)


def test_config_normalizes_complete_to_none():
    c0 = SimConfig(n_nodes=16, n_faulty=2, trials=4)
    c1 = SimConfig(n_nodes=16, n_faulty=2, trials=4, topology="complete")
    assert c1.topology is None
    assert c0 == c1 and hash(c0) == hash(c1)


def test_config_rejections():
    with pytest.raises(ValueError, match="delivery='all'"):
        SimConfig(n_nodes=16, n_faulty=2, topology="ring:2",
                  delivery="quorum")
    with pytest.raises(ValueError, match="backend"):
        SimConfig(n_nodes=16, n_faulty=2, topology="ring:2",
                  backend="express")
    with pytest.raises(ValueError, match="mutually exclusive"):
        SimConfig(n_nodes=16, n_faulty=2, topology="ring:2",
                  committee_cap=2, committee_count=2, committee_size=4)
    with pytest.raises(ValueError, match="committee_count"):
        SimConfig(n_nodes=16, n_faulty=2, committee_cap=2,
                  committee_count=3, committee_size=4)
    with pytest.raises(ValueError, match="committee_cap"):
        SimConfig(n_nodes=16, n_faulty=2, committee_count=2)
    with pytest.raises(ValueError, match="equivocate"):
        SimConfig(n_nodes=16, n_faulty=2, committee_cap=2,
                  committee_count=2, committee_size=4,
                  fault_model="equivocate")
    with pytest.raises(ValueError, match="covers"):
        SimConfig(n_nodes=17, n_faulty=2, topology="torus2d:4x4")


def test_expander_aliasing_offsets_rejected():
    # +-32 mod 64 name the SAME sender: an aliasing pair would silently
    # double-count that sender's vote in every tally
    with pytest.raises(ValueError, match="alias"):
        SimConfig(n_nodes=64, n_faulty=2, topology="expander:12")
    with pytest.raises(ValueError, match="alias"):
        parse_topology("expander:8").validate(12)
    # one power below the wrap is fine, and every row holds d distinct
    spec = parse_topology("expander:10")
    spec.validate(64)
    tbl = build_neighbor_table(spec, 64)
    for row in tbl:
        assert len(set(row.tolist())) == 10


def test_degree_curve_rejects_complete_as_a_point():
    base = SimConfig(n_nodes=16, n_faulty=0, trials=2)
    with pytest.raises(ValueError, match="baseline"):
        degree_curve(base, ["complete", "ring:2"])
    with pytest.raises(ValueError, match="baseline"):
        unanimity_fault("complete")


def test_diameter_metadata():
    assert TopologySpec("ring", 2).diameter(16) == 8        # exact
    assert TopologySpec("ring", 4).diameter(16) == 4
    assert TopologySpec("torus2d", 4, rows=4, cols=6).diameter(24) == 5
    assert TopologySpec("ring", 2).diameter_exact()
    assert not TopologySpec("expander", 4).diameter_exact()
    # expander's estimate shrinks as degree grows
    d4 = TopologySpec("expander", 4).diameter(1024)
    d8 = TopologySpec("expander", 8).diameter(1024)
    assert d8 < d4


# --------------------------------------------------------------------------
# neighbor indices vs a tiny NumPy oracle
# --------------------------------------------------------------------------


def _oracle_ring(n, d):
    out = []
    for i in range(n):
        row = []
        for j in range(1, d // 2 + 1):
            row += [(i + j) % n, (i - j) % n]
        out.append(row)
    return out


def test_ring_neighbors_match_oracle():
    n, d = 12, 4
    cfg = SimConfig(n_nodes=n, n_faulty=0, topology=f"ring:{d}")
    got = np.asarray(neighbor_ids(cfg, jnp.arange(n, dtype=jnp.int32)))
    want = _oracle_ring(n, d)
    for i in range(n):
        assert sorted(got[i].tolist()) == sorted(want[i]), i


def test_torus_neighbors_match_oracle():
    rows, cols = 3, 4
    n = rows * cols
    cfg = SimConfig(n_nodes=n, n_faulty=0,
                    topology=f"torus2d:{rows}x{cols}")
    got = np.asarray(neighbor_ids(cfg, jnp.arange(n, dtype=jnp.int32)))
    for i in range(n):
        r, c = divmod(i, cols)
        want = {r * cols + (c + 1) % cols, r * cols + (c - 1) % cols,
                ((r + 1) % rows) * cols + c, ((r - 1) % rows) * cols + c}
        assert set(got[i].tolist()) == want, i


def test_random_regular_table_properties():
    spec = parse_topology("random_regular:5:3")
    t1 = build_neighbor_table(spec, 64)
    t2 = build_neighbor_table(spec, 64)
    np.testing.assert_array_equal(t1, t2)          # reproducible
    t3 = build_neighbor_table(parse_topology("random_regular:5:4"), 64)
    assert not np.array_equal(t1, t3)              # seed matters
    ids = np.arange(64)[:, None]
    assert (t1 != ids).all()                       # no self-loops
    for row in t1:                                 # d distinct senders
        assert len(set(row.tolist())) == 5
    assert t1.dtype == np.int32 and t1.shape == (64, 5)
    # past half-density the collision repair stops being geometric — a
    # cheap-to-validate dense spec would stall the shared batcher at
    # trace time, so validate() bounds the degree at N//2
    with pytest.raises(ValueError, match="half-density"):
        SimConfig(n_nodes=64, n_faulty=0, topology="random_regular:60")


def test_no_dense_adjacency_on_compiled_path():
    """The acceptance shape bound: nothing on the compiled topology
    tally path materializes an N x N (or larger) intermediate — the
    whole point of carrying [N, d] indices instead of an adjacency
    matrix."""
    n, trials = 4096, 2
    cfg = SimConfig(n_nodes=n, n_faulty=4, trials=trials,
                    topology="ring:8")
    sent = jnp.zeros((trials, n), jnp.int8)
    alive = jnp.ones((trials, n), bool)
    key = jax.random.key(0)

    jaxpr = jax.make_jaxpr(
        lambda s, a, k: neighborhood_counts(
            cfg, k, jnp.int32(1), 0, s, a, SINGLE))(sent, alive, key)
    cap = n * n
    for eqn in jaxpr.jaxpr.eqns:
        for var in list(eqn.outvars) + list(eqn.invars):
            aval = getattr(var, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                size = int(np.prod(aval.shape)) if aval.shape else 1
                assert size < cap, (eqn.primitive, aval.shape)


# --------------------------------------------------------------------------
# the identity spec: bit-identical results AND compile counts
# --------------------------------------------------------------------------


def test_complete_identity_traced():
    base = SimConfig(n_nodes=32, n_faulty=6, trials=8, delivery="quorum",
                     scheduler="uniform", path="histogram", seed=5)
    pt0 = run_point(base)
    with count_backend_compiles() as cc:
        pt1 = run_point(base.replace(topology="complete"))
    assert cc.count == 0                   # the jit cache simply hit
    assert pt0.rounds_executed == pt1.rounds_executed
    assert pt0.decided_frac == pt1.decided_frac
    assert pt0.mean_k == pt1.mean_k
    assert pt0.ones_frac == pt1.ones_frac
    np.testing.assert_array_equal(pt0.k_hist, pt1.k_hist)


def test_complete_identity_batched():
    base = SimConfig(n_nodes=32, n_faulty=0, trials=8, delivery="quorum",
                     scheduler="uniform", path="histogram", seed=5)
    cb0 = run_curve_batched(base, [0, 4, 8])
    cb1 = run_curve_batched(base.replace(topology="complete"), [0, 4, 8])
    assert cb0.compile_count == cb1.compile_count
    assert cb0.n_buckets == cb1.n_buckets
    for a, b in zip(cb0.points, cb1.points):
        assert a.mean_k == b.mean_k and a.decided_frac == b.decided_frac
        np.testing.assert_array_equal(a.k_hist, b.k_hist)


def test_complete_identity_sharded():
    from benor_tpu.parallel import make_mesh, run_consensus_sharded

    cfg = SimConfig(n_nodes=16, n_faulty=4, trials=8, delivery="quorum",
                    scheduler="uniform", seed=7,
                    topology="complete")        # normalizes to None
    faults = FaultSpec.first_f(cfg)
    state = init_state(cfg, [i % 2 for i in range(16)], faults)
    key = jax.random.key(cfg.seed)
    r1, s1 = run_consensus_sharded(cfg, state, faults, key,
                                   make_mesh(2, 2))
    cfg0 = SimConfig(n_nodes=16, n_faulty=4, trials=8, delivery="quorum",
                     scheduler="uniform", seed=7)
    from benor_tpu.sim import run_consensus
    r0, s0 = run_consensus(cfg0, state, faults, key)
    assert int(r0) == int(r1)
    np.testing.assert_array_equal(np.asarray(s0.x), np.asarray(s1.x))
    np.testing.assert_array_equal(np.asarray(s0.decided),
                                  np.asarray(s1.decided))


# --------------------------------------------------------------------------
# topology runs: sharded bit-identity + batched-vs-oracle
# --------------------------------------------------------------------------


@pytest.mark.parametrize("spec", ["torus2d:4x4", "ring:4",
                                  "random_regular:3:2", "expander:4"])
def test_topology_sharded_bit_identical(spec):
    from benor_tpu.parallel import make_mesh, run_consensus_sharded
    from benor_tpu.sim import run_consensus

    cfg = SimConfig(n_nodes=16, n_faulty=3, trials=8, topology=spec,
                    max_rounds=12, seed=3)
    faults = FaultSpec.none(8, 16)
    state = init_state(cfg, [i % 2 for i in range(16)], faults)
    key = jax.random.key(cfg.seed)
    r0, s0 = run_consensus(cfg, state, faults, key)
    r1, s1 = run_consensus_sharded(cfg, state, faults, key,
                                   make_mesh(2, 2))
    assert int(r0) == int(r1)
    np.testing.assert_array_equal(np.asarray(s0.x), np.asarray(s1.x))
    np.testing.assert_array_equal(np.asarray(s0.decided),
                                  np.asarray(s1.decided))
    np.testing.assert_array_equal(np.asarray(s0.k), np.asarray(s1.k))


def test_degree_curve_batched_matches_per_point_oracle():
    base = SimConfig(n_nodes=36, n_faulty=0, trials=8, max_rounds=12,
                     seed=11)
    specs = ["ring:2", "torus2d:6x6"]
    rows = degree_curve(base, specs)
    assert [r["degree"] for r in rows] == sorted(r["degree"] for r in rows)
    for spec_str in specs:
        cfg = base.replace(topology=spec_str,
                           n_faulty=unanimity_fault(spec_str))
        pt = run_point(cfg, faults=FaultSpec.none(8, 36))
        row = next(r for r in rows
                   if r["spec"] == parse_topology(spec_str).spec_string())
        assert row["rounds_executed"] == pt.rounds_executed
        assert row["mean_k"] == round(pt.mean_k, 4)
        assert row["decided_frac"] == round(pt.decided_frac, 4)


def test_topology_recorder_off_on_bit_identical():
    """The house rule extends to the topo plane: arming the flight
    recorder must not move a single bit of the results."""
    cfg = SimConfig(n_nodes=16, n_faulty=3, trials=4,
                    topology="torus2d:4x4", max_rounds=12, seed=9)
    pt0 = run_point(cfg, faults=FaultSpec.none(4, 16))
    pt1 = run_point(cfg.replace(record=True),
                    faults=FaultSpec.none(4, 16))
    assert pt0.mean_k == pt1.mean_k
    assert pt0.decided_frac == pt1.decided_frac
    np.testing.assert_array_equal(pt0.k_hist, pt1.k_hist)
    assert pt1.round_history is not None


# --------------------------------------------------------------------------
# committees
# --------------------------------------------------------------------------


def test_committee_membership_reproducible_and_round_varying():
    cfg = SimConfig(n_nodes=64, n_faulty=0, trials=4, committee_cap=4,
                    committee_count=4, committee_size=8, seed=2)
    key = jax.random.key(cfg.seed)
    tid = jnp.arange(4, dtype=jnp.int32)
    nid = jnp.arange(64, dtype=jnp.int32)
    m1, c1 = committees.membership(cfg, key, jnp.int32(1), tid, nid, 4, 8)
    m2, c2 = committees.membership(cfg, key, jnp.int32(1), tid, nid, 4, 8)
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(m2))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    m3, c3 = committees.membership(cfg, key, jnp.int32(2), tid, nid, 4, 8)
    assert not np.array_equal(np.asarray(m1), np.asarray(m3))
    assert (np.asarray(c1) < 4).all() and (np.asarray(c1) >= 0).all()
    # expected participation ~ c*g/N = 1/2
    frac = float(np.asarray(m1).mean())
    assert 0.3 < frac < 0.7


def test_committee_curve_one_bucket_and_matches_oracle():
    base = SimConfig(n_nodes=64, n_faulty=1, trials=8, max_rounds=24,
                     seed=4)
    rows, cb = committee_curve(base, sizes=[4, 8, 16],
                               committee_count=4)
    assert cb.n_buckets == 1
    assert cb.compile_count == 1          # the whole sweep, one compile
    for row in rows:
        cfg = base.replace(committee_cap=4, committee_count=4,
                           committee_size=row["committee_size"])
        pt = run_point(cfg, faults=FaultSpec.none(8, 64))
        assert row["rounds_executed"] == pt.rounds_executed
        assert row["mean_k"] == round(pt.mean_k, 4)
        assert row["decided_frac"] == round(pt.decided_frac, 4)


def test_committee_count_sweep_shares_bucket_key():
    base = SimConfig(n_nodes=64, n_faulty=1, trials=8, committee_cap=8,
                     committee_count=2, committee_size=8)
    keys = {sweep_bucket_key(base.replace(committee_count=g))
            for g in (2, 4, 8)}
    assert len(keys) == 1                 # count is a DynParams axis
    # but the static cap is part of the key: a different histogram
    # shape may never share an executable
    other = sweep_bucket_key(base.replace(committee_cap=16))
    assert other not in keys


# --------------------------------------------------------------------------
# the relaxed auditor
# --------------------------------------------------------------------------


def _torus_bundle():
    cfg = SimConfig(n_nodes=16, n_faulty=2, topology="torus2d:4x4",
                    trials=4, max_rounds=12, seed=2,
                    witness_trials=(0, 1), witness_nodes=8)
    report, bundle = audit.audit_point(
        cfg, initial_values=np.ones((4, 16), np.int8),
        faults=FaultSpec.none(4, 16), unanimous=1, label="torus")
    return cfg, report, bundle


def test_torus_audit_clean_with_neighborhood_bound():
    _, report, bundle = _torus_bundle()
    assert report.ok, report.summary()
    assert bundle.tally_bound == 5        # d + 1 on the 4-neighbor torus
    assert report.checks["quorum_evidence"] > 0


def test_forged_tally_beyond_neighborhood_is_pinpointed():
    from benor_tpu.state import WIT_V1, WIT_WRITTEN

    _, _, bundle = _torus_bundle()
    buf = np.array(bundle.buffer)
    written = np.nonzero(buf[:, 0, 0, WIT_WRITTEN] > 0)[0]
    rd = int(written[-1])
    buf[rd, 1, 3, WIT_V1] = 12            # > d+1 = 5: unrealizable
    forged = audit.WitnessBundle(
        buffer=buf, trial_ids=bundle.trial_ids,
        node_ids=bundle.node_ids, rule=bundle.rule,
        n_faulty=bundle.n_faulty, n_nodes=bundle.n_nodes,
        tally_bound=bundle.tally_bound)
    report = audit.audit_witness(forged)
    assert not report.ok
    v = next(x for x in report.violations
             if "neighborhood" in x.message)
    assert v.invariant == "quorum_evidence"
    assert v.trial == int(bundle.trial_ids[1])
    assert v.nodes == [int(bundle.node_ids[3])]
    assert v.round == rd
    # the SAME buffer without the bound sails through the classic checks
    unbounded = audit.WitnessBundle(
        buffer=buf, trial_ids=bundle.trial_ids,
        node_ids=bundle.node_ids, rule=bundle.rule,
        n_faulty=bundle.n_faulty, n_nodes=bundle.n_nodes)
    assert not any("neighborhood" in x.message
                   for x in audit.audit_witness(unbounded).violations)


def test_bundle_roundtrip_and_schema_with_tally_bound(tmp_path):
    _, report, bundle = _torus_bundle()
    path = str(tmp_path / "bundle.json")
    audit.save_bundle(path, bundle, report)
    with open(path) as fh:
        doc = json.load(fh)
    assert check_metrics_schema.check_witness_bundle(doc) == []
    back = audit.load_bundle(path)
    assert back.tally_bound == bundle.tally_bound
    assert audit.audit_witness(back).ok


# --------------------------------------------------------------------------
# serve integration: CONFIG_FIELDS + structured 400s + bucket keys
# --------------------------------------------------------------------------


def test_serve_jobspec_topology_fields():
    from benor_tpu.serve.jobs import JobError, JobSpec

    spec = JobSpec.from_dict({"n_nodes": 16, "n_faulty": 2,
                              "topology": "torus2d:4x4"})
    cfg = spec.to_config()
    assert cfg.topology == "torus2d:4x4"
    spec2 = JobSpec.from_dict({"n_nodes": 64, "n_faulty": 1,
                               "committee_cap": 4, "committee_count": 4,
                               "committee_size": 8})
    assert spec2.to_config().committee_cap == 4
    # round-trips through the wire form
    assert JobSpec.from_dict(spec.to_dict()).topology == "torus2d:4x4"


def test_serve_jobspec_structured_400s():
    from benor_tpu.serve.jobs import JobError, JobSpec

    with pytest.raises(JobError) as e:
        JobSpec.from_dict({"n_nodes": 16, "topology": 4})
    assert e.value.body["field"] == "topology"
    with pytest.raises(JobError) as e:
        JobSpec.from_dict({"n_nodes": 16, "topology": "moebius:4"})
    assert e.value.body["field"] == "config"
    with pytest.raises(JobError) as e:
        JobSpec.from_dict({"n_nodes": 17, "topology": "torus2d:4x4"})
    assert e.value.body["field"] == "config"
    with pytest.raises(JobError) as e:
        JobSpec.from_dict({"n_nodes": 16, "committee_count": 2})
    assert e.value.body["field"] == "config"
    with pytest.raises(JobError) as e:
        JobSpec.from_dict({"n_nodes": 16, "committee_cap": "four"})
    assert e.value.body["field"] == "committee_cap"
    with pytest.raises(JobError) as e:
        JobSpec.from_dict({"n_nodes": 1 << 14, "committee_cap": 1 << 14,
                           "committee_count": 2, "committee_size": 4})
    assert e.value.body["field"] == "committee_cap"
    assert "caps" in e.value.body["reason"]


def test_serve_bucket_key_separates_topologies_coalesces_committees():
    from benor_tpu.serve.batcher import serve_bucket_key

    base = dict(n_nodes=16, n_faulty=2, trials=4)
    k_none = serve_bucket_key(SimConfig(**base))
    k_ring = serve_bucket_key(SimConfig(**base, topology="ring:2"))
    k_torus = serve_bucket_key(SimConfig(**base, topology="torus2d:4x4"))
    assert len({k_none, k_ring, k_torus}) == 3   # never coalesce
    # 'complete' IS the complete-graph bucket (the identity spec)
    assert serve_bucket_key(SimConfig(**base, topology="complete")) \
        == k_none
    # committee count/size are DynParams axes: one warm executable
    cbase = dict(n_nodes=64, n_faulty=1, trials=4, committee_cap=4)
    ka = serve_bucket_key(SimConfig(**cbase, committee_count=2,
                                    committee_size=8))
    kb = serve_bucket_key(SimConfig(**cbase, committee_count=4,
                                    committee_size=16))
    assert ka == kb


def test_serve_end_to_end_topology_job_bit_equal_run_point():
    """A topology job through the real batcher equals the oracle —
    the serve house rule extended to the new workloads."""
    from benor_tpu.serve.batcher import Batcher

    b = Batcher(start=False)
    try:
        jobs = b.submit_dict({"n_nodes": 16, "n_faulty": 3, "trials": 4,
                              "max_rounds": 12, "seed": 6,
                              "topology": "torus2d:4x4"})
        assert b.step() == 1
        job = jobs[0]
        assert job.state == "done", job.error
        pt = run_point(job.cfg)
        assert job.result["mean_k"] == pt.mean_k
        assert job.result["decided_frac"] == pt.decided_frac
        assert job.result["k_hist"] == pt.k_hist.tolist()
    finally:
        b.close()


# --------------------------------------------------------------------------
# structural pallas demotion + schema gate
# --------------------------------------------------------------------------


def test_structured_demotion_warns_once():
    import benor_tpu.sim as sim

    sim._structured_demotion_warned = False
    cfg = SimConfig(n_nodes=16, n_faulty=2, trials=2,
                    topology="ring:2", use_pallas_round=True,
                    use_pallas_hist=True)
    faults = FaultSpec.none(2, 16)
    state = init_state(cfg, [i % 2 for i in range(16)], faults)
    with pytest.warns(UserWarning, match="delivery plane"):
        sim.run_consensus(cfg, state, faults, jax.random.key(0))
    # the batched engine reaches run_consensus_traced directly (never
    # run_consensus) — the announcement must fire there too
    sim._structured_demotion_warned = False
    with pytest.warns(UserWarning, match="delivery plane"):
        run_curve_batched(cfg, [2])
    sim._structured_demotion_warned = True


def test_check_topo_blob_cross_field_pins():
    blob = {
        "ok": True,
        "complete_identity": {"bit_equal": True, "extra_compiles": 0},
        "degree_curve": [
            {"spec": "ring:2", "degree": 2, "diameter": 8,
             "diameter_exact": True, "n_nodes": 16, "n_faulty": 2,
             "rounds_executed": 3, "mean_k": 2.5, "decided_frac": 1.0},
            {"spec": "torus2d:4x4", "degree": 4, "diameter": 4,
             "diameter_exact": True, "n_nodes": 16, "n_faulty": 4,
             "rounds_executed": 2, "mean_k": 2.0, "decided_frac": 1.0},
        ],
        "committee_curve": [
            {"committee_size": 4, "committee_count": 4,
             "committee_cap": 4, "n_nodes": 64, "rounds_executed": 4,
             "mean_k": 3.0, "decided_frac": 1.0},
        ],
        "committee_compile_count": 1,
        "audit_ok": True,
    }
    assert check_metrics_schema.check_topo_blob(blob) == []
    bad = copy.deepcopy(blob)
    bad["degree_curve"][0]["diameter"] = 99
    assert any("recomputed" in e
               for e in check_metrics_schema.check_topo_blob(bad))
    bad = copy.deepcopy(blob)
    bad["degree_curve"].reverse()
    assert any("sorted" in e
               for e in check_metrics_schema.check_topo_blob(bad))
    bad = copy.deepcopy(blob)
    bad["committee_compile_count"] = 2
    errs = check_metrics_schema.check_topo_blob(bad)
    assert any("one-bucket" in e for e in errs)
    bad = copy.deepcopy(blob)
    bad["audit_ok"] = False                  # ok must follow its parts
    assert any("contradicts" in e
               for e in check_metrics_schema.check_topo_blob(bad))
    bad = copy.deepcopy(blob)
    bad["committee_curve"][0]["committee_size"] = 32   # 32*4 > 64
    assert any("clips" in e
               for e in check_metrics_schema.check_topo_blob(bad))
    bad = copy.deepcopy(blob)
    bad["degree_curve"][0]["spec"] = "complete"  # no degree axis
    assert any("identity" in e
               for e in check_metrics_schema.check_topo_blob(bad))
    bad = copy.deepcopy(blob)
    del bad["complete_identity"]
    assert check_metrics_schema.check_topo_blob(bad)
    # the DEGRADED never-fail shape bench emits when _topo_check blew
    # up is legal (topo_ok=false is the signal, not missing-key noise)
    assert check_metrics_schema.check_topo_blob(
        {"ok": False, "error": "RuntimeError: boom"}) == []
    assert any("ok=true" in e for e in check_metrics_schema.check_topo_blob(
        {"ok": True, "error": "RuntimeError: boom"}))
