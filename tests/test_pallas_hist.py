"""Fused histogram-path pallas sampler (ops/pallas_hist.py).

Runs in interpreter mode on the CPU test mesh (the kernel's threefry is
hand-rolled uint32 arithmetic precisely so interpret mode works — the pltpu
PRNG primitives have no interpret lowering).  Gates:

  * AS241 ndtri accuracy,
  * draw moments vs scipy's exact hypergeometric,
  * determinism + (round, phase, seed) stream separation,
  * feasibility clamps at degenerate histograms,
  * protocol-level KS: a full consensus run with use_pallas_hist=True must
    be distributionally indistinguishable from the XLA sampler path (the
    streams differ by design, so the comparison is per-trial statistical,
    same harness as TestApproxRegimeProtocol in test_sampling.py).
"""

import numpy as np
import pytest
import scipy.stats as st

import jax
import jax.numpy as jnp

from benor_tpu.config import SimConfig
from benor_tpu.ops import sampling
from benor_tpu.ops.pallas_hist import cf_counts_pallas, _ndtri_as241


def _counts(seed, r, phase, hist, m, n, trials=None):
    h = jnp.tile(jnp.asarray(hist, jnp.int32)[None, :], (trials or 4, 1))
    return np.asarray(cf_counts_pallas(
        jax.random.key(seed), jnp.int32(r), phase, h, m, n, interpret=True))


class TestKernel:
    def test_ndtri_accuracy(self):
        p = np.linspace(1e-7, 1 - 1e-7, 50001).astype(np.float32)
        z = np.asarray(_ndtri_as241(jnp.asarray(p)))
        ref = st.norm.ppf(p.astype(np.float64))
        assert np.abs(z - ref).max() < 2e-6

    def test_moments_match_exact_hypergeometric(self):
        m, n = 5000, 4096
        c = _counts(42, 3, 0, [4000, 3000, 1000], m, n, trials=8)
        np.testing.assert_array_equal(c.sum(-1), m)
        h0 = c[..., 0].ravel().astype(np.float64)
        d = st.hypergeom(8000, 4000, m)
        assert abs(h0.mean() - d.mean()) < 0.05 * d.std()
        assert abs(h0.std() - d.std()) < 0.05 * d.std()

    @pytest.mark.slow
    def test_deterministic_and_stream_separated(self):
        args = ([4000, 3000, 1000], 5000, 1024)
        a = _counts(42, 3, 0, *args)
        assert np.array_equal(a, _counts(42, 3, 0, *args))       # same
        assert not np.array_equal(a, _counts(42, 4, 0, *args))   # round
        assert not np.array_equal(a, _counts(42, 3, 1, *args))   # phase
        assert not np.array_equal(a, _counts(43, 3, 0, *args))   # base key

    def test_keys_on_base_key_not_config_seed(self):
        """Independent MC replications run the supported way — same config,
        distinct base keys (e.g. fold_in(key, batch)) — must draw
        independent message-plane randomness (regression: the kernel once
        keyed on cfg.seed, silently correlating replications)."""
        h = jnp.tile(jnp.array([[4000, 3000, 1000]], jnp.int32), (4, 1))
        k = jax.random.key(42)
        a = np.asarray(cf_counts_pallas(jax.random.fold_in(k, 0),
                                        jnp.int32(1), 0, h, 5000, 1024,
                                        interpret=True))
        b = np.asarray(cf_counts_pallas(jax.random.fold_in(k, 1),
                                        jnp.int32(1), 0, h, 5000, 1024,
                                        interpret=True))
        assert not np.array_equal(a, b)

    def test_clamps_at_degenerate_histograms(self):
        m, n = 600, 512
        # all mass in class 0: h0 == m exactly
        c = _counts(1, 1, 0, [1000, 0, 0], m, n)
        np.testing.assert_array_equal(c[..., 0], m)
        np.testing.assert_array_equal(c[..., 1], 0)
        # total == m: the draw is the whole population
        c = _counts(1, 1, 0, [300, 200, 100], m, n)
        np.testing.assert_array_equal(c[..., 0], 300)
        np.testing.assert_array_equal(c[..., 1], 200)
        np.testing.assert_array_equal(c[..., 2], 100)

    def test_coin_kernel_fair_and_deterministic(self):
        from benor_tpu.ops.pallas_hist import coin_flips_pallas
        k = jax.random.key(3)
        a = np.asarray(coin_flips_pallas(k, jnp.int32(2), 16, 2048,
                                         interpret=True))
        assert a.shape == (16, 2048) and set(np.unique(a)) <= {0, 1}
        # fair within binomial noise (32768 draws, sigma ~ 0.0028)
        assert abs(a.mean() - 0.5) < 0.012
        b = np.asarray(coin_flips_pallas(k, jnp.int32(2), 16, 2048,
                                         interpret=True))
        assert np.array_equal(a, b)                          # deterministic
        c = np.asarray(coin_flips_pallas(k, jnp.int32(3), 16, 2048,
                                         interpret=True))
        assert not np.array_equal(a, c)                      # round stream
        # global-id offsets: shard (offset 1024) == right half of full grid
        d = np.asarray(coin_flips_pallas(k, jnp.int32(2), 16, 1024,
                                         interpret=True, node_offset=1024))
        np.testing.assert_array_equal(a[:, 1024:], d)

    def test_ragged_n_padding(self):
        # N not a multiple of TILE_N exercises the pad+slice path
        c = _counts(7, 2, 0, [900, 800, 300], 1500, 700)
        assert c.shape == (4, 700, 3)
        np.testing.assert_array_equal(c.sum(-1), 1500)


def _equiv_counts(seed, r, phase, hist, ne, m, n, trials=4):
    from benor_tpu.ops.pallas_hist import equiv_counts_pallas
    h = jnp.tile(jnp.asarray(hist, jnp.int32)[None, :], (trials, 1))
    nev = jnp.full((trials,), ne, jnp.int32)
    return np.asarray(equiv_counts_pallas(
        jax.random.key(seed), jnp.int32(r), phase, h, nev, m, n,
        interpret=True))


class TestWeakCoinKernel:
    """Fused weak-common coin (ops/pallas_hist.py:_weak_coin_kernel)."""

    def _flip(self, eps, seed=3, r=2, trials=16, n=1024, shared=None):
        import jax.numpy as jnp

        from benor_tpu.ops.pallas_hist import weak_coin_flips_pallas
        if shared is None:
            shared = jnp.arange(trials, dtype=jnp.int32) % 2
        return np.asarray(weak_coin_flips_pallas(
            jax.random.key(seed), jnp.int32(r), trials, n, eps, shared,
            interpret=True))

    def test_limits_match_component_streams(self):
        import jax.numpy as jnp

        from benor_tpu.ops.pallas_hist import coin_flips_pallas
        shared = jnp.arange(16, dtype=jnp.int32) % 2
        # eps=1: every lane deviates -> exactly the private-coin kernel
        a = self._flip(1.0, shared=shared)
        b = np.asarray(coin_flips_pallas(jax.random.key(3), jnp.int32(2),
                                         16, 1024, interpret=True))
        np.testing.assert_array_equal(a, b)
        # eps=0: no lane deviates -> the shared bit broadcast
        c = self._flip(0.0, shared=shared)
        np.testing.assert_array_equal(c, np.asarray(shared)[:, None] *
                                      np.ones((16, 1024), np.int8))

    def test_deviation_rate_and_streams(self):
        a = self._flip(0.3)
        assert np.array_equal(a, self._flip(0.3))            # deterministic
        assert not np.array_equal(a, self._flip(0.3, r=3))   # round stream
        # measured deviation rate ~ eps (lanes whose bit != shared bit are
        # deviators holding the private value != shared: rate eps/2)
        shared = (np.arange(16) % 2)[:, None]
        mismatch = (a != shared).mean()
        assert abs(mismatch - 0.15) < 0.01                   # eps/2 = 0.15

    @pytest.mark.slow
    def test_protocol_ks_vs_xla_weak_coin(self):
        from stat_harness import trial_mean_k
        kw = dict(table_max=64, coin_mode="weak_common", coin_eps=0.5)
        xla = trial_mean_k(750, 255, 128, 321, use_pallas_hist=False, **kw)
        pallas = trial_mean_k(750, 255, 128, 322, use_pallas_hist=True, **kw)
        res = st.ks_2samp(xla, pallas)
        assert res.pvalue > 1e-3, (res.statistic, res.pvalue)
        sem = np.hypot(xla.std() / len(xla) ** 0.5,
                       pallas.std() / len(pallas) ** 0.5)
        assert abs(xla.mean() - pallas.mean()) < 4 * sem + 1e-9

    @pytest.mark.slow
    def test_sharded_bit_identical(self):
        from benor_tpu.parallel import make_mesh, run_consensus_sharded
        from benor_tpu.sim import run_consensus
        from benor_tpu.state import FaultSpec, init_state

        old = sampling.EXACT_TABLE_MAX
        sampling.EXACT_TABLE_MAX = 8     # CF regime at m=12
        try:
            n, f, trials = 16, 4, 8
            cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials,
                            delivery="quorum", scheduler="uniform",
                            path="histogram", use_pallas_hist=True,
                            coin_mode="weak_common", coin_eps=0.5, seed=23)
            no_crash = FaultSpec.none(trials, n)
            state = init_state(cfg, [i % 2 for i in range(n)], no_crash)
            key = jax.random.key(23)
            r1, s1 = run_consensus(cfg, state, no_crash, key)
            for mesh_shape in ((2, 4), (4, 1)):
                r2, s2 = run_consensus_sharded(cfg, state, no_crash, key,
                                               make_mesh(*mesh_shape))
                assert int(r1) == int(r2), mesh_shape
                np.testing.assert_array_equal(
                    np.asarray(s1.x), np.asarray(s2.x),
                    err_msg=str(mesh_shape))
        finally:
            sampling.EXACT_TABLE_MAX = old


class TestEquivKernel:
    """Fused equivocate-regime sampler (ops/pallas_hist.py:_equiv_kernel)."""

    @pytest.mark.slow
    def test_moments_all_honest_zero(self):
        # honest all-0: the honest split is deterministic (h0 = rem), so
        # class-1 counts come ONLY from the equivocators' fair bits:
        # h1 ~ Binomial(h_b, 1/2), h_b ~ Hypergeom(total, ne, m)
        total_h, ne, m, n = 6000, 2000, 5000, 2048
        c = _equiv_counts(21, 2, 0, [total_h, 0, 0], ne, m, n, trials=8)
        np.testing.assert_array_equal(c.sum(-1), m)
        np.testing.assert_array_equal(c[..., 2], 0)
        h1 = c[..., 1].ravel().astype(np.float64)
        hb = st.hypergeom(total_h + ne, ne, m)
        exp_mean = hb.mean() / 2
        exp_var = hb.mean() / 4 + hb.var() / 4   # law of total variance
        assert abs(h1.mean() - exp_mean) < 0.05 * np.sqrt(exp_var)
        assert abs(h1.std() - np.sqrt(exp_var)) < 0.05 * np.sqrt(exp_var)

    @pytest.mark.slow
    def test_deterministic_and_stream_separated(self):
        args = ([4000, 3000, 1000], 1500, 5000, 1024)
        a = _equiv_counts(42, 3, 0, *args)
        assert np.array_equal(a, _equiv_counts(42, 3, 0, *args))
        assert not np.array_equal(a, _equiv_counts(42, 4, 0, *args))
        assert not np.array_equal(a, _equiv_counts(42, 3, 1, *args))
        assert not np.array_equal(a, _equiv_counts(43, 3, 0, *args))

    @pytest.mark.slow
    def test_protocol_ks_vs_xla_equiv_sampler(self):
        """Full consensus with fault_model='equivocate': the fused kernel's
        stream vs the four-grid_uniforms XLA pipeline must be
        distributionally indistinguishable (per-trial aggregates)."""
        from stat_harness import trial_mean_k
        xla = trial_mean_k(750, 255, 128, 311, table_max=64,
                           use_pallas_hist=False, fault_model="equivocate")
        pallas = trial_mean_k(750, 255, 128, 312, table_max=64,
                              use_pallas_hist=True, fault_model="equivocate")
        res = st.ks_2samp(xla, pallas)
        assert res.pvalue > 1e-3, (
            f"equiv kernel shifts protocol outcomes: KS={res.statistic:.4f} "
            f"p={res.pvalue:.2e} (xla {xla.mean():.3f}, "
            f"pallas {pallas.mean():.3f})")
        sem = np.hypot(xla.std() / len(xla) ** 0.5,
                       pallas.std() / len(pallas) ** 0.5)
        assert abs(xla.mean() - pallas.mean()) < 4 * sem + 1e-9

    @pytest.mark.slow
    def test_sharded_bit_identical(self):
        """Global-id counters + psum'd (hist, n_equiv): sharded equivocate
        runs with the kernel are bit-identical to single-device."""
        from benor_tpu.parallel import make_mesh, run_consensus_sharded
        from benor_tpu.sim import run_consensus
        from benor_tpu.state import FaultSpec, init_state

        old = sampling.EXACT_TABLE_MAX
        sampling.EXACT_TABLE_MAX = 8     # CF regime at m=12
        try:
            n, f, trials = 16, 4, 8
            cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials,
                            delivery="quorum", scheduler="uniform",
                            path="histogram", use_pallas_hist=True,
                            fault_model="equivocate", seed=17)
            faults = FaultSpec.first_f(cfg)
            state = init_state(cfg, [i % 2 for i in range(n)], faults)
            key = jax.random.key(17)
            r1, s1 = run_consensus(cfg, state, faults, key)
            for mesh_shape in ((2, 4), (4, 1)):
                r2, s2 = run_consensus_sharded(cfg, state, faults, key,
                                               make_mesh(*mesh_shape))
                assert int(r1) == int(r2), mesh_shape
                np.testing.assert_array_equal(
                    np.asarray(s1.x), np.asarray(s2.x),
                    err_msg=str(mesh_shape))
                np.testing.assert_array_equal(
                    np.asarray(s1.k), np.asarray(s2.k),
                    err_msg=str(mesh_shape))
        finally:
            sampling.EXACT_TABLE_MAX = old


class TestProtocolParity:
    """use_pallas_hist=True vs False through the full consensus loop.
    Shared harness (balanced inputs, zero crashes, F > N/3, per-trial
    aggregation — see tests/stat_harness.py for why each matters); the CF
    regime is forced at m=495 via table_max so the kernel engages on CPU."""

    @pytest.mark.slow
    def test_ks_vs_xla_sampler(self):
        from stat_harness import trial_mean_k
        xla = trial_mean_k(750, 255, 128, 301, table_max=64,
                           use_pallas_hist=False)
        pallas = trial_mean_k(750, 255, 128, 302, table_max=64,
                              use_pallas_hist=True)
        res = st.ks_2samp(xla, pallas)
        assert res.pvalue > 1e-3, (
            f"pallas sampler shifts protocol outcomes: "
            f"KS={res.statistic:.4f} p={res.pvalue:.2e} "
            f"(xla mean {xla.mean():.3f}, pallas mean {pallas.mean():.3f})")
        sem = np.hypot(xla.std() / len(xla) ** 0.5,
                       pallas.std() / len(pallas) ** 0.5)
        assert abs(xla.mean() - pallas.mean()) < 4 * sem + 1e-9

    @pytest.mark.slow
    def test_sharded_bit_identical(self):
        """use_pallas_hist under shard_map: global-id counters + the psum'd
        global histogram make the sharded run bit-identical to the
        single-device run for every mesh shape (SURVEY §7 hard-part 5,
        extended to the pallas sampler)."""
        from benor_tpu.parallel import make_mesh, run_consensus_sharded
        from benor_tpu.sim import run_consensus
        from benor_tpu.state import FaultSpec, init_state

        old = sampling.EXACT_TABLE_MAX
        sampling.EXACT_TABLE_MAX = 8     # CF regime at m=12
        try:
            n, f, trials = 16, 4, 8
            cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials,
                            delivery="quorum", scheduler="uniform",
                            path="histogram", use_pallas_hist=True, seed=13)
            no_crash = FaultSpec.none(trials, n)
            state = init_state(cfg, [i % 2 for i in range(n)], no_crash)
            key = jax.random.key(13)
            r1, s1 = run_consensus(cfg, state, no_crash, key)
            for mesh_shape in ((2, 4), (1, 8), (4, 1)):
                r2, s2 = run_consensus_sharded(cfg, state, no_crash, key,
                                               make_mesh(*mesh_shape))
                assert int(r1) == int(r2), mesh_shape
                np.testing.assert_array_equal(
                    np.asarray(s1.x), np.asarray(s2.x), err_msg=str(mesh_shape))
                np.testing.assert_array_equal(
                    np.asarray(s1.k), np.asarray(s2.k), err_msg=str(mesh_shape))
        finally:
            sampling.EXACT_TABLE_MAX = old

    @pytest.mark.slow
    def test_flag_ignored_outside_cf_regime(self):
        """In the exact-table regime the flag must be a no-op (bitwise)."""
        from benor_tpu.sim import simulate
        n, f = 64, 16
        cfg = SimConfig(n_nodes=n, n_faulty=f, trials=8, delivery="quorum",
                        scheduler="uniform", path="histogram", seed=5)
        r1, s1, _ = simulate(cfg, [i % 2 for i in range(n)],
                             [True] * f + [False] * (n - f))
        cfg2 = cfg.replace(use_pallas_hist=True)
        r2, s2, _ = simulate(cfg2, [i % 2 for i in range(n)],
                             [True] * f + [False] * (n - f))
        assert int(r1) == int(r2)
        np.testing.assert_array_equal(np.asarray(s1.x), np.asarray(s2.x))
        np.testing.assert_array_equal(np.asarray(s1.k), np.asarray(s2.k))
