"""Checkpoint/resume round-trip (SURVEY.md §5.4).

The key property: interrupt-at-round-r + resume is BIT-IDENTICAL to an
uninterrupted run, because randomness is keyed on (seed, round, phase,
trial, node) and never on loop history (ops/rng.py).
"""

import numpy as np
import pytest

import jax

from benor_tpu.config import SimConfig
from benor_tpu.sim import run_consensus
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.utils.checkpoint import (load_checkpoint, resume_from,
                                        save_checkpoint)


def _setup(**overrides):
    n, f = 120, 40
    kw = dict(delivery="quorum", scheduler="uniform", path="dense", seed=7)
    kw.update(overrides)
    cfg = SimConfig(n_nodes=n, n_faulty=f, trials=32, max_rounds=48, **kw)
    faulty = [True] * f + [False] * (n - f)
    vals = [1] * f + [1] * 40 + [0] * 40  # balanced healthy inputs
    faults = FaultSpec.from_faulty_list(cfg, faulty)
    state = init_state(cfg, vals, faults)
    return cfg, state, faults


@pytest.mark.slow
def test_resume_bit_identical(tmp_path):
    cfg, state, faults = _setup()
    base_key = jax.random.key(cfg.seed)

    # uninterrupted run
    rounds_full, final_full = run_consensus(cfg, state, faults, base_key)
    assert int(rounds_full) >= 3, "config must take several rounds"

    # capped run -> checkpoint -> resume with the full config
    cfg_cap = cfg.replace(max_rounds=2)
    rounds_cap, mid = run_consensus(cfg_cap, state, faults, base_key)
    assert int(rounds_cap) == 2
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, cfg, mid, faults, next_round=int(rounds_cap) + 1)

    rounds_res, final_res, _ = resume_from(path)
    assert int(rounds_res) == int(rounds_full)
    np.testing.assert_array_equal(np.asarray(final_res.x),
                                  np.asarray(final_full.x))
    np.testing.assert_array_equal(np.asarray(final_res.decided),
                                  np.asarray(final_full.decided))
    np.testing.assert_array_equal(np.asarray(final_res.k),
                                  np.asarray(final_full.k))
    np.testing.assert_array_equal(np.asarray(final_res.killed),
                                  np.asarray(final_full.killed))


@pytest.mark.slow
def test_resume_bit_identical_new_streams(tmp_path):
    """The resume guarantee must hold for EVERY random stream: the
    equivocate fault plane (per-edge bits / mixed-population sampler) and
    the weak-common coin (shared + deviation + private) are all keyed on
    (key, round, phase, global ids) — never loop history — so cut+resume
    stays bit-identical with both engaged."""
    from benor_tpu.sweep import balanced_inputs

    n, f = 96, 36
    cfg = SimConfig(n_nodes=n, n_faulty=f, trials=16, max_rounds=48,
                    delivery="quorum", scheduler="uniform",
                    path="histogram", fault_model="equivocate",
                    coin_mode="weak_common", coin_eps=0.5, seed=9)
    faults = FaultSpec.first_f(cfg)
    state = init_state(cfg, balanced_inputs(16, n), faults)
    base_key = jax.random.key(cfg.seed)

    rounds_full, final_full = run_consensus(cfg, state, faults, base_key)
    assert int(rounds_full) >= 3, "config must take several rounds"

    cfg_cap = cfg.replace(max_rounds=2)
    rounds_cap, mid = run_consensus(cfg_cap, state, faults, base_key)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, cfg, mid, faults, next_round=int(rounds_cap) + 1)

    rounds_res, final_res, _ = resume_from(path)
    assert int(rounds_res) == int(rounds_full)
    for leaf in ("x", "decided", "k", "killed"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final_res, leaf)),
            np.asarray(getattr(final_full, leaf)), err_msg=leaf)


@pytest.mark.slow
def test_resume_bit_identical_fused_round(tmp_path):
    """Cut + resume with the fully-fused round kernels engaged
    (use_pallas_round): the kernel streams are keyed on (key, round,
    phase, global ids) like everything else, so the guarantee carries."""
    from benor_tpu.ops import sampling
    from benor_tpu.sweep import balanced_inputs

    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = 4
    try:
        n, f = 96, 40
        cfg = SimConfig(n_nodes=n, n_faulty=f, trials=16, max_rounds=48,
                        delivery="quorum", scheduler="uniform",
                        path="histogram", use_pallas_hist=True,
                        use_pallas_round=True, seed=5)
        from benor_tpu.ops import tally
        assert tally.pallas_round_active(cfg)
        faults = FaultSpec.none(16, n)
        state = init_state(cfg, balanced_inputs(16, n), faults)
        base_key = jax.random.key(cfg.seed)

        rounds_full, final_full = run_consensus(cfg, state, faults,
                                                base_key)
        assert int(rounds_full) >= 3, "config must take several rounds"

        cfg_cap = cfg.replace(max_rounds=2)
        rounds_cap, mid = run_consensus(cfg_cap, state, faults, base_key)
        path = str(tmp_path / "ckpt.npz")
        save_checkpoint(path, cfg, mid, faults,
                        next_round=int(rounds_cap) + 1)

        rounds_res, final_res, _ = resume_from(path)
        assert int(rounds_res) == int(rounds_full)
        for leaf in ("x", "decided", "k", "killed"):
            np.testing.assert_array_equal(
                np.asarray(getattr(final_res, leaf)),
                np.asarray(getattr(final_full, leaf)), err_msg=leaf)
    finally:
        sampling.EXACT_TABLE_MAX = old


@pytest.mark.slow
def test_resume_on_mesh_bit_identical(tmp_path):
    """A single-device checkpoint resumes on a device mesh (and the result
    is bit-identical to the uninterrupted single-device run): checkpoints
    are mesh-agnostic because randomness keys on global ids."""
    from benor_tpu.parallel import make_mesh

    cfg, state, faults = _setup(path="histogram")
    base_key = jax.random.key(cfg.seed)
    rounds_full, final_full = run_consensus(cfg, state, faults, base_key)
    assert int(rounds_full) >= 3, "config must take several rounds"

    cfg_cap = cfg.replace(max_rounds=2)
    rounds_cap, mid = run_consensus(cfg_cap, state, faults, base_key)
    path = str(tmp_path / "ckpt_mesh.npz")
    save_checkpoint(path, cfg, mid, faults, next_round=int(rounds_cap) + 1)

    rounds_res, final_res, _ = resume_from(path, mesh=make_mesh(2, 4))
    assert int(rounds_res) == int(rounds_full)
    np.testing.assert_array_equal(np.asarray(final_res.x),
                                  np.asarray(final_full.x))
    np.testing.assert_array_equal(np.asarray(final_res.decided),
                                  np.asarray(final_full.decided))
    np.testing.assert_array_equal(np.asarray(final_res.k),
                                  np.asarray(final_full.k))


@pytest.mark.slow
def test_resume_with_crash_at_round_bit_identical(tmp_path):
    """Mid-run crashes scheduled AFTER the checkpoint round still fire on
    resume: FaultSpec.crash_round is persisted and the kernel re-derives
    killed-at-round-r from it, so interrupting before a scheduled crash
    cannot lose it."""
    n, f = 60, 25
    # F > N/3 (decide threshold above the typical class count) + balanced
    # inputs so the run takes several rounds; crashes staggered across
    # rounds 1..5 — some fire after the round-2 checkpoint cut
    cfg = SimConfig(n_nodes=n, n_faulty=f, trials=16, max_rounds=48,
                    delivery="quorum", scheduler="uniform", path="dense",
                    fault_model="crash_at_round", seed=11)
    faulty = [True] * f + [False] * (n - f)
    crash_rounds = [1 + (i % 5) for i in range(f)] + [0] * (n - f)
    vals = [i % 2 for i in range(n)]
    faults = FaultSpec.from_faulty_list(cfg, faulty, crash_rounds)
    state = init_state(cfg, vals, faults)
    base_key = jax.random.key(cfg.seed)

    rounds_full, final_full = run_consensus(cfg, state, faults, base_key)
    assert int(rounds_full) >= 3

    cfg_cap = cfg.replace(max_rounds=2)
    rounds_cap, mid = run_consensus(cfg_cap, state, faults, base_key)
    path = str(tmp_path / "ckpt_car.npz")
    save_checkpoint(path, cfg, mid, faults, next_round=int(rounds_cap) + 1)

    rounds_res, final_res, _ = resume_from(path)
    assert int(rounds_res) == int(rounds_full)
    np.testing.assert_array_equal(np.asarray(final_res.x),
                                  np.asarray(final_full.x))
    np.testing.assert_array_equal(np.asarray(final_res.killed),
                                  np.asarray(final_full.killed))
    # every crash scheduled at-or-before the last executed round really
    # fired post-resume (later ones can't: the loop exits on termination)
    cr = np.asarray(crash_rounds[:f])
    due = cr <= int(rounds_res)
    assert ((cr > 2) & due).any(), \
        "test must cover crashes after the round-2 cut"
    assert np.asarray(final_res.killed)[:, :f][:, due].all()


@pytest.mark.slow
def test_resume_preserves_custom_base_key(tmp_path):
    """A run started with a non-default key resumes on the SAME streams."""
    cfg, state, faults = _setup()
    custom_key = jax.random.key(12345)          # != key(cfg.seed)
    rounds_full, final_full = run_consensus(cfg, state, faults, custom_key)
    cfg_cap = cfg.replace(max_rounds=2)
    rounds_cap, mid = run_consensus(cfg_cap, state, faults, custom_key)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, cfg, mid, faults, next_round=int(rounds_cap) + 1,
                    base_key=custom_key)
    rounds_res, final_res, _ = resume_from(path)
    assert int(rounds_res) == int(rounds_full)
    np.testing.assert_array_equal(np.asarray(final_res.x),
                                  np.asarray(final_full.x))


def test_load_round_trips_config_and_arrays(tmp_path):
    cfg, state, faults = _setup(fault_model="crash", coin_mode="common")
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, cfg, state, faults, next_round=1)
    cfg2, state2, faults2, nr, key = load_checkpoint(path)
    assert cfg2 == cfg
    assert nr == 1
    import jax as _jax
    np.testing.assert_array_equal(
        np.asarray(_jax.random.key_data(key)),
        np.asarray(_jax.random.key_data(_jax.random.key(cfg.seed))))
    np.testing.assert_array_equal(np.asarray(state2.x), np.asarray(state.x))
    np.testing.assert_array_equal(np.asarray(faults2.faulty),
                                  np.asarray(faults.faulty))
    assert state2.x.dtype == state.x.dtype
    assert state2.k.dtype == state.k.dtype


def test_version_gate(tmp_path):
    cfg, state, faults = _setup()
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, cfg, state, faults, next_round=1)
    import numpy as _np
    with _np.load(path) as z:
        data = {k: z[k] for k in z.files}
    data["version"] = _np.int32(99)
    with open(path, "wb") as fh:
        _np.savez(fh, **data)
    with pytest.raises(ValueError, match="version"):
        load_checkpoint(path)
