"""Witness traces + protocol invariant auditor (ISSUE 3).

Acceptance contract:
  * the witness buffer is bit-identical across the traced, fused-pallas,
    sliced (poll_rounds), batched-sweep and sharded regimes on one seed;
  * witness=off runs are bit-identical in results AND compile counts to
    pre-feature behavior (the utils/compile_counter discipline
    tests/test_flight_recorder.py pins for ``record``);
  * a seeded equivocator run produces a PINPOINTED agreement-violation
    witness (trial, round, node ids, tallies); clean 'reference' and
    'textbook' runs audit clean across all five regimes;
  * the TpuNetwork surface (get_witness) and the bundle schema hold.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from benor_tpu.audit import (WitnessBundle, audit_point, audit_witness,
                             save_bundle, witness_rows)
from benor_tpu.config import SimConfig
from benor_tpu.sim import (run_consensus, run_consensus_slice, simulate,
                           start_state)
from benor_tpu.state import (WIT_COINED, WIT_DECIDED, WIT_KILLED, WIT_P0,
                             WIT_P1, WIT_V0, WIT_V1, WIT_WIDTH,
                             WIT_WRITTEN, WIT_X, FaultSpec, init_state,
                             witness_node_ids)
from benor_tpu.sweep import balanced_inputs

T, N = 8, 24

#: The cross-path fixture (same doctrine as tests/test_flight_recorder.py):
#: count-controlling adversary + common coin — every regime shares EVERY
#: random bit, so full witness buffers must be bit-identical, not just
#: invariant-equivalent.
ADV = dict(n_nodes=N, n_faulty=4, trials=T, delivery="quorum",
           scheduler="adversarial", coin_mode="common", path="histogram",
           max_rounds=12, seed=3, witness_trials=(0, 3), witness_nodes=6)


def _adv_inputs():
    cfg = SimConfig(**ADV)
    faults = FaultSpec.none(T, N)
    state = init_state(cfg, balanced_inputs(T, N), faults)
    return cfg, state, faults, jax.random.key(ADV["seed"])


def _slice_all(cfg, state, faults, key, chunk):
    """Drive run_consensus_slice to termination in ``chunk``-round steps,
    threading one witness buffer across slices — the poll_rounds shape."""
    st = start_state(cfg, state)
    r, wit = jnp.int32(1), None
    while True:
        r_next, st, wit = run_consensus_slice(cfg, st, faults, key, r,
                                              r + chunk, None, wit)
        if int(r_next) == int(r) or int(r_next) > cfg.max_rounds:
            break
        r = r_next
    return st, wit


def test_witness_identical_across_all_regimes():
    """The acceptance pin: one seed, five regimes, one witness buffer."""
    from benor_tpu.parallel import make_mesh, run_consensus_sharded
    from benor_tpu.sweep import run_curve_batched

    cfg, state, faults, key = _adv_inputs()
    r, fin, wit = run_consensus(cfg, state, faults, key)
    wit = np.asarray(wit)
    assert int(r) >= 2                     # multi-round, or the pin is vacuous
    assert wit.shape == (cfg.max_rounds + 1, 2, 6, WIT_WIDTH)

    # fused pallas round (bit-identical here: delivered counts + common coin)
    cfg_p = cfg.replace(use_pallas_round=True)
    from benor_tpu.ops.tally import pallas_round_active
    assert pallas_round_active(cfg_p)
    rp, finp, witp = run_consensus(cfg_p, state, faults, key)
    assert int(rp) == int(r)
    np.testing.assert_array_equal(wit, np.asarray(witp))
    np.testing.assert_array_equal(np.asarray(fin.x), np.asarray(finp.x))

    # sliced (poll_rounds shape), both compute paths
    for c, chunk in ((cfg, 3), (cfg_p, 2)):
        fin_s, wit_s = _slice_all(c, state, faults, key, chunk)
        np.testing.assert_array_equal(wit, np.asarray(wit_s))

    # batched dynamic-F sweep (the adversarial curve is a dyn bucket)
    cb = run_curve_batched(cfg.replace(n_faulty=0), [4, 6],
                           initial_values=balanced_inputs(T, N),
                           faults_for=lambda c: FaultSpec.none(T, N))
    np.testing.assert_array_equal(wit, cb.points[0].witness)

    # sharded mesh (multiple shapes; rows psum-globalized before the write)
    for shape in ((2, 4), (1, 8), (4, 1)):
        rs, fs, wit_m = run_consensus_sharded(cfg, state, faults, key,
                                              make_mesh(*shape))
        assert int(rs) == int(r)
        np.testing.assert_array_equal(wit, np.asarray(wit_m),
                                      err_msg=str(shape))


def test_witness_off_results_and_compile_count():
    """witness=off must be indistinguishable from a build without the
    feature: bit-identical results to witness=on, and exactly ONE backend
    compile for the run (the flag is static), measured by the
    jax.monitoring hook — the same discipline the flight recorder pins."""
    from benor_tpu.utils.compile_counter import count_backend_compiles

    # max_rounds=18 keeps this shape distinct from the flight recorder's
    # 26/5/5/16 pin so the witness-off compile can't hit its jit cache
    base = dict(n_nodes=26, n_faulty=5, trials=5, delivery="quorum",
                scheduler="uniform", max_rounds=18, seed=77)
    cfg_off = SimConfig(**base)
    cfg_on = SimConfig(witness_trials=(0, 2), witness_nodes=4, **base)
    faults = FaultSpec.from_faulty_list(cfg_off, [True] * 5 + [False] * 21)
    state = init_state(cfg_off, [i % 2 for i in range(26)], faults)
    key = jax.random.key(cfg_off.seed)

    with count_backend_compiles() as cc:
        r0, fin0 = run_consensus(cfg_off, state, faults, key)
        int(r0)
    assert cc.count == 1, cc.count

    r1, fin1, _wit = run_consensus(cfg_on, state, faults, key)
    assert int(r0) == int(r1)
    for leaf in ("x", "decided", "k", "killed"):
        np.testing.assert_array_equal(np.asarray(getattr(fin0, leaf)),
                                      np.asarray(getattr(fin1, leaf)))


def test_witness_row_semantics():
    """Row invariants on the forced-tie fixture: row 0 snapshots the
    balanced inputs, round 1 is an all-coin round with tied proposal
    tallies and zero vote counts (everyone voted \"?\"), and the decide
    round's evidence clears the bar."""
    cfg, state, faults, key = _adv_inputs()
    r, fin, wit = run_consensus(cfg, state, faults, key)
    wit, rounds = np.asarray(wit), int(r)
    ids = witness_node_ids(cfg)
    assert list(ids) == [0, 1, 2, 21, 22, 23]    # both ends of the range

    assert (wit[:rounds + 1, :, :, WIT_WRITTEN] == 1).all()
    assert (wit[rounds + 1:] == 0).all()         # unwritten tail stays zero
    # row 0: the post-/start snapshot — interleaved balanced inputs
    np.testing.assert_array_equal(wit[0, 0, :, WIT_X], ids % 2)
    assert (wit[0, :, :, WIT_DECIDED] == 0).all()
    assert (wit[0, :, :, [WIT_P0, WIT_P1, WIT_V0, WIT_V1]] == 0).all()
    # round 1: perfect tie -> every watched lane coins, zero vote counts
    assert (wit[1, :, :, WIT_COINED] == 1).all()
    np.testing.assert_array_equal(wit[1, :, :, WIT_P0],
                                  wit[1, :, :, WIT_P1])
    assert (wit[1, :, :, [WIT_V0, WIT_V1]] == 0).all()
    # decide round: every watched lane decided with > F evidence
    last = wit[rounds]
    assert (last[:, :, WIT_DECIDED] == 1).all()
    v = np.where(last[:, :, WIT_X] == 0, last[:, :, WIT_V0],
                 last[:, :, WIT_V1])
    assert (v > cfg.n_faulty).all()


@pytest.mark.parametrize("rule", ["reference", "textbook"])
def test_audit_clean_across_all_regimes(rule):
    """Honest runs (reference contract: crash faults pinned to F, so
    alive == quorum) must audit clean in every regime, both rules."""
    from benor_tpu.parallel import make_mesh, run_consensus_sharded
    from benor_tpu.sweep import run_curve_batched

    base = dict(n_nodes=16, n_faulty=4, trials=4, delivery="quorum",
                scheduler="uniform", path="histogram", max_rounds=16,
                seed=5, rule=rule, witness_trials=(0, 2), witness_nodes=6)
    cfg = SimConfig(**base)
    faults = FaultSpec.first_f(cfg)
    state = init_state(cfg, [i % 2 for i in range(16)], faults)
    key = jax.random.key(cfg.seed)

    buffers = {}
    r, fin, buffers["traced"] = run_consensus(cfg, state, faults, key)
    _, buffers["sliced"] = _slice_all(cfg, state, faults, key, 2)
    _, _, buffers["sharded"] = run_consensus_sharded(cfg, state, faults,
                                                     key, make_mesh(2, 2))
    cb = run_curve_batched(cfg.replace(n_faulty=0), [4],
                           initial_values=np.asarray(
                               [[i % 2 for i in range(16)]] * 4, np.int8))
    buffers["batched"] = cb.points[0].witness
    # the fused-pallas regime shares the adversarial fixture's witness
    # checks via test_witness_identical_across_all_regimes; audit it on
    # the count-controlling adversary where its bits match the XLA loop
    acfg = SimConfig(**{**ADV, "use_pallas_round": True, "rule": rule})
    afaults = FaultSpec.none(T, N)
    astate = init_state(acfg, balanced_inputs(T, N), afaults)
    _, _, buffers["pallas"] = run_consensus(acfg, astate, afaults,
                                            jax.random.key(ADV["seed"]))

    for regime, buf in buffers.items():
        c, fl = (acfg, afaults) if regime == "pallas" else (cfg, faults)
        report = audit_witness(WitnessBundle.from_run(
            c, buf, faults=fl, label=f"{rule}/{regime}"))
        assert report.ok, (regime, [v.message for v in report.violations])
        assert report.checks["irrevocability"] > 0
        assert report.checks["quorum_evidence"] > 0


def test_audit_catches_seeded_equivocator():
    """One equivocator under the targeted adversary splits agreement at
    any N (tests/test_equivocate.py scenarios): the auditor must emit a
    pinpointed agreement-violation witness — trial, round, the two node
    ids, and the > F tallies both decisions were justified by."""
    n = 16
    cfg = SimConfig(n_nodes=n, n_faulty=1, trials=4, delivery="quorum",
                    scheduler="targeted", fault_model="equivocate",
                    path="histogram", max_rounds=16, seed=0,
                    witness_trials=(0, 1, 2, 3), witness_nodes=n)
    report, bundle = audit_point(
        cfg, initial_values=balanced_inputs(4, n), label="equivocator")
    assert not report.ok
    agr = [v for v in report.violations if v.invariant == "agreement"]
    assert agr, [v.invariant for v in report.violations]
    # every watched trial violates, each with a minimal witness
    assert {v.trial for v in agr} == {0, 1, 2, 3}
    for v in agr:
        assert len(v.nodes) == 2
        a, b = v.detail["node_a"], v.detail["node_b"]
        assert a["value"] == 0 and b["value"] == 1
        assert a["v0"] > cfg.n_faulty and b["v1"] > cfg.n_faulty
        # the equivocator (node 0, faulty) is never blamed for agreement
        assert 0 not in v.nodes
    # ONLY agreement breaks: each camp's decide evidence is individually
    # sound (that is the attack — the rule has no Byzantine margin)
    assert {v.invariant for v in report.violations} == {"agreement"}


def test_audit_validity_and_killed_silence():
    """Unanimous inputs arm the validity check (clean here); a
    crash_at_round run exercises killed-silence on real kills."""
    cfg = SimConfig(n_nodes=12, n_faulty=3, trials=2, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=32,
                    seed=2, witness_trials=(0, 1), witness_nodes=12)
    report, _ = audit_point(cfg, initial_values=np.ones((2, 12), np.int8),
                            faults=FaultSpec.none(2, 12))
    assert report.ok and report.checks["validity"] > 0

    ccfg = cfg.replace(fault_model="crash_at_round", witness_nodes=6)
    crash = [2, 3, 0] + [0] * 9
    report2, bundle2 = audit_point(
        ccfg, faults=FaultSpec.first_f(ccfg, crash_rounds=crash))
    assert report2.ok
    # the watched killed lane really recorded its kill
    buf = np.asarray(bundle2.buffer)
    assert (buf[3:, :, 0, WIT_KILLED][buf[3:, 0, 0, WIT_WRITTEN] > 0]
            == 1).all()


def test_audit_flags_forged_evidence():
    """The auditor is not a rubber stamp: corrupting a clean witness must
    produce quorum-evidence / irrevocability violations."""
    cfg = SimConfig(n_nodes=16, n_faulty=4, trials=2, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=16,
                    seed=5, witness_trials=(0, 1), witness_nodes=4)
    report, bundle = audit_point(cfg)
    assert report.ok
    buf = np.array(bundle.buffer)
    rounds = np.nonzero(buf[:, 0, 0, WIT_WRITTEN] > 0)[0]
    # find a watched lane that decides mid-history (the first watched
    # nodes are birth-crashed under the default first-F fault mask)
    rd = ki = None
    for k in range(buf.shape[2]):
        for r in rounds[1:]:
            if buf[r, 0, k, WIT_DECIDED] and \
                    not buf[r - 1, 0, k, WIT_DECIDED]:
                rd, ki = r, k
                break
        if rd is not None:
            break
    assert rd is not None
    forged = buf.copy()
    forged[rd, 0, ki, [WIT_V0, WIT_V1]] = cfg.n_faulty  # tally under the bar
    rep = audit_witness(WitnessBundle(
        buffer=forged, trial_ids=bundle.trial_ids,
        node_ids=bundle.node_ids, rule=cfg.rule, n_faulty=cfg.n_faulty,
        n_nodes=cfg.n_nodes))
    assert any(v.invariant == "quorum_evidence" for v in rep.violations)

    # append one forged post-termination row in which the lane un-decides
    assert rounds[-1] + 1 < buf.shape[0]
    revoked = buf.copy()
    revoked[rounds[-1] + 1] = revoked[rounds[-1]]
    revoked[rounds[-1] + 1, 0, ki, WIT_DECIDED] = 0
    rep2 = audit_witness(WitnessBundle(
        buffer=revoked, trial_ids=bundle.trial_ids,
        node_ids=bundle.node_ids, rule=cfg.rule, n_faulty=cfg.n_faulty,
        n_nodes=cfg.n_nodes))
    assert any(v.invariant == "irrevocability" for v in rep2.violations)


def test_audit_freeze_off_coin_and_failstop_population():
    """Two checker-side regressions.  (1) With freeze_decided=False a
    decided lane keeps participating and legally re-coins on a later tie
    — only the frozen contract forbids coins after decide.  (2) Fail-stop
    lanes (crash/crash_at_round) follow the protocol until death, so
    from_run must keep them in the agreement/validity population; only
    the lying models (byzantine/equivocate) carry a faulty mask."""
    buf = np.zeros((4, 1, 1, WIT_WIDTH), np.int64)
    buf[:3, :, :, WIT_WRITTEN] = 1
    buf[:, :, :, WIT_X] = 1
    buf[1:, :, :, WIT_DECIDED] = 1          # decides 1 at round 1 on v1=2
    buf[1, :, :, WIT_V1] = 2
    buf[2, :, :, WIT_COINED] = 1            # ...then coins on a 1-1 tie
    buf[2, :, :, [WIT_V0, WIT_V1]] = 1
    common = dict(buffer=buf, trial_ids=np.array([0]),
                  node_ids=np.array([0]), rule="reference", n_faulty=1,
                  n_nodes=4)
    assert audit_witness(WitnessBundle(freeze_decided=False,
                                       **common)).ok
    frozen = audit_witness(WitnessBundle(freeze_decided=True, **common))
    assert any(v.invariant == "quorum_evidence"
               for v in frozen.violations)

    # a snapshot-decided lane (fresh-buffer resume: decided in row 0,
    # tallies never witnessed) still counts for agreement, but the
    # violation must not fabricate quorum evidence from the zeroed row
    buf2 = np.zeros((4, 1, 2, WIT_WIDTH), np.int64)
    buf2[:2, :, :, WIT_WRITTEN] = 1
    buf2[:, :, 0, WIT_DECIDED] = 1          # lane 0: decided 0 pre-window
    buf2[1:, :, 1, [WIT_X, WIT_DECIDED]] = 1
    buf2[1, :, 1, WIT_V1] = 2               # lane 1: decides 1 on v1=2
    rep = audit_witness(WitnessBundle(
        buffer=buf2, trial_ids=np.array([0]), node_ids=np.array([0, 1]),
        rule="reference", n_faulty=1, n_nodes=4))
    agr = [v for v in rep.violations if v.invariant == "agreement"]
    assert agr and agr[0].detail["node_a"]["v0"] is None
    assert "pre-dates the witness window" in agr[0].message
    assert "v0=0" not in agr[0].message

    shape_only = np.zeros((17, 1, 4, WIT_WIDTH), np.int64)
    base = dict(n_nodes=12, n_faulty=3, trials=2, delivery="quorum",
                scheduler="uniform", max_rounds=16, seed=1,
                witness_trials=(0,), witness_nodes=4)
    for model, excluded in (("crash", False), ("crash_at_round", False),
                            ("byzantine", True), ("equivocate", True)):
        cfg = SimConfig(fault_model=model, **base)
        faults = (FaultSpec.first_f(cfg, crash_rounds=[2, 3, 4] + [0] * 9)
                  if model == "crash_at_round" else FaultSpec.first_f(cfg))
        b = WitnessBundle.from_run(cfg, shape_only, faults=faults)
        assert (b.faulty is not None) == excluded, model
        if excluded:
            assert b.faulty[0, 0] and not b.faulty[0, -1]


def test_tpu_network_get_witness():
    """TpuNetwork.get_witness(): the parity-API surface, live under
    poll_rounds slicing and loud when the witness is off — the
    get_round_history contract."""
    from benor_tpu.backends.tpu import TpuNetwork

    cfg = SimConfig(n_nodes=10, n_faulty=2, trials=4, delivery="quorum",
                    scheduler="uniform", seed=1, max_rounds=16,
                    poll_rounds=2, witness_trials=(0, 1), witness_nodes=4)
    net = TpuNetwork(cfg, [1] * 10, [True] * 2 + [False] * 8)
    seen = []
    net.start(on_slice=lambda: seen.append(len(net.get_witness())))
    rows = net.get_witness()
    n_written = net.rounds_executed + 1
    assert len(rows) == n_written * 2 * 4
    assert rows[0] == {"round": 0, "trial": 0, "node": 0, "x": 1,
                       "decided": 0, "killed": 1, "coined": 0,
                       "p0": 0, "p1": 0, "v0": 0, "v1": 0}
    assert seen and seen[0] <= len(rows)    # grew live between slices

    # one-shot (no poll) path fills it too; witness off raises
    cfg1 = cfg.replace(poll_rounds=0)
    net1 = TpuNetwork(cfg1, [1] * 10, [True] * 2 + [False] * 8)
    net1.start()
    assert net1.get_witness() == rows
    net0 = TpuNetwork(cfg1.replace(witness_trials=None, witness_nodes=0),
                      [1] * 10, [True] * 2 + [False] * 8)
    net0.start()
    with pytest.raises(ValueError, match="witness_trials"):
        net0.get_witness()


def test_simulate_arity_and_config_guards():
    """simulate() appends the witness after the recorder; config rejects
    malformed witness settings and oracle backends."""
    cfg = SimConfig(n_nodes=10, n_faulty=2, trials=2, delivery="quorum",
                    scheduler="uniform", seed=9, record=True,
                    witness_trials=(1,), witness_nodes=2)
    rounds, final, faults, rec, wit = simulate(
        cfg, [1] * 10, [True] * 2 + [False] * 8)
    assert np.asarray(wit).shape == (cfg.max_rounds + 1, 1, 2, WIT_WIDTH)
    with pytest.raises(ValueError, match="witness_nodes"):
        SimConfig(n_nodes=4, n_faulty=0, witness_trials=(0,))
    with pytest.raises(ValueError, match="witness_trials"):
        SimConfig(n_nodes=4, n_faulty=0, witness_nodes=2)
    with pytest.raises(ValueError, match="witness_trials"):
        SimConfig(n_nodes=4, n_faulty=0, trials=2, witness_trials=(5,),
                  witness_nodes=2)
    with pytest.raises(ValueError, match="WITNESS_MAX_NODES"):
        SimConfig(n_nodes=100, n_faulty=0, witness_trials=(0,),
                  witness_nodes=40)
    with pytest.raises(ValueError, match="backend"):
        SimConfig(n_nodes=4, n_faulty=0, backend="express",
                  witness_trials=(0,), witness_nodes=2)


def test_witness_bundle_schema():
    """Saved bundles must validate against tools/witness_bundle_schema.json
    (the CI contract results.py's witness_*.json artifacts ride on)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    try:
        from tools.check_metrics_schema import check_witness_bundle
    finally:
        sys.path.pop(0)
    import json
    import tempfile

    cfg = SimConfig(n_nodes=12, n_faulty=3, trials=2, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=16,
                    seed=1, witness_trials=(0,), witness_nodes=4)
    report, bundle = audit_point(cfg, label="schema")
    with tempfile.NamedTemporaryFile("r", suffix=".json") as fh:
        save_bundle(fh.name, bundle, report)
        doc = json.load(open(fh.name))
    assert check_witness_bundle(doc) == []
    # the cross-field pin actually bites
    doc["trial_ids"] = [0, 1]
    assert check_witness_bundle(doc)


def test_witness_rows_rendering():
    """witness_rows: one dict per written (round, trial, node), skipping
    unwritten gap rows — the shared renderer contract."""
    cfg, state, faults, key = _adv_inputs()
    r, fin, wit = run_consensus(cfg, state, faults, key)
    rows = witness_rows(np.asarray(wit), cfg.witness_trials,
                        witness_node_ids(cfg))
    assert len(rows) == (int(r) + 1) * 2 * 6
    assert {row["round"] for row in rows} == set(range(int(r) + 1))
    assert all(set(row) == {"round", "trial", "node", "x", "decided",
                            "killed", "coined", "p0", "p1", "v0", "v1"}
               for row in rows)