"""perfscope (ISSUE 5): AOT cost/memory observatory + perf regression gate.

Acceptance contract:
  * a capture's manifest is schema-valid (tools/perf_report_schema.json
    via check_metrics_schema.check_perf_manifest) with non-zero FLOPs /
    bytes accessed / peak-HBM on the CPU backend;
  * tools/check_perf_regression.py exits 0 against the committed
    PERF_BASELINE.json, 2 against a manifest with an injected 2x
    peak-HBM regression, and 3 on incomparable documents;
  * profiling OFF is bit-identical in results AND compile counts (the
    tests/test_flight_recorder.py / test_witness_audit.py discipline):
    the out-of-band AOT capture neither adds dispatch compiles nor
    perturbs results — including a checkpoint-resumed
    ``run_consensus_slice`` leg (utils/checkpoint interaction).
"""

import copy
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from benor_tpu.config import SimConfig
from benor_tpu.perfscope import (IncomparableManifests, build_manifest,
                                 capture_stages, check_bench_trajectory,
                                 compare_manifests, missing_regimes)
from benor_tpu.perfscope.regimes import REGIME_NAMES, capture_regime
from benor_tpu.sim import run_consensus, run_consensus_slice
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import balanced_inputs
from benor_tpu.utils.compile_counter import count_backend_compiles
from benor_tpu.utils.metrics import REGISTRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "PERF_BASELINE.json")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


schema_tool = _load_tool("check_metrics_schema")
gate_tool = _load_tool("check_perf_regression")


@pytest.fixture(scope="module")
def baseline():
    with open(BASELINE) as fh:
        return json.load(fh)


# --------------------------------------------------------------------------
# manifest schema (mirrors tests/test_metrics_schema.py)
# --------------------------------------------------------------------------


def test_committed_baseline_passes_schema(baseline):
    assert schema_tool.check_perf_manifest(baseline) == []
    assert missing_regimes(baseline) == []
    assert set(baseline["regimes"]) == set(REGIME_NAMES)


def test_committed_baseline_has_nonzero_cost_model(baseline):
    """The acceptance pin: every regime's CPU capture carries a real cost
    model — zero FLOPs/bytes/peak would mean a degenerated capture."""
    for name, rep in baseline["regimes"].items():
        assert rep["flops"] > 0, name
        assert rep["bytes_accessed"] > 0, name
        assert rep["peak_bytes"] > 0, name
        assert rep["rounds_executed"] >= 2, name   # the loop iterated
        assert rep["backend_compiles"] == 1, name  # one AOT round trip


def test_schema_catches_missing_required(baseline):
    broken = {k: v for k, v in baseline.items() if k != "scale"}
    assert any("scale" in e
               for e in schema_tool.check_perf_manifest(broken))


def test_schema_catches_regime_report_drift(baseline):
    broken = copy.deepcopy(baseline)
    del broken["regimes"]["traced"]["flops"]
    assert any("flops" in e
               for e in schema_tool.check_perf_manifest(broken))


def test_schema_catches_cross_field_violations(baseline):
    # map key vs report's own regime name
    broken = copy.deepcopy(baseline)
    broken["regimes"]["traced"]["regime"] = "sliced"
    assert any("regime key" in e
               for e in schema_tool.check_perf_manifest(broken))
    # the peak = arg + out + temp - alias identity the widest gate band
    # relies on
    broken = copy.deepcopy(baseline)
    broken["regimes"]["traced"]["peak_bytes"] += 1
    assert any("peak_bytes" in e
               for e in schema_tool.check_perf_manifest(broken))


def test_schema_errors_isolated_per_regime(baseline):
    """One regime's schema error must not mask another regime's
    cross-field drift (the iteration is per-regime scoped)."""
    broken = copy.deepcopy(baseline)
    broken["regimes"]["traced"]["flops"] = "many"          # schema error
    broken["regimes"]["sharded"]["peak_bytes"] += 1        # identity drift
    errs = schema_tool.check_perf_manifest(broken)
    assert any("traced" in e and "flops" in e for e in errs)
    assert any("sharded" in e and "peak_bytes" in e for e in errs)


def test_schema_tool_main_autodetects_manifest(capsys):
    assert schema_tool.main([BASELINE]) == 0
    assert "perf manifest OK" in capsys.readouterr().out


# --------------------------------------------------------------------------
# regression gate (perfscope/baseline.py + tools/check_perf_regression.py)
# --------------------------------------------------------------------------


def _regress_peak(manifest, factor=2.0):
    """The acceptance fixture: a ``factor``x peak-HBM regression in every
    regime, with the arg+out+temp-alias identity kept honest."""
    out = copy.deepcopy(manifest)
    for rep in out["regimes"].values():
        grown = int(rep["temp_bytes"] + (factor - 1) * rep["peak_bytes"])
        rep["temp_bytes"] = grown
        rep["peak_bytes"] = (rep["argument_bytes"] + rep["output_bytes"]
                             + grown - rep["alias_bytes"])
    return out


def test_gate_in_band_against_itself(baseline):
    assert compare_manifests(baseline, baseline) == []


def test_gate_catches_2x_peak_hbm(baseline):
    regs = compare_manifests(_regress_peak(baseline), baseline)
    assert regs
    assert {r.metric for r in regs} >= {"peak_bytes"}
    assert all(r.ratio is None or r.ratio > 1 for r in regs)


def test_gate_flags_improvement_direction_too(baseline):
    """A 10x drop is either a real optimization or a degenerated capture;
    the gate cannot tell which, so it flags for a human re-baseline."""
    shrunk = copy.deepcopy(baseline)
    shrunk["regimes"]["traced"]["flops"] /= 10.0
    regs = compare_manifests(shrunk, baseline)
    assert any(r.metric == "flops" and "re-baseline" in r.message
               for r in regs)


def test_gate_flags_missing_regime_and_rounds_drift(baseline):
    partial = copy.deepcopy(baseline)
    del partial["regimes"]["sharded"]
    partial["regimes"]["traced"]["rounds_executed"] += 1
    msgs = [r.message for r in compare_manifests(partial, baseline)]
    assert any("sharded" in m and "missing" in m for m in msgs)
    assert any("determinism drift" in m for m in msgs)


def test_gate_refuses_incomparable(baseline):
    alien = copy.deepcopy(baseline)
    alien["platform"] = "tpu"
    with pytest.raises(IncomparableManifests):
        compare_manifests(alien, baseline)


def test_gate_tool_exit_codes(tmp_path, baseline, capsys):
    """The CI contract end-to-end through tools/check_perf_regression.py:
    0 in-band, 2 on the injected 2x peak-HBM regression, 3 incomparable."""
    clean = tmp_path / "clean.json"
    clean.write_text(json.dumps(baseline))
    assert gate_tool.main([str(clean), BASELINE]) == 0

    bad = tmp_path / "regressed.json"
    bad.write_text(json.dumps(_regress_peak(baseline)))
    assert gate_tool.main([str(bad), BASELINE]) == 2
    assert "peak_bytes" in capsys.readouterr().out

    alien = copy.deepcopy(baseline)
    alien["scale"]["n_nodes"] *= 2
    weird = tmp_path / "alien.json"
    weird.write_text(json.dumps(alien))
    assert gate_tool.main([str(weird), BASELINE]) == 3

    assert gate_tool.main([str(clean), str(tmp_path / "absent.json"),
                           "--strict"]) == 3


def test_bench_trajectory_collapse(tmp_path):
    recs = [("r01", {"platform": "cpu", "node_rounds_per_sec": 900.0}),
            ("r02", {"platform": "cpu", "node_rounds_per_sec": 1200.0}),
            ("r03", {"platform": "tpu", "node_rounds_per_sec": 5.0}),
            ("r04", {"platform": "cpu", "node_rounds_per_sec": 100.0}),
            ("r05", {"error": "probe timeout"}),
            ("r06", {"platform": "cpu", "node_rounds_per_sec": 0.0})]
    paths = []
    for name, rec in recs:
        p = tmp_path / f"BENCH_{name}.json"
        p.write_text(json.dumps(rec))
        paths.append(str(p))
    findings = check_bench_trajectory(paths)
    hits = [f for f in findings if f.startswith("REGRESSION")]
    # r04 collapses vs the cpu best (r02); the tpu record is its own
    # platform series; the error record is skipped with a note; the
    # 0.0 record is the WORST collapse, not a pre-metric skip
    assert len(hits) == 2
    assert "BENCH_r04" in hits[0] and "BENCH_r06" in hits[1]
    assert any("error record" in f for f in findings)


# --------------------------------------------------------------------------
# capture smoke (CPU): the observatory itself is tested, not just available
# --------------------------------------------------------------------------

#: Small but multi-round capture scale for tier-1 (the committed baseline
#: is captured at the 256/8/12 smoke scale by `-m benor_tpu profile`).
SMOKE = dict(n_nodes=32, trials=4, max_rounds=8)


def test_capture_traced_regime_smoke():
    report, out = capture_regime("traced", seed=0, **SMOKE)
    assert report.regime == "traced" and report.platform == "cpu"
    assert report.flops > 0 and report.bytes_accessed > 0
    assert report.peak_bytes > 0 and report.temp_bytes > 0
    assert report.backend_compiles == 1
    assert report.trace_lower_s > 0 and report.compile_s > 0
    assert report.first_execute_s > 0 and report.steady_execute_s > 0
    assert report.rounds_executed == int(out[0])
    # stage timings landed in the unified metrics registry
    for stage in ("lower", "compile", "first_execute", "steady_execute"):
        t = REGISTRY.timer(f"perfscope.regime.traced.{stage}")
        assert t.count >= 1 and t.total_s > 0
    # a single-regime manifest is schema-valid; completeness is a
    # separate, explicit question
    manifest = build_manifest([report], dict(seed=0, **SMOKE))
    assert schema_tool.check_perf_manifest(manifest) == []
    assert set(missing_regimes(manifest)) == set(REGIME_NAMES) - {"traced"}


def test_capture_unknown_regime_rejected():
    with pytest.raises(ValueError, match="unknown regime"):
        capture_regime("warp_drive")


def test_profiled_capture_bit_identical_and_cache_untouched():
    """The flight-recorder discipline for perfscope: dispatch compiles
    exactly once with profiling off; the out-of-band AOT capture returns
    bit-identical outputs and leaves the dispatch cache untouched (a
    re-dispatch recompiles nothing)."""
    # shape distinct from every other suite pin so no jit cache is warm
    cfg = SimConfig(n_nodes=28, n_faulty=5, trials=6, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=14,
                    seed=21)
    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes),
                       faults)
    key = jax.random.key(cfg.seed)

    with count_backend_compiles() as cc:
        r0, fin0 = run_consensus(cfg, state, faults, key)
        int(r0)
    assert cc.count == 1, cc.count

    cap = capture_stages("test.traced", run_consensus,
                         (cfg, state, faults, key), (state, faults, key))
    assert cap.art.backend_compiles == 1
    r1, fin1 = cap.out
    assert int(r0) == int(r1)
    for leaf in ("x", "decided", "k", "killed"):
        np.testing.assert_array_equal(np.asarray(getattr(fin0, leaf)),
                                      np.asarray(getattr(fin1, leaf)))

    with count_backend_compiles() as cc2:
        r2, fin2 = run_consensus(cfg, state, faults, key)
        int(r2)
    assert cc2.count == 0, cc2.count
    np.testing.assert_array_equal(np.asarray(fin0.x), np.asarray(fin2.x))


def test_checkpoint_resume_unchanged_by_profiling(tmp_path):
    """utils/checkpoint interaction (ISSUE 5 satellite): profiling a
    resumed ``run_consensus_slice`` run changes neither its results nor
    its dispatch compile counts, and the resumed+profiled leg stays
    bit-identical to the uninterrupted run."""
    from benor_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    n, f = 30, 10
    cfg = SimConfig(n_nodes=n, n_faulty=f, trials=6, delivery="quorum",
                    scheduler="uniform", path="histogram", max_rounds=24,
                    seed=6)
    # f silent-faulty nodes leave the quorum N - F exactly met by the
    # healthy population, whose inputs are balanced: several rounds of
    # genuine coin-flipping before quiescence (same recipe as
    # tests/test_checkpoint.py, smaller)
    faults = FaultSpec.from_faulty_list(cfg, [True] * f + [False] * (n - f))
    state = init_state(cfg, [1] * (f + 10) + [0] * 10, faults)
    key = jax.random.key(cfg.seed)

    r_full, fin_full = run_consensus(cfg, state, faults, key)
    assert int(r_full) >= 3, "config must take several rounds"

    r_cap, mid = run_consensus(cfg.replace(max_rounds=2), state, faults,
                               key)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, cfg, mid, faults, next_round=int(r_cap) + 1)
    cfg2, st2, fl2, next_round, key2 = load_checkpoint(path)
    bounds = (jnp.int32(next_round), jnp.int32(cfg.max_rounds + 2))

    # unprofiled resume: the slice executable compiles once, fresh shape
    with count_backend_compiles() as cc:
        r_a, fin_a = run_consensus_slice(cfg2, st2, fl2, key2, *bounds)
        int(r_a)
    assert cc.count == 1, cc.count
    assert int(r_a) - 1 == int(r_full)
    np.testing.assert_array_equal(np.asarray(fin_a.x),
                                  np.asarray(fin_full.x))

    # profiled resume: out-of-band AOT capture of the SAME slice
    # executable at the resumed operands...
    cap = capture_stages("test.resume", run_consensus_slice,
                         (cfg2, st2, fl2, key2) + bounds,
                         (st2, fl2, key2) + bounds)
    assert cap.art.backend_compiles == 1
    np.testing.assert_array_equal(np.asarray(cap.out[1].x),
                                  np.asarray(fin_full.x))

    # ...then the dispatch resume again, under a jax.profiler trace:
    # zero new compiles, bit-identical results, and the capture is
    # visible in the metrics registry (satellite: utils/tracing.py)
    from benor_tpu.utils.tracing import profile_trace

    ticks0 = REGISTRY.counter("tracing.profile_capture").value
    tb_dir = str(tmp_path / "tb")
    with profile_trace(tb_dir) as trace_path, \
            count_backend_compiles() as cc2:
        r_b, fin_b = run_consensus_slice(cfg2, st2, fl2, key2, *bounds)
        int(r_b)
    assert cc2.count == 0, cc2.count
    assert trace_path == tb_dir
    assert REGISTRY.counter("tracing.profile_capture").value == ticks0 + 1
    assert int(r_b) == int(r_a)
    for leaf in ("x", "decided", "k", "killed"):
        np.testing.assert_array_equal(np.asarray(getattr(fin_a, leaf)),
                                      np.asarray(getattr(fin_b, leaf)))


# --------------------------------------------------------------------------
# surfaces: CLI + bench headline
# --------------------------------------------------------------------------


def test_cli_profile_partial_capture_json(tmp_path, capsys):
    from benor_tpu.__main__ import main

    out_path = str(tmp_path / "m.json")
    assert main(["profile", "--regimes", "traced", "--n", "32",
                 "--trials", "4", "--max-rounds", "8", "--format",
                 "json", "--profile-out", out_path]) == 0
    manifest = json.loads(capsys.readouterr().out)
    assert manifest["kind"] == "perf_manifest"
    assert schema_tool.check_perf_manifest(manifest) == []
    assert list(manifest["regimes"]) == ["traced"]
    with open(out_path) as fh:
        assert json.load(fh) == manifest


def test_cli_profile_rejects_unknown_regime(capsys):
    from benor_tpu.__main__ import main

    assert main(["profile", "--regimes", "warp_drive"]) == 1
    assert "unknown regimes" in capsys.readouterr().err


def test_cli_profile_refuses_partial_baseline(tmp_path, capsys):
    """A --regimes subset must never become the baseline: the gate only
    walks baseline regimes, so a partial baseline passes vacuously."""
    from benor_tpu.__main__ import main

    bp = str(tmp_path / "b.json")
    assert main(["profile", "--regimes", "traced", "--n", "32",
                 "--trials", "4", "--max-rounds", "8",
                 "--baseline", bp, "--update-baseline"]) == 1
    assert "refusing to write a partial baseline" in \
        capsys.readouterr().err
    assert not os.path.exists(bp)


def test_bench_headline_gains_exactly_perf_ok():
    """bench._split_headline routes the perfscope blob to the sidecar and
    keeps exactly ONE new bool (perf_ok) on the stdout headline."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    blob = {"n_nodes": 4, "perfscope": {"ok": True, "manifest": {},
                                        "regressions": []}}
    head, detail = bench._split_headline(blob)
    assert head["perf_ok"] is True
    assert "perfscope" not in head and "perfscope" in detail
    assert "perfscope" in bench._DETAIL_KEYS


@pytest.mark.slow
def test_full_manifest_in_band_with_committed_baseline(baseline):
    """All five regimes captured at the committed baseline's scale gate
    in-band — the same capture `python -m benor_tpu profile` and
    bench.py's `_perfscope_check` run."""
    from benor_tpu.perfscope import capture_all

    scale = dict(baseline["scale"])
    seed = scale.pop("seed")
    reports = capture_all(seed=seed, **scale)
    manifest = build_manifest(reports, dict(seed=seed, **scale))
    assert schema_tool.check_perf_manifest(manifest) == []
    assert missing_regimes(manifest) == []
    assert compare_manifests(manifest, baseline) == []
