"""Statistical validation of the O(N) histogram/hypergeometric scale path.

SURVEY.md §7 hard-part 3: before trusting the histogram path at N=10^6 we
verify, at N small enough for the exact dense path, that

  * the hypergeometric samplers (ops/sampling.py) match the analytic
    distribution (exact inverse-CDF class) and moments (normal-approx class),
  * the end-to-end rounds-to-decide distribution of the histogram path is
    statistically indistinguishable (two-sample KS) from the dense path,
    which tallies an explicit per-receiver subset of senders and is exact by
    construction.

The two paths consume different random realizations (edge delays vs direct
count draws) from the same seed, so agreement must be distributional, not
bitwise.
"""

import numpy as np
import pytest
import scipy.stats as st

import jax
import jax.numpy as jnp

from benor_tpu.config import SimConfig
from benor_tpu.ops import sampling
from benor_tpu.sim import simulate


class TestHypergeomExact:
    def test_matches_scipy_cdf(self):
        total, good, m = 40, 17, 12
        tbl = np.asarray(sampling.hypergeom_cdf_table(
            jnp.array([total]), jnp.array([good]), m))[0]
        ref = st.hypergeom(total, good, m).cdf(np.arange(m + 1))
        np.testing.assert_allclose(tbl, ref, atol=1e-5)

    def test_exact_shared_distribution(self):
        total, good, m = 60, 25, 20
        n_draws = 20000
        u = jax.random.uniform(jax.random.key(1), (1, n_draws))
        draws = np.asarray(sampling.hypergeom_exact_shared(
            u, jnp.array([total]), jnp.array([good]), m))[0]
        # chi-square against the analytic pmf over the support
        lo, hi = max(0, m - (total - good)), min(good, m)
        support = np.arange(lo, hi + 1)
        pmf = st.hypergeom(total, good, m).pmf(support)
        obs = np.array([(draws == h).sum() for h in support])
        keep = pmf * n_draws >= 5
        chi2 = ((obs[keep] - n_draws * pmf[keep]) ** 2 /
                (n_draws * pmf[keep])).sum()
        pval = st.chi2(df=keep.sum() - 1).sf(chi2)
        assert pval > 1e-4, f"exact sampler deviates: chi2={chi2}, p={pval}"

    def test_normal_approx_moments(self):
        total, good, m = 5000, 2100, 4000
        n_draws = 20000
        u = jax.random.uniform(jax.random.key(2), (n_draws,))
        draws = np.asarray(sampling.hypergeom_normal_approx(
            u, jnp.full((n_draws,), total), jnp.full((n_draws,), good),
            jnp.full((n_draws,), m))).astype(np.float64)
        dist = st.hypergeom(total, good, m)
        assert abs(draws.mean() - dist.mean()) < 0.05 * dist.std()
        assert abs(draws.std() - dist.std()) < 0.1 * dist.std()

    def test_cornish_fisher_quantiles_large_m(self):
        """Approx regime (m > EXACT_TABLE_MAX): CF quantiles track scipy's
        exact ppf to within ~2 counts — far inside one std (~sigma/100)."""
        total, good, m = 1_000_000, 420_000, 800_000
        dist = st.hypergeom(total, good, m)
        qs = np.array([0.001, 0.01, 0.1, 0.3, 0.5, 0.7, 0.9, 0.99, 0.999])
        draws = np.asarray(sampling.hypergeom_normal_approx(
            jnp.asarray(qs, jnp.float32), jnp.full(9, total),
            jnp.full(9, good), jnp.full(9, m), skew_correct=True))
        exact = dist.ppf(qs)
        assert np.abs(draws - exact).max() <= max(2.0, 0.02 * dist.std()), \
            f"CF quantile error {np.abs(draws - exact).max()} counts"

    @pytest.mark.slow
    def test_multivariate_large_m_uses_approx_and_sums(self):
        T, N = 4, 1024
        m = sampling.EXACT_TABLE_MAX + 1000
        c0 = m; c1 = m // 2; cq = m // 2
        hist = jnp.tile(jnp.array([[c0, c1, cq]], jnp.int32), (T, 1))
        u0 = jax.random.uniform(jax.random.key(5), (T, N))
        u1 = jax.random.uniform(jax.random.key(6), (T, N))
        counts = np.asarray(
            sampling.multivariate_hypergeom_counts(u0, u1, hist, m))
        np.testing.assert_array_equal(counts.sum(-1), m)
        assert counts.min() >= 0
        assert (counts[..., 0] <= c0).all() and (counts[..., 1] <= c1).all()

    @pytest.mark.slow
    def test_multivariate_counts_sum_and_range(self):
        T, N, m = 8, 64, 48
        hist = jnp.tile(jnp.array([[30, 25, 9]], jnp.int32), (T, 1))
        u0 = jax.random.uniform(jax.random.key(3), (T, N))
        u1 = jax.random.uniform(jax.random.key(4), (T, N))
        counts = np.asarray(
            sampling.multivariate_hypergeom_counts(u0, u1, hist, m))
        assert counts.min() >= 0
        np.testing.assert_array_equal(counts.sum(-1), m)
        assert (counts[..., 0] <= 30).all()
        assert (counts[..., 1] <= 25).all()


def _rounds_to_decide(path: str, seed: int, trials: int = 192) -> np.ndarray:
    """Per-healthy-lane decision round k for one MC batch."""
    n, f = 120, 40
    cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials, max_rounds=48,
                    delivery="quorum", scheduler="uniform", path=path,
                    seed=seed)
    faulty = [True] * f + [False] * (n - f)
    # adversarially balanced healthy inputs: 40 ones / 40 zeros among healthy
    vals = [1] * f + [1] * 40 + [0] * 40
    rounds, final, faults = simulate(cfg, vals, faulty)
    healthy = ~np.asarray(faults.faulty[0])
    decided = np.asarray(final.decided)[:, healthy]
    k = np.asarray(final.k)[:, healthy]
    assert decided.mean() > 0.99, f"{path} path failed to converge"
    return k[decided].ravel()


def _biased_path_stats(path: str, seed: int, strength: float,
                       no_crash: bool = False):
    """MC aggregates of one biased-scheduler batch — the shared
    dense-vs-histogram parity harness for both strength regimes.

    ``no_crash`` keeps every node alive so the quorum N-F leaves real
    selection slack for the delay adversary (with crashes pinned to F the
    tallied multiset is forced and the comparison is vacuous)."""
    from benor_tpu.state import FaultSpec
    from benor_tpu.sweep import run_point
    cfg = SimConfig(n_nodes=80, n_faulty=24, trials=192, max_rounds=32,
                    delivery="quorum", scheduler="biased",
                    adversary_strength=strength, path=path, seed=seed)
    faults = None
    if no_crash:
        faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    pt = run_point(cfg, faults=faults)
    return pt.decided_frac, pt.mean_k, pt.ones_frac


def _assert_stats_agree(d, h):
    assert abs(d[0] - h[0]) < 0.1, f"decided_frac {d[0]} vs {h[0]}"
    assert abs(d[1] - h[1]) < 0.5, f"mean_k {d[1]} vs {h[1]}"
    assert abs(d[2] - h[2]) < 0.15, f"ones_frac {d[2]} vs {h[2]}"


class TestBiasedPriorityCounts:
    """Histogram-level biased scheduler (strength >= 1, strict priority)."""

    @pytest.mark.slow
    def test_counts_invariants(self):
        from benor_tpu.ops import rng as _rng
        from benor_tpu.ops.tally import biased_priority_counts
        T, N, m = 4, 32, 20
        hist = jnp.tile(jnp.array([[12, 10, 6]], jnp.int32), (T, 1))
        u0 = jax.random.uniform(jax.random.key(7), (T, N))
        u1 = jax.random.uniform(jax.random.key(8), (T, N))
        out = np.asarray(biased_priority_counts(u0, hist, m, _rng.ids(N)))
        np.testing.assert_array_equal(out.sum(-1), m)
        assert out.min() >= 0
        # even receivers: favored = {0, ?} = 18 < m=20 -> all favored taken,
        # exactly 2 starved 1s leak through; odd receivers: favored
        # {1, ?} = 16 -> 4 starved 0s leak
        even = out[:, 0::2]
        odd = out[:, 1::2]
        np.testing.assert_array_equal(even[..., 0], 12)
        np.testing.assert_array_equal(even[..., 2], 6)
        np.testing.assert_array_equal(even[..., 1], 2)
        np.testing.assert_array_equal(odd[..., 1], 10)
        np.testing.assert_array_equal(odd[..., 0], 4)

    @pytest.mark.slow
    def test_dense_histogram_agree_statistically(self):
        """Both paths implement the same strict-priority adversary: their
        MC-aggregate behavior must match (different RNG realizations, so
        statistical, not bitwise).  Also run with zero crashes so the
        selection slack is real."""
        _assert_stats_agree(
            _biased_path_stats("dense", 31, 1.5, no_crash=True),
            _biased_path_stats("histogram", 32, 1.5, no_crash=True))

class TestBiasedFractionalCounts:
    """Histogram-level biased scheduler at fractional strength 0 < s < 1
    (the uniform-race model, VERDICT r1 item 5)."""

    @pytest.mark.parametrize("nf_val,nq,ns,m,s", [
        (30, 10, 40, 56, 0.5),    # competition window
        (20, 5, 55, 56, 0.25),    # weak bias
        (12, 4, 10, 20, 0.6),     # favored short of quorum (tau ~ 1)
        (10, 2, 68, 56, 0.75),    # favored exhausted (deterministic)
    ])
    @pytest.mark.slow
    def test_race_marginal_matches_brute_force(self, nf_val, nq, ns, m, s):
        """J = #favored among the m smallest must match an explicit
        numpy simulation of the dense delay race in mean and spread."""
        from benor_tpu.ops.tally import biased_fractional_counts
        NF, REP = nf_val + nq, 12000
        r = np.random.default_rng(17)
        fav = r.random((REP, NF))
        sta = r.random((REP, ns)) + s
        order = np.argsort(np.concatenate([fav, sta], axis=1), axis=1)[:, :m]
        j_true = (order < NF).sum(axis=1)
        hist = jnp.tile(jnp.array([[nf_val, ns, nq]], jnp.int32), (1, 1))
        u_r = jax.random.uniform(jax.random.key(1), (1, REP))
        u_s = jax.random.uniform(jax.random.key(2), (1, REP))
        out = np.asarray(biased_fractional_counts(
            s, u_r, u_s, hist, m, jnp.zeros(REP, jnp.int32)))[0]
        j_model = out[:, 0] + out[:, 2]
        assert abs(j_true.mean() - j_model.mean()) < 0.3, \
            f"mean {j_true.mean():.2f} vs {j_model.mean():.2f}"
        assert abs(j_true.std() - j_model.std()) < 0.3, \
            f"std {j_true.std():.2f} vs {j_model.std():.2f}"
        np.testing.assert_array_equal(out.sum(-1) <= m, True)
        assert out.min() >= 0

    @pytest.mark.slow
    def test_dense_histogram_agree_statistically(self):
        """Same fractional-delay adversary on both paths: MC aggregates must
        match (different RNG realizations, so statistical, not bitwise)."""
        _assert_stats_agree(
            _biased_path_stats("dense", 41, 0.5, no_crash=True),
            _biased_path_stats("histogram", 42, 0.5, no_crash=True))


class TestApproxRegimeProtocol:
    """End-to-end protocol validation of the Cornish-Fisher sampler — the
    entire N=1M operating point (m > EXACT_TABLE_MAX) previously had no
    protocol-level check (round-2 VERDICT weak #3; SURVEY §7 hard-part 3).
    Harness (balanced inputs, zero crashes, F > N/3, per-trial
    aggregation): tests/stat_harness.py."""

    @pytest.mark.slow
    def test_cf_forced_matches_exact_table_m495(self):
        """Force CF at m=495 (deep inside the exact regime, where the exact
        shared-CDF table is available as ground truth): rounds-to-decide
        must be distributionally indistinguishable."""
        from stat_harness import trial_mean_k
        exact = trial_mean_k(750, 255, 128, 101, table_max=4096)
        cf = trial_mean_k(750, 255, 128, 102, table_max=64)
        res = st.ks_2samp(exact, cf)
        assert res.pvalue > 1e-3, (
            f"CF sampler shifts protocol outcomes at m=495: "
            f"KS={res.statistic:.4f} p={res.pvalue:.2e} "
            f"(exact mean {exact.mean():.3f}, cf mean {cf.mean():.3f})")
        # mean drift gate: catches a systematic quantile bias even if the
        # shapes happen to KS-match (4 x combined SEM ~ 0.12 rounds)
        sem = np.hypot(exact.std() / len(exact) ** 0.5,
                       cf.std() / len(cf) ** 0.5)
        assert abs(exact.mean() - cf.mean()) < 4 * sem + 1e-9

    @pytest.mark.slow
    def test_cf_forced_seed_control_m495(self):
        """Control: two seeds of the SAME (exact) regime pass the same
        gates, so the comparison above is calibrated, not vacuous."""
        from stat_harness import trial_mean_k
        a = trial_mean_k(750, 255, 128, 101, table_max=4096)
        b = trial_mean_k(750, 255, 128, 103, table_max=4096)
        assert st.ks_2samp(a, b).pvalue > 1e-3

    @pytest.mark.slow
    def test_production_cf_matches_exact_table_m4506(self):
        """The production boundary: m=4506 > EXACT_TABLE_MAX runs CF by
        default; raising the table cap to 8192 forces the exact shared-CDF
        sampler at the same m.  The protocol statistics must agree — this is
        the direct certificate for the samplers the N=1M flagship uses."""
        from stat_harness import trial_mean_k
        cf = trial_mean_k(8192, 3686, 64, 201, table_max=4096)
        exact = trial_mean_k(8192, 3686, 64, 202, table_max=8192)
        res = st.ks_2samp(cf, exact)
        assert res.pvalue > 1e-3, (
            f"production CF regime diverges from exact sampling at m=4506: "
            f"KS={res.statistic:.4f} p={res.pvalue:.2e}")
        sem = np.hypot(cf.std() / len(cf) ** 0.5,
                       exact.std() / len(exact) ** 0.5)
        assert abs(cf.mean() - exact.mean()) < 4 * sem + 1e-9


class TestPathParity:
    """Two-sample KS: dense (exact) vs histogram (sampled) rounds-to-decide."""

    @pytest.mark.slow
    def test_ks_dense_vs_histogram(self):
        dense = _rounds_to_decide("dense", seed=11)
        hist = _rounds_to_decide("histogram", seed=12)
        # spread sanity: the config must actually exercise multi-round runs,
        # otherwise the KS test would trivially pass on constant data
        assert len(np.unique(np.concatenate([dense, hist]))) >= 2
        res = st.ks_2samp(dense, hist)
        assert res.pvalue > 1e-4, (
            f"histogram path diverges from exact dense path: "
            f"KS={res.statistic:.4f} p={res.pvalue:.2e} "
            f"(dense mean {dense.mean():.3f}, hist mean {hist.mean():.3f})")

    @pytest.mark.slow
    def test_dense_seeds_self_consistent(self):
        """Control: two seeds of the SAME path pass the same KS gate."""
        a = _rounds_to_decide("dense", seed=21)
        b = _rounds_to_decide("dense", seed=22)
        assert st.ks_2samp(a, b).pvalue > 1e-4
