"""atlas (benor_tpu/atlas) — the phase-boundary observatory.

Pins the PR 20 contract:

  * the ``<name>:<lo>:<hi>[:<tol>]`` axis grammar parses/validates and
    ``apply`` realizes every knob as a plain SimConfig the existing
    planes already validate (no new delivery semantics);
  * the quorum cliff search brackets F = N/2 to the integer lattice
    with EVERY generation one dyn bucket / one compile, and a journal
    truncated mid-search (the SIGKILL shape) resumes bit-identically
    with exactly the remaining generations' compiles;
  * forensics emits a shrunk ``kind: atlas_repro`` document whose
    replay is bit-identical by construction, ANY tamper (payload or
    digest) fails the replay, and ``python -m benor_tpu replay`` maps
    ok/mismatch/unreadable to exit 0/2/1;
  * the ``kind: atlas_manifest`` document validates through
    check_metrics_schema (registered checker + cross-field recomputes)
    and journal parity holds;
  * tools/check_atlas_regression.py exits 0 on the committed
    ATLAS_BASELINE.json, 2 on a moved/vanished cliff or stale repro,
    3 on a platform/scale mismatch;
  * the express/native oracles agree with the TPU path on which SIDE of
    the discovered quorum cliff decides vs stalls.
"""

import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from benor_tpu.api import launch_network
from benor_tpu.atlas import CLIFF_KIND, PROBE_KIND, render_heatmap
from benor_tpu.atlas.gate import (CLIFF_BAND, AtlasFinding,  # noqa: F401
                                  IncomparableAtlas, compare_atlas,
                                  repro_digest)
from benor_tpu.atlas.manifest import (ATLAS_MANIFEST_KIND, build_manifest,
                                      capture_atlas, journal_parity,
                                      load_manifest, save_manifest)
from benor_tpu.atlas.repro import (REPRO_KIND, build_repro, load_repro,
                                   replay_repro, save_repro)
from benor_tpu.atlas.scenario import AXIS_KINDS, ScenarioAxis, parse_axis
from benor_tpu.atlas.search import find_cliffs, heatmap_slice
from benor_tpu.backends.native_oracle import native_available
from benor_tpu.config import SimConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "ATLAS_BASELINE.json")
GATE_TOOL = os.path.join(REPO, "tools", "check_atlas_regression.py")
SCHEMA_TOOL = os.path.join(REPO, "tools", "check_metrics_schema.py")

sys.path.insert(0, os.path.join(REPO, "tools"))
import check_metrics_schema  # noqa: E402

#: The quorum regime: F sweeps through N/2 = 8 where unanimous-ones
#: Ben-Or flips from round-1 decision to livelock — the cheapest cliff
#: in the atlas (N=16, 4 trials, one dyn bucket per generation).
QN, QT, QR = 16, 4, 8


def _qcfg(**kw):
    base = dict(n_nodes=QN, n_faulty=1, trials=QT, max_rounds=QR,
                delivery="all", path="histogram", seed=0)
    base.update(kw)
    return SimConfig(**base)


def _ones():
    return np.ones((QT, QN), dtype=np.int32)


# --------------------------------------------------------------------------
# scenario: the axis grammar
# --------------------------------------------------------------------------


def test_parse_axis_all_kinds_and_defaults():
    for name, kind in AXIS_KINDS.items():
        ax = parse_axis(f"{name}:2:8")
        assert ax.name == name and (ax.lo, ax.hi) == (2.0, 8.0)
        assert ax.tol == kind["tol"] and ax.integer == kind["integer"]
        assert ax.faults in ("none", "default")
    # explicit tolerance wins (but never below the lattice floor)
    assert parse_axis("drop_prob:0.1:0.4:0.05").tol == 0.05
    assert parse_axis("f:1:12:0.25").tol == 1.0     # integer floor


@pytest.mark.parametrize("spec,msg", [
    ("drop_prob:0.1", "grammar"),
    ("banana:1:2", "unknown scenario axis"),
    ("f:one:2", "must be numbers"),
    ("f:5:5", "lo < hi"),
    ("drop_prob:0.1:0.4:0", "tol must be > 0"),
    ("heal_round:1.5:4", "must be integers"),
])
def test_parse_axis_rejects(spec, msg):
    with pytest.raises(ValueError, match=msg):
        parse_axis(spec)


def test_axis_apply_realizes_every_knob():
    cfg = _qcfg()
    assert parse_axis("drop_prob:0:0.5").apply(cfg, 0.3).drop_prob == 0.3
    assert parse_axis("f:1:12").apply(cfg, 7).n_faulty == 7
    assert parse_axis("heal_round:2:18").apply(cfg, 5).partition == \
        "halves:5"
    rec = parse_axis("recovery_down:1:6").apply(cfg, 3)
    assert rec.fault_model == "crash_recover" and rec.recovery == "at:2:3"
    topo = parse_axis("topology_degree:2:8").apply(cfg, 5)   # snaps to even
    assert topo.topology in ("ring:4", "ring:6")
    armed = cfg.replace(committee_cap=8, committee_count=2,
                        committee_size=2)
    assert parse_axis("committee_size:2:8").apply(armed, 4) \
        .committee_size == 4
    with pytest.raises(ValueError, match="committee plane"):
        parse_axis("committee_size:2:8").apply(cfg, 4)
    # apply fails loudly on an incoherent combination (SimConfig's error)
    with pytest.raises(ValueError):
        parse_axis("f:1:32").apply(cfg, 32)     # F > N


def test_axis_lattice_snap_grid_midpoint():
    ax = parse_axis("topology_degree:2:10")
    assert ax.snap(5.1) == 6.0 and ax.snap(99) == 10.0
    assert all(v % 2 == 0 for v in ax.grid(4))
    f = parse_axis("f:1:12")
    assert f.grid(11) == [float(v) for v in range(1, 13)]
    assert f.midpoint(7, 8) is None              # converged bracket
    assert f.midpoint(4, 9) in (6.0, 7.0)
    d = parse_axis("drop_prob:0.0:0.4")
    assert not d.converged(0.0, 0.4) and d.converged(0.2, 0.21)


# --------------------------------------------------------------------------
# search: the quorum cliff, compile pins, journal resume
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def quorum_capture(tmp_path_factory):
    """ONE forensics-armed quorum capture shared by the search /
    manifest / repro / gate tests (amortizes the backend compiles)."""
    d = tmp_path_factory.mktemp("atlas")
    journal = str(d / "journal.jsonl")
    out_dir = str(d / "forensics")
    os.makedirs(out_dir)
    manifest = capture_atlas(searches=("quorum",), forensics=True,
                             journal_path=journal, out_dir=out_dir)
    return {"manifest": manifest, "journal": journal,
            "out_dir": out_dir, "dir": d}


def _quorum_search(cap):
    (s,) = cap["manifest"]["searches"]
    return s


def test_quorum_search_brackets_half_n(quorum_capture):
    s = _quorum_search(quorum_capture)
    (cliff,) = s["cliffs"]
    assert (cliff["lo"], cliff["hi"]) == (7.0, 8.0)   # F = N/2 exactly
    assert cliff["lo_verdict"] == "decided"
    assert cliff["hi_verdict"] == "stalled"
    assert cliff["width"] <= 1.0


def test_every_generation_is_one_bucket_one_compile(quorum_capture):
    s = _quorum_search(quorum_capture)
    assert len(s["generations"]) >= 2
    for g in s["generations"]:
        assert g["n_buckets"] == 1, g
        assert g["compile_count"] == 1, g
    assert s["compile_count"] == len(s["generations"])
    assert s["probe_count"] == sum(g["n_points"] for g in s["generations"])


def test_truncated_journal_resumes_bit_identical(tmp_path):
    """The SIGKILL shape: cut the journal after generation 0's records
    and resume — the coarse generation replays from the journal with
    ZERO compiles, the refinement generations recompile, and the
    search result is bit-equal to the uninterrupted one."""
    journal = str(tmp_path / "j.jsonl")
    axis = parse_axis("f:1:12")
    full = find_cliffs(_qcfg(), axis, coarse=4, initial_values=_ones(),
                       journal_path=journal)
    n_gens = len(full.generations)
    assert n_gens >= 2

    # keep only generation 0's sweep records (everything up to and
    # including the FIRST sweep_done) — the kill landed in generation 1
    kept, done_seen = [], False
    with open(journal) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("kind") in (PROBE_KIND, CLIFF_KIND):
                continue                  # atlas records are derived
            kept.append(line)
            if rec.get("kind") == "sweep_done":
                done_seen = True
                break
    assert done_seen
    with open(journal, "w") as fh:
        fh.writelines(kept)

    resumed = find_cliffs(_qcfg(), axis, coarse=4,
                          initial_values=_ones(), journal_path=journal,
                          resume=True)
    assert resumed.generations[0]["compile_count"] == 0      # reused
    assert resumed.generations[0]["buckets_reused"] == 1
    for g in resumed.generations[1:]:
        assert g["compile_count"] == 1                       # recompiled
    # science is bit-equal: same probes, same brackets (only the
    # compile accounting differs — the resume reused generation 0)
    a, b = full.to_dict(), resumed.to_dict()
    for k in ("generations", "compile_count"):
        a.pop(k), b.pop(k)
    for ca, cb in zip(a["cliffs"], b["cliffs"]):
        ca.pop("compile_count"), cb.pop("compile_count")
    assert a == b


def test_heatmap_slice_renders_and_is_one_bucket(tmp_path):
    doc = heatmap_slice(_qcfg(), "drop_prob:0.05:0.35", "f:2:6",
                        na=3, nb=2, initial_values=_ones())
    assert doc["kind"] == "atlas_heatmap"
    assert doc["n_buckets"] == 1 and doc["compile_count"] == 1
    text = render_heatmap(doc)
    assert "drop_prob" in text and "stall_frac" in text
    assert len(text.splitlines()) == len(doc["values_b"]) + 2


# --------------------------------------------------------------------------
# repro: shrink, replay, tamper
# --------------------------------------------------------------------------


def test_repro_shrinks_and_replays(quorum_capture):
    s = _quorum_search(quorum_capture)
    (cliff,) = s["cliffs"]
    doc = cliff["repro"]
    assert doc["kind"] == REPRO_KIND
    assert cliff["repro_reproduced"] is True
    # the emitter shrank at least one of (trials, nodes, rounds)
    cfg = doc["config"]
    assert (cfg["trials"] < doc["shrunk_from"]["trials"]
            or cfg["n_nodes"] < doc["shrunk_from"]["n_nodes"]
            or cfg["max_rounds"] < doc["shrunk_from"]["max_rounds"])
    assert doc["verdict"]["verdict"] == "stalled"    # cliff's hi side
    assert replay_repro(doc)["ok"] is True


def test_repro_tamper_fails_replay(quorum_capture):
    s = _quorum_search(quorum_capture)
    doc = copy.deepcopy(s["cliffs"][0]["repro"])
    doc["verdict"]["rounds_executed"] += 1           # edit the payload
    rep = replay_repro(doc)
    assert rep["ok"] is False and rep["digest_ok"] is False
    doc2 = copy.deepcopy(s["cliffs"][0]["repro"])
    doc2["digest"] = "sha256:" + "0" * 64            # edit the digest
    assert replay_repro(doc2)["digest_ok"] is False


def test_replay_cli_exit_codes(quorum_capture, tmp_path):
    """0 reproduced / 2 mismatch / 1 unreadable — the CI contract."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    s = _quorum_search(quorum_capture)
    ok_path = tmp_path / "ok.json"
    save_repro(str(ok_path), s["cliffs"][0]["repro"])
    proc = subprocess.run(
        [sys.executable, "-m", "benor_tpu", "replay", str(ok_path)],
        capture_output=True, text=True, env=env, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "REPRODUCED" in proc.stdout

    bad = copy.deepcopy(s["cliffs"][0]["repro"])
    bad["verdict"]["decided_frac"] = 0.123
    bad_path = tmp_path / "bad.json"
    bad_path.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, "-m", "benor_tpu", "replay", str(bad_path)],
        capture_output=True, text=True, env=env, timeout=240)
    assert proc.returncode == 2, proc.stdout + proc.stderr

    junk = tmp_path / "junk.json"
    junk.write_text('{"kind": "not_a_repro"}')
    proc = subprocess.run(
        [sys.executable, "-m", "benor_tpu", "replay", str(junk)],
        capture_output=True, text=True, env=env, timeout=240)
    assert proc.returncode == 1, proc.stdout + proc.stderr


# --------------------------------------------------------------------------
# manifest: schema + cross-field checker + journal parity
# --------------------------------------------------------------------------


def test_manifest_passes_registered_checker(quorum_capture):
    m = quorum_capture["manifest"]
    assert m["kind"] == ATLAS_MANIFEST_KIND
    assert ATLAS_MANIFEST_KIND in check_metrics_schema.MANIFEST_CHECKERS
    assert check_metrics_schema.check_atlas_manifest(m) == []


def test_manifest_checker_flags_cross_field_drift(quorum_capture):
    m = copy.deepcopy(quorum_capture["manifest"])
    # bracket no longer contains the point estimate
    m["searches"][0]["cliffs"][0]["point"] = 99.0
    assert any("point" in e for e in
               check_metrics_schema.check_atlas_manifest(m))
    m2 = copy.deepcopy(quorum_capture["manifest"])
    m2["probe_count"] += 1                          # totals drift
    assert any("probe_count" in e for e in
               check_metrics_schema.check_atlas_manifest(m2))
    m3 = copy.deepcopy(quorum_capture["manifest"])
    m3["searches"][0]["cliffs"][0]["repro"]["label"] = "edited"
    assert any("digest" in e for e in
               check_metrics_schema.check_atlas_manifest(m3))


def test_journal_parity(quorum_capture):
    par = journal_parity(quorum_capture["manifest"],
                         quorum_capture["journal"])
    assert par["parity"], par
    assert par["journal_probes"] == par["manifest_probes"]


def test_save_load_roundtrip(quorum_capture, tmp_path):
    p = str(tmp_path / "m.json")
    save_manifest(p, quorum_capture["manifest"])
    assert load_manifest(p) == json.loads(
        json.dumps(quorum_capture["manifest"]))


# --------------------------------------------------------------------------
# gate: committed baseline + exit codes
# --------------------------------------------------------------------------


def _baseline():
    with open(BASELINE) as fh:
        return json.load(fh)


def test_committed_baseline_schema_and_self_gate():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, SCHEMA_TOOL, BASELINE],
                          capture_output=True, text=True, env=env,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "atlas manifest OK" in proc.stdout
    proc = subprocess.run([sys.executable, GATE_TOOL, BASELINE],
                          capture_output=True, text=True, env=env,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "in-band" in proc.stdout


def test_committed_baseline_pins_two_cliffs_with_brackets():
    m = _baseline()
    assert m["cliff_count"] >= 2
    names = {s["name"] for s in m["searches"]}
    assert {"omission", "partition"} <= names
    for s in m["searches"]:
        for c in s["cliffs"]:
            assert c["lo"] < c["hi"]
            assert c["lo"] <= c["point"] <= c["hi"]


def test_gate_in_band_on_identical_manifests():
    m = _baseline()
    assert compare_atlas(m, m) == []


def test_gate_flags_moved_vanished_and_stale():
    m = _baseline()
    moved = copy.deepcopy(m)
    c = moved["searches"][0]["cliffs"][0]
    span = c["hi"] - c["lo"]
    for k in ("lo", "hi", "point"):
        c[k] += 10 * span
    assert any("moved" in f.message for f in compare_atlas(moved, m))

    vanished = copy.deepcopy(m)
    vanished["searches"][0]["cliffs"] = []
    assert any("vanished" in f.message
               for f in compare_atlas(vanished, m))

    stale = copy.deepcopy(m)
    for s in stale["searches"]:
        for c in s["cliffs"]:
            if c.get("repro") is not None:
                c["repro_reproduced"] = False
    assert any("no longer reproduces" in f.message
               for f in compare_atlas(stale, m))


def test_gate_incomparable_on_platform_and_scale():
    m = _baseline()
    other = copy.deepcopy(m)
    other["platform"] = "definitely-not-" + str(m["platform"])
    with pytest.raises(IncomparableAtlas, match="platform"):
        compare_atlas(other, m)
    other = copy.deepcopy(m)
    other["scale"] = {"factor": 64.0}
    with pytest.raises(IncomparableAtlas, match="scale"):
        compare_atlas(other, m)


def test_gate_cli_exit_codes(tmp_path):
    """End-to-end: 0 in-band, 2 on a moved cliff, 3 on platform
    mismatch / missing baseline under --strict."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    m = _baseline()

    moved = copy.deepcopy(m)
    c = moved["searches"][0]["cliffs"][0]
    span = c["hi"] - c["lo"]
    for k in ("lo", "hi", "point"):
        c[k] += 10 * span
    mp = tmp_path / "moved.json"
    mp.write_text(json.dumps(moved))
    proc = subprocess.run([sys.executable, GATE_TOOL, str(mp), BASELINE],
                          capture_output=True, text=True, env=env,
                          timeout=60)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "REGRESSION" in proc.stdout

    foreign = copy.deepcopy(m)
    foreign["platform"] = "tpu-from-another-lab"
    fp = tmp_path / "foreign.json"
    fp.write_text(json.dumps(foreign))
    proc = subprocess.run([sys.executable, GATE_TOOL, str(fp), BASELINE],
                          capture_output=True, text=True, env=env,
                          timeout=60)
    assert proc.returncode == 3, proc.stdout + proc.stderr

    missing = subprocess.run(
        [sys.executable, GATE_TOOL, str(mp),
         str(tmp_path / "nope.json"), "--strict"],
        capture_output=True, text=True, env=env, timeout=60)
    assert missing.returncode == 3


def test_build_manifest_totals(quorum_capture):
    m = quorum_capture["manifest"]
    assert m["probe_count"] == sum(s["probe_count"]
                                   for s in m["searches"])
    assert m["compile_count"] == sum(s["compile_count"]
                                     for s in m["searches"])
    assert m["cliff_count"] == sum(len(s["cliffs"])
                                   for s in m["searches"])
    rebuilt = build_manifest(m["searches"], scale=m["scale"]["factor"])
    assert rebuilt["probe_count"] == m["probe_count"]


# --------------------------------------------------------------------------
# oracle differential: same side of the quorum cliff
# --------------------------------------------------------------------------


def _oracle_side(f, backend):
    """Run one unanimous-ones trial at fault level ``f`` through an
    event-loop oracle; 'decided' iff every healthy node decided."""
    values = [1] * QN
    faulty = [i < f for i in range(QN)]       # first-F, crash-from-birth
    net = launch_network(QN, f, values, faulty, backend=backend,
                         seed=0, max_rounds=QR)
    net.start()
    # the global-halt probe kills everyone once all healthy decided, so
    # judge by ``decided`` on the healthy slice (faulty carry null)
    states = net.get_states()
    return ("decided" if all(st["decided"] for st in states[f:])
            else "stalled")


@pytest.mark.parametrize("backend", [
    "express",
    pytest.param("native", marks=pytest.mark.skipif(
        not native_available(),
        reason="g++ unavailable; native oracle not built")),
])
def test_oracle_agrees_on_quorum_cliff_sides(quorum_capture, backend):
    """Differential acceptance: at the discovered cliff's bracketing
    grid points the reference oracle lands on the SAME stall/decide
    side as the TPU path that found the cliff."""
    (cliff,) = _quorum_search(quorum_capture)["cliffs"]
    lo_f, hi_f = int(cliff["lo"]), int(cliff["hi"])
    assert _oracle_side(lo_f, backend) == cliff["lo_verdict"]
    assert _oracle_side(hi_f, backend) == cliff["hi_verdict"]
