"""Wire-level parity: the reference's HTTP control plane over real sockets.

Mirrors the reference test harness's usage (__test__/tests/utils.ts:4-12:
fetch /getState; benorconsensus.test.ts:50-75: /status codes) against both
backends, on a non-default port base so parallel CI runs don't collide.
"""

import json
import urllib.error
import urllib.request

import pytest

from benor_tpu.api import launch_network
from benor_tpu.backends.http_api import NodeHttpCluster

BASE = 3100


def _get(port: int, path: str):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.mark.parametrize("backend", ["tpu", "express"])
class TestHttpParity:
    def test_status_codes(self, backend):
        """benorconsensus.test.ts:45-75: faulty => 500 'faulty',
        healthy => 200 'live'."""
        net = launch_network(3, 1, [1, 1, 1], [True, False, False],
                             backend=backend)
        with NodeHttpCluster(net, BASE):
            assert _get(BASE + 0, "/status") == (500, "faulty")
            assert _get(BASE + 1, "/status") == (200, "live")
            assert _get(BASE + 2, "/status") == (200, "live")

    def test_full_consensus_over_http(self, backend):
        """launch -> /start -> poll /getState until finality -> assertions
        (the unanimous N=5 scenario, benorconsensus.test.ts:133-175)."""
        net = launch_network(5, 0, [1] * 5, [False] * 5, backend=backend,
                             seed=1)
        with NodeHttpCluster(net, BASE):
            code, body = _get(BASE, "/start")
            assert code == 200 and json.loads(body) == {
                "message": "Algorithm started"}
            states = []
            for i in range(5):
                code, body = _get(BASE + i, "/getState")
                assert code == 200
                states.append(json.loads(body))
            assert all(s["decided"] is not False for s in states)  # finality
            assert all(s["x"] == 1 and s["k"] <= 2 for s in states)

    def test_stop_route_kills_single_node(self, backend):
        net = launch_network(3, 0, [1, 1, 1], [False] * 3, backend=backend)
        with NodeHttpCluster(net, BASE):
            assert _get(BASE + 1, "/stop") == (200, "killed")
            assert _get(BASE + 1, "/status")[0] == 500
            assert _get(BASE + 0, "/status")[0] == 200

    def test_unknown_route_404(self, backend):
        net = launch_network(1, 0, [1], [False], backend=backend)
        with NodeHttpCluster(net, BASE):
            assert _get(BASE, "/nope")[0] == 404

    def test_post_message_route(self, backend):
        """POST /message (node.ts:43-163): served on the event-loop oracle
        (200 {"message": "Message received"}, node.ts:161); deliberate
        non-parity on the TPU backend — 405 with an explanation, not a 404
        (PARITY.md)."""
        net = launch_network(1, 0, [1], [False], backend=backend)
        with NodeHttpCluster(net, BASE):
            req = urllib.request.Request(
                f"http://127.0.0.1:{BASE}/message", method="POST",
                data=json.dumps({"k": 1, "x": 1,
                                 "messageType": "proposal phase"}).encode())
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    code, body = resp.status, resp.read().decode()
            except urllib.error.HTTPError as e:
                code, body = e.code, e.read().decode()
            if backend == "express":
                assert code == 200
                assert json.loads(body) == {"message": "Message received"}
            else:
                assert code == 405
                assert "express" in json.loads(body)["detail"]

    def test_faulty_node_state_is_null(self, backend):
        """faulty nodes report all-null state (node.ts:21-26)."""
        net = launch_network(3, 1, [1, 1, 1], [True, False, False],
                             backend=backend)
        with NodeHttpCluster(net, BASE):
            state = json.loads(_get(BASE, "/getState")[1])
            assert state == {"killed": True, "x": None,
                             "decided": None, "k": None}


def _raw_request(port: int, payload: bytes) -> bytes:
    import socket
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(payload)
        s.settimeout(10)
        chunks = []
        try:
            while True:
                b = s.recv(4096)
                if not b:
                    break
                chunks.append(b)
        except OSError:
            pass
    return b"".join(chunks)


def test_post_chunked_body_411():
    """A chunked body cannot be drained by count: 411 + connection close,
    and the response must actually arrive (no RST discard)."""
    net = launch_network(1, 0, [1], [False], backend="tpu")
    with NodeHttpCluster(net, BASE + 60):
        resp = _raw_request(
            BASE + 60,
            b"POST /message HTTP/1.1\r\nHost: x\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            b"5\r\nhello\r\n0\r\n\r\n")
        assert b"411" in resp.split(b"\r\n", 1)[0]
        assert b"chunked" in resp


def test_post_malformed_content_length_400():
    """A garbage Content-Length must produce a 400, not a handler crash
    with no response at all."""
    net = launch_network(1, 0, [1], [False], backend="tpu")
    with NodeHttpCluster(net, BASE + 61):
        resp = _raw_request(
            BASE + 61,
            b"POST /message HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: abc\r\n\r\nxx")
        assert b"400" in resp.split(b"\r\n", 1)[0]
        assert b"Content-Length" in resp


def test_taken_port_parks_node_instead_of_crashing():
    """A port already bound inside the cluster's range must not kill the
    whole cluster: the colliding node id is PARKED (recorded, no
    listener) after the bind retries, every other node serves normally,
    and the parked node's state stays observable via siblings'
    /getState (NodeHttpCluster docstring contract)."""
    import socket

    blocker = socket.socket()
    blocker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    blocker.bind(("127.0.0.1", BASE + 71))       # node 1's port
    blocker.listen(1)
    try:
        net = launch_network(3, 0, [1, 1, 1], [False] * 3, backend="tpu")
        with NodeHttpCluster(net, BASE + 70, addr_retries=1,
                             addr_retry_delay_s=0.01) as cluster:
            assert cluster.parked == [1]
            assert len(cluster.servers) == 2
            assert _get(BASE + 70, "/status") == (200, "live")
            assert _get(BASE + 72, "/status") == (200, "live")
            # the parked node still exists in the simulated network
            code, _ = _get(BASE + 70, "/start")
            assert code == 200
            assert json.loads(_get(BASE + 72, "/getState")[1])["decided"] \
                is not False
    finally:
        blocker.close()


def test_fully_taken_range_still_raises():
    """Parking covers stragglers, not a fully occupied range: zero
    bound listeners means clients would reach a FOREIGN process's
    ports, so construction must fail loudly."""
    import socket

    blockers = []
    try:
        for i in range(2):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", BASE + 80 + i))
            s.listen(1)
            blockers.append(s)
        net = launch_network(2, 0, [1, 1], [False] * 2, backend="tpu")
        with pytest.raises(OSError, match="all 2 ports"):
            NodeHttpCluster(net, BASE + 80, addr_retries=0)
    finally:
        for s in blockers:
            s.close()


def test_drain_cap_is_a_constructor_knob():
    """NodeHttpCluster(drain_cap=...) reaches the handler class (the
    _drain_best_effort budget) instead of the hardwired 1 MiB."""
    net = launch_network(1, 0, [1], [False], backend="tpu")
    with NodeHttpCluster(net, BASE + 75, drain_cap=1 << 10) as cluster:
        handler_cls = cluster.servers[0].RequestHandlerClass
        assert handler_cls.drain_cap == 1 << 10
        # the knobbed cluster still serves the malformed-length path
        resp = _raw_request(
            BASE + 75,
            b"POST /message HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: abc\r\n\r\nxx")
        assert b"400" in resp.split(b"\r\n", 1)[0]


# --- mid-run observability (cfg.poll_rounds) ---------------------------
# The reference polls /getState every 200 ms WHILE consensus runs and
# observes k growing toward the k>10 livelock assertion
# (benorconsensus.test.ts:149-160, :341).  poll_rounds=c restores that
# contract: the compiled loop runs in c-round slices with the snapshot
# republished between slices.

# N=10, F=5 "Exceeding Fault Tolerance" livelock: count > F is
# unsatisfiable, so the network stays undecided for max_rounds — the one
# scenario guaranteed to stay live long enough to observe mid-run.
_LIVELOCK = dict(n=10, f=5, vals=[1, 1, 0, 0, 1, 1, 0, 0, 1, 1],
                 faulty=[True] * 5 + [False] * 5)


@pytest.mark.parametrize("scenario", ["livelock", "decides"])
@pytest.mark.parametrize("poll_rounds", [1, 3])
@pytest.mark.slow
def test_poll_rounds_final_state_bit_identical(scenario, poll_rounds):
    """Sliced execution must change WHEN snapshots are visible, never what
    the final one is: every observable field and rounds_executed match the
    one-shot compiled loop exactly (sim.run_consensus_slice contract)."""
    if scenario == "livelock":
        kw = dict(_LIVELOCK, max_rounds=16)
    else:
        kw = dict(n=7, f=2, vals=[1, 0, 1, 1, 0, 1, 1],
                  faulty=[True, True] + [False] * 5, max_rounds=32)
    nets = {}
    for pr in (0, poll_rounds):
        net = launch_network(kw["n"], kw["f"], kw["vals"], kw["faulty"],
                             backend="tpu", seed=3, delivery="quorum",
                             max_rounds=kw["max_rounds"], poll_rounds=pr)
        net.start()
        nets[pr] = net
    assert nets[0].rounds_executed == nets[poll_rounds].rounds_executed
    assert nets[0].get_states() == nets[poll_rounds].get_states()


def test_poll_rounds_observes_live_undecided_network():
    """Mid-run snapshots show a live (decided=False) network with k growing
    across slices — deterministically captured via the on_slice hook."""
    net = launch_network(_LIVELOCK["n"], _LIVELOCK["f"], _LIVELOCK["vals"],
                         _LIVELOCK["faulty"], backend="tpu", seed=0,
                         delivery="quorum", max_rounds=16, poll_rounds=1)
    snaps = []
    net.start(on_slice=lambda: snaps.append(net.get_state(5)))
    assert len(snaps) >= 10
    ks = [s["k"] for s in snaps]
    assert all(s["decided"] is False for s in snaps)    # live throughout
    assert ks == sorted(ks) and len(set(ks)) >= 10      # k grows
    # livelock parity: k exceeds 10 (benorconsensus.test.ts:341)
    assert net.get_state(5)["k"] > 10


@pytest.mark.slow
def test_poll_rounds_http_getstate_sees_live_network():
    """Over real sockets: /getState DURING /start returns an undecided
    snapshot with 1 <= k < final (the reference's poll loop observation).
    The start handler is slowed per-slice via the on_slice hook so the
    poller cannot miss the window."""
    import functools
    import threading
    import time

    net = launch_network(_LIVELOCK["n"], _LIVELOCK["f"], _LIVELOCK["vals"],
                         _LIVELOCK["faulty"], backend="tpu", seed=0,
                         delivery="quorum", max_rounds=16, poll_rounds=1)
    net.start = functools.partial(net.start,
                                  on_slice=lambda: time.sleep(0.05))
    with NodeHttpCluster(net, BASE + 70):
        starter = threading.Thread(
            target=lambda: _get(BASE + 70, "/start"), daemon=True)
        starter.start()
        live = []
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and starter.is_alive():
            s = json.loads(_get(BASE + 70 + 6, "/getState")[1])
            if s["decided"] is False and s["k"] is not None and s["k"] >= 1:
                live.append(s["k"])
            time.sleep(0.01)
        starter.join(timeout=20)
        assert live, "poller never saw a live mid-run snapshot"
        final = json.loads(_get(BASE + 70 + 6, "/getState")[1])
        assert final["k"] > 10                      # livelock parity
        assert min(live) < final["k"]               # k was observed growing


def test_serve_network_usable_as_context_manager():
    """serve_network() returns an already-serving cluster; entering it as a
    context manager must be a no-op start (regression: threads were started
    twice -> RuntimeError)."""
    from benor_tpu.backends.http_api import serve_network
    net = launch_network(2, 0, [1, 1], [False, False], backend="tpu")
    with serve_network(net, BASE + 50):
        assert _get(BASE + 50, "/status") == (200, "live")
    net.close()


# ---------------------------------------------------------------------------
# POST /message injection on the event-loop oracle (node.ts:43-163) —
# r4 VERDICT task 7: the last reference wire surface, served where
# injection is deterministic.
# ---------------------------------------------------------------------------

def _post(port: int, obj: dict):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/message", method="POST",
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _forged_proposal_attack(order: str, base: int):
    """Unanimous-0 network, forged all-1 proposals injected over HTTP at
    every healthy node pre-start -> the network decides 1.

    N=4 F=1: each healthy node's proposal buffer reaches the n-f=3
    threshold on forged [1,1,1] alone, so its FIRST vote is 1, and no
    healthy node ever votes 0 — count0 can never exceed F, making the
    flip stable under the quirk-8 refires as real 0-proposals arrive."""
    net = launch_network(4, 1, [0, 0, 0, 0], [False, False, False, True],
                         backend="express", seed=7, oracle_order=order)
    with NodeHttpCluster(net, base):
        for nid in range(3):                       # healthy nodes
            for _ in range(3):
                code, body = _post(base + nid, {
                    "k": 1, "x": 1, "messageType": "proposal phase"})
                assert code == 200
                assert json.loads(body) == {"message": "Message received"}
        assert _get(base, "/start")[0] == 200
        states = [json.loads(_get(base + i, "/getState")[1])
                  for i in range(4)]
    net.close()
    return states


def test_injected_forged_proposals_flip_the_outcome():
    """The injection is REAL: without it the unanimous-0 scenario decides
    0 (validity); with three forged 1-proposals per healthy node it
    decides 1 — an observable state change through the reference's POST
    /message wire surface."""
    clean = launch_network(4, 1, [0, 0, 0, 0], [False, False, False, True],
                           backend="express", seed=7)
    clean.start()
    assert all(s["decided"] and s["x"] == 0
               for s in clean.get_states() if s["decided"] is not None)

    states = _forged_proposal_attack("fifo", BASE + 80)
    healthy = [s for s in states[:3]]
    assert all(s["decided"] for s in healthy)
    assert all(s["x"] == 1 for s in healthy), healthy
    assert states[3]["killed"] and states[3]["x"] is None   # faulty: null


def test_injection_is_deterministic_under_shuffle():
    """Under oracle_order='shuffle' the injected message's delivery
    position is drawn from the SEEDED delivery stream: two identical
    injected runs are bit-identical."""
    a = _forged_proposal_attack("shuffle", BASE + 85)
    b = _forged_proposal_attack("shuffle", BASE + 90)
    assert a == b


def test_post_message_to_killed_node_gets_no_response():
    """The reference's 200 sits INSIDE the !killed guard (node.ts:44-161):
    a killed node observably never answers /message.  On the wire that is
    a closed connection with no status line."""
    net = launch_network(2, 1, [1, 1], [True, False], backend="express",
                         seed=0)
    with NodeHttpCluster(net, BASE + 95):
        # node 0 is faulty (killed from birth): no response at all
        resp = _raw_request(
            BASE + 95,
            b"POST /message HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\nContent-Length: 45\r\n\r\n"
            b'{"k":1,"x":1,"messageType":"proposal phase"}\n')
        assert resp == b""
        # the healthy node still answers
        code, _ = _post(BASE + 96, {"k": 1, "x": 1,
                                    "messageType": "proposal phase"})
        assert code == 200
    net.close()


def test_post_message_malformed_body_400():
    net = launch_network(1, 0, [1], [False], backend="express", seed=0)
    with NodeHttpCluster(net, BASE + 98):
        req = urllib.request.Request(
            f"http://127.0.0.1:{BASE + 98}/message", method="POST",
            data=b"not json")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        # a well-formed body missing a field is also a 400, not a crash
        code, _ = _post(BASE + 98, {"k": 1})
        assert code == 400
    net.close()


def test_post_injection_after_termination_targets_killed_nodes():
    """After the halt probe has killed the (all-decided) network, every
    node is killed: injection gets the reference's no-response behavior
    and the final state is untouched."""
    net = launch_network(3, 0, [1, 1, 1], [False] * 3, backend="express",
                         seed=2)
    with NodeHttpCluster(net, BASE + 99):
        _get(BASE + 99, "/start")
        before = [json.loads(_get(BASE + 99 + i, "/getState")[1])
                  for i in range(3)]
        resp = _raw_request(
            BASE + 99,
            b"POST /message HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 45\r\n\r\n"
            b'{"k":9,"x":0,"messageType":"voting phase"}\n  ')
        assert resp == b""
        after = [json.loads(_get(BASE + 99 + i, "/getState")[1])
                 for i in range(3)]
        assert before == after
    net.close()


def test_post_message_body_cap_413():
    """Bodies past the 1 MiB cap are drained and refused — buffered memory
    is bounded no matter the declared Content-Length."""
    net = launch_network(1, 0, [1], [False], backend="express", seed=0)
    with NodeHttpCluster(net, BASE + 55):
        req = urllib.request.Request(
            f"http://127.0.0.1:{BASE + 55}/message", method="POST",
            data=b"x" * ((1 << 20) + 100))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 413
    net.close()


def test_post_unknown_route_404_with_body():
    """A POST with a body to an unknown route drains and 404s (no
    buffering: only /message keeps its body)."""
    net = launch_network(1, 0, [1], [False], backend="express", seed=0)
    with NodeHttpCluster(net, BASE + 56):
        req = urllib.request.Request(
            f"http://127.0.0.1:{BASE + 56}/elsewhere", method="POST",
            data=b"y" * 4096)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 404
    net.close()


# --- incremental round-history cursor (meshscope live progress plane) ---
# GET /getRoundHistory?since_round=N and TpuNetwork.get_round_history(
# since_round=...) serve the flight recorder as a cursor feed: strictly
# newer rows only, keyed by TRUE round index.

_CURSOR_NET = dict(n=10, f=5, vals=[1, 1, 0, 0, 1, 1, 0, 0, 1, 1],
                   faulty=[True] * 5 + [False] * 5)


def _cursor_net(**overrides):
    kw = dict(backend="tpu", seed=0, delivery="quorum", max_rounds=12,
              record=True)
    kw.update(overrides)
    return launch_network(_CURSOR_NET["n"], _CURSOR_NET["f"],
                          _CURSOR_NET["vals"], _CURSOR_NET["faulty"], **kw)


def test_round_history_cursor_incremental_under_poll_rounds():
    """Polling with the cursor between slices yields exactly the new
    rows each time; their concatenation equals the full history, and a
    cursor at (or past) the end yields nothing."""
    net = _cursor_net(poll_rounds=2)
    chunks, cursor = [], None

    def poll():
        nonlocal cursor
        rows = net.get_round_history(since_round=cursor)
        if rows:
            cursor = rows[-1]["round"]
            chunks.append(rows)

    net.start(on_slice=poll)
    poll()                                   # drain the final slice
    flat = [r for chunk in chunks for r in chunk]
    assert flat == net.get_round_history()   # no gaps, no duplicates
    rounds = [r["round"] for r in flat]
    assert rounds == sorted(rounds) and len(set(rounds)) == len(rounds)
    assert len(chunks) >= 3                  # genuinely incremental
    # cursor at the end, and far past it: both empty
    assert net.get_round_history(since_round=cursor) == []
    assert net.get_round_history(since_round=10 ** 6) == []


def test_round_history_cursor_mid_resume_gap():
    """A fresh-buffer resume leaves an unwritten gap before the re-entry
    round; a cursor INSIDE the gap must return exactly the post-gap rows
    (rows key on their true round index, so the cursor stays stable
    across the gap)."""
    import jax

    from benor_tpu.config import SimConfig
    from benor_tpu.sim import resume_consensus, run_consensus
    from benor_tpu.state import FaultSpec, init_state
    from benor_tpu.utils.metrics import round_history_rows

    cfg = SimConfig(n_nodes=_CURSOR_NET["n"], n_faulty=_CURSOR_NET["f"],
                    trials=1, delivery="quorum", max_rounds=12,
                    record=True, seed=0)
    faults = FaultSpec.from_faulty_list(cfg, _CURSOR_NET["faulty"])
    state = init_state(cfg, _CURSOR_NET["vals"], faults)
    key = jax.random.key(cfg.seed)
    _, mid, _ = run_consensus(cfg.replace(max_rounds=5), state, faults,
                              key)
    # resume at round 6 with a FRESH recorder: rows 1..5 stay unwritten
    out = resume_consensus(cfg, mid, faults, key, from_round=6)
    rec = out[2]
    full = round_history_rows(rec)
    written = [r["round"] for r in full]
    assert 0 in written and 6 in written and 3 not in written
    # cursor inside the gap: exactly the post-gap rows
    post_gap = round_history_rows(rec, since_round=3)
    assert [r["round"] for r in post_gap] == [r for r in written if r > 3]
    # cursor at the snapshot row: everything after row 0
    assert [r["round"] for r in round_history_rows(rec, since_round=0)] \
        == [r for r in written if r > 0]


def test_round_history_http_route_cursor_and_errors():
    """The wire surface: GET /getRoundHistory serves rows + cursor,
    since_round pages incrementally, a past-end cursor yields an empty
    page, malformed cursors 400, record-off networks 400, and the
    event-loop oracle (no device recorder) 405."""
    net = _cursor_net(poll_rounds=0)
    with NodeHttpCluster(net, BASE + 80):
        _get(BASE + 80, "/start")
        code, body = _get(BASE + 80, "/getRoundHistory")
        assert code == 200
        doc = json.loads(body)
        rows, cursor = doc["rows"], doc["cursor"]
        assert rows and cursor == rows[-1]["round"]
        assert rows == net.get_round_history()
        # incremental page: only rows after the mid cursor
        mid = rows[len(rows) // 2]["round"]
        code, body = _get(BASE + 80,
                          f"/getRoundHistory?since_round={mid}")
        assert code == 200
        page = json.loads(body)
        assert [r["round"] for r in page["rows"]] == \
            [r["round"] for r in rows if r["round"] > mid]
        # cursor past the end: empty page, cursor echoed back
        code, body = _get(BASE + 80,
                          f"/getRoundHistory?since_round={cursor + 99}")
        assert code == 200
        empty = json.loads(body)
        assert empty["rows"] == [] and empty["cursor"] == cursor + 99
        # malformed cursor
        code, _ = _get(BASE + 80, "/getRoundHistory?since_round=nope")
        assert code == 400
    net.close()

    off = _cursor_net(record=False)
    with NodeHttpCluster(off, BASE + 81):
        code, body = _get(BASE + 81, "/getRoundHistory")
        assert code == 400 and "record=True" in body
    off.close()

    oracle = launch_network(2, 0, [1, 1], [False, False],
                            backend="express", seed=0)
    with NodeHttpCluster(oracle, BASE + 82):
        code, _ = _get(BASE + 82, "/getRoundHistory")
        assert code == 405
    oracle.close()
