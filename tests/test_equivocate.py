"""fault_model='equivocate': two-faced Byzantine senders.

The reference has no Byzantine behavior at all (SURVEY §2.1 quirk 7 —
faulty means crash-from-birth, node.ts:21-26); 'byzantine' (bit-flip
broadcast) and 'equivocate' (per-receiver values) are framework extensions
(SURVEY N5).  Equivocation is the strictly stronger classical model: under
the count-controlling adversary it reproduces the N > 3F resilience bound
exactly (Pease-Shostak-Lamport; Ben-Or section 4) — the sharpest
correctness anchor available for the fault plane.

Covers: the 3F threshold on BOTH compute paths, dense-vs-histogram
statistical parity of the equivocate sampler, structural count invariants,
mesh-shape bit-identity, and the config guard.
"""

import numpy as np
import pytest
import scipy.stats as st

import jax
import jax.numpy as jnp

from benor_tpu.config import SimConfig
from benor_tpu.ops import rng, tally
from benor_tpu.parallel import make_mesh, run_consensus_sharded
from benor_tpu.sim import run_consensus, simulate
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import balanced_inputs


def _cfg(n, f, path, scheduler="uniform", coin="private", **kw):
    return SimConfig(n_nodes=n, n_faulty=f, delivery="quorum",
                     scheduler=scheduler, coin_mode=coin, path=path,
                     fault_model="equivocate", **kw)


def _faulty(n, f):
    m = np.zeros(n, bool)
    m[:f] = True
    return m


# ---------------------------------------------------------------------------
# The N > 3F Byzantine resilience bound, reproduced sharply on both paths:
# at F >= N/3 the count-controlling adversary (which chooses equivocators'
# per-receiver values) ties every tally forever — even the common coin
# cannot terminate, matching the impossibility bound; one node fewer of
# adversary share (F < N/3) and the unified honest class count m - F > F
# forces a decision within a couple of coin rounds.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("path", ["dense", "histogram"])
@pytest.mark.parametrize("n,f,decides", [
    (15, 5, False), (16, 5, True),       # 3F = N vs 3F = N - 1
    (30, 10, False), (31, 10, True),
])
def test_3f_resilience_threshold(path, n, f, decides):
    cfg = _cfg(n, f, path, scheduler="adversarial", coin="common",
               trials=8, max_rounds=20, seed=2)
    rounds, final, faults = simulate(cfg, balanced_inputs(8, n),
                                     _faulty(n, f))
    dec = np.asarray(final.decided)[:, f:]
    if decides:
        assert dec.all()
        assert int(rounds) < cfg.max_rounds
        # agreement still holds among honest nodes
        x = np.asarray(final.x)[:, f:]
        assert (x == x[:, :1]).all()
    else:
        assert not dec.any()
        assert int(rounds) == cfg.max_rounds


# ---------------------------------------------------------------------------
# Dense (per-edge fair bits) vs histogram (mixed-population sampler)
# statistical parity: per-trial mean rounds-to-decide distributions must
# agree (the same harness doctrine as tests/stat_harness.py — per-trial
# aggregates, balanced inputs, F > N/3 for multi-round dynamics).
# ---------------------------------------------------------------------------
def _equiv_trial_mean_k(n, f, trials, seed, path):
    cfg = _cfg(n, f, path, trials=trials, max_rounds=64, seed=seed)
    state = init_state(cfg, balanced_inputs(trials, n),
                       FaultSpec.from_faulty_list(cfg, _faulty(n, f)))
    faults = FaultSpec.from_faulty_list(cfg, _faulty(n, f))
    _, final = run_consensus(cfg, state, faults, jax.random.key(seed))
    dec = np.asarray(final.decided)[:, f:]
    k = np.asarray(final.k)[:, f:]
    assert dec.any(axis=1).all(), "a trial failed to converge"
    return (k * dec).sum(axis=1) / dec.sum(axis=1)


@pytest.mark.slow
def test_dense_vs_histogram_parity():
    n, f, trials = 96, 36, 256
    a = _equiv_trial_mean_k(n, f, trials, seed=11, path="dense")
    b = _equiv_trial_mean_k(n, f, trials, seed=12, path="histogram")
    _, p = st.ks_2samp(a, b)
    assert p > 0.01, (p, a.mean(), b.mean())
    # seed control: two dense runs must look at least as similar
    c = _equiv_trial_mean_k(n, f, trials, seed=13, path="dense")
    _, p_ctrl = st.ks_2samp(a, c)
    assert p_ctrl > 0.01, p_ctrl


# ---------------------------------------------------------------------------
# Structural invariants of the tallied counts.
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_dense_counts_sum_to_quorum_and_exclude_equivocator_slots():
    n, f, trials = 24, 6, 16
    cfg = _cfg(n, f, "dense", trials=trials, seed=5)
    faults = FaultSpec.from_faulty_list(cfg, _faulty(n, f))
    x = jnp.asarray(balanced_inputs(trials, n))
    alive = jnp.ones((trials, n), bool)
    equiv = faults.faulty
    counts = tally.receiver_counts(cfg, jax.random.key(0), jnp.int32(1),
                                   rng.PHASE_PROPOSAL, x, alive,
                                   equiv=equiv)
    c = np.asarray(counts)
    assert (c.sum(-1) == cfg.quorum).all()
    # equivocators contribute only 0/1 bits, never "?" — with balanced
    # honest inputs and no "?" sent, the "?" class must be empty
    assert (c[..., 2] == 0).all()
    # the delivered-bit stream is phase-keyed: the vote phase must differ
    counts2 = tally.receiver_counts(cfg, jax.random.key(0), jnp.int32(1),
                                    rng.PHASE_VOTE, x, alive, equiv=equiv)
    assert not np.array_equal(c, np.asarray(counts2))


@pytest.mark.slow
def test_all_delivery_tallies_every_sender():
    n, f, trials = 20, 5, 8
    cfg = SimConfig(n_nodes=n, n_faulty=f, delivery="all", trials=trials,
                    fault_model="equivocate", seed=7)
    faults = FaultSpec.from_faulty_list(cfg, _faulty(n, f))
    x = jnp.asarray(balanced_inputs(trials, n))
    alive = jnp.ones((trials, n), bool)
    counts = tally.receiver_counts(cfg, jax.random.key(0), jnp.int32(1),
                                   rng.PHASE_PROPOSAL, x, alive,
                                   equiv=faults.faulty)
    c = np.asarray(counts)
    assert (c.sum(-1) == n).all()          # every live sender tallied
    # equivocator bits are fair: pooled 1-share within a couple of sigma
    ones_from_equiv = c[..., 1] - np.asarray(
        ((x == 1) & ~np.asarray(faults.faulty)).sum(-1))[:, None]
    frac = ones_from_equiv.mean() / f
    assert abs(frac - 0.5) < 4 * np.sqrt(0.25 / (f * trials * n))


@pytest.mark.parametrize("path", ["dense", "histogram"])
@pytest.mark.slow
def test_validity_holds_under_equivocation(path):
    """VALIDITY survives equivocation at ANY F under the uniform scheduler:
    with unanimous honest inputs v, the ¬v count comes only from delivered
    equivocator bits, which never exceed h_b <= F — so count(¬v) > F is
    unsatisfiable and no honest lane can decide the wrong value.  (The
    plurality-adopt branch can still be noise-steered, so the guarantee is
    about DECIDED values, which is exactly validity.)"""
    n, f, trials = 60, 25, 32                     # F > N/3, still valid
    cfg = _cfg(n, f, path, trials=trials, max_rounds=64, seed=8)
    rounds, final, faults = simulate(
        cfg, np.ones((trials, n), np.int8), _faulty(n, f))
    dec = np.asarray(final.decided)[:, f:]
    x = np.asarray(final.x)[:, f:]
    assert ((x == 1) | ~dec).all(), "an honest lane decided the wrong value"
    # termination too: equivocator noise can delay lanes near the F > N/3
    # threshold a few rounds, but never livelocks the uniform scheduler
    assert dec.all() and int(rounds) < cfg.max_rounds


@pytest.mark.slow
def test_all_delivery_small_f_split_is_exact():
    """With trial-global n_equiv the 'all'-delivery class split uses the
    exact shared-CDF binomial table: at F=2 the per-receiver byz-ones
    distribution must be exactly (1/4, 1/2, 1/4), which the rounded normal
    quantile gets measurably wrong (~0.24/0.52/0.24)."""
    n, f, trials = 1024, 2, 64
    cfg = SimConfig(n_nodes=n, n_faulty=f, delivery="all", trials=trials,
                    fault_model="equivocate", seed=3)
    faults = FaultSpec.first_f(cfg)
    x = jnp.asarray(balanced_inputs(trials, n))
    alive = jnp.ones((trials, n), bool)
    counts = tally.receiver_counts(cfg, jax.random.key(0), jnp.int32(1),
                                   rng.PHASE_PROPOSAL, x, alive,
                                   equiv=faults.faulty)
    honest_ones = np.asarray(
        ((x == 1) & ~np.asarray(faults.faulty)).sum(-1))[:, None]
    b1 = np.asarray(counts)[..., 1] - honest_ones          # in {0, 1, 2}
    freq = np.bincount(b1.ravel(), minlength=3) / b1.size
    # ~65k iid samples: sigma(p=1/4) ~ 0.0017 — 0.008 is ~4.5 sigma, and
    # the normal-approx bias (~0.015 on the extremes) fails it
    np.testing.assert_allclose(freq, [0.25, 0.5, 0.25], atol=0.008)


# ---------------------------------------------------------------------------
# Mesh-shape bit-identity: the equivocate plane (gathered equiv mask on the
# dense path, psum'd n_equiv + global-id keyed draws on the histogram path)
# must not depend on how lanes are sharded.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("path", ["dense", "histogram"])
@pytest.mark.slow
def test_sharded_bit_identity(path):
    n, f, trials = 32, 8, 4
    cfg = _cfg(n, f, path, trials=trials, max_rounds=16, seed=9)
    faults = FaultSpec.from_faulty_list(cfg, _faulty(n, f))
    state = init_state(cfg, balanced_inputs(trials, n), faults)
    key = jax.random.key(cfg.seed)
    r1, f1 = run_consensus(cfg, state, faults, key)
    for shape in ((1, 8), (2, 4), (4, 2)):
        mesh = make_mesh(*shape)
        r2, f2 = run_consensus_sharded(cfg, state, faults, key, mesh)
        assert int(r2) == int(r1), shape
        np.testing.assert_array_equal(np.asarray(f2.x), np.asarray(f1.x),
                                      err_msg=str(shape))
        np.testing.assert_array_equal(np.asarray(f2.decided),
                                      np.asarray(f1.decided),
                                      err_msg=str(shape))


def test_biased_scheduler_rejected():
    with pytest.raises(ValueError, match="equivocate"):
        SimConfig(n_nodes=10, n_faulty=2, scheduler="biased",
                  fault_model="equivocate")


@pytest.mark.parametrize("backend", ["express", "native"])
@pytest.mark.parametrize("overrides,msg", [
    ({"fault_model": "byzantine"}, "fault_model='crash'"),
    ({"fault_model": "equivocate"}, "fault_model='crash'"),
    ({"coin_mode": "common"}, "coin_mode='private'"),
    ({"coin_mode": "weak_common", "coin_eps": 0.5}, "coin_mode='private'"),
    ({"rule": "textbook"}, "rule='reference'"),
    ({"scheduler": "adversarial"}, "scheduler='uniform'"),
    ({"scheduler": "biased", "adversary_strength": 1.0},
     "scheduler='uniform'"),
])
def test_oracle_backends_reject_extension_knobs(backend, overrides, msg):
    """The event-loop oracles replicate the reference exactly (crash
    faults, private coins, plurality-adopt) — asking them for a framework
    extension must fail loudly, not silently fall back (api.py guard)."""
    from benor_tpu.api import launch_network
    with pytest.raises(ValueError, match=msg):
        launch_network(6, 2, [1] * 6, [True] * 2 + [False] * 4,
                       backend=backend, **overrides)
