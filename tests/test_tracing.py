"""Debug tracing hooks: per-round callbacks out of the compiled loop."""

import numpy as np
import pytest

import jax

from benor_tpu.config import SimConfig
from benor_tpu.sim import simulate
from benor_tpu.utils import tracing


@pytest.mark.slow
def test_round_events_emitted_in_order():
    rows = []
    sink = lambda r, d, k: rows.append((r, d, k))
    tracing.add_sink(sink)
    try:
        cfg = SimConfig(n_nodes=30, n_faulty=8, trials=16, max_rounds=32,
                        delivery="quorum", scheduler="uniform", seed=9,
                        debug=True)
        rounds, final, _ = simulate(
            cfg, [1] * 22 + [0] * 8, [True] * 8 + [False] * 22)
        jax.effects_barrier()  # flush pending debug callbacks
    finally:
        tracing.remove_sink(sink)
    assert len(rows) == int(rounds)
    # monotone round counter; decided count non-decreasing; final row matches
    ks = [r for r, _, _ in rows]
    assert ks == sorted(ks)
    decs = [d for _, d, _ in rows]
    assert decs == sorted(decs)
    assert decs[-1] == int(np.asarray(final.decided).sum())


@pytest.mark.slow
def test_debug_off_emits_nothing():
    rows = []
    sink = lambda *a: rows.append(a)
    tracing.add_sink(sink)
    try:
        cfg = SimConfig(n_nodes=10, n_faulty=2, trials=4, seed=9,
                        delivery="quorum", scheduler="uniform")
        simulate(cfg, [1] * 10, [True] * 2 + [False] * 8)
        jax.effects_barrier()
    finally:
        tracing.remove_sink(sink)
    assert rows == []


@pytest.mark.slow
def test_round_events_under_sharded_runner():
    """cfg.debug must not be silently dropped by the shard_map runner
    (round-2 VERDICT weak #5): one event per round, network-global counts,
    matching the single-device trace (which is bit-identical by contract)."""
    from benor_tpu.parallel import make_mesh, run_consensus_sharded
    from benor_tpu.sim import run_consensus
    from benor_tpu.state import FaultSpec, init_state

    cfg = SimConfig(n_nodes=16, n_faulty=4, trials=8, max_rounds=32,
                    delivery="quorum", scheduler="uniform", seed=9,
                    debug=True, path="histogram")
    faults = FaultSpec.from_faulty_list(
        cfg, [True] * 4 + [False] * 12)
    state = init_state(cfg, [i % 2 for i in range(16)], faults)
    key = jax.random.key(cfg.seed)

    single_rows, shard_rows = [], []
    sink = lambda r, d, k: single_rows.append((r, d, k))
    tracing.add_sink(sink)
    try:
        rounds1, _ = run_consensus(cfg, state, faults, key)
        jax.effects_barrier()
    finally:
        tracing.remove_sink(sink)

    sink = lambda r, d, k: shard_rows.append((r, d, k))
    tracing.add_sink(sink)
    try:
        rounds2, _ = run_consensus_sharded(cfg, state, faults, key,
                                           make_mesh(2, 4))
        jax.effects_barrier()
    finally:
        tracing.remove_sink(sink)

    assert int(rounds1) == int(rounds2)
    assert len(shard_rows) == int(rounds2)          # exactly one per round
    # unordered emission: compare as sets of (round, decided, killed)
    assert sorted(shard_rows) == sorted(single_rows)


def test_round_events_fast_in_order():
    """Tier-1 (non-slow) coverage for the debug-callback path: the only
    other emission tests are @slow, so a regression in emit_round_event /
    the _run_body wiring used to reach the fast lane unseen.  Tiny
    network, ordered single-device emission, counts match the final
    state."""
    rows = []
    sink = lambda r, d, k: rows.append((r, d, k))
    tracing.add_sink(sink)
    try:
        cfg = SimConfig(n_nodes=8, n_faulty=2, trials=2, max_rounds=12,
                        delivery="quorum", scheduler="uniform", seed=5,
                        debug=True)
        rounds, final, _ = simulate(
            cfg, [1] * 6 + [0] * 2, [True] * 2 + [False] * 6)
        jax.effects_barrier()
    finally:
        tracing.remove_sink(sink)
    assert len(rows) == int(rounds) >= 1
    assert [r for r, _, _ in rows] == sorted(r for r, _, _ in rows)
    assert rows[-1][1] == int(np.asarray(final.decided).sum())
    assert all(k == 2 * 2 for _, _, k in rows)      # killed count, all trials


def test_debug_demotion_warns_once():
    """Satellite: a pallas-eligible config with debug=True silently loses
    the fused regime — that demotion now warns, once per process."""
    import warnings
    from benor_tpu import sim
    from benor_tpu.ops.tally import pallas_round_active
    from benor_tpu.state import FaultSpec, init_state
    from benor_tpu.sweep import balanced_inputs

    cfg = SimConfig(n_nodes=16, n_faulty=2, trials=2, max_rounds=2,
                    delivery="quorum", scheduler="adversarial",
                    coin_mode="common", path="histogram",
                    use_pallas_round=True, debug=True, seed=2)
    assert pallas_round_active(cfg)
    faults = FaultSpec.none(2, 16)
    state = init_state(cfg, balanced_inputs(2, 16), faults)
    quiet = lambda *a: None
    tracing.add_sink(quiet)                 # keep the default sink quiet
    old = sim._debug_demotion_warned
    sim._debug_demotion_warned = False
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            sim.run_consensus(cfg, state, faults, jax.random.key(2))
            jax.effects_barrier()
        demote = [x for x in w if "demotes" in str(x.message)]
        assert len(demote) == 1
        assert "record=True" in str(demote[0].message)
        # one-time: a second run stays quiet
        with warnings.catch_warnings(record=True) as w2:
            warnings.simplefilter("always")
            sim.run_consensus(cfg.replace(seed=3), state, faults,
                              jax.random.key(3))
            jax.effects_barrier()   # flush callbacks while `quiet` holds
        assert not [x for x in w2 if "demotes" in str(x.message)]
    finally:
        sim._debug_demotion_warned = old
        tracing.remove_sink(quiet)


def test_timed_context(capsys):
    msgs = []
    with tracing.timed("unit", sink=msgs.append):
        pass
    assert len(msgs) == 1 and "unit" in msgs[0]
