"""Debug tracing hooks: per-round callbacks out of the compiled loop."""

import numpy as np

import jax

from benor_tpu.config import SimConfig
from benor_tpu.sim import simulate
from benor_tpu.utils import tracing


def test_round_events_emitted_in_order():
    rows = []
    sink = lambda r, d, k: rows.append((r, d, k))
    tracing.add_sink(sink)
    try:
        cfg = SimConfig(n_nodes=30, n_faulty=8, trials=16, max_rounds=32,
                        delivery="quorum", scheduler="uniform", seed=9,
                        debug=True)
        rounds, final, _ = simulate(
            cfg, [1] * 22 + [0] * 8, [True] * 8 + [False] * 22)
        jax.effects_barrier()  # flush pending debug callbacks
    finally:
        tracing.remove_sink(sink)
    assert len(rows) == int(rounds)
    # monotone round counter; decided count non-decreasing; final row matches
    ks = [r for r, _, _ in rows]
    assert ks == sorted(ks)
    decs = [d for _, d, _ in rows]
    assert decs == sorted(decs)
    assert decs[-1] == int(np.asarray(final.decided).sum())


def test_debug_off_emits_nothing():
    rows = []
    sink = lambda *a: rows.append(a)
    tracing.add_sink(sink)
    try:
        cfg = SimConfig(n_nodes=10, n_faulty=2, trials=4, seed=9,
                        delivery="quorum", scheduler="uniform")
        simulate(cfg, [1] * 10, [True] * 2 + [False] * 8)
        jax.effects_barrier()
    finally:
        tracing.remove_sink(sink)
    assert rows == []


def test_timed_context(capsys):
    msgs = []
    with tracing.timed("unit", sink=msgs.append):
        pass
    assert len(msgs) == 1 and "unit" in msgs[0]
