"""kernelscope (benor_tpu/kernelscope) — tile-level pallas observability.

Four layers, mirroring the instrument's contract:

  * HOUSE RULE: ``kernel_telemetry=False`` (the default) is bit-identical
    to pre-PR behavior in results AND backend-compile counts on every
    pallas regime — the fused one-pass kernel, the two-kernel plane
    pipeline, sliced/resume, and the batched sweep's static pallas
    bucket; telemetry ON changes no science bit either.
  * ORACLE: the pad-lane waste / active-lane / hop counters are exact
    against a NumPy recomputation from the geometry (they are
    deterministic integers, not samples).
  * MANIFEST: the capture's ``kind: kernel_manifest`` is schema-valid,
    its cross-field recomputations (pad waste, predicted bytes, byte
    ratio, per-tile sums) reject a tamper matrix, and the predicted-byte
    arithmetic in tools/check_metrics_schema.py stays column-for-column
    equal to perfscope/roofline.stage_traffic.
  * GATE: tools/check_kernel_regression.py exits 0 on the self-gate,
    2 on injected pad-waste / byte-ratio / counter regressions, 3 on a
    scale mismatch.

CPU runs the pallas kernels in interpret mode (the only mode XLA:CPU
has); the manifest records ``interpret`` so compiled-mode captures are
distinguishable, and the counter/byte logic under test is mode-
independent (the same kernel python runs either way).
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.ops import pallas_round as pr
from benor_tpu.ops import sampling, tally
from benor_tpu.sim import (run_consensus, run_consensus_slice,
                           start_state, warn_debug_demotes_pallas,
                           warn_structured_demotes_pallas)
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import balanced_inputs
from benor_tpu.utils.compile_counter import count_backend_compiles
from benor_tpu.utils.metrics import REGISTRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
TILE = 512  # pallas_hist.TILE_N — the lane tile every oracle reckons in


def _cms():
    spec = importlib.util.spec_from_file_location(
        "_cms_for_kernelscope",
        os.path.join(TOOLS, "check_metrics_schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _science(out):
    r, fin = out[0], out[1]
    return (int(r), np.asarray(fin.x), np.asarray(fin.decided),
            np.asarray(fin.k), np.asarray(fin.killed))


def _assert_bit_equal(a, b):
    assert a[0] == b[0]
    for x, y, name in zip(a[1:], b[1:], ("x", "decided", "k", "killed")):
        np.testing.assert_array_equal(x, y, err_msg=name)


def _one_pass_cfg(n, t, seed, **kw):
    kw.setdefault("n_faulty", 2 * n // 5)
    kw.setdefault("max_rounds", 8)
    return SimConfig(n_nodes=n, trials=t, delivery="quorum",
                     scheduler="uniform", path="histogram",
                     use_pallas_hist=True, use_pallas_round=True,
                     seed=seed, **kw)


def _two_kernel_cfg(n, t, seed, **kw):
    kw.setdefault("n_faulty", n // 4 + (n - n // 4) % 2)
    kw.setdefault("max_rounds", 8)
    return SimConfig(n_nodes=n, trials=t, delivery="quorum",
                     scheduler="adversarial", coin_mode="common",
                     path="histogram", use_pallas_round=True, seed=seed,
                     **kw)


@pytest.fixture
def cf_regime(monkeypatch):
    """Lower the exact-table bound so the CF regime (and with it the
    one-pass kernel gate) engages at test scale — the established
    CPU-smoke trick (tests/test_packed_state.py)."""
    monkeypatch.setattr(sampling, "EXACT_TABLE_MAX", 4)


def _inputs(cfg):
    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes),
                       faults)
    return state, faults, jax.random.key(cfg.seed)


# --------------------------------------------------------------------------
# house rule: off == pre-PR, on == off in science bits, compile parity
# --------------------------------------------------------------------------


def test_one_pass_off_on_bit_identical_and_compile_parity(cf_regime):
    n, t = 64, 4
    counts = []
    outs = []
    for telem, seed in ((False, 31), (True, 31)):
        cfg = _one_pass_cfg(n, t, seed=seed, kernel_telemetry=telem)
        assert tally.pallas_round_active(cfg)
        assert pr.fused_one_pass_eligible(cfg, t, n)
        state, faults, key = _inputs(cfg)
        with count_backend_compiles() as cc:
            out = run_consensus(cfg, state, faults, key)
            int(out[0])
        counts.append(cc.count)
        outs.append(_science(out))
        if telem:
            assert len(out) == 3, "telemetry accumulator must ride last"
        else:
            assert len(out) == 2, "telemetry off must not change arity"
    _assert_bit_equal(outs[0], outs[1])
    # off and on are DIFFERENT executables (extra output) but must cost
    # the same NUMBER of backend compiles — one each
    assert counts[0] == counts[1] == 1, counts


def test_two_kernel_off_on_bit_identical_and_compile_parity():
    n, t = 600, 4              # np_total = 1024 -> 2 tiles
    counts = []
    outs = []
    for telem, seed in ((False, 7), (True, 7)):
        cfg = _two_kernel_cfg(n, t, seed=seed, kernel_telemetry=telem)
        assert tally.pallas_round_active(cfg)
        assert tally.pallas_round_counts_mode(cfg) == "delivered"
        assert not pr.fused_one_pass_eligible(cfg, t, n)
        state, faults, key = _inputs(cfg)
        with count_backend_compiles() as cc:
            out = run_consensus(cfg, state, faults, key)
            int(out[0])
        counts.append(cc.count)
        outs.append(_science(out))
    _assert_bit_equal(outs[0], outs[1])
    assert counts[0] == counts[1] == 1, counts


def test_telemetry_rides_after_recorder_and_witness(cf_regime):
    """Tail order contract: recorder, witness, telemetry — positional
    consumers that predate the flag keep working."""
    n, t = 64, 4
    cfg = _one_pass_cfg(n, t, seed=5, kernel_telemetry=True,
                        record=True, witness_trials=(0,),
                        witness_nodes=2)
    state, faults, key = _inputs(cfg)
    out = run_consensus(cfg, state, faults, key)
    assert len(out) == 5
    rec, wit, telem = (np.asarray(out[2]), np.asarray(out[3]),
                       np.asarray(out[4]))
    assert rec.shape == (cfg.max_rounds + 1, 7)
    assert wit.shape == (cfg.max_rounds + 1, 1, 2, 9)
    assert telem.shape == (2, 1, pr.TELEM_WIDTH)
    # and the science bits still match a bare run
    bare = run_consensus(_one_pass_cfg(n, t, seed=5), state, faults, key)
    _assert_bit_equal(_science(bare), _science(out))


# --------------------------------------------------------------------------
# oracle: pad-lane waste and friends, exact vs NumPy recomputation
# --------------------------------------------------------------------------


def test_pad_waste_exact_oracle_two_kernel():
    n, t = 600, 4              # tiles: [512 real | 88 real + 424 pad]
    cfg = _two_kernel_cfg(n, t, seed=7, kernel_telemetry=True)
    state, faults, key = _inputs(cfg)
    out = run_consensus(cfg, state, faults, key)
    rounds = int(out[0])
    telem = np.asarray(out[2])
    assert rounds > 0
    cols = {c: i for i, c in enumerate(pr.TELEM_COLUMNS)}
    np_total = n + (-n) % TILE
    tiles = np_total // TILE
    assert telem.shape == (2, tiles, pr.TELEM_WIDTH)
    for stage in range(2):
        for ti in range(tiles):
            real = min(TILE, max(0, n - ti * TILE))
            exp_active = rounds * t * real
            exp_pad = rounds * t * (TILE - real)
            assert telem[stage, ti, cols["active_lanes"]] == exp_active
            assert telem[stage, ti, cols["pad_lanes"]] == exp_pad
    # delivered counts run NO sampler; hops: proposal reads (1), vote
    # reads+writes (2) — per tile, per trial, per round
    assert (telem[:, :, cols["sampler_draws"]] == 0).all()
    assert (telem[0, :, cols["plane_hops"]] == rounds * t).all()
    assert (telem[1, :, cols["plane_hops"]] == 2 * rounds * t).all()


def test_counters_exact_oracle_one_pass(cf_regime):
    n, t = 100, 4              # np_total = 512, pad = 412
    cfg = _one_pass_cfg(n, t, seed=3, kernel_telemetry=True)
    state, faults, key = _inputs(cfg)
    out = run_consensus(cfg, state, faults, key)
    rounds = int(out[0])
    telem = np.asarray(out[2])
    assert rounds > 0 and telem.shape == (2, 1, pr.TELEM_WIDTH)
    cols = {c: i for i, c in enumerate(pr.TELEM_COLUMNS)}
    np_total = n + (-n) % TILE
    for stage in range(2):
        assert telem[stage, 0, cols["active_lanes"]] == rounds * t * n
        assert telem[stage, 0, cols["pad_lanes"]] == \
            rounds * t * (np_total - n)
        # the CF regime samples: every lane of the padded tile is
        # touched by the vectorized sampler
        assert telem[stage, 0, cols["sampler_draws"]] == \
            rounds * t * np_total
        # one-pass: ONE plane hop per stage (read, then write)
        assert telem[stage, 0, cols["plane_hops"]] == rounds * t
    # no crashes in FaultSpec.none + quorum == every-trial-pass: the
    # vote stage's quorum_passes count the live non-frozen lanes, which
    # never exceed the active lanes
    assert 0 < telem[1, 0, cols["quorum_passes"]] <= rounds * t * n
    assert telem[0, 0, cols["quorum_passes"]] == 0
    assert telem[0, 0, cols["coin_draws"]] == 0


# --------------------------------------------------------------------------
# sliced / resume and the batched static bucket
# --------------------------------------------------------------------------


def test_sliced_telemetry_adds_up_to_one_shot(cf_regime):
    n, t = 96, 8
    cfg = _one_pass_cfg(n, t, seed=2, n_faulty=40, max_rounds=16,
                        kernel_telemetry=True)
    state, faults, key = _inputs(cfg)
    one_shot = run_consensus(cfg, state, faults, key)
    assert int(one_shot[0]) > 1, "needs multi-round to pin slicing"
    telem_ref = np.asarray(one_shot[2])

    st, r = start_state(cfg, state), 1
    acc = np.zeros_like(telem_ref)
    while True:
        out = run_consensus_slice(cfg, st, faults, key, jnp.int32(r),
                                  jnp.int32(r + 3))
        rn, st = int(out[0]), out[1]
        acc += np.asarray(out[2])
        done = bool(np.asarray((st.decided | st.killed).all()))
        if rn == r or rn > cfg.max_rounds or done:
            break
        r = rn
    np.testing.assert_array_equal(acc, telem_ref)
    # and the sliced science bits equal the one-shot's
    _assert_bit_equal(_science(one_shot), _science((jnp.int32(rn - 1),
                                                    st)))


def test_batched_static_bucket_off_on_bit_identical(cf_regime):
    from benor_tpu.sweep import run_points_batched

    n, t = 64, 4
    curves = []
    compiles = []
    for telem in (False, True):
        base = _one_pass_cfg(n, t, seed=11, kernel_telemetry=telem)
        cb = run_points_batched(base, [base, base.replace(n_faulty=20)])
        curves.append(cb)
        compiles.append(cb.compile_count)
    assert compiles[0] == compiles[1], compiles
    for a, b in zip(curves[0].points, curves[1].points):
        assert a.rounds_executed == b.rounds_executed
        assert a.decided_frac == b.decided_frac
        assert a.mean_k == b.mean_k
        assert a.ones_frac == b.ones_frac
        assert a.disagree_frac == b.disagree_frac
        np.testing.assert_array_equal(a.k_hist, b.k_hist)


# --------------------------------------------------------------------------
# traffic model: roofline.stage_traffic == the checker's replay
# --------------------------------------------------------------------------


def test_traffic_model_matches_checker_replay(cf_regime):
    from benor_tpu.perfscope.roofline import kernel_geometry, stage_traffic

    cms = _cms()
    for cfg in (_one_pass_cfg(64, 4, seed=0),
                _two_kernel_cfg(600, 4, seed=0),
                _two_kernel_cfg(2048, 2, seed=0)):
        geom = kernel_geometry(cfg)
        assert stage_traffic(geom) == cms._predicted_stage_bytes(geom), \
            f"traffic-model drift for {cfg.scheduler} at {cfg.n_nodes}"


# --------------------------------------------------------------------------
# capture -> manifest -> schema checker -> gate
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def manifest():
    from benor_tpu.kernelscope import capture_kernels

    return capture_kernels()


def test_capture_manifest_schema_valid(manifest):
    errs = _cms().check_kernel_manifest(manifest)
    assert errs == []
    ks = manifest["kernels"]
    assert set(ks) == {"fused_one_pass", "two_kernel"}
    assert ks["fused_one_pass"]["dispatch"] == "one_pass"
    assert ks["two_kernel"]["dispatch"] == "two_kernel"
    # the measured hop counts match the dispatch story: 2 vs 3
    assert ks["fused_one_pass"]["plane_hops_per_round"] == 2.0
    assert ks["two_kernel"]["plane_hops_per_round"] == 3.0
    for k in ks.values():
        assert k["bit_equal_off_on"] is True
        assert k["rounds_executed"] > 0
        if k["measured_bytes_per_round"]:
            assert k["byte_ratio"] is not None
    fvx = manifest["fused_vs_xla"]
    assert fvx["bit_equal"] is True
    assert abs(sum(fvx["stage_attribution"].values()) - 1.0) < 1e-3


@pytest.mark.parametrize("tamper", [
    ("pad_waste", lambda m: m["kernels"]["two_kernel"].update(
        pad_waste_frac=0.01)),
    ("per_tile_sum", lambda m: m["kernels"]["two_kernel"]["stages"]
        ["vote"]["counters"].update(coin_draws=1)),
    ("byte_ratio", lambda m: m["kernels"]["fused_one_pass"].update(
        byte_ratio=42.0)),
    ("predicted", lambda m: m["kernels"]["fused_one_pass"]
        ["predicted_bytes_per_round"].update(total=1)),
    ("stage_names", lambda m: m["kernels"]["two_kernel"]["stages"].update(
        rogue={"counters": {}, "per_tile": []})),
    ("dispatch", lambda m: m["kernels"]["fused_one_pass"].update(
        dispatch="two_kernel")),
    ("attribution", lambda m: m["fused_vs_xla"]["stage_attribution"]
        .update(proposal=0.9, vote=0.9)),
    ("gap", lambda m: m["fused_vs_xla"].update(gap_bytes=123456.0)),
    ("counter_keys", lambda m: m["kernels"]["two_kernel"]["stages"]
        ["proposal"]["counters"].pop("pad_lanes")),
    # a stage block missing its whole counters dict must come back as
    # an error LIST, never a KeyError out of the checker itself
    ("missing_counters", lambda m: m["kernels"]["two_kernel"]["stages"]
        ["proposal"].pop("counters")),
])
def test_manifest_tamper_matrix(manifest, tamper):
    name, mutate = tamper
    doc = json.loads(json.dumps(manifest))
    mutate(doc)
    errs = _cms().check_kernel_manifest(doc)
    assert errs, f"tamper {name!r} survived the checker"


def test_gate_exit_codes(manifest, tmp_path):
    from benor_tpu.kernelscope import save_kernel_manifest

    base = tmp_path / "KERNEL_BASELINE.json"
    save_kernel_manifest(str(base), manifest)
    tool = os.path.join(TOOLS, "check_kernel_regression.py")

    def run(man_path):
        return subprocess.run([sys.executable, tool, str(man_path),
                               str(base)], capture_output=True,
                              text=True)

    # 0: self-gate
    r = run(base)
    assert r.returncode == 0, r.stderr

    # 2: injected pad-waste AND byte-ratio regression fixture
    bad = json.loads(json.dumps(manifest))
    bad["kernels"]["two_kernel"]["pad_waste_frac"] = 0.99
    if bad["kernels"]["fused_one_pass"]["byte_ratio"]:
        bad["kernels"]["fused_one_pass"]["byte_ratio"] *= 10.0
    p_bad = tmp_path / "bad.json"
    p_bad.write_text(json.dumps(bad))
    r = run(p_bad)
    assert r.returncode == 2, (r.returncode, r.stderr)
    assert "pad-waste-regression" in r.stderr

    # 2: counter drift at the same scale
    drift = json.loads(json.dumps(manifest))
    drift["kernels"]["two_kernel"]["stages"]["vote"]["counters"][
        "coin_draws"] += 1
    p_drift = tmp_path / "drift.json"
    p_drift.write_text(json.dumps(drift))
    r = run(p_drift)
    assert r.returncode == 2 and "counter-drift" in r.stderr

    # 3: scale mismatch is incomparable, never silently passed
    other = json.loads(json.dumps(manifest))
    other["scale"]["n_nodes"] = 999
    p_other = tmp_path / "other.json"
    p_other.write_text(json.dumps(other))
    r = run(p_other)
    assert r.returncode == 3 and "INCOMPARABLE" in r.stderr


def test_gate_missing_kernel_is_a_regression(manifest):
    from benor_tpu.kernelscope import compare_kernels

    m2 = json.loads(json.dumps(manifest))
    del m2["kernels"]["fused_one_pass"]
    findings = compare_kernels(m2, manifest)
    assert any(f.kind == "missing-kernel" for f in findings)


# --------------------------------------------------------------------------
# satellites: demotion counters, watch renderer, config validation
# --------------------------------------------------------------------------


def test_demotion_counters_tick_every_announcer_call():
    # every CALL of the announcer ticks, unlike the once-per-process
    # warning it wraps (the counter semantics sim.py documents)
    c_struct = REGISTRY.counter("sim.demotion.structured")
    c_debug = REGISTRY.counter("sim.demotion.debug")
    cfg = SimConfig(n_nodes=16, n_faulty=2, topology="ring:2",
                    use_pallas_round=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        v0 = c_struct.value
        warn_structured_demotes_pallas(cfg)
        warn_structured_demotes_pallas(cfg)
        assert c_struct.value == v0 + 2, \
            "the counter must tick on every call, not once per process"
        v0 = c_debug.value
        warn_debug_demotes_pallas(cfg)
        assert c_debug.value == v0 + 1


def test_structured_run_ticks_demotion_counter_per_traced_build():
    # the announcers live inside jitted bodies: one tick per TRACED
    # demoted executable build — and a warm jit cache re-runs the
    # executable without re-ticking (both halves of the documented
    # semantic)
    c = REGISTRY.counter("sim.demotion.structured")
    v0 = c.value
    cfg = SimConfig(n_nodes=16, n_faulty=2, trials=2, topology="ring:2",
                    max_rounds=4, use_pallas_round=True,
                    use_pallas_hist=True)
    state, faults, key = _inputs(cfg)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        run_consensus(cfg, state, faults, key)
        assert c.value == v0 + 1
        run_consensus(cfg, state, faults, key)   # jit-cache hit
    assert c.value == v0 + 1, \
        "a cached execution must not re-tick (counts builds, not calls)"


def test_watch_renders_kernel_telemetry(tmp_path):
    from benor_tpu.__main__ import _format_kernel_telem
    from benor_tpu.kernelscope.report import (KERNEL_TELEM_KIND,
                                              telemetry_record)

    stages = {"proposal": {"counters": {"hist_visits": 7,
                                        "quorum_passes": 0,
                                        "coin_draws": 0,
                                        "plane_hops": 4},
                           "per_tile": [[7, 0, 0, 4]]},
              "vote": {"counters": {"hist_visits": 7,
                                    "quorum_passes": 7, "coin_draws": 2,
                                    "plane_hops": 8},
                       "per_tile": [[7, 7, 2, 8]]}}
    rec = telemetry_record("kernelscope", "two_kernel", stages, 2, 0.5)
    assert rec["kind"] == KERNEL_TELEM_KIND
    line = _format_kernel_telem(rec)
    assert "kernel=two_kernel" in line
    assert "pad_waste=0.500" in line
    assert "coins=2" in line

    # end-to-end through the watch CLI (interleaved with a heartbeat;
    # the done-beat LAST — watch stops at the first done record)
    from benor_tpu.utils.metrics import append_jsonl
    path = tmp_path / "mixed.jsonl"
    append_jsonl(str(path), rec)
    append_jsonl(str(path), {"kind": "heartbeat", "label": "x",
                             "done": True})
    r = subprocess.run(
        [sys.executable, "-m", "benor_tpu", "watch", str(path),
         "--no-follow"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "kernel=two_kernel" in r.stdout


def test_kernel_telemetry_config_validation():
    with pytest.raises(ValueError, match="backend='tpu'"):
        SimConfig(n_nodes=8, n_faulty=0, backend="express",
                  kernel_telemetry=True)
    with pytest.raises(ValueError, match="single-device"):
        SimConfig(n_nodes=8, n_faulty=0, mesh_shape=(1, 2),
                  kernel_telemetry=True)


def test_manifest_kind_registered():
    from benor_tpu.kernelscope.manifest import KERNEL_MANIFEST_KIND

    cms = _cms()
    assert cms.MANIFEST_CHECKERS[KERNEL_MANIFEST_KIND] == \
        "check_kernel_manifest"
    assert hasattr(cms, "check_kernel_manifest")
