"""Multi-host (multi-process) backend: REAL cross-process collectives.

Spawns two OS processes, each a full JAX runtime with 4 virtual CPU devices,
joined via jax.distributed (Gloo) into one 8-device cluster — the CPU
stand-in for two TPU hosts on DCN.  Each worker runs the shard_map'd
consensus loop over the process-spanning ('trials', 'nodes') mesh and
asserts bit-identity against its own single-process run, on both compute
paths (dense all-gather + psum, histogram psum-only).

This is the distributed-communication-backend claim (SURVEY §5.8) tested at
the strongest level available without pod hardware: the collectives really
cross a process boundary over TCP, not just a virtual-device boundary inside
one runtime.
"""

import concurrent.futures
import os
import socket
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow   # real OS processes + Gloo: ~2 min

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "tests", "multihost_worker.py")
NPROC = 2


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cluster_bit_identity():
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(NPROC), str(port)],
            cwd=ROOT, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in range(NPROC)
    ]
    # Drain both workers' pipes from the start (a blocked pipe write would
    # deadlock the run) while polling exit states: if one worker crashes,
    # its peer blocks forever in the distributed barrier — kill survivors
    # and report the CRASHED worker first, not the victim we killed.
    with concurrent.futures.ThreadPoolExecutor(NPROC) as ex:
        futs = [ex.submit(p.communicate) for p in procs]
        deadline = time.time() + 420
        while time.time() < deadline and any(p.poll() is None for p in procs):
            if any(p.poll() not in (None, 0) for p in procs):
                time.sleep(2)          # let the crash finish writing stderr
                break
            time.sleep(0.5)
        for p in procs:
            if p.poll() is None:
                p.kill()
        outs = [f.result(timeout=60) for f in futs]

    # a worker we killed exits -9; a genuine crash carries the real rc and
    # traceback — surface the genuine one first
    order = sorted(range(NPROC),
                   key=lambda i: 0 if procs[i].returncode not in (0, -9)
                   else 1)
    for pid in order:
        p, (out, err) = procs[pid], outs[pid]
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\nstdout:\n{out}\nstderr:\n"
            f"{err[-3000:]}")
    for pid, (out, _) in enumerate(outs):
        for path in ("dense", "histogram"):
            assert f"worker{pid}[{path}]" in out and \
                "bit-identical vs single-process OK" in out, out
        assert f"worker{pid}[resume]" in out, out
        assert f"worker{pid}[xhost-nodes]" in out, out
        assert f"worker{pid}[sliced]" in out, out
