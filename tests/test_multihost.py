"""Multi-host (multi-process) backend: REAL cross-process collectives.

Spawns two OS processes, each a full JAX runtime with 4 virtual CPU devices,
joined via jax.distributed (Gloo) into one 8-device cluster — the CPU
stand-in for two TPU hosts on DCN.  Each worker runs the shard_map'd
consensus loop over the process-spanning ('trials', 'nodes') mesh and
asserts bit-identity against its own single-process run, on both compute
paths (dense all-gather + psum, histogram psum-only).

This is the distributed-communication-backend claim (SURVEY §5.8) tested at
the strongest level available without pod hardware: the collectives really
cross a process boundary over TCP, not just a virtual-device boundary inside
one runtime.
"""

import os
import socket
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "tests", "multihost_worker.py")
NPROC = 2


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cluster_bit_identity():
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(NPROC), str(port)],
            cwd=ROOT, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in range(NPROC)
    ]
    # Poll BOTH workers: if one crashes at startup, its peer (blocked in
    # the distributed barrier) would hang — kill the survivors and surface
    # the crashed worker's stderr instead of an opaque timeout.
    deadline = time.time() + 420
    while time.time() < deadline and any(p.poll() is None for p in procs):
        if any(p.poll() not in (None, 0) for p in procs):
            break                      # someone failed; stop waiting
        time.sleep(0.5)
    for p in procs:
        if p.poll() is None:
            p.kill()
    outs = [p.communicate() for p in procs]
    for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\nstdout:\n{out}\nstderr:\n"
            f"{err[-3000:]}")
        for path in ("dense", "histogram"):
            assert f"worker{pid}[{path}]" in out and \
                "bit-identical vs single-process OK" in out, out
