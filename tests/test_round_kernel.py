"""Unit tests of the Ben-Or round kernel and sim loop — pure-function level.

The reference has no unit tests (its only suite is black-box HTTP
integration, SURVEY.md §4); these pin the kernel's semantics directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from benor_tpu import (FaultSpec, NetState, SimConfig, VAL0, VAL1, VALQ,
                       init_state, simulate, start_state)
from benor_tpu.models.benor import benor_round


def _mk(cfg, vals, faulty=None):
    faults = FaultSpec.from_faulty_list(cfg, faulty or [False] * cfg.n_nodes)
    return init_state(cfg, vals, faults), faults


class TestInit:
    def test_healthy_init_matches_reference(self):
        # node.ts:21-26: {killed:false, x:initial, decided:false, k:0}
        cfg = SimConfig(n_nodes=3, n_faulty=0)
        state, _ = _mk(cfg, [1, 0, 1])
        assert np.asarray(state.x).tolist() == [[1, 0, 1]]
        assert not np.asarray(state.decided).any()
        assert np.asarray(state.k).tolist() == [[0, 0, 0]]
        assert not np.asarray(state.killed).any()

    def test_faulty_killed_at_birth(self):
        cfg = SimConfig(n_nodes=3, n_faulty=1)
        state, _ = _mk(cfg, [1, 1, 1], [True, False, False])
        assert np.asarray(state.killed).tolist() == [[True, False, False]]

    def test_faulty_count_validated(self):
        # launchNodes.ts:12-13: "faultyList doesnt have F faulties"
        cfg = SimConfig(n_nodes=3, n_faulty=2)
        with pytest.raises(ValueError, match="faulties"):
            _mk(cfg, [1, 1, 1], [True, False, False])

    def test_length_validated(self):
        # launchNodes.ts:10-11: "Arrays don't match"
        cfg = SimConfig(n_nodes=3, n_faulty=0)
        with pytest.raises(ValueError):
            _mk(cfg, [1, 1])

    def test_start_sets_k1_on_live_lanes(self):
        # node.ts:172: /start sets k=1 (killed lanes untouched)
        cfg = SimConfig(n_nodes=3, n_faulty=1)
        state, _ = _mk(cfg, [1, 1, 1], [True, False, False])
        started = start_state(cfg, state)
        assert np.asarray(started.k).tolist() == [[0, 1, 1]]


class TestSingleRound:
    def run_one(self, cfg, vals, faulty=None):
        state, faults = _mk(cfg, vals, faulty)
        state = start_state(cfg, state)
        key = jax.random.key(cfg.seed)
        return benor_round(cfg, state, faults, key, jnp.int32(1))

    def test_unanimous_decides_round_one(self):
        cfg = SimConfig(n_nodes=5, n_faulty=0)
        out = self.run_one(cfg, [1] * 5)
        assert np.asarray(out.decided).all()
        assert (np.asarray(out.x) == 1).all()
        # decided in round 1 => k=2 (node.ts:147 increments after deciding)
        assert (np.asarray(out.k) == 2).all()

    def test_majority_tally_quirk4_quorum_includes_question(self):
        # Quorum gate counts "?" messages; decide counts only 0/1 (quirk 4).
        # N=4, F=2, quorum=2. Values [?, ?, ?, ?]: phase1 tie -> "?",
        # phase2 all vote "?" -> v0=v1=0 -> no decide, coin.
        cfg = SimConfig(n_nodes=4, n_faulty=2)
        out = self.run_one(cfg, ["?"] * 4, [True, True, False, False])
        live = np.asarray(out.decided)[0, 2:]
        assert not live.any()          # no decision possible
        xs = np.asarray(out.x)[0, 2:]
        assert set(xs.tolist()) <= {0, 1}   # coin flipped to a binary value

    def test_tie_gives_question_then_plurality_or_coin(self):
        # N=2, F=0: values [0, 1] -> phase1 tie -> both propose "?";
        # phase2 votes are ["?", "?"] -> v0=v1=0 -> coin.
        cfg = SimConfig(n_nodes=2, n_faulty=0)
        out = self.run_one(cfg, [0, 1])
        assert not np.asarray(out.decided).any()
        assert set(np.asarray(out.x).ravel().tolist()) <= {0, 1}

    def test_decide_requires_count_strictly_above_F(self):
        # N=10, F=5, live=5: v <= 5 = F can never satisfy count > F.
        cfg = SimConfig(n_nodes=10, n_faulty=5)
        out = self.run_one(cfg, [1] * 10, [True] * 5 + [False] * 5)
        assert not np.asarray(out.decided)[0, 5:].any()
        # but plurality-adopt keeps x=1 (all 5 votes are 1)
        assert (np.asarray(out.x)[0, 5:] == 1).all()

    def test_quorum_stall_below_n_minus_f(self):
        # 2 live senders < quorum N-F = 3: no tally ever fires and state
        # stays frozen, like reference receivers waiting forever for a 3rd
        # message.  (More dead lanes than F is unreachable via the launch
        # validator, so construct the FaultSpec directly.)
        cfg = SimConfig(n_nodes=4, n_faulty=1)
        faults = FaultSpec(
            faulty=jnp.asarray([[True, True, False, False]]),
            crash_round=jnp.zeros((1, 4), jnp.int32))
        state = init_state(cfg, [1, 1, 1, 1], faults)
        state = NetState(x=state.x, decided=state.decided, k=state.k,
                         killed=state.killed | faults.faulty)
        state = start_state(cfg, state)
        out = benor_round(cfg, state, faults, jax.random.key(0), jnp.int32(1))
        assert not np.asarray(out.decided)[0, 2:].any()
        assert (np.asarray(out.k)[0, 2:] == 1).all()   # k never advanced
        assert (np.asarray(out.x)[0, 2:] == 1).all()   # x untouched

    def test_textbook_rule_flips_coin_instead_of_plurality(self):
        # N=10, F=5, live=5, all-1 votes: reference rule adopts 1;
        # textbook rule coins (so across many seeds some lanes pick 0).
        vals = [1] * 10
        fl = [True] * 5 + [False] * 5
        seen0 = False
        for seed in range(8):
            cfg = SimConfig(n_nodes=10, n_faulty=5, rule="textbook", seed=seed)
            out = self.run_one(cfg, vals, fl)
            if (np.asarray(out.x)[0, 5:] == 0).any():
                seen0 = True
        assert seen0


class TestFullRun:
    def test_unanimous_agreement(self):
        # reference :133-175 — all decide 1, k <= 2
        cfg = SimConfig(n_nodes=5, n_faulty=0, max_rounds=16)
        r, final, _ = simulate(cfg, [1] * 5)
        assert np.asarray(final.decided).all()
        assert (np.asarray(final.x) == 1).all()
        assert (np.asarray(final.k) <= 2).all()

    def test_unanimous_zero(self):
        cfg = SimConfig(n_nodes=5, n_faulty=0, max_rounds=16)
        r, final, _ = simulate(cfg, [0] * 5)
        assert np.asarray(final.decided).all()
        assert (np.asarray(final.x) == 0).all()

    def test_simple_majority(self):
        # reference :179-223 — healthy decide 1, k <= 2
        cfg = SimConfig(n_nodes=5, n_faulty=1, max_rounds=16)
        r, final, _ = simulate(cfg, [1, 1, 1, 0, 0],
                               [False, False, False, False, True])
        live = np.s_[0, :4]
        assert np.asarray(final.decided)[live].all()
        assert (np.asarray(final.x)[live] == 1).all()
        assert (np.asarray(final.k)[live] <= 2).all()

    def test_fault_tolerance_threshold_agreement(self):
        # reference :227-286 — N=9, F=4, mixed inputs: all healthy decide
        # the same value
        cfg = SimConfig(n_nodes=9, n_faulty=4, max_rounds=32)
        r, final, _ = simulate(cfg, [0, 0, 1, 1, 1, 0, 0, 1, 1],
                               [True] * 4 + [False] * 5)
        d = np.asarray(final.decided)[0, 4:]
        x = np.asarray(final.x)[0, 4:]
        assert d.all()
        assert (x == x[0]).all()

    def test_exceeding_fault_tolerance_livelock(self):
        # reference :292-345 — N=10, F=5: never decides, k > 10
        cfg = SimConfig(n_nodes=10, n_faulty=5, max_rounds=15)
        r, final, _ = simulate(cfg, [0, 0, 1, 1, 1, 0, 0, 1, 1, 0],
                               [True] * 5 + [False] * 5)
        live = np.s_[0, 5:]
        assert not np.asarray(final.decided)[live].any()
        assert (np.asarray(final.k)[live] > 10).all()

    def test_no_faulty_mixed_decides_one(self):
        # reference :351-393 — [0,1,0,1,1] with plurality rule -> all decide 1
        cfg = SimConfig(n_nodes=5, n_faulty=0, max_rounds=16)
        r, final, _ = simulate(cfg, [0, 1, 0, 1, 1])
        assert np.asarray(final.decided).all()
        assert (np.asarray(final.x) == 1).all()
        assert (np.asarray(final.k) <= 2).all()

    def test_one_node(self):
        # reference :454-486
        cfg = SimConfig(n_nodes=1, n_faulty=0, max_rounds=16)
        r, final, _ = simulate(cfg, [1])
        assert np.asarray(final.decided).all()
        assert (np.asarray(final.x) == 1).all()

    @pytest.mark.slow
    def test_freeze_decided_off_keeps_lanes_looping(self):
        """freeze_decided=False models the reference's literal quirk 5
        (decided nodes keep executing rounds, node.ts:147-157): decided
        lanes keep advancing k until the TRIAL settles, so every lane of a
        trial ends at the same k = rounds+1; with the default freeze, each
        lane's k stays pinned at its own decide round."""
        import benor_tpu.sweep as sweep

        base = SimConfig(n_nodes=48, n_faulty=18, trials=16, max_rounds=64,
                         delivery="quorum", scheduler="uniform",
                         path="histogram", seed=11)
        vals = sweep.balanced_inputs(16, 48)
        no_crash = FaultSpec.none(16, 48)
        from benor_tpu.sim import run_consensus
        out = {}
        for freeze in (True, False):
            cfg = base.replace(freeze_decided=freeze)
            state = init_state(cfg, vals, no_crash)
            r, final = run_consensus(cfg, state, no_crash,
                                     jax.random.key(11))
            assert np.asarray(final.decided).all()      # still terminates
            out[freeze] = (int(r), np.asarray(final.k))
        r_frozen, k_frozen = out[True]
        r_loose, k_loose = out[False]
        # unfrozen: every lane advanced through the WHOLE run (settled
        # trials' lanes keep looping until the global loop exits), so all
        # end at exactly k = rounds_executed + 1
        assert (k_loose == r_loose + 1).all()
        # frozen: in multi-round trials, early deciders' k stays behind
        multi = k_frozen.max(axis=1) > 2
        assert multi.any(), "need at least one multi-round trial"
        assert (k_frozen[multi].min(axis=1) <
                k_frozen[multi].max(axis=1)).any()

    @pytest.mark.slow
    def test_agreement_and_validity_invariants_random(self):
        # Property: agreement (all deciders agree) + validity (decided value
        # was some node's input) over randomized inputs — reference :399-450
        rng = np.random.default_rng(7)
        for trial in range(5):
            vals = rng.integers(0, 2, size=7).tolist()
            cfg = SimConfig(n_nodes=7, n_faulty=2, max_rounds=32,
                            seed=trial)
            r, final, _ = simulate(
                cfg, vals, [False, False, True, False, True, False, False])
            live = [0, 1, 3, 5, 6]
            d = np.asarray(final.decided)[0, live]
            x = np.asarray(final.x)[0, live]
            assert d.all()
            assert (x == x[0]).all()
            assert x[0] in (0, 1)
            if len(set(vals)) == 1:
                assert x[0] == vals[0]
