"""Shared protocol-level statistical harness for sampler-parity tests.

Used by tests/test_sampling.py (CF-forced vs exact-table regimes) and
tests/test_pallas_hist.py (fused pallas sampler vs the XLA pipeline).  The
load-bearing choices live here ONCE:

  * balanced inputs + zero crashes (alive > quorum): with crash-from-birth
    faults the live population equals the quorum and every sampler draws
    the whole population — trivially identical, vacuous comparison;
  * F > N/3 so the decide threshold sits above the typical class count and
    runs take a random 1-4 rounds (otherwise everything decides in round 1
    and distributions are constants);
  * PER-TRIAL aggregation: lanes within a trial share the global histogram
    trajectory and are strongly correlated, so pooled per-lane KS wildly
    overstates significance; per-trial means are iid by construction;
  * per-trial convergence guard: a single dead trial would make its mean
    0/0 NaN and poison the KS gate with a misleading failure.
"""

from __future__ import annotations

import numpy as np

import jax

from benor_tpu.config import SimConfig
from benor_tpu.ops import sampling
from benor_tpu.state import FaultSpec, init_state


def trial_mean_k(n: int, f: int, trials: int, seed: int, *,
                 table_max: int | None = None,
                 use_pallas_hist: bool = False,
                 fault_model: str = "crash",
                 coin_mode: str = "private",
                 coin_eps: float = 0.0) -> np.ndarray:
    """Per-trial mean rounds-to-decide under a forced sampler regime.

    ``table_max`` (if given) overrides ``sampling.EXACT_TABLE_MAX`` for the
    duration of the run, steering the histogram path between the exact
    shared-CDF sampler and the Cornish-Fisher sampler (and gating the
    pallas kernels, which serve only the CF regime).  Distinct seeds give
    distinct static configs, so the jit cache cannot serve a trace from
    another regime.

    ``fault_model='crash'`` (default) runs the zero-crash spec (F purely a
    protocol parameter — see module docstring); ``'equivocate'`` marks the
    first F lanes as live equivocators instead, exercising the
    mixed-population sampler with the same multi-round dynamics.
    """
    from benor_tpu.sim import run_consensus

    old = sampling.EXACT_TABLE_MAX
    if table_max is not None:
        sampling.EXACT_TABLE_MAX = table_max
    try:
        cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials, max_rounds=64,
                        delivery="quorum", scheduler="uniform",
                        path="histogram", use_pallas_hist=use_pallas_hist,
                        fault_model=fault_model, coin_mode=coin_mode,
                        coin_eps=coin_eps, seed=seed)
        faults = (FaultSpec.first_f(cfg) if fault_model == "equivocate"
                  else FaultSpec.none(trials, n))
        from benor_tpu.sweep import balanced_inputs
        balanced = balanced_inputs(trials, n)
        state = init_state(cfg, balanced, faults)
        _, final = run_consensus(cfg, state, faults, jax.random.key(seed))
    finally:
        sampling.EXACT_TABLE_MAX = old
    healthy = ~np.asarray(faults.faulty)
    dec = np.asarray(final.decided) & healthy
    k = np.asarray(final.k)
    assert dec.any(axis=1).all(), "some trial failed to converge entirely"
    assert (dec.sum(axis=1) > 0.99 * healthy.sum(axis=1)).all(), \
        "failed to converge"
    return (k * dec).sum(axis=1) / dec.sum(axis=1)
