"""sweepscope (benor_tpu/sweepscope) — bucket-lifecycle tracing,
overlap-headroom attribution, and the durable resumable sweep journal.

Pins the PR 13 house rules:

  * journal OFF and ON are bit-identical in the science fields AND
    backend compile counts, across dyn and static buckets;
  * span tracing OFF and ON are bit-identical the same way, and the
    emitted spans nest (four lifecycle stages inside each bucket span)
    with 1:1 flow links from every bucket to the points it carried;
  * a resumed sweep is bit-equal to an uninterrupted one — including
    after a SIGKILL mid-bucket — with exactly the unfinished buckets
    recompiled; ANY journal tamper (fingerprint drift, truncated line,
    reordered indices) reruns rather than reuses;
  * the ``kind: sweep_manifest`` document validates against
    tools/sweep_manifest_schema.json with its cross-field pins
    (stage telescoping, headroom recomputed from stages), and
    tools/check_sweep_regression.py exits 0 on the committed
    SWEEP_BASELINE.json, 2 on an injected serialized-pipeline
    regression, 3 on a platform mismatch;
  * ``python -m benor_tpu watch`` tails mixed-kind JSON-lines files
    (heartbeats + journal bucket records interleaved, unknown kinds
    passed through raw, torn trailing lines skipped).
"""

import copy
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from benor_tpu.config import SimConfig
from benor_tpu.ops import sampling
from benor_tpu.sweep import run_curve_batched, run_points_batched
from benor_tpu.sweepscope import (IncomparableSweep, build_sweep_manifest,
                                  bucket_fingerprint, compare_sweep,
                                  ideal_pipeline_s, read_journal,
                                  serial_s)
from benor_tpu.sweepscope.gate import SweepFinding  # noqa: F401  (API)
from benor_tpu.sweepscope.journal import BUCKET_KIND, DONE_KIND

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "SWEEP_BASELINE.json")
GATE_TOOL = os.path.join(REPO, "tools", "check_sweep_regression.py")
SCHEMA_TOOL = os.path.join(REPO, "tools", "check_metrics_schema.py")

#: Mixed-bucket geometry: two CF-regime points share a dyn bucket
#: (quorum > EXACT_TABLE_MAX), one exact-table point gets a static
#: bucket — the smallest sweep exercising BOTH bucket kinds.
CF_N = 9000
EXACT_F = CF_N - sampling.EXACT_TABLE_MAX + 500
MIXED_FS = [600, 1200, EXACT_F]


def _cfg(seed=3, **kw):
    base = dict(n_nodes=CF_N, n_faulty=0, trials=4, delivery="quorum",
                scheduler="uniform", path="histogram", max_rounds=12,
                seed=seed)
    base.update(kw)
    return SimConfig(**base)


def science(p):
    return (p.rounds_executed, p.decided_frac, p.mean_k, p.ones_frac,
            p.disagree_frac, tuple(p.k_hist.tolist()))


def assert_bit_equal(pa, pb):
    assert len(pa) == len(pb)
    for a, b in zip(pa, pb):
        assert science(a) == science(b), (a.n_faulty, b.n_faulty)


@pytest.fixture(scope="module")
def mixed_runs(tmp_path_factory):
    """One mixed dyn+static curve run journal-off and journal-on (the
    expensive compiles paid once for the whole module)."""
    td = tmp_path_factory.mktemp("sweepscope")
    jp = str(td / "journal.jsonl")
    cfg = _cfg()
    cb_off = run_curve_batched(cfg, MIXED_FS)
    cb_on = run_curve_batched(cfg, MIXED_FS, journal_path=jp)
    return cfg, jp, cb_off, cb_on


# --------------------------------------------------------------------------
# house rule: journal off/on bit-identical, across dyn AND static buckets
# --------------------------------------------------------------------------


def test_journal_off_on_bit_identical_and_compile_parity(mixed_runs):
    cfg, jp, cb_off, cb_on = mixed_runs
    assert set(cb_off.bucket_kinds) == {"dyn", "static"}
    assert_bit_equal(cb_off.points, cb_on.points)
    assert cb_off.compile_count == cb_on.compile_count == 2
    recs = read_journal(jp)
    kinds = [r["kind"] for r in recs]
    assert kinds == [BUCKET_KIND, BUCKET_KIND, DONE_KIND]
    for rec in recs[:2]:
        assert rec["fingerprint"].startswith("sha256:")
        assert rec["compile_count"] == 1
        assert len(rec["points"]) == len(rec["point_indices"])
        for stage in ("prepare_s", "compile_s", "run_s", "fetch_s"):
            assert rec[stage] >= 0.0
    assert recs[2]["done"] is True


def test_batched_curve_stage_attribution(mixed_runs):
    cfg, jp, cb, _ = mixed_runs
    n = cb.n_buckets
    for lst in (cb.bucket_prepare_s, cb.bucket_compile_s,
                cb.bucket_run_s, cb.bucket_fetch_s, cb.bucket_kinds,
                cb.bucket_point_indices, cb.bucket_compile_counts,
                cb.bucket_reused):
        assert len(lst) == n
    # the legacy aggregates are exactly the per-bucket sums
    assert abs(cb.compile_s - sum(cb.bucket_compile_s)) < 1e-6
    assert abs(cb.run_s - (sum(cb.bucket_run_s)
                           + sum(cb.bucket_fetch_s))) < 1e-6
    assert cb.compile_count == sum(cb.bucket_compile_counts)
    # indices partition the input order
    flat = sorted(i for idx in cb.bucket_point_indices for i in idx)
    assert flat == list(range(len(cb.points)))
    # the wall clock bounds the stage sums; headroom is non-negative
    stage_sum = (sum(cb.bucket_prepare_s) + sum(cb.bucket_compile_s)
                 + sum(cb.bucket_run_s) + sum(cb.bucket_fetch_s))
    assert cb.wall_s >= stage_sum - 1e-3
    assert cb.overlap_headroom_s >= 0.0
    # seconds stays the amortized bucket share (compat satellite)
    for bi, idx in enumerate(cb.bucket_point_indices):
        share = (cb.bucket_run_s[bi] + cb.bucket_fetch_s[bi]) / len(idx)
        for i in idx:
            assert cb.points[i].seconds == pytest.approx(share)


def test_verbose_prints_max_bucket_share(mixed_runs, capsys, tmp_path):
    cfg, jp, cb_off, _ = mixed_runs
    # a zero-compile verbose resume is the cheap way to see the line
    cb = run_curve_batched(cfg, MIXED_FS, journal_path=jp, resume=True,
                           verbose=True)
    out = capsys.readouterr().out
    assert "max bucket share" in out
    assert "overlap headroom" in out
    assert "journal-restored" in out
    assert_bit_equal(cb_off.points, cb.points)


# --------------------------------------------------------------------------
# resume: bit-equality + exact compile accounting + tamper matrix
# --------------------------------------------------------------------------


def test_resume_full_journal_zero_compiles_bit_equal(mixed_runs):
    cfg, jp, cb_off, _ = mixed_runs
    cb = run_curve_batched(cfg, MIXED_FS, journal_path=jp, resume=True)
    assert cb.compile_count == 0
    assert cb.bucket_reused == [True, True]
    assert cb.bucket_compile_counts == [0, 0]
    assert_bit_equal(cb_off.points, cb.points)
    # the journaled stage clocks survive the resume (attribution)
    assert all(c > 0 for c in cb.bucket_compile_s)


def test_resume_requires_journal_path():
    with pytest.raises(ValueError, match="journal_path"):
        run_points_batched(_cfg(), [_cfg(n_faulty=600)], resume=True)


def test_fresh_run_truncates_stale_journal(mixed_runs, tmp_path):
    cfg, jp, cb_off, _ = mixed_runs
    stale = tmp_path / "stale.jsonl"
    stale.write_text('{"kind": "sweep_bucket", "bucket_index": 99}\n')
    # journal-on WITHOUT resume: the stale content must not survive
    cb = run_curve_batched(cfg, MIXED_FS, journal_path=str(stale))
    recs = read_journal(str(stale))
    assert [r["kind"] for r in recs] == [BUCKET_KIND, BUCKET_KIND,
                                         DONE_KIND]
    assert all(r.get("bucket_index") != 99 for r in recs)
    assert_bit_equal(cb_off.points, cb.points)


def _tamper(jp, tmp_path, mode):
    """Copy the journal and tamper ONE bucket record; returns the path
    and the index of the tampered bucket."""
    with open(jp) as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    # lines: bucket 0, bucket 1, done
    target = 1                       # the static single-point bucket
    if mode == "fingerprint":
        rec = json.loads(lines[target])
        rec["fingerprint"] = "sha256:" + "0" * 64
        lines[target] = json.dumps(rec)
    elif mode == "truncated":
        lines[target] = lines[target][:len(lines[target]) // 2]
    elif mode == "reordered":
        rec = json.loads(lines[0])   # the 2-point dyn bucket
        rec["point_indices"] = list(reversed(rec["point_indices"]))
        lines[0] = json.dumps(rec)
        target = 0
    elif mode == "short_payload":
        rec = json.loads(lines[0])
        rec["points"] = rec["points"][:1]
        lines[0] = json.dumps(rec)
        target = 0
    elif mode == "payload_value":
        # an edited science value: indices + fingerprint untouched, so
        # only the payload digest can catch it
        rec = json.loads(lines[target])
        rec["points"][0]["mean_k"] = 99.0
        lines[target] = json.dumps(rec)
    elif mode == "payload_key":
        # a renamed payload key: must rerun, not crash the resume
        rec = json.loads(lines[target])
        rec["points"][0]["mean_kk"] = rec["points"][0].pop("mean_k")
        lines[target] = json.dumps(rec)
    out = tmp_path / f"tampered_{mode}.jsonl"
    out.write_text("\n".join(lines) + "\n")
    return str(out), target


@pytest.mark.parametrize("mode", ["fingerprint", "truncated",
                                  "reordered", "short_payload",
                                  "payload_value", "payload_key"])
def test_tampered_journal_reruns_never_reuses(mixed_runs, tmp_path,
                                              mode):
    cfg, jp, cb_off, _ = mixed_runs
    tp, target = _tamper(jp, tmp_path, mode)
    cb = run_curve_batched(cfg, MIXED_FS, journal_path=tp, resume=True)
    # exactly the tampered bucket reruns; the untouched one restores
    assert cb.compile_count == 1
    assert sum(cb.bucket_reused) == cb.n_buckets - 1
    assert cb.bucket_reused[target] is False
    # and the rerun is still bit-equal to the uninterrupted oracle
    assert_bit_equal(cb_off.points, cb.points)


def test_partial_journal_reruns_only_missing(mixed_runs, tmp_path):
    cfg, jp, cb_off, _ = mixed_runs
    with open(jp) as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    partial = tmp_path / "partial.jsonl"
    partial.write_text(lines[0] + "\n")      # only bucket 0 completed
    cb = run_curve_batched(cfg, MIXED_FS, journal_path=str(partial),
                           resume=True)
    assert cb.compile_count == 1
    assert cb.bucket_reused == [True, False]
    assert_bit_equal(cb_off.points, cb.points)
    # the rerun bucket appended its fresh record + a done record
    kinds = [r["kind"] for r in read_journal(str(partial))]
    assert kinds == [BUCKET_KIND, BUCKET_KIND, DONE_KIND]


def test_fingerprint_covers_every_input():
    from benor_tpu.state import FaultSpec
    from benor_tpu.sweep import default_crash_faults, random_inputs
    cfg = _cfg(n_faulty=600)
    iv = random_inputs(cfg.seed, cfg.trials, cfg.n_nodes)
    fl = default_crash_faults(cfg)
    fp = bucket_fingerprint([cfg], iv, [fl])
    assert fp == bucket_fingerprint([cfg], iv, [fl])      # deterministic
    assert fp != bucket_fingerprint([cfg.replace(seed=4)], iv, [fl])
    iv2 = iv.copy()
    iv2[0, 0] ^= 1
    assert fp != bucket_fingerprint([cfg], iv2, [fl])
    assert fp != bucket_fingerprint(
        [cfg], iv, [FaultSpec.none(cfg.trials, cfg.n_nodes)])


# --------------------------------------------------------------------------
# SIGKILL forensics: preemption mid-bucket, resume bit-equal
# --------------------------------------------------------------------------


_CHILD_SRC = """\
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, sys.argv[2])
from benor_tpu.config import SimConfig
from benor_tpu.sweep import default_crash_faults, run_points_batched

base = SimConfig(n_nodes=64, n_faulty=0, trials=8, delivery="quorum",
                 scheduler="uniform", path="histogram", max_rounds=8,
                 seed=5)
cfgs = [base.replace(n_faulty=f) for f in (8, 12, 16)]


def slow_faults(c):
    # widen the kill window: the parent SIGKILLs while a later bucket
    # is mid-prepare (the fault masks themselves are identical to the
    # default policy, so the fingerprints match the parent's resume)
    time.sleep(1.0)
    return default_crash_faults(c)


run_points_batched(base, cfgs, faults_for=slow_faults,
                   journal_path=sys.argv[1])
"""


def test_sigkill_mid_sweep_resumes_bit_equal(tmp_path):
    """The preemption-forensics acceptance: SIGKILL a journaled sweep
    mid-bucket, resume, pin bit-equality vs the uninterrupted oracle
    AND exactly n_remaining_buckets compiles."""
    jp = str(tmp_path / "kill_journal.jsonl")
    script = tmp_path / "child.py"
    script.write_text(_CHILD_SRC)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, str(script), jp, REPO],
                            env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            done = [r for r in read_journal(jp)
                    if r.get("kind") == BUCKET_KIND]
            if done:
                break
            time.sleep(0.05)
        assert proc.poll() is None, \
            "child exited before the kill — the sweep ran to completion"
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    recs = [r for r in read_journal(jp) if r.get("kind") == BUCKET_KIND]
    n_done = len(recs)
    assert 1 <= n_done < 3, n_done

    base = SimConfig(n_nodes=64, n_faulty=0, trials=8,
                     delivery="quorum", scheduler="uniform",
                     path="histogram", max_rounds=8, seed=5)
    cfgs = [base.replace(n_faulty=f) for f in (8, 12, 16)]
    oracle = run_points_batched(base, cfgs)
    resumed = run_points_batched(base, cfgs, journal_path=jp,
                                 resume=True)
    assert resumed.compile_count == 3 - n_done
    assert sum(resumed.bucket_reused) == n_done
    assert_bit_equal(oracle.points, resumed.points)


# --------------------------------------------------------------------------
# span tracing: off/on bit-identity, nesting, flow links
# --------------------------------------------------------------------------


@pytest.fixture
def span_log():
    from benor_tpu.utils.metrics import SPANS
    SPANS.clear()
    SPANS.enable()
    yield SPANS
    SPANS.disable()
    SPANS.clear()


def test_tracing_off_on_bit_identical_with_nested_flow_spans(
        span_log, tmp_path):
    base = SimConfig(n_nodes=64, n_faulty=0, trials=8,
                     delivery="quorum", scheduler="uniform",
                     path="histogram", max_rounds=8, seed=7)
    fs = [8, 12]
    span_log.disable()
    cb_off = run_curve_batched(base, fs)
    span_log.enable()
    cb_on = run_curve_batched(base, fs)
    assert_bit_equal(cb_off.points, cb_on.points)
    assert cb_off.compile_count == cb_on.compile_count

    spans = span_log.snapshot()
    buckets = [s for s in spans if s.name.startswith("sweep.bucket[")]
    points = [s for s in spans if s.name.startswith("sweep.point[")]
    assert len(buckets) == cb_on.n_buckets
    assert len(points) == len(cb_on.points)
    eps = 1e-3
    all_point_flows = set()
    for b in buckets:
        children = [s for s in spans if s.parent_id == b.span_id]
        assert [s.name for s in children] == [
            "sweep.prepare", "sweep.compile", "sweep.execute",
            "sweep.fetch"]
        for c in children:
            assert c.start >= b.start - eps
            assert c.start + c.dur_s <= b.start + b.dur_s + eps
        # lifecycle stages are consecutive, in order
        for a, c in zip(children, children[1:]):
            assert c.start >= a.start + a.dur_s - eps
        assert len(b.flow_out) == b.args["size"]
    for p in points:
        assert p.track == "sweep.points"
        assert len(p.flow_in) == 1
        all_point_flows.add(p.flow_in[0])
    # 1:1 flow resolution: every bucket-emitted flow id terminates at
    # exactly one point span
    emitted = {fid for b in buckets for fid in b.flow_out}
    assert emitted == all_point_flows
    assert len(all_point_flows) == len(points)

    # the Perfetto export renders the arrows as s/f pairs
    from benor_tpu.utils.metrics import export_chrome_trace
    out = tmp_path / "sweep_trace.json"
    export_chrome_trace(str(out), spans=True)
    events = json.load(open(out))["traceEvents"]
    flows_s = [e for e in events if e.get("ph") == "s"]
    flows_f = [e for e in events if e.get("ph") == "f"]
    assert {e["id"] for e in flows_s} == emitted
    assert {e["id"] for e in flows_f} == emitted


# --------------------------------------------------------------------------
# manifest: schema + cross-field pins, pipeline model, builder guards
# --------------------------------------------------------------------------


def _load_schema_tool():
    import importlib.util
    spec = importlib.util.spec_from_file_location("_cms", SCHEMA_TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_pipeline_model_bounds():
    one = [{"prepare_s": 0.1, "compile_s": 2.0, "run_s": 1.0,
            "fetch_s": 0.2}]
    # a single bucket cannot overlap with itself
    assert ideal_pipeline_s(one) == pytest.approx(serial_s(one))
    two = one + [{"prepare_s": 0.1, "compile_s": 2.0, "run_s": 3.0,
                  "fetch_s": 0.2}]
    ideal = ideal_pipeline_s(two)
    assert ideal < serial_s(two)
    # bucket 2's prepare+compile (2.1s, host) overlaps bucket 1's
    # execute+fetch (1.2s, device+drain): host finishes at 4.2, the
    # device then runs bucket 2 for 3.0 and its fetch drains 0.2 ->
    # ideal 7.4 of the 8.6 serial, headroom = the hidden 1.2
    assert ideal == pytest.approx(4.2 + 3.0 + 0.2)
    assert serial_s(two) - ideal == pytest.approx(1.2)


def test_manifest_schema_valid_and_cross_field(mixed_runs):
    cfg, jp, cb, _ = mixed_runs
    tool = _load_schema_tool()
    manifest = build_sweep_manifest(cb, cfg)
    assert tool.check_sweep_manifest(manifest) == []

    # hand-edited headroom cannot survive the recompute
    bad = copy.deepcopy(manifest)
    bad["overlap_headroom_s"] = bad["overlap_headroom_s"] + 1.0
    assert any("overlap_headroom_s" in e
               for e in tool.check_sweep_manifest(bad))
    # neither can a drifted stage total
    bad = copy.deepcopy(manifest)
    bad["stage_totals"]["compile_s"] += 1.0
    assert any("stage_totals.compile_s" in e
               for e in tool.check_sweep_manifest(bad))
    # point indices must partition the point set
    bad = copy.deepcopy(manifest)
    bad["buckets"][1]["point_indices"] = list(
        bad["buckets"][0]["point_indices"])
    bad["buckets"][1]["size"] = len(bad["buckets"][1]["point_indices"])
    assert any("partition" in e for e in tool.check_sweep_manifest(bad))
    # compile_count must sum the bucket counts
    bad = copy.deepcopy(manifest)
    bad["compile_count"] += 1
    assert any("compile_count" in e
               for e in tool.check_sweep_manifest(bad))
    # telescoping coverage is recomputed, not trusted
    bad = copy.deepcopy(manifest)
    bad["telescoping"]["coverage"] = 0.2
    assert any("coverage" in e for e in tool.check_sweep_manifest(bad))


def test_manifest_builder_refuses_resumed_curve(mixed_runs):
    cfg, jp, cb_off, _ = mixed_runs
    cb = run_curve_batched(cfg, MIXED_FS, journal_path=jp, resume=True)
    with pytest.raises(ValueError, match="resumed"):
        build_sweep_manifest(cb, cfg)


def test_committed_baseline_schema_autodetect_and_self_gate():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, SCHEMA_TOOL, BASELINE],
                          capture_output=True, text=True, env=env,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "sweep manifest OK" in proc.stdout
    proc = subprocess.run([sys.executable, GATE_TOOL, BASELINE],
                          capture_output=True, text=True, env=env,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --------------------------------------------------------------------------
# gate: exit codes + finding semantics
# --------------------------------------------------------------------------


def _baseline():
    with open(BASELINE) as fh:
        return json.load(fh)


def test_gate_in_band_on_identical_manifests():
    m = _baseline()
    assert compare_sweep(m, m) == []


def test_gate_flags_serialized_pipeline_regression():
    m = _baseline()
    bad = copy.deepcopy(m)
    bad["overlap_headroom_frac"] = 0.6
    findings = compare_sweep(bad, m)
    assert any("serialized-pipeline" in f.message for f in findings)


def test_gate_flags_vanished_headroom_and_compile_creep():
    m = _baseline()
    bad = copy.deepcopy(m)
    del bad["overlap_headroom_frac"]
    bad["compile_count"] = m["compile_count"] + 3
    metrics = {f.metric for f in compare_sweep(bad, m)}
    assert "overlap_headroom_frac" in metrics
    assert "compile_count" in metrics


def test_gate_flags_broken_telescoping():
    m = _baseline()
    bad = copy.deepcopy(m)
    bad["telescoping"]["coverage"] = 0.3
    assert any(f.metric == "telescoping.coverage"
               for f in compare_sweep(bad, m))


def test_gate_incomparable_on_platform_and_scale():
    m = _baseline()
    other = copy.deepcopy(m)
    other["platform"] = "definitely-not-" + str(m["platform"])
    with pytest.raises(IncomparableSweep, match="platform"):
        compare_sweep(other, m)
    other = copy.deepcopy(m)
    other["scale"] = dict(other["scale"], n_nodes=123)
    with pytest.raises(IncomparableSweep, match="scale"):
        compare_sweep(other, m)


def test_gate_cli_exit_codes(tmp_path):
    """The CI contract end-to-end: 0 in-band, 2 on the injected
    serialized-pipeline regression fixture, 3 on platform mismatch."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    m = _baseline()

    regressed = copy.deepcopy(m)
    regressed["overlap_headroom_frac"] = 0.6
    rp = tmp_path / "regressed.json"
    rp.write_text(json.dumps(regressed))
    proc = subprocess.run([sys.executable, GATE_TOOL, str(rp), BASELINE],
                          capture_output=True, text=True, env=env,
                          timeout=60)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "serialized-pipeline" in proc.stdout

    foreign = copy.deepcopy(m)
    foreign["platform"] = "tpu-from-another-lab"
    fp = tmp_path / "foreign.json"
    fp.write_text(json.dumps(foreign))
    proc = subprocess.run([sys.executable, GATE_TOOL, str(fp), BASELINE],
                          capture_output=True, text=True, env=env,
                          timeout=60)
    assert proc.returncode == 3, proc.stdout + proc.stderr

    missing = subprocess.run(
        [sys.executable, GATE_TOOL, str(rp),
         str(tmp_path / "nope.json"), "--strict"],
        capture_output=True, text=True, env=env, timeout=60)
    assert missing.returncode == 3


# --------------------------------------------------------------------------
# watch: mixed-kind tailing
# --------------------------------------------------------------------------


def test_watch_renders_mixed_kinds_and_survives_torn_tail(tmp_path,
                                                          capsys):
    from benor_tpu.__main__ import main
    p = tmp_path / "mixed.jsonl"
    lines = [
        json.dumps({"kind": "heartbeat", "label": "sweep",
                    "round": None, "max_rounds": 8,
                    "rounds_per_sec": None, "decided_frac": None,
                    "eta_s": None, "progress": 0.5, "points_done": 1,
                    "points_total": 3, "elapsed_s": 0.1,
                    "done": False}),
        json.dumps({"kind": "sweep_bucket", "label": "sweep",
                    "bucket_index": 0, "bucket_kind": "dyn",
                    "point_indices": [0, 1, 2],
                    "fingerprint": "sha256:x", "compile_count": 1,
                    "prepare_s": 0.1, "compile_s": 2.0, "run_s": 0.3,
                    "fetch_s": 0.01, "points": []}),
        json.dumps({"kind": "mystery_kind", "payload": 7}),
        json.dumps([1, 2, 3]),
    ]
    p.write_text("\n".join(lines) + "\n" + '{"kind": "sweep_bu')
    assert main(["watch", str(p), "--no-follow"]) == 0
    out_lines = capsys.readouterr().out.strip().splitlines()
    assert len(out_lines) == 4          # the torn tail line is skipped
    assert "points=1/3" in out_lines[0]
    assert "bucket 0 (dyn, 3 pts)" in out_lines[1]
    assert "compile=2.00s" in out_lines[1]
    assert "mystery_kind" in out_lines[2]      # unknown kind: raw
    assert out_lines[3] == "[1, 2, 3]"         # non-dict JSON: raw


def test_watch_stops_on_sweep_done(tmp_path, capsys):
    from benor_tpu.__main__ import main
    p = tmp_path / "journal.jsonl"
    lines = [
        json.dumps({"kind": "sweep_bucket", "label": "sweep",
                    "bucket_index": 0, "bucket_kind": "static",
                    "point_indices": [0], "fingerprint": "sha256:x",
                    "compile_count": 1, "prepare_s": 0.0,
                    "compile_s": 1.0, "run_s": 0.1, "fetch_s": 0.0,
                    "points": []}),
        json.dumps({"kind": "sweep_done", "label": "sweep",
                    "done": True, "points_total": 1, "n_buckets": 1,
                    "buckets_reused": 0, "overlap_headroom_s": 0.0}),
        json.dumps({"kind": "heartbeat", "label": "after",
                    "done": False}),
    ]
    p.write_text("\n".join(lines) + "\n")
    # --timeout large: the done record must be what stops the tail
    assert main(["watch", str(p), "--timeout", "30"]) == 0
    out = capsys.readouterr().out
    assert "sweep complete: 1 points / 1 buckets" in out
    assert "DONE" in out
    assert "[after]" not in out        # tail stopped AT the done record


def test_watch_renders_atlas_records_and_keeps_going(tmp_path, capsys):
    """PR 20: an atlas search journal interleaves sweepscope bucket
    records with atlas_probe / atlas_cliff records and carries one
    sweep_done PER GENERATION — ``--keep-going`` tails past them, the
    kind-dispatched formatters render the atlas records, and the torn
    tail is still skipped."""
    from benor_tpu.__main__ import main
    p = tmp_path / "atlas.jsonl"
    lines = [
        json.dumps({"kind": "sweep_bucket", "label": "atlas",
                    "bucket_index": 0, "bucket_kind": "dyn",
                    "point_indices": [0, 1], "fingerprint": "sha256:x",
                    "compile_count": 1, "prepare_s": 0.0,
                    "compile_s": 1.0, "run_s": 0.1, "fetch_s": 0.0,
                    "points": []}),
        json.dumps({"kind": "atlas_probe", "axis": "f", "generation": 0,
                    "value": 7.0, "verdict": "decided",
                    "stall_frac": 0.0, "decided_frac": 1.0,
                    "rounds_executed": 2}),
        json.dumps({"kind": "sweep_done", "label": "atlas",
                    "done": True, "points_total": 2, "n_buckets": 1,
                    "buckets_reused": 0, "overlap_headroom_s": 0.0}),
        json.dumps({"kind": "atlas_cliff", "axis": "f", "generation": 1,
                    "metric": "stall_frac", "lo": 7.0, "hi": 8.0,
                    "width": 1.0, "point": 7.5,
                    "lo_verdict": "decided", "hi_verdict": "stalled",
                    "converged": True}),
    ]
    p.write_text("\n".join(lines) + "\n" + '{"kind": "atlas_pro')
    assert main(["watch", str(p), "--no-follow", "--keep-going"]) == 0
    out_lines = capsys.readouterr().out.strip().splitlines()
    assert len(out_lines) == 4          # the torn tail line is skipped
    assert "[atlas:f] gen=0 f=7.0 verdict=decided" in out_lines[1]
    assert "stall=0.000" in out_lines[1]
    assert "cliff [7.0, 8.0]" in out_lines[3]
    assert "decided->stalled" in out_lines[3]
    assert "CONVERGED" in out_lines[3]

    # without --keep-going the per-generation done record still stops
    # the tail — the atlas_cliff after it is never printed
    assert main(["watch", str(p), "--timeout", "30"]) == 0
    out = capsys.readouterr().out
    assert "cliff [7.0, 8.0]" not in out
