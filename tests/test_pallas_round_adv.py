"""Fused round kernels under the count-controlling adversaries.

ops/pallas_round.py counts_mode='delivered' (scheduler='adversarial') and
'camps' (scheduler='targeted'): the adversary's per-receiver counts are
CLOSED FORMS of the per-trial class histogram (tally.adversarial_counts /
targeted_camp_triples), so the fused kernels consume them as broadcast
scalars — no sampler runs at all.  That makes the fused path exactly as
deterministic as the XLA path given the same coin bits:

  * coin_mode='common' draws ONE shared bit per (trial, round) from the
    SAME XLA-side stream on both paths (rng.coin_flips keys on trial ids
    only), so a fused adversarial run is BIT-IDENTICAL to the unfused XLA
    run — these tests are exact pins, not statistical gates.
  * coin_mode='private'/'weak_common' use the in-kernel threefry streams
    (the pallas path's documented coherent alternative stream, as for the
    uniform regime) — per-lane coin bits differ from the XLA streams, so
    the pins cover the coin-free parts (decided/k/camp values) and the
    science-level behavior (termination transition), not raw x bits.

Reference for the adversary semantics: tally.adversarial_counts /
targeted_counts docstrings; the attack itself realizes the reference's
"first N-F arrivals win" nondeterminism (node.ts:52,88) worst case.
"""

import jax
import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.ops import tally
from benor_tpu.sim import resume_consensus, run_consensus
from benor_tpu.state import FaultSpec, init_state
from benor_tpu.sweep import balanced_inputs
from benor_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

N, T = 96, 8


def _cfg(use_round, **kw):
    base = dict(n_nodes=N, n_faulty=24, trials=T, delivery="quorum",
                scheduler="adversarial", coin_mode="common",
                path="histogram", max_rounds=12, seed=3,
                use_pallas_round=use_round)
    base.update(kw)
    return SimConfig(**base)


def _run(cfg, crash_rounds=None, seed=None):
    if cfg.fault_model in ("equivocate", "byzantine", "crash_at_round"):
        faults = FaultSpec.first_f(cfg, crash_rounds=crash_rounds)
    else:
        faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes), faults)
    r, fin = run_consensus(cfg, state, faults,
                           jax.random.key(cfg.seed if seed is None else seed))
    return int(r), fin, faults


def _assert_bit_identical(a, b):
    (ra, fa), (rb, fb) = a, b
    assert ra == rb
    np.testing.assert_array_equal(np.asarray(fa.x), np.asarray(fb.x))
    np.testing.assert_array_equal(np.asarray(fa.decided),
                                  np.asarray(fb.decided))
    np.testing.assert_array_equal(np.asarray(fa.k), np.asarray(fb.k))


@pytest.mark.parametrize("kw", [
    dict(),                                                # crash, tie camp
    dict(rule="textbook"),
    dict(freeze_decided=False),
    dict(fault_model="byzantine", n_faulty=20),
    dict(fault_model="equivocate", n_faulty=20),
    dict(fault_model="equivocate", n_faulty=33),           # 3F > N livelock
    dict(scheduler="targeted"),
    dict(scheduler="targeted", n_faulty=47),               # f just under N/2
    dict(scheduler="targeted", fault_model="equivocate", n_faulty=1),
], ids=["adv-crash", "adv-textbook", "adv-nofreeze", "adv-byzantine",
        "adv-equiv-sub3f", "adv-equiv-super3f", "targeted", "targeted-wide",
        "targeted-one-equivocator"])
@pytest.mark.slow
def test_fused_adv_bit_identical_common_coin(kw):
    """Common coin => both paths share every random bit => exact equality."""
    cfg0, cfg1 = _cfg(False, **kw), _cfg(True, **kw)
    assert not tally.pallas_round_active(cfg0)
    assert tally.pallas_round_active(cfg1)
    r0, f0, _ = _run(cfg0)
    r1, f1, _ = _run(cfg1)
    _assert_bit_identical((r0, f0), (r1, f1))


@pytest.mark.slow
def test_fused_adv_crash_at_round_bit_identical():
    cr = np.where(np.arange(N) < 20, 3, 0)
    kw = dict(fault_model="crash_at_round", n_faulty=20)
    r0, f0, _ = _run(_cfg(False, **kw), crash_rounds=cr)
    r1, f1, _ = _run(_cfg(True, **kw), crash_rounds=cr)
    _assert_bit_identical((r0, f0), (r1, f1))


@pytest.mark.slow
def test_fused_targeted_private_coin_decisions_exact():
    """The targeted camps' decisions are coin-free (their counts clear the
    bar before any coin fires), so decided/k — and the camp lanes' values —
    must match the XLA path exactly even though the private-coin streams
    differ; only the "?"-camp lanes' never-deciding x bits may diverge."""
    kw = dict(scheduler="targeted", coin_mode="private")
    r0, f0, _ = _run(_cfg(False, **kw))
    r1, f1, _ = _run(_cfg(True, **kw))
    assert r0 == r1
    np.testing.assert_array_equal(np.asarray(f0.decided),
                                  np.asarray(f1.decided))
    np.testing.assert_array_equal(np.asarray(f0.k), np.asarray(f1.k))
    size_v, _ = tally.targeted_camp_sizes(_cfg(True, **kw))
    camp = np.arange(N) >= N - 2 * size_v
    np.testing.assert_array_equal(np.asarray(f0.x)[:, camp],
                                  np.asarray(f1.x)[:, camp])
    # the attack itself: both camps decide, opposite values
    assert np.asarray(f1.decided)[:, camp].all()


@pytest.mark.slow
def test_fused_adv_weak_coin_transition_preserved():
    """The weak-coin termination transition (eps* = 1 - f) is a law of the
    DELIVERED COUNTS, not of the coin stream — the fused path's alternative
    deviator stream must reproduce it: decisive below eps*, livelocked
    above (f = 0.375 => eps* = 0.625; margins wide enough for N = 96)."""
    for eps, want in ((0.3, 1.0), (0.95, 0.0)):
        cfg = _cfg(True, coin_mode="weak_common", coin_eps=eps, n_faulty=36)
        assert tally.pallas_round_active(cfg)
        _, fin, faults = _run(cfg)
        healthy = ~np.asarray(faults.faulty)[0]
        frac = np.asarray(fin.decided)[:, healthy].mean()
        assert frac == want, (eps, frac)


@pytest.mark.slow
def test_fused_adv_private_livelock_aggregates():
    """Private coins against the count adversary: at a tie-proof scale the
    run must livelock on BOTH paths (decided stays 0 for every stream) —
    the classic Ben-Or contrast the bench's adv_private regime pins at
    N=1M.  N=512 keeps min(c0, c1) >= m/2 overwhelmingly likely per round
    (P(fail) ~ 1e-12), so 8 trials x 8 rounds are deterministic in
    practice."""
    kw = dict(coin_mode="private", n_nodes=512, n_faulty=128, max_rounds=8)
    r0, f0, _ = _run(_cfg(False, **kw))
    r1, f1, _ = _run(_cfg(True, **kw))
    assert r0 == r1 == 8
    assert not np.asarray(f0.decided).any()
    assert not np.asarray(f1.decided).any()
    np.testing.assert_array_equal(np.asarray(f0.k), np.asarray(f1.k))


@pytest.mark.slow
@pytest.mark.parametrize("kw", [
    dict(),
    dict(scheduler="targeted"),
    dict(fault_model="equivocate", n_faulty=20),
], ids=["adversarial", "targeted", "adv-equivocate"])
def test_fused_adv_mesh_bit_identical(kw):
    """The delivered/camps closed forms psum per-trial histograms and key
    every in-kernel stream on GLOBAL lane ids, so the fused adversarial
    run must be bit-identical across mesh shapes (the same contract the
    sampled mode pins in tests/test_pallas_round.py)."""
    from benor_tpu.parallel import make_mesh, run_consensus_sharded

    cfg = _cfg(True, **kw)
    r_ref, fin_ref, faults = _run(cfg)
    for mesh_shape in ((2, 2), (4, 1)):
        cfg_m = cfg.replace(mesh_shape=mesh_shape)
        state = init_state(cfg_m,
                           balanced_inputs(cfg.trials, cfg.n_nodes), faults)
        r, fin = run_consensus_sharded(cfg_m, state, faults,
                                       jax.random.key(cfg.seed),
                                       make_mesh(*mesh_shape))
        assert int(r) == r_ref, mesh_shape
        np.testing.assert_array_equal(np.asarray(fin.x),
                                      np.asarray(fin_ref.x))
        np.testing.assert_array_equal(np.asarray(fin.decided),
                                      np.asarray(fin_ref.decided))
        np.testing.assert_array_equal(np.asarray(fin.k),
                                      np.asarray(fin_ref.k))


@pytest.mark.slow
def test_fused_adv_checkpoint_resume_bit_identical(tmp_path):
    """Cut the fused adversarial run mid-flight, restore, resume: the
    spliced run must equal the uncut one bit-for-bit (the packed slice
    path's contract, now covering counts_mode='delivered')."""
    cfg = _cfg(True, fault_model="equivocate", n_faulty=20)
    faults = FaultSpec.first_f(cfg)
    state = init_state(cfg, balanced_inputs(T, N), faults)
    key = jax.random.key(cfg.seed)
    r_full, fin_full = run_consensus(cfg, state, faults, key)

    cut = cfg.replace(max_rounds=1)
    r_cut, fin_cut = run_consensus(cut, state, faults, key)
    path = str(tmp_path / "adv.npz")
    save_checkpoint(path, cfg, fin_cut, faults,
                    next_round=int(r_cut) + 1)
    cfg_r, st_r, faults_r, nxt, key_r = load_checkpoint(path)
    r_res, fin_res = resume_consensus(cfg_r, st_r, faults_r, key_r, nxt)
    assert int(r_full) == int(r_res)
    np.testing.assert_array_equal(np.asarray(fin_full.x),
                                  np.asarray(fin_res.x))
    np.testing.assert_array_equal(np.asarray(fin_full.decided),
                                  np.asarray(fin_res.decided))
    np.testing.assert_array_equal(np.asarray(fin_full.k),
                                  np.asarray(fin_res.k))


def test_targeted_counts_refactor_matches_triples():
    """targeted_counts == a camp-id gather of targeted_camp_triples (the
    refactor that lets the kernel select triples in-VMEM must not have
    changed the closed form)."""
    cfg = SimConfig(n_nodes=50, n_faulty=12, trials=4, delivery="quorum",
                    scheduler="targeted", path="histogram")
    hist = np.array([[20, 18, 0], [10, 10, 18], [38, 0, 0], [0, 0, 38]],
                    np.int32)
    node_ids = np.arange(50)
    full = np.asarray(tally.targeted_counts(cfg, hist, node_ids))
    trip = np.asarray(tally.targeted_camp_triples(cfg, hist))
    size_v, _ = tally.targeted_camp_sizes(cfg)
    camp = np.where(node_ids >= 50 - size_v, 1,
                    np.where(node_ids >= 50 - 2 * size_v, 0, 2))
    np.testing.assert_array_equal(full, trip[:, camp, :])
    assert (full.sum(-1) == cfg.quorum).all()


def test_pallas_round_active_adv_gating():
    """The fused-round predicate: engages for the count adversaries only
    with use_pallas_round + quorum delivery + a kernel-supported coin;
    never for biased (no closed form) or the weak endpoints."""
    base = dict(n_nodes=64, n_faulty=16, delivery="quorum",
                path="histogram", use_pallas_round=True)
    assert tally.pallas_round_active(SimConfig(scheduler="adversarial",
                                               **base))
    assert tally.pallas_round_active(SimConfig(scheduler="targeted",
                                               **base))
    assert tally.pallas_round_counts_mode(
        SimConfig(scheduler="adversarial", **base)) == "delivered"
    assert tally.pallas_round_counts_mode(
        SimConfig(scheduler="targeted", **base)) == "camps"
    assert not tally.pallas_round_active(SimConfig(scheduler="biased",
                                                   **base))
    assert not tally.pallas_round_active(
        SimConfig(scheduler="adversarial", **{**base,
                                              "use_pallas_round": False}))
    assert not tally.pallas_round_active(
        SimConfig(scheduler="adversarial", coin_mode="weak_common",
                  coin_eps=1.0, **base))
    # adversarial + delivery='all' can't even be constructed (the config
    # layer rejects powerless scheduler/delivery combos), so the
    # predicate's quorum-delivery clause is belt-and-braces
    with pytest.raises(ValueError, match="has no effect"):
        SimConfig(scheduler="adversarial", **{**base, "delivery": "all"})


@pytest.mark.slow
def test_fused_adv_poll_rounds_bit_identical():
    """Mid-run observability over the fused adversarial loop: slicing the
    packed while-loop (SimConfig.poll_rounds) must reproduce the one-shot
    run bit-for-bit — the TpuNetwork polling contract, now covering
    counts_mode='delivered'."""
    from benor_tpu.api import launch_network

    k_seen = []
    nets = []
    for poll in (0, 2):
        net = launch_network(
            N, 24, [i % 2 for i in range(N)],
            [True] * 24 + [False] * (N - 24),
            trials=1, delivery="quorum", scheduler="adversarial",
            coin_mode="common", path="histogram", max_rounds=12,
            use_pallas_round=True, poll_rounds=poll)
        if poll:
            net.start(on_slice=lambda n=net: k_seen.append(
                max(s["k"] or 0 for s in n.get_states())))
        else:
            net.start()
        nets.append(net)
    assert nets[0].get_states() == nets[1].get_states()
    assert k_seen, "poller must observe at least one mid-run snapshot"
