"""benor-serve (benor_tpu/serve) — the request plane's tier-1 suite.

Four layers, mirroring the subsystem's contract:

  * THE HOUSE RULE: a job submitted through the serve plane returns
    results bit-equal to the same SimConfig run through
    ``sweep.run_point`` directly, and steady-state serving adds ZERO
    new XLA compiles (pinned via utils/compile_counter — the same
    discipline as the recorder/witness/heartbeat off-switches).
  * BATCH PLANE: coalescing (many jobs, fewer launches), round-robin
    fairness (a bucket-mismatched job never blocks an in-flight
    batch), cancelled slots freed, capacity-rung reuse.
  * FAILURE PATHS over real sockets: malformed JobSpec -> 400 with a
    structured error body, client disconnect mid-SSE frees the batch
    slot, unknown routes/jobs -> 404.
  * ARTIFACTS: the serve manifest passes the pinned schema
    (tools/serve_manifest_schema.json) and the regression gate honours
    its 0/2/3 exit contract against doctored baselines.

Everything runs at smoke scale (N<=64, T<=8) on CPU; the batcher is
driven SYNCHRONOUSLY (``Batcher(start=False)`` + ``step()``) wherever
determinism matters, with the real threaded server used for the
socket-level tests.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import time

import numpy as np
import pytest

from benor_tpu.config import SimConfig
from benor_tpu.serve import (Batcher, IncomparableServe, JobError,
                             JobSpec, ServeApp, compare_serve,
                             serve_bucket_key)
from benor_tpu.sweep import run_point
from benor_tpu.utils.compile_counter import count_backend_compiles

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import check_metrics_schema  # noqa: E402
import check_serve_regression  # noqa: E402

#: The dyn-bucket smoke spec every batching test coalesces on
#: (delivery='all' + crash + uniform has no quorum-specialized shapes).
SPEC = {"kind": "simulate", "n_nodes": 16, "n_faulty": 2, "trials": 4,
        "max_rounds": 8, "delivery": "all", "seed": 3}


def _drain(batcher, deadline_s: float = 30.0) -> int:
    n = 0
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        got = batcher.step()
        if not got:
            break
        n += got
    return n


# --------------------------------------------------------------------------
# the house rule: bit-equality + zero steady-state compiles
# --------------------------------------------------------------------------


def test_serve_result_bit_equal_to_run_point():
    """Jobs with DIFFERENT f and seed coalesce into one launch, and each
    slot's summary is bit-equal to run_point on the identical config —
    floats compared with ==, not approx."""
    b = Batcher(start=False)
    variants = [dict(SPEC), {**SPEC, "seed": 11, "n_faulty": 1},
                {**SPEC, "seed": 7, "n_faulty": 5}]
    jobs = [j for v in variants for j in b.submit_dict(v)]
    assert len({j.bucket for j in jobs}) == 1      # one shared bucket
    assert _drain(b) == 3
    assert b.launches == 1                         # ONE coalesced launch
    for job, v in zip(jobs, variants):
        cfg = SimConfig(n_nodes=v["n_nodes"], n_faulty=v["n_faulty"],
                        trials=v["trials"], max_rounds=v["max_rounds"],
                        delivery="all", seed=v["seed"])
        pt = run_point(cfg)
        r = job.result
        assert job.state == "done"
        assert r["rounds_executed"] == pt.rounds_executed
        assert r["decided_frac"] == pt.decided_frac
        assert r["mean_k"] == pt.mean_k
        assert r["ones_frac"] == pt.ones_frac
        assert r["disagree_frac"] == pt.disagree_frac
        assert r["k_hist"] == pt.k_hist.tolist()


def test_steady_state_serving_adds_zero_compiles():
    """After the warm-up launch, further same-bucket traffic — including
    a PARTIAL batch, which must reuse a larger warm rung padded rather
    than compile a tighter one — runs with 0 backend compiles."""
    b = Batcher(start=False)
    for s in range(4):
        b.submit_dict({**SPEC, "seed": 20 + s})
    _drain(b)                                      # warm: capacity-4 rung
    warm_executors = len(b._pool)
    with count_backend_compiles() as cc:
        for s in range(4):
            b.submit_dict({**SPEC, "seed": 30 + s})
        _drain(b)
        for s in range(3):                         # partial batch of 3
            b.submit_dict({**SPEC, "seed": 40 + s})
        _drain(b)
    assert cc.count == 0, "steady-state serving must not compile"
    assert len(b._pool) == warm_executors          # no new rungs either
    assert b.jobs_completed == 11


def test_trajectory_job_streams_round_rows_bit_equal_to_recorder():
    """kind=trajectory arms the flight recorder; the streamed rows match
    run_point(record=True)'s recorder rows exactly, cursor semantics
    included."""
    from benor_tpu.utils.metrics import round_history_rows

    b = Batcher(start=False)
    spec = {**SPEC, "kind": "trajectory", "seed": 5}
    job = b.submit_dict(spec)[0]
    _drain(b)
    rows = [p for (t, p) in job.events if t == "round"]
    cfg = SimConfig(n_nodes=SPEC["n_nodes"], n_faulty=SPEC["n_faulty"],
                    trials=SPEC["trials"], max_rounds=SPEC["max_rounds"],
                    delivery="all", seed=5, record=True)
    want = round_history_rows(run_point(cfg).round_history)
    assert rows == want
    assert rows[0]["round"] == 0                   # the /start snapshot


def test_audit_job_carries_clean_verdict():
    b = Batcher(start=False)
    job = b.submit_dict({**SPEC, "kind": "audit"})[0]
    _drain(b)
    assert job.state == "done"
    assert job.result["audit"]["ok"] is True
    assert any(t == "witness" for (t, _p) in job.events)


def test_sweep_job_expands_to_coalesced_points():
    """One sweep job = one batch slot per f value, all in one bucket,
    each point bit-equal to the per-point oracle."""
    b = Batcher(start=False)
    jobs = b.submit_dict({"kind": "sweep", "n_nodes": 16, "trials": 4,
                          "max_rounds": 8, "delivery": "all", "seed": 2,
                          "f_values": [0, 2, 4]})
    assert [j.spec.n_faulty for j in jobs] == [0, 2, 4]
    _drain(b)
    assert b.launches == 1
    for job in jobs:
        cfg = SimConfig(n_nodes=16, n_faulty=job.spec.n_faulty, trials=4,
                        max_rounds=8, delivery="all", seed=2)
        assert job.result["mean_k"] == run_point(cfg).mean_k


# --------------------------------------------------------------------------
# batch plane: fairness, cancellation, bucketing
# --------------------------------------------------------------------------


def test_bucket_mismatched_job_never_blocks_in_flight_batch():
    """A job whose static shape mismatches the queued batch gets its own
    launch on the next round-robin turn — submitting it must not stall
    or join the other bucket's executable."""
    b = Batcher(start=False)
    a_jobs = [b.submit_dict({**SPEC, "seed": s})[0] for s in (1, 2)]
    mismatched = b.submit_dict({**SPEC, "n_nodes": 24, "seed": 9})[0]
    assert mismatched.bucket != a_jobs[0].bucket
    first = b.step()
    second = b.step()
    assert sorted((first, second)) == [1, 2]       # two separate launches
    assert mismatched.state == "done"
    assert all(j.state == "done" for j in a_jobs)
    assert b.launches == 2
    # and the mismatched result is still oracle-exact
    cfg = SimConfig(n_nodes=24, n_faulty=2, trials=4, max_rounds=8,
                    delivery="all", seed=9)
    assert mismatched.result["mean_k"] == run_point(cfg).mean_k


def test_cancelled_job_frees_its_batch_slot():
    b = Batcher(start=False)
    keep = b.submit_dict({**SPEC, "seed": 1})[0]
    gone = b.submit_dict({**SPEC, "seed": 2})[0]
    assert gone.cancel() is True
    assert gone.state == "cancelled"
    assert b.step() == 1                           # only the live slot ran
    assert keep.state == "done"
    assert gone.result is None
    assert b.jobs_completed == 1


def test_jobspec_from_config_round_trips():
    """results.py's serve_replay provenance hook: from_config -> wire
    dict -> from_dict -> to_config reproduces the SimConfig exactly."""
    cfg = SimConfig(n_nodes=16, n_faulty=2, trials=4, max_rounds=8,
                    delivery="all", seed=3)
    spec = JobSpec.from_config(cfg)
    assert spec.to_config() == cfg
    assert JobSpec.from_dict(spec.to_dict()).to_config() == cfg
    assert JobSpec.from_config(cfg.replace(record=True)).kind \
        == "trajectory"


def test_seed_is_erased_from_the_bucket_key():
    cfg_a = SimConfig(n_nodes=16, n_faulty=2, trials=4, delivery="all",
                      seed=1)
    cfg_b = cfg_a.replace(seed=999)
    assert serve_bucket_key(cfg_a) == serve_bucket_key(cfg_b)
    assert serve_bucket_key(cfg_a) != serve_bucket_key(
        cfg_a.replace(trials=8))


def test_quorum_specialized_config_gets_static_bucket():
    """A dense-path quorum config is quorum-specialized: capacity-1
    static bucket, classic dispatch, still oracle-exact and warm across
    seeds."""
    b = Batcher(start=False)
    spec = {"kind": "simulate", "n_nodes": 16, "n_faulty": 3, "trials": 4,
            "max_rounds": 8, "delivery": "quorum", "seed": 4}
    j1 = b.submit_dict(spec)[0]
    assert j1.bucket[0] == "static"
    _drain(b)
    cfg = SimConfig(n_nodes=16, n_faulty=3, trials=4, max_rounds=8,
                    delivery="quorum", seed=4)
    assert j1.result["mean_k"] == run_point(cfg).mean_k
    with count_backend_compiles() as cc:
        j2 = b.submit_dict({**spec, "seed": 77})[0]
        _drain(b)
    assert cc.count == 0                           # warm across seeds
    assert j2.state == "done"


# --------------------------------------------------------------------------
# JobSpec validation -> structured 400s
# --------------------------------------------------------------------------


@pytest.mark.parametrize("doc,field", [
    ([1, 2], "$"),
    ({"kind": "nope"}, "kind"),
    ({"n_nodes": "ten"}, "n_nodes"),
    ({"n_nodes": True}, "n_nodes"),
    ({"trials": 0}, "trials"),
    ({"n_nodes": 1 << 20}, "n_nodes"),
    ({"seed": -1}, "seed"),
    ({"bogus_knob": 1}, "bogus_knob"),
    ({"kind": "sweep"}, "f_values"),
    ({"kind": "sweep", "f_values": [1, "x"]}, "f_values"),
    ({"kind": "simulate", "f_values": [1]}, "f_values"),
    ({"n_nodes": 8, "n_faulty": 9}, "config"),
    ({"delivery": "all", "scheduler": "adversarial"}, "config"),
])
def test_jobspec_validation_is_structured(doc, field):
    with pytest.raises(JobError) as ei:
        JobSpec.from_dict(doc)
    assert ei.value.body["error"] == "invalid job"
    assert ei.value.body["field"] == field
    assert ei.value.body["reason"]


# --------------------------------------------------------------------------
# the wire: real sockets against a live ServeApp
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def app():
    with ServeApp(max_batch_jobs=8) as a:
        yield a


def _request(app, payload: bytes, read_until=None,
             timeout: float = 60.0) -> bytes:
    s = socket.create_connection((app.host, app.port), timeout=timeout)
    try:
        s.sendall(payload)
        chunks = b""
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks += b
            if read_until and read_until in chunks:
                break
    finally:
        s.close()
    return chunks


def _post(app, doc, stream: bool = False, query: str = "",
          read_until=None) -> bytes:
    body = json.dumps(doc).encode()
    q = ("?stream=sse" if stream else "") + query
    return _request(
        app,
        f"POST /v1/jobs{q} HTTP/1.1\r\nHost: x\r\n"
        f"Content-Length: {len(body)}\r\n\r\n".encode() + body,
        read_until=read_until)


def _status_and_json(resp: bytes):
    head, _, body = resp.partition(b"\r\n\r\n")
    return int(head.split()[1]), json.loads(body)


def test_http_malformed_jobspec_is_a_structured_400(app):
    code, body = _status_and_json(_post(app, {"kind": "bogus"}))
    assert code == 400
    assert body["error"] == "invalid job" and body["field"] == "kind"
    # non-JSON body: same structured shape
    raw = b"not json"
    code, body = _status_and_json(_request(
        app, b"POST /v1/jobs HTTP/1.1\r\nHost: x\r\n"
             b"Content-Length: %d\r\n\r\n" % len(raw) + raw))
    assert code == 400 and body["field"] == "$"


def test_http_submit_stream_and_poll(app):
    resp = _post(app, {**SPEC, "seed": 50}, stream=True,
                 read_until=b"event: done")
    assert resp.startswith(b"HTTP/1.1 200")
    assert b"text/event-stream" in resp
    assert b"event: result" in resp
    # the result event carries the summary payload
    for line in resp.split(b"\n"):
        if line.startswith(b"data: ") and b"rounds_executed" in line:
            payload = json.loads(line[len(b"data: "):])
            break
    else:
        raise AssertionError("no result payload in stream")
    cfg = SimConfig(n_nodes=16, n_faulty=2, trials=4, max_rounds=8,
                    delivery="all", seed=50)
    assert payload["mean_k"] == run_point(cfg).mean_k
    # 202 + poll path
    code, body = _status_and_json(_post(app, {**SPEC, "seed": 51}))
    assert code == 202 and len(body["jobs"]) == 1
    job_id = body["jobs"][0]
    deadline = time.time() + 30
    while time.time() < deadline:
        code, snap = _status_and_json(_request(
            app, f"GET /v1/jobs/{job_id} HTTP/1.1\r\nHost: x"
                 f"\r\n\r\n".encode()))
        if snap["state"] == "done":
            break
        time.sleep(0.05)
    assert snap["result"]["job"] == job_id


def test_http_sse_since_round_cursor(app):
    """?since_round=N filters round rows at/below the cursor — the
    /getRoundHistory contract, pushed over SSE."""
    full = _post(app, {**SPEC, "kind": "trajectory", "seed": 52},
                 stream=True, read_until=b"event: done")
    rounds_full = [int(line.split(b": ")[1]) for line in full.split(b"\n")
                   if line.startswith(b"id: ")]
    assert rounds_full and rounds_full[0] == 0
    resumed = _post(app, {**SPEC, "kind": "trajectory", "seed": 52},
                    stream=True, query="&since_round=0",
                    read_until=b"event: done")
    rounds_res = [int(line.split(b": ")[1]) for line in resumed.split(b"\n")
                  if line.startswith(b"id: ")]
    assert rounds_res == [r for r in rounds_full if r > 0]


def test_http_client_disconnect_mid_sse_frees_the_slot(app):
    """Open the SSE stream, read the headers, slam the connection before
    the batch runs: the job must end cancelled (slot freed), and the
    plane must keep serving other clients."""
    before = app.batcher.jobs_submitted
    doc = json.dumps({**SPEC, "seed": 60,
                      "max_rounds": 8}).encode()
    s = socket.create_connection((app.host, app.port), timeout=30)
    s.sendall(f"POST /v1/jobs?stream=sse HTTP/1.1\r\nHost: x\r\n"
              f"Content-Length: {len(doc)}\r\n\r\n".encode() + doc)
    # wait for the queued event so the job exists server-side
    buf = b""
    while b"event: queued" not in buf:
        buf += s.recv(4096)
    job_id = json.loads(
        [ln for ln in buf.split(b"\n") if ln.startswith(b"data: ")][-1]
        [len(b"data: "):])["job"]
    s.close()                                      # the disconnect
    job = app.batcher.get(job_id)
    deadline = time.time() + 30
    while time.time() < deadline and not job.done:
        time.sleep(0.02)
    assert job.state in ("cancelled", "done")
    if job.state == "done":
        # raced the batcher: the launch had already claimed the slot —
        # legal, but the orphan result must not leak to anyone
        assert job.result is not None
    assert app.batcher.jobs_submitted == before + 1
    # the plane still serves
    code, _ = _status_and_json(_request(
        app, b"GET /healthz HTTP/1.1\r\nHost: x\r\n\r\n"))
    assert code == 200


def test_http_unknown_routes_and_stats(app):
    code, _ = _status_and_json(_request(
        app, b"GET /nope HTTP/1.1\r\nHost: x\r\n\r\n"))
    assert code == 404
    code, _ = _status_and_json(_request(
        app, b"GET /v1/jobs/nope HTTP/1.1\r\nHost: x\r\n\r\n"))
    assert code == 404
    code, stats = _status_and_json(_request(
        app, b"GET /v1/stats HTTP/1.1\r\nHost: x\r\n\r\n"))
    assert code == 200
    assert stats["jobs_completed"] >= 1
    assert any(d["label"].startswith("serve.bucket.")
               for d in stats["executors_detail"])


# --------------------------------------------------------------------------
# artifacts: manifest schema + gate exit codes
# --------------------------------------------------------------------------


def _stage(p50, p99, mean) -> dict:
    return {"p50": p50, "p99": p99, "mean": mean}


def _manifest(**over) -> dict:
    stages = {"validate": _stage(1.0, 2.0, 1.0),
              "enqueue": _stage(0.1, 0.2, 0.1),
              "queue_wait": _stage(20.0, 40.0, 20.0),
              "batch_assemble": _stage(5.0, 10.0, 5.0),
              "launch": _stage(8.0, 15.0, 8.0),
              "result_slice": _stage(1.0, 2.0, 1.0),
              "stream_out": _stage(5.0, 10.0, 5.0)}
    # stage means sum to 40.1 of the 45.0 ms client mean: coverage
    # 0.8911, inside the 0.25 attribution band
    attribution = {"jobs_timed": 100, "stage_mean_sum_ms": 40.1,
                   "client_mean_ms": 45.0,
                   "coverage": round(40.1 / 45.0, 4),
                   "band": 0.25, "ok": True}
    m = {"kind": "serve_manifest", "schema_version": 2, "platform": "cpu",
         "device_kind": "cpu", "clients": 100, "jobs_submitted": 100,
         "jobs_completed": 100, "errors": 0, "duration_s": 1.5,
         "latency_ms": {"p50": 40.0, "p99": 90.0, "mean": 45.0,
                        "max": 95.0},
         "throughput_jobs_per_sec": 66.6, "launches": 5,
         "jobs_per_launch": 20.0, "executor_compiles": 2,
         "stages": stages, "attribution": attribution,
         "scale": {"n_nodes": 32, "n_faulty": 4, "trials": 8,
                   "max_rounds": 16, "delivery": "all",
                   "kind": "simulate"}}
    m.update(over)
    return m


def test_serve_manifest_schema_and_cross_fields():
    assert check_metrics_schema.check_serve_manifest(_manifest()) == []
    errs = check_metrics_schema.check_serve_manifest(
        _manifest(jobs_per_launch=3.0))
    assert any("jobs_completed/launches" in e for e in errs)
    errs = check_metrics_schema.check_serve_manifest(_manifest(
        latency_ms={"p50": 99.0, "p99": 50.0, "mean": 60.0, "max": 99.0}))
    assert any("percentiles out of order" in e for e in errs)
    errs = check_metrics_schema.check_serve_manifest(
        _manifest(kind="scaling_manifest"))
    assert errs                                    # wrong kind rejected


def test_committed_baseline_is_schema_valid():
    with open(os.path.join(REPO, "SERVE_BASELINE.json")) as fh:
        base = json.load(fh)
    assert check_metrics_schema.check_serve_manifest(base) == []
    assert base["clients"] >= 1000                 # the acceptance scale
    assert base["jobs_per_launch"] > 1.0
    assert base["errors"] == 0


def test_gate_rules_and_exit_codes(tmp_path):
    base = _manifest()
    # in-band
    assert compare_serve(_manifest(), base) == []
    # coalescing collapse = the worst finding
    fs = compare_serve(_manifest(jobs_per_launch=1.0,
                                 launches=100), base)
    assert any("per-job dispatch" in f.message for f in fs)
    # band regression
    fs = compare_serve(_manifest(jobs_per_launch=10.0,
                                 launches=10), base)
    assert any("jobs_per_launch" == f.metric for f in fs)
    # client errors always gate
    fs = compare_serve(_manifest(errors=3, jobs_completed=97,
                                 jobs_per_launch=19.4), base)
    assert {f.metric for f in fs} >= {"errors", "jobs_completed"}
    # timing only under an explicit band
    slow = _manifest(throughput_jobs_per_sec=1.0,
                     latency_ms={"p50": 4000.0, "p99": 9000.0,
                                 "mean": 4500.0, "max": 9500.0})
    assert compare_serve(slow, base) == []
    assert compare_serve(slow, base, timing_band=0.5)
    # incomparable: platform / scale / fewer clients
    for bad in (_manifest(platform="tpu"),
                _manifest(scale={**_manifest()["scale"], "n_nodes": 64}),
                _manifest(clients=10)):
        with pytest.raises(IncomparableServe):
            compare_serve(bad, base)
    # the CLI contract end to end: 0 / 2 / 3
    mp, bp = str(tmp_path / "m.json"), str(tmp_path / "b.json")
    with open(bp, "w") as fh:
        json.dump(base, fh)
    with open(mp, "w") as fh:
        json.dump(_manifest(), fh)
    assert check_serve_regression.main([mp, bp]) == 0
    with open(mp, "w") as fh:
        json.dump(_manifest(jobs_per_launch=1.0, launches=100), fh)
    assert check_serve_regression.main([mp, bp]) == 2
    with open(mp, "w") as fh:
        json.dump(_manifest(platform="tpu"), fh)
    assert check_serve_regression.main([mp, bp]) == 3
    missing = str(tmp_path / "nope.json")
    assert check_serve_regression.main([mp, missing]) == 0
    assert check_serve_regression.main([mp, missing, "--strict"]) == 3


def test_committed_baseline_gates_itself():
    """The committed SERVE_BASELINE.json must be in-band against itself
    through the real CLI — the exact command the acceptance runs."""
    path = os.path.join(REPO, "SERVE_BASELINE.json")
    assert check_serve_regression.main([path, path]) == 0


@pytest.mark.slow
def test_loadgen_smoke_end_to_end():
    """A small real load run: concurrent SSE clients against an
    in-process server -> schema-valid manifest, zero errors, coalescing
    above 1 (the acceptance shape at smoke scale)."""
    from benor_tpu.serve import run_load

    m = run_load(clients=40, timeout=90,
                 job={**SPEC, "n_nodes": 32, "n_faulty": 4, "trials": 8,
                      "max_rounds": 16})
    assert check_metrics_schema.check_serve_manifest(m) == []
    assert m["errors"] == 0
    assert m["jobs_completed"] == 40
    assert m["jobs_per_launch"] > 1.0
