"""One process of the multi-host test cluster (tests/test_multihost.py).

Each worker is a REAL OS process with its own JAX runtime and 4 virtual CPU
devices; jax.distributed + Gloo collectives tie the processes into one
cluster, exactly as hosts of a TPU pod slice would be tied over DCN.  The
worker runs the flagship consensus loop over the process-spanning
('trials', 'nodes') mesh on both compute paths and asserts bit-identity
with a single-process single-device run — the SURVEY §7 hard-part-5
guarantee (results independent of mesh shape) extended across process
boundaries.

Not a pytest module (no test_ prefix): invoked as
    python tests/multihost_worker.py <process_id> <num_processes> <port>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    # Platform forcing BEFORE jax import (same dance as tests/conftest.py:
    # the axon TPU plugin overrides JAX_PLATFORMS, the config update wins).
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=4")
    os.environ["XLA_FLAGS"] = " ".join(flags)

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.experimental import multihost_utils

    from benor_tpu.config import SimConfig
    from benor_tpu.parallel.multihost import (global_mesh, init_multihost,
                                              local_block,
                                              resume_consensus_multihost,
                                              run_consensus_multihost,
                                              run_consensus_slice_multihost,
                                              to_global)
    from benor_tpu.sim import run_consensus, start_state
    from benor_tpu.state import FaultSpec, init_state

    init_multihost(f"localhost:{port}", num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == 4 * nproc, jax.device_count()

    # Default layout: trials across processes (DCN), nodes across each
    # process's local devices (ICI).
    mesh = global_mesh()
    T, N = 4, 32

    for path in ("dense", "histogram"):
        cfg = SimConfig(n_nodes=N, n_faulty=8, trials=T, delivery="quorum",
                        scheduler="uniform", path=path, max_rounds=16, seed=3)
        faulty = np.zeros(N, bool)
        faulty[:cfg.n_faulty] = True
        faults = FaultSpec.from_faulty_list(cfg, faulty)
        full = init_state(cfg, np.tile((np.arange(N) % 2).astype(np.int8),
                                       (T, 1)), faults)
        base_key = jax.random.key(cfg.seed)

        # single-process baseline on this process's device 0
        r1, f1 = run_consensus(cfg, full, faults, base_key)

        # multi-host run: build ONLY this process's slab, assemble globals
        def assemble(m):
            tr, nd = local_block(m, T, N)
            sl = lambda a: np.asarray(a)[tr, nd]
            return (to_global(jax.tree.map(sl, full), m, (T, N)),
                    to_global(jax.tree.map(sl, faults), m, (T, N)))

        def assert_leaves_equal(fin, label):
            for leaf in ("x", "decided", "k", "killed"):
                got = np.asarray(multihost_utils.process_allgather(
                    getattr(fin, leaf), tiled=True))
                np.testing.assert_array_equal(
                    got, np.asarray(getattr(f1, leaf)),
                    err_msg=f"{label}:{leaf}")

        gstate, gfaults = assemble(mesh)
        r, fin = run_consensus_multihost(cfg, gstate, gfaults, base_key, mesh)
        assert_leaves_equal(fin, "default-mesh")
        assert int(r) == int(r1), (int(r), int(r1))
        print(f"worker{pid}[{path}]: mesh="
              f"({mesh.shape['trials']}x{mesh.shape['nodes']}) "
              f"procs={nproc} rounds={int(r)} "
              f"bit-identical vs single-process OK", flush=True)

        if path == "histogram":
            # the PATHOLOGICAL layout: the node axis spanning both
            # processes, so the per-round histogram psum rides the
            # cross-host (DCN) link.  Wrong for performance, but the
            # result must still be bit-identical — layout never affects
            # semantics (global-id RNG keys).
            mesh_x = global_mesh(trial_shards=1)
            gx_state, gx_faults = assemble(mesh_x)
            rx, finx = run_consensus_multihost(cfg, gx_state, gx_faults,
                                               base_key, mesh_x)
            assert_leaves_equal(finx, "xhost-nodes")
            assert int(rx) == int(r1)
            print(f"worker{pid}[xhost-nodes]: mesh=(1x{4 * nproc}) "
                  f"node-psum across processes bit-identical OK", flush=True)

            # checkpoint re-entry across hosts: cut the run at round 2,
            # resume from round 3 — cut + resume must equal the
            # uninterrupted run bitwise (randomness keys on (key, round,
            # phase, global ids), never loop history)
            r_cut, fin_cut = run_consensus_multihost(
                cfg.replace(max_rounds=2), gstate, gfaults, base_key, mesh)
            assert int(r_cut) == 2, int(r_cut)
            r_res, fin_res = resume_consensus_multihost(
                cfg, fin_cut, gfaults, base_key, mesh,
                from_round=int(r_cut) + 1)
            for leaf in ("x", "decided", "k", "killed"):
                got = np.asarray(multihost_utils.process_allgather(
                    getattr(fin_res, leaf), tiled=True))
                np.testing.assert_array_equal(
                    got, np.asarray(getattr(f1, leaf)), err_msg=leaf)
            assert int(r_res) == int(r1), (int(r_res), int(r1))
            print(f"worker{pid}[resume]: cut@2 + resume == uninterrupted "
                  f"(rounds={int(r_res)}) OK", flush=True)

            # sliced mid-run observability across hosts (r5): every
            # process steps the loop in 2-round slices SPMD-style; the
            # replicated next_round keeps hosts in lockstep, snapshots
            # are observable between slices, and the final state equals
            # the uninterrupted run bitwise
            st = start_state(cfg, gstate)
            r_s, snapshots = 1, 0
            while True:
                r_next, st = run_consensus_slice_multihost(
                    cfg, st, gfaults, base_key, mesh, r_s, r_s + 2)
                snapshots += 1
                rn = int(r_next)
                ks = np.asarray(multihost_utils.process_allgather(
                    st.k, tiled=True))
                if rn == r_s or rn > cfg.max_rounds or bool(np.asarray(
                        (st.decided | st.killed).all())):
                    break
                assert ks.max() <= rn, (ks.max(), rn)  # live snapshot sane
                r_s = rn
            for leaf in ("x", "decided", "k", "killed"):
                got = np.asarray(multihost_utils.process_allgather(
                    getattr(st, leaf), tiled=True))
                np.testing.assert_array_equal(
                    got, np.asarray(getattr(f1, leaf)), err_msg=leaf)
            assert rn - 1 == int(r1), (rn - 1, int(r1))
            print(f"worker{pid}[sliced]: {snapshots} slices, final bit-"
                  f"identical vs uninterrupted (rounds={rn - 1}) OK",
                  flush=True)

    # round-4 paths across REAL process boundaries: the targeted
    # (partitioned) adversary's closed form and the fully-fused round
    # kernels — both must stay bit-identical when the mesh spans hosts
    from benor_tpu.ops import sampling

    extra = [
        ("targeted", dict(scheduler="targeted"), None),
        ("fused-round", dict(use_pallas_hist=True, use_pallas_round=True),
         4),
        # r5: the fused ADVERSARIAL round (counts_mode='delivered' — the
        # closed-form tied tallies broadcast in-VMEM, no sampler): its
        # per-trial histogram psum + shared-coin stream must survive the
        # process-spanning mesh bit-for-bit
        ("adv-fused-round", dict(scheduler="adversarial",
                                 coin_mode="common",
                                 use_pallas_round=True), None),
    ]
    for label, overrides, table_max in extra:
        old_tm = sampling.EXACT_TABLE_MAX
        try:
            if table_max is not None:
                sampling.EXACT_TABLE_MAX = table_max
            kw = dict(n_nodes=N, n_faulty=8, trials=T, delivery="quorum",
                      scheduler="uniform", path="histogram", max_rounds=16,
                      seed=9)
            kw.update(overrides)
            cfg = SimConfig(**kw)
            faults = FaultSpec.none(T, N)
            full = init_state(cfg, np.tile((np.arange(N) % 2)
                                           .astype(np.int8), (T, 1)), faults)
            base_key = jax.random.key(cfg.seed)
            r1, f1 = run_consensus(cfg, full, faults, base_key)
            gstate, gfaults = assemble(mesh)
            r, fin = run_consensus_multihost(cfg, gstate, gfaults,
                                             base_key, mesh)
            for leaf in ("x", "decided", "k", "killed"):
                got = np.asarray(multihost_utils.process_allgather(
                    getattr(fin, leaf), tiled=True))
                np.testing.assert_array_equal(
                    got, np.asarray(getattr(f1, leaf)),
                    err_msg=f"{label}:{leaf}")
            assert int(r) == int(r1)
            print(f"worker{pid}[{label}]: cross-process bit-identical OK",
                  flush=True)
        finally:
            sampling.EXACT_TABLE_MAX = old_tm

    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
