#!/usr/bin/env python3
"""On-chip recapture daemon — "wedged round" insurance.

The axon TPU tunnel wedges for hours at a time (round 3: 8+ h; round 4:
the ENTIRE round — every deliverable shipped with CPU/interpret-mode
numbers only).  This loop turns "the chip came back at 3am" into a
captured artifact with no human in the loop:

  probe (subprocess, 120 s timeout)  ──down──>  sleep, retry forever
        │ live
        v
  snapshot committed HEAD into a git worktree  (.capture/wt — live edits
        │                                       in the main tree can't
        v                                       contaminate the capture)
  python bench.py          -> BENCH_TPU.json + BENCH_DETAIL.json (repo root)
  python -m benor_tpu results -> RESULTS/      (N=1M x 32 on the chip)
        │
        v
  record the captured sha; keep watching — a NEW commit triggers a fresh
  capture (so features landed after the chip returns still get on-chip
  evidence), an unchanged HEAD just idles.

Artifacts are written into the MAIN repo root but never committed by the
daemon (committing would race the human's index); the round driver
commits stragglers at round end.

Usage:  python recapture.py [--once] [--interval 240] [--no-results]
Logs:   .capture/recapture.log (tail -f it), state in .capture/state.json
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import shutil
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
CAP = os.path.join(HERE, ".capture")
WT = os.path.join(CAP, "wt")
STATE = os.path.join(CAP, "state.json")
LOGF = os.path.join(CAP, "recapture.log")

#: Generous per-stage budgets: a cold N=1M bench is ~17 regimes + 5 kernel
#: checks of ~10-40 s remote compiles each (measured 9 min cold on v5
#: lite).  Results is the long pole: ~45 study configs at ~60-90 s of
#: REMOTE compile each when the cache is cold — the 2026-07-31 attempt
#: was still compiling at 57 min when the tunnel wedged — so its budget
#: is 2 h; the persistent cache makes any retry resume roughly where the
#: last attempt died.
BENCH_TIMEOUT = 4200
RESULTS_TIMEOUT = 7200


def log(msg: str) -> None:
    line = f"[{datetime.datetime.now():%H:%M:%S}] {msg}"
    print(line, flush=True)
    try:
        with open(LOGF, "a") as fh:
            fh.write(line + "\n")
    except OSError:
        pass


def _git(*args: str, cwd: str = HERE) -> str:
    r = subprocess.run(["git", *args], cwd=cwd, capture_output=True,
                       text=True, check=True)
    return r.stdout.strip()


def head_sha() -> str:
    return _git("rev-parse", "HEAD")


def load_state() -> dict:
    try:
        with open(STATE) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def save_state(st: dict) -> None:
    os.makedirs(CAP, exist_ok=True)
    with open(STATE, "w") as fh:
        json.dump(st, fh, indent=1)


def refresh_worktree(sha: str) -> None:
    """Detached worktree at ``sha``; shares the main repo's compile cache
    via symlink so the capture benefits from (and re-warms) one cache."""
    os.makedirs(CAP, exist_ok=True)
    if not os.path.isdir(os.path.join(WT, ".git")) and \
            not os.path.isfile(os.path.join(WT, ".git")):
        if os.path.isdir(WT):
            # half-created worktree (daemon killed mid-add): 'git worktree
            # add' would refuse forever — clear the carcass and prune the
            # stale registration first
            shutil.rmtree(WT, ignore_errors=True)
            subprocess.run(["git", "worktree", "prune"], cwd=HERE,
                           capture_output=True)
        subprocess.run(["git", "worktree", "add", "--detach", WT, sha],
                       cwd=HERE, check=True, capture_output=True)
    else:
        # -f: bench.py writes its tracked BENCH_DETAIL.json sidecar into
        # the worktree, which would otherwise block every later checkout
        _git("checkout", "-f", "--detach", sha, cwd=WT)
    cache_link = os.path.join(WT, ".jax_cache")
    main_cache = os.path.join(HERE, ".jax_cache")
    os.makedirs(main_cache, exist_ok=True)
    if not os.path.islink(cache_link):
        if os.path.isdir(cache_link):
            shutil.rmtree(cache_link)
        os.symlink(main_cache, cache_link)
    # native oracle builds on first use, but do it eagerly for clean logs
    subprocess.run(["make", "-C", os.path.join(WT, "native")],
                   capture_output=True)


def probe(timeout_s: float = 120.0) -> str | None:
    sys.path.insert(0, HERE)
    try:
        from benor_tpu.utils.backend import probe_backend
    finally:
        sys.path.pop(0)
    return probe_backend(timeout_s, log=lambda s: log(f"probe: {s}"))


def run_bench(sha: str) -> bool:
    """bench.py in the worktree; promote artifacts only for a REAL
    on-chip run (platform tpu-ish, no mid-run CPU fallback)."""
    log(f"bench: starting at {sha[:10]} (budget {BENCH_TIMEOUT}s)")
    env = {**os.environ, "BENCH_INIT_RETRIES": "2",
           "BENCH_PROBE_TIMEOUT": "120"}
    env.pop("BENCH_ALLOW_CPU", None)
    try:
        r = subprocess.run([sys.executable, "bench.py"], cwd=WT, env=env,
                           capture_output=True, text=True,
                           timeout=BENCH_TIMEOUT)
    except subprocess.TimeoutExpired:
        log("bench: TIMED OUT (tunnel likely wedged mid-run); will retry")
        return False
    tail = "\n".join((r.stderr or "").strip().splitlines()[-3:])
    if r.returncode != 0:
        log(f"bench: rc={r.returncode}\n{tail}")
        return False
    line = (r.stdout or "").strip().splitlines()[-1:]
    try:
        out = json.loads(line[0]) if line else None
    except ValueError:
        out = None
    if not isinstance(out, dict) or "metric" not in out:
        log(f"bench: rc=0 but final stdout line is not the emit() JSON "
            f"({line[:1]!r}); not promoting")
        return False
    plat = out.get("platform", "?")
    if out.get("fallback_cpu") or plat == "cpu" or out.get("error"):
        log(f"bench: completed but NOT on-chip (platform={plat}, "
            f"fallback={out.get('fallback_cpu')}, "
            f"error={out.get('error')!r}); not promoting")
        return False
    out["capture"] = {"sha": sha,
                      "utc": datetime.datetime.now(datetime.timezone.utc)
                      .isoformat(timespec="seconds")
                      .replace("+00:00", "Z")}
    with open(os.path.join(HERE, "BENCH_TPU.json"), "w") as fh:
        json.dump(out, fh, indent=1)
    detail = os.path.join(WT, "BENCH_DETAIL.json")
    if os.path.exists(detail):
        shutil.copy2(detail, os.path.join(HERE, "BENCH_DETAIL.json"))
    log(f"bench: CAPTURED on {plat}: value={out.get('value')} "
        f"{out.get('unit')} (vs_baseline={out.get('vs_baseline')})")
    return True


def run_results(sha: str) -> bool:
    """'benor_tpu results' into a STAGING dir, promoted to the main repo's
    RESULTS/ only after the on-chip honesty check — a mid-run CPU fallback
    must never overwrite previously captured on-chip artifacts."""
    log(f"results: starting at {sha[:10]} (budget {RESULTS_TIMEOUT}s)")
    stage = os.path.join(CAP, "RESULTS.stage")
    shutil.rmtree(stage, ignore_errors=True)
    try:
        r = subprocess.run(
            [sys.executable, "-m", "benor_tpu", "results", "--out", stage],
            cwd=WT, capture_output=True, text=True, timeout=RESULTS_TIMEOUT)
    except subprocess.TimeoutExpired:
        log("results: TIMED OUT; will retry")
        return False
    tail = "\n".join(((r.stdout or "") + (r.stderr or ""))
                     .strip().splitlines()[-4:])
    if r.returncode != 0:
        log(f"results: rc={r.returncode}\n{tail}")
        return False
    # honesty check: the artifact must say it ran on the accelerator
    try:
        with open(os.path.join(stage, "results.json")) as fh:
            meta = json.load(fh).get("meta", {})
    except (OSError, ValueError, AttributeError):
        meta = {}
    if not isinstance(meta, dict):       # {"meta": "tpu"}-style corruption
        meta = {}
    # FAIL CLOSED: promotion requires a parseable artifact that
    # affirmatively claims an accelerator — a missing/corrupt
    # results.json (meta == {}) or an absent platform string must never
    # overwrite a previously captured on-chip RESULTS/
    plat = str(meta.get("platform") or "")
    if not plat or "cpu" in plat.lower():
        log(f"results: artifact platform={plat!r} — not a verifiable "
            f"on-chip run, not promoting")
        return False
    out_dir = os.path.join(HERE, "RESULTS")
    shutil.rmtree(out_dir, ignore_errors=True)
    shutil.move(stage, out_dir)
    log(f"results: CAPTURED (platform={plat!r}, n={meta.get('n_large')})")
    return True


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--once", action="store_true",
                    help="one probe+capture attempt, then exit")
    ap.add_argument("--interval", type=float, default=240.0,
                    help="seconds between probes while the tunnel is down")
    ap.add_argument("--idle-interval", type=float, default=600.0,
                    help="seconds between HEAD re-checks after a capture")
    ap.add_argument("--no-results", action="store_true")
    args = ap.parse_args()

    log(f"recapture daemon up (pid {os.getpid()})")
    while True:
        st = load_state()
        sha = head_sha()
        done_bench = st.get("bench_sha") == sha
        done_results = args.no_results or st.get("results_sha") == sha
        if done_bench and done_results:
            if args.once:
                log("nothing to do (HEAD already captured)")
                return 0
            time.sleep(args.idle_interval)
            continue
        plat = probe()
        if plat is None or plat == "cpu":
            log(f"tunnel down (probe={plat!r}); "
                f"next probe in {args.interval:.0f}s")
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        log(f"tunnel LIVE (platform={plat}) — capturing {sha[:10]}")
        try:
            refresh_worktree(sha)
        except subprocess.CalledProcessError as e:
            log(f"worktree refresh failed: {e.stderr or e}")
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        if not done_bench and run_bench(sha):
            st["bench_sha"] = sha
            save_state(st)
        if not done_results and run_results(sha):
            st["results_sha"] = sha
            save_state(st)
        if args.once:
            ok = (st.get("bench_sha") == sha and
                  (args.no_results or st.get("results_sha") == sha))
            return 0 if ok else 1
        time.sleep(30)


if __name__ == "__main__":
    sys.exit(main())
