"""Protocol models. The flagship (and the reference's only protocol) is Ben-Or."""

from .benor import all_settled, benor_round

__all__ = ["all_settled", "benor_round"]
