"""The Ben-Or round kernel (SURVEY.md N3) — one pure function per round.

Reproduces, lane-vectorized over [trials, nodes], the exact semantics of the
reference's ``/message`` handler (src/nodes/node.ts:43-163), including the
behavioral quirks the reference tests co-evolved with (SURVEY §2.1):

  * quorum gate counts raw messages INCLUDING "?" (node.ts:52,88 — quirk 4),
  * phase-1 majority with tie -> "?" (node.ts:63-69),
  * phase-2 decide when count(v) > F (node.ts:99-104),
  * plurality-adopt before the coin (node.ts:106-112 — quirk 9; the
    'textbook' rule flag removes this branch),
  * broadcasts include self (node.ts:72,149,173 — quirk 6),
  * faulty crash nodes never send (killed at birth, node.ts:21-26).

Everything is branch-free jnp.where masking: static shapes, no Python
control flow, fuses into a handful of XLA kernels per round.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import SimConfig, VAL0, VAL1, VALQ
from ..ops import rng, tally
from ..ops.collectives import SINGLE, ShardCtx
from ..state import DynParams, FaultSpec, NetState


def _flip(x: jax.Array) -> jax.Array:
    """Byzantine bit-flip: 0 <-> 1, "?" unchanged."""
    return jnp.where(x == VAL0, jnp.int8(VAL1),
                     jnp.where(x == VAL1, jnp.int8(VAL0), jnp.int8(VALQ)))


def _sent_values(cfg: SimConfig, x: jax.Array, faults: FaultSpec) -> jax.Array:
    """What each lane broadcasts: byzantine lanes flip their value."""
    if cfg.fault_model == "byzantine":
        return jnp.where(faults.faulty, _flip(x), x)
    return x


def benor_round(cfg: SimConfig, state: NetState, faults: FaultSpec,
                base_key: jax.Array, r: jax.Array,
                ctx: ShardCtx = SINGLE,
                dyn: Optional[DynParams] = None,
                recorder: Optional[jax.Array] = None,
                witness: Optional[jax.Array] = None):
    """Advance every lane by one full Ben-Or round (proposal + vote phase).

    ``r`` is the 1-based round index; matches the reference's message ``k``.
    Under a mesh, ``state``/``faults`` hold this shard's [T_loc, N_loc]
    blocks and ``ctx`` names the mesh axes; tallies psum over ICI and RNG
    keys derive from global ids, so results are bit-identical to the
    single-device run regardless of mesh shape.

    ``recorder`` (flight-recorder buffer, state.new_recorder, or None)
    makes this round write its telemetry row (state.REC_* columns,
    psum-globalized under a mesh) at index ``r`` and changes the return
    to ``(new_state, new_recorder)``; with None (every record=False
    caller) the return is the plain NetState and the trace is untouched.
    The recorder only REDUCES values the round already computes — no
    random stream moves — so recorded results are bit-identical to
    unrecorded ones.

    ``witness`` (witness buffer, state.new_witness, or None) makes this
    round write its per-node forensic row (state.WIT_* columns — value,
    decided/killed bits, coin-commit bit, and the proposal/vote tallies
    that justified the transition — for every watched (trial, node),
    psum-globalized under a mesh) at index ``r`` and appends the new
    buffer to the return, after the recorder when both ride.  Like the
    recorder, the witness only REDUCES values the round already computes,
    so witnessed results are bit-identical to unwitnessed ones.

    Structured delivery (benor_tpu/topo): with ``cfg.topology`` set the
    tallies come from each receiver's d+1 graph neighborhood
    (tally.receiver_counts dispatches to topo/deliver.py), and with
    ``cfg.committee_cap`` from this round's sampled committee — whose
    membership is drawn ONCE below and masks ``active`` so
    non-participants sit the round out with frozen state.  The decide
    logic is unchanged either way: count > F, now read against the
    neighborhood/committee tally.

    ``dyn`` (DynParams or None) supplies F and the quorum as TRACED
    scalars for the batched dynamic-F sweep (sweep.run_curve_batched) —
    plus the committee count/size axes for the topo sweeps:
    with it, one compiled round loop serves every fault count whose
    static shape/mode matches ``cfg`` — the decide thresholds, quorum
    gate, closed-form adversaries and CF samplers all take the traced
    values.  ``dyn=None`` (every classic caller) is the unchanged static
    path, bit-for-bit.  Quorum-specialized regimes (exact-table sampler,
    dense top-k masks, pallas kernels — sweep.quorum_specialized) must
    pass dyn=None.
    """
    T, N = state.x.shape
    F = cfg.n_faulty if dyn is None else dyn.n_faulty
    m = cfg.quorum if dyn is None else dyn.quorum

    if tally.pallas_round_active(cfg):
        if dyn is not None:
            raise ValueError(
                "dynamic-F tracing cannot drive the fused pallas round "
                "(kernels bake the quorum into their closures); bucket "
                "such configs statically (sweep.quorum_specialized)")
        # Fully-fused round (r3 VERDICT item 2, relaid in PR 8): the round
        # runs as pallas kernels over BIT-PLANE packed state
        # (state.PACK_LAYOUT — x/decided/killed/coin-commit/faulty bits +
        # k planes at 32 nodes per uint32 word) with the decide/adopt/
        # coin/commit chain in-kernel — no [T,N,3] counts, x1, or coin
        # tensor ever reaches HBM, and on a single device the whole round
        # is ONE kernel pass (pallas_round.fused_round_pallas).
        # Bit-identical to the unfused pallas path (same streams),
        # mesh-safe (global-id offsets + psum'd partials).  This
        # per-round wrapper packs/unpacks at the round boundary; the
        # single-device runner (sim.run_consensus) instead carries the
        # plane stack through the whole loop (pallas_round.run_packed).
        # state.killed is packed PRE-crash-update: the kernels (and
        # sent_hist_from_pack) re-derive killed_now from crash_round + r,
        # matching the XLA path's start-of-round update below.
        from ..ops import pallas_round as pr
        pack = pr.pack_state(cfg, state, faults.faulty)
        np_total = pack.shape[2] * pr.PACK_NODES_PER_WORD
        cr, rec = pr.pad_fault_rounds(cfg, faults, np_total)
        hist1 = pr.sent_hist_from_pack(cfg, pack, cr, rec, r, ctx)
        # [:5] — under cfg.kernel_telemetry packed_round appends the
        # per-tile stage counters; this per-round wrapper has no run
        # accumulator to add them to (the packed loop carries one), so
        # the per-round increment is dropped here by design
        new_pack, _, _, row, wrow = pr.packed_round(
            cfg, pack, faults, base_key, r, hist1, ctx, N)[:5]
        new_state = pr.unpack_state(new_pack, N)
        extras = []
        if recorder is not None:
            from ..state import recorder_write
            extras.append(recorder_write(recorder, r, row))
        if witness is not None:
            from ..state import witness_write
            extras.append(witness_write(witness, r, wrow))
        return (new_state, *extras) if extras else new_state

    # --- crash-at-round fault injection (start of round) -----------------
    killed = state.killed
    x_cur = state.x
    down = None
    if cfg.fault_model == "crash_at_round":
        crashing = faults.faulty & (faults.crash_round > 0) & \
            (r >= faults.crash_round)
        killed = killed | crashing
    elif cfg.fault_model == "crash_recover":
        # Down-intervals (benor_tpu/faults/recovery.py): a lane whose
        # schedule never rejoins (recover_round <= 0) latches ``killed``
        # exactly like crash_at_round; a lane inside
        # [crash_round, recover_round) is DOWN for this round only —
        # liveness re-derives from the bounds every round (never loop
        # history), so sliced/resumed runs stay bit-identical.
        if faults.recover_round is None:
            raise ValueError(
                "fault_model='crash_recover' needs FaultSpec."
                "recover_round (build the spec via "
                "faults.recovery.crash_recover_faults or "
                "FaultSpec.from_faulty_list(..., recover_rounds=...))")
        cr, rr = faults.crash_round, faults.recover_round
        started = faults.faulty & (cr > 0) & (r >= cr)
        killed = killed | (started & (rr <= 0))          # never rejoins
        down = started & (rr > 0) & (r < rr)
        if cfg.recovery is not None:
            from ..faults.recovery import rejoin_mode
            if rejoin_mode(cfg.recovery) == "amnesia":
                # the volatile x did not survive the crash: an UNDECIDED
                # rejoiner restarts from "?" at its first round back
                # (decisions are durable, written before the decide is
                # announced — irrevocability holds across recovery).
                # cr > 0 guards lanes with a recover bound but no crash
                # (a spec hand-built past from_faulty_list): no crash,
                # nothing to forget
                rejoin_now = faults.faulty & (cr > 0) & (rr > 0) & \
                    (r == rr) & ~state.decided
                x_cur = jnp.where(rejoin_now, jnp.int8(VALQ), x_cur)

    alive = ~killed                                          # senders this round
    if down is not None:
        alive = alive & ~down
    n_alive = ctx.psum_nodes(
        jnp.sum(alive, axis=-1, dtype=jnp.int32))            # [T] global
    # Quorum gate: a tally only ever fires if >= N-F messages can arrive
    # (node.ts:52,88). With fewer live senders the whole trial stalls forever,
    # exactly like reference receivers waiting for fetches that never come.
    quorum_ok = (n_alive >= m)[:, None]                      # [T, 1]

    # Lanes that actually run the round logic: alive, trial has quorum, and
    # (unless freeze_decided is off) not already decided — quirk 5 handling.
    frozen = state.decided & cfg.freeze_decided
    active = alive & quorum_ok & ~frozen

    # Committee delivery (benor_tpu/topo/committees.py): sample this
    # round's membership ONCE (both phases tally the same committees) and
    # sit non-participants out — their state, k included, freezes for the
    # round, and their broadcast goes silent (the senders mask below).
    # count/size ride DynParams on the batched path, so a committee
    # size/count curve shares one executable.
    member = com_id = None
    if cfg.committee_cap:
        from ..topo import committees
        g = cfg.committee_count if dyn is None else dyn.committee_count
        csz = cfg.committee_size if dyn is None else dyn.committee_size
        member, com_id = committees.membership(
            cfg, base_key, r, ctx.trial_ids(T), ctx.node_ids(N), g, csz)
        active = active & member

    # --- phase 1: "proposal phase" (node.ts:46-82) -----------------------
    # Dense sharded path AND the topology gather path: gather the
    # (round-constant) alive mask once for both phases instead of once
    # per tally.  Equivocators (alive, per-receiver random/adversarial
    # values) ride the same prefetch.
    gather_masks = tally.dense_gather_needed(cfg) or \
        cfg.topology is not None
    alive_g = ctx.all_gather_nodes(alive) if gather_masks else None
    equiv = faults.faulty if cfg.fault_model == "equivocate" else None
    equiv_g = ctx.all_gather_nodes(equiv) \
        if (gather_masks and equiv is not None) else None
    # global live-equivocator count: round-constant, hoisted so the
    # histogram path keeps its one-psum-per-phase collective budget
    n_equiv = ctx.psum_nodes(
        jnp.sum(equiv & alive, axis=-1, dtype=jnp.int32)) \
        if equiv is not None else None
    sent1 = _sent_values(cfg, x_cur, faults)
    if member is not None:
        cnt1 = committees.committee_counts(cfg, sent1, alive & member,
                                           com_id, ctx)
    else:
        cnt1 = tally.receiver_counts(cfg, base_key, r, rng.PHASE_PROPOSAL,
                                     sent1, alive, ctx, alive_g,
                                     equiv, equiv_g, n_equiv, dyn)  # [T, N, 3]
    p0, p1 = cnt1[..., 0], cnt1[..., 1]
    # majority -> value, tie -> "?" (node.ts:63-69)
    x1 = jnp.where(p0 > p1, jnp.int8(VAL0),
                   jnp.where(p1 > p0, jnp.int8(VAL1), jnp.int8(VALQ)))

    # --- phase 2: "voting phase" (node.ts:83-158) ------------------------
    # A live undecided lane votes its phase-1 result; a frozen decided lane
    # keeps vouching for its decided value (the reference's decided nodes keep
    # broadcasting forever, node.ts:147-157 — freezing the lane must not
    # starve its peers' quorums).
    vote_val = jnp.where(frozen, x_cur, x1)
    sent2 = _sent_values(cfg, vote_val, faults)
    if member is not None:
        cnt2 = committees.committee_counts(cfg, sent2, alive & member,
                                           com_id, ctx)
    else:
        cnt2 = tally.receiver_counts(cfg, base_key, r, rng.PHASE_VOTE,
                                     sent2, alive, ctx, alive_g,
                                     equiv, equiv_g, n_equiv, dyn)
    v0, v1 = cnt2[..., 0], cnt2[..., 1]

    # --- faultlab per-lane quorum gate (benor_tpu/faults, PR 15) ---------
    # Omission / partitions make the DELIVERED count per-receiver random
    # (thinned) or group-bounded: a receiver that clears fewer than the
    # quorum N - F messages in either phase stalls this round — the
    # reference's node waiting on fetches that never arrive
    # (node.ts:52,88), now per lane instead of per trial.  The gate
    # governs COMMITS only: a stalled lane's phase-2 broadcast (built
    # from its sub-quorum phase-1 tally above) still reaches its peers
    # this round — the round-synchronous approximation the framework
    # has ALWAYS made (under quorum delivery, too, every alive lane
    # broadcasts both phases regardless of what its scheduler
    # delivered; the reference's blocked node would stay silent).
    # Modeling per-lane send-side coupling would make the phase-2
    # histogram a per-receiver random variable with cross-lane
    # dependencies — intractable in the O(N) closed forms — so the
    # approximation is documented rather than hidden (README "Fault &
    # adversary matrix").  Under an adjacency topology the wait bar
    # relativizes like the decide rule: d + 1 - F of the d + 1
    # neighborhood (the complete graph's N - F of N, degree-scaled).
    # Static gate: injection off never traces this, so off stays
    # bit-identical.
    if cfg.drop_prob or cfg.partition is not None:
        if cfg.topology is not None:
            from ..topo.graphs import parse_topology
            bar = parse_topology(cfg.topology).degree + 1 - F
        else:
            bar = m
        cleared = (jnp.sum(cnt1, axis=-1) >= bar) & \
            (jnp.sum(cnt2, axis=-1) >= bar)
        active = active & cleared

    decide0 = v0 > F                                         # node.ts:99
    decide1 = v1 > F                                         # node.ts:102
    if cfg.coin_mode == "weak_common":
        if tally.pallas_stream_active(cfg) and 0.0 < cfg.coin_eps < 1.0:
            # fused weak-coin kernel (private bits + deviation mask in
            # VMEM); the per-trial shared bit stays XLA-side.  Endpoints
            # fall through to the XLA helper, which short-circuits them
            # to the plain common/private streams.
            from ..ops.pallas_hist import weak_coin_flips_pallas
            # node axis passed as a 1-wide placeholder (rng.ids(1), NOT a
            # shard-dependent id): the common branch keys on trial ids
            # only, and the bit must be identical on every node shard
            shared = rng.coin_flips(base_key, r, ctx.trial_ids(T),
                                    rng.ids(1), common=True)[:, 0]
            coin = weak_coin_flips_pallas(
                base_key, r, T, N, cfg.coin_eps, shared,
                interpret=jax.default_backend() == "cpu",
                node_offset=ctx.node_ids(N)[0],
                trial_offset=ctx.trial_ids(T)[0])
        else:
            coin = rng.weak_common_coin_flips(base_key, r, ctx.trial_ids(T),
                                              ctx.node_ids(N), cfg.coin_eps)
    elif tally.pallas_stream_active(cfg) and cfg.coin_mode == "private":
        # One threefry block per lane in VMEM instead of the chained
        # fold_in pipeline — switches together with the sampler kernel so
        # use_pallas_hist selects ONE coherent alternative stream
        # (statistically identical; KS-gated in tests/test_pallas_hist.py).
        from ..ops.pallas_hist import coin_flips_pallas
        coin = coin_flips_pallas(
            base_key, r, T, N, interpret=jax.default_backend() == "cpu",
            node_offset=ctx.node_ids(N)[0],
            trial_offset=ctx.trial_ids(T)[0])
    else:
        coin = rng.coin_flips(base_key, r, ctx.trial_ids(T),
                              ctx.node_ids(N),
                              common=(cfg.coin_mode == "common"))
    if cfg.rule == "reference":
        # plurality-adopt before coin (node.ts:106-112)
        any_votes = (v0 + v1) > 0
        adopt0 = any_votes & (v0 > v1)
        adopt1 = any_votes & (v0 < v1)
        x2 = jnp.where(decide0, jnp.int8(VAL0),
             jnp.where(decide1, jnp.int8(VAL1),
             jnp.where(adopt0, jnp.int8(VAL0),
             jnp.where(adopt1, jnp.int8(VAL1), coin))))
    else:  # textbook: coin whenever no value exceeds F votes
        x2 = jnp.where(decide0, jnp.int8(VAL0),
             jnp.where(decide1, jnp.int8(VAL1), coin))

    newly_decided = active & (decide0 | decide1)

    # --- commit (node.ts:100-103, 147) -----------------------------------
    new_x = jnp.where(active, x2, x_cur)
    new_decided = state.decided | newly_decided
    # k <- k+1 after the vote tally, unconditionally for lanes that ran the
    # round — including the round in which they decide (node.ts:147 runs
    # after the decide branch), so a lane deciding in round r reports k=r+1.
    new_k = jnp.where(active, r + 1, state.k)

    new_state = NetState(x=new_x, decided=new_decided, k=new_k,
                         killed=killed)
    if recorder is None and witness is None:
        return new_state
    # lanes that COMMITTED a coin flip: ran the round, no decide and
    # (reference rule) no plurality-adopt — the same branch structure as
    # the x2 selection above; shared by the recorder and the witness
    no_decide = active & ~decide0 & ~decide1
    if cfg.rule == "reference":
        coined = no_decide & ~adopt0 & ~adopt1
    else:
        coined = no_decide
    extras = []
    if recorder is not None:
        from ..state import recorder_round_row, recorder_write
        margin = jnp.where(active, jnp.abs(v0 - v1), 0).astype(jnp.int32)
        row = recorder_round_row(new_x, new_decided, killed, coined,
                                 margin, ctx)
        extras.append(recorder_write(recorder, r, row))
    if witness is not None:
        from ..state import witness_round_row, witness_write
        wrow = witness_round_row(cfg, new_x, new_decided, killed, coined,
                                 cnt1[..., 0], cnt1[..., 1], v0, v1, ctx)
        extras.append(witness_write(witness, r, wrow))
    return (new_state, *extras)


def all_settled(state: NetState, ctx: ShardCtx = SINGLE) -> jax.Array:
    """True when every lane is decided or dead — the termination predicate
    replacing the reference's racy global-halt probe (node.ts:119-145).

    Under a mesh this is a psum of the per-shard unsettled count, so every
    shard agrees on termination (the while-loop carry stays replicated)."""
    unsettled = jnp.sum(~(state.decided | state.killed), dtype=jnp.int32)
    return ctx.psum_all(unsettled) == 0
