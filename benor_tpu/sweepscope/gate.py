"""Sweep regression gate: band-compare two sweep manifests.

STDLIB-ONLY by contract: ``tools/check_sweep_regression.py`` loads this
file BY PATH so a CI image can gate a sweep capture against the
committed SWEEP_BASELINE.json without initializing any JAX backend —
the same discipline as ``perfscope/baseline.py``,
``meshscope/scalegate.py`` and ``serve/gate.py`` (an import creep here
breaks that gate immediately).  ``tools/check_metrics_schema.py`` also
loads this file to RECOMPUTE the ideal-pipeline bound from a manifest's
per-bucket stages, so the cross-field check and the gate can never
disagree about what "headroom" means.

The pipeline model (``ideal_pipeline_s``): today ``sweep.
run_points_batched`` runs its buckets strictly serially — prepare,
compile, execute, fetch, next bucket — so the host sits idle while the
device executes and the device sits idle while the host compiles and
fetches.  The ideal compile-ahead/execute-behind pipeline overlaps
them: the host prepares+compiles bucket b+1 while the device executes
bucket b, and fetch/assembly drains off the critical path (an async
callback).  ``overlap_headroom_s = serial_s - ideal_pipeline_s`` is the
wall-clock that pipeline would reclaim — the before/after number
ROADMAP item 4's per-bucket async dispatch lands against.

What gates by default (structural, machine-insensitive):

  * ``overlap_headroom_frac``   headroom as a fraction of the serial
                                wall.  A manifest whose fraction GREW
                                past ``HEADROOM_BAND`` x baseline (over
                                the ``HEADROOM_FRAC_SLACK`` noise floor)
                                spends relatively more time with one
                                side idle — the sweep plane became MORE
                                serialized (the injected-regression
                                fixture shape).  A missing/non-numeric
                                headroom is the worst finding: the
                                attribution vanished.
  * ``compile_count``           more backend compiles than baseline at
                                the same scale means the bucketing
                                collapsed toward compile-per-point —
                                the regression the batched engine
                                exists to prevent.
  * ``telescoping.coverage``    the per-bucket stage clocks must
                                telescope to the sweep wall clock
                                (>= ``TELESCOPE_MIN``); a manifest whose
                                stages no longer account for the wall is
                                hiding where the time went.

Wall-clock metrics (``wall_s``) gate only under an explicit
``timing_band`` — shared CI machines make them noisy, exactly like the
perf/serve gates.

Comparability (exit 3, never a confident verdict): kind/schema_version
mismatch, different platform, or a different scale block (bucket
timings at another geometry say nothing about this one).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

#: Ratio band on the headroom fraction vs baseline before it counts as
#: a serialization regression.
HEADROOM_BAND = 1.5

#: Absolute noise floor on the headroom-fraction delta (1.5x of nearly
#: nothing is timer jitter, not a regression).
HEADROOM_FRAC_SLACK = 0.15

#: Minimum fraction of the sweep wall clock the per-bucket stage clocks
#: must account for (the telescoping band; the remainder is bucketing /
#: input-build overhead outside any stage).
TELESCOPE_MIN = 0.7

#: Stage-clock sums may exceed the wall only by timer noise (serial
#: dispatch; a pipelined sweep legitimately exceeds it — see
#: :func:`telescope_max`).
TELESCOPE_MAX = 1.05

#: The reclaimed-headroom checks arm only when the serial model shows at
#: least this much absolute headroom: below it (CPU smoke captures sit
#: in the tens of milliseconds) "reclaimed ~ 0" is timer noise, not a
#: dead pipeline.
RECLAIM_MODEL_FLOOR_S = 0.5

#: A pipelined run must reclaim at least this fraction of the modeled
#: headroom once the floor arms — reclaimed ~ 0 where the serial model
#: shows substantive overlap means the async dispatch serialized.
RECLAIM_MIN_FRAC = 0.25

#: Ratio band on headroom_reclaimed_frac vs the baseline's before the
#: drop counts as a pipeline collapse.
RECLAIM_BAND = 3.0

#: Schema version this comparator understands.  v2 (PR 16): manifests
#: carry a ``pipeline`` block (pipelined flag, bucket-loop span,
#: modeled vs reclaimed headroom).
SCHEMA_VERSION = 2

#: The four bucket lifecycle stages, in execution order.  ``prepare``
#: and ``compile`` are host work, ``run`` is device work, ``fetch`` is
#: host work that an async pipeline drains off the critical path.
STAGES = ("prepare_s", "compile_s", "run_s", "fetch_s")


class IncomparableSweep(Exception):
    """The two manifests cannot be honestly compared."""


@dataclasses.dataclass
class SweepFinding:
    """One gated regression."""

    metric: str
    message: str

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


def serial_s(buckets: List[dict]) -> float:
    """Total strictly-serial wall: every stage of every bucket, summed —
    what the engine measures today."""
    return float(sum(sum(float(b.get(s) or 0.0) for s in STAGES)
                     for b in buckets))


def ideal_pipeline_s(buckets: List[dict]) -> float:
    """Wall clock of the ideal compile-ahead/execute-behind pipeline
    over the measured per-bucket stages.

    Two resources: the HOST (prepare + compile, in bucket order, plus
    fetch handled off-thread) and the DEVICE (execute).  Bucket b's
    execute can start only after its own compile lands AND the device
    finished bucket b-1; its fetch drains concurrently with later
    compiles.  Always <= ``serial_s`` (equal for a single bucket: a
    bucket cannot overlap with itself), so the headroom is >= 0 by
    construction."""
    host = 0.0          # host cursor: prepare+compile in bucket order
    device = 0.0        # device cursor: executes back to back
    end = 0.0
    for b in buckets:
        host += float(b.get("prepare_s") or 0.0)
        host += float(b.get("compile_s") or 0.0)
        start = max(host, device)
        device = start + float(b.get("run_s") or 0.0)
        end = max(end, device + float(b.get("fetch_s") or 0.0))
    return float(max(end, host))


def overlap_headroom_s(buckets: List[dict]) -> float:
    """The wall-clock an ideal pipeline would reclaim from the
    measured serial schedule (>= 0)."""
    return max(0.0, serial_s(buckets) - ideal_pipeline_s(buckets))


def headroom_reclaimed_s(buckets: List[dict], span_s: float) -> float:
    """Headroom actually reclaimed by a measured bucket-loop span.

    ``span_s`` is the wall clock of the bucket loop ALONE (no input
    build, no bucketing, no assembly — the engine measures it around
    exactly the work the four stage clocks cover), so
    ``serial_s - span_s`` is the overlap the real scheduler achieved
    against the strictly-serial stage schedule.  Clamped at 0: a serial
    run's span equals the stage sum up to timer noise."""
    return max(0.0, serial_s(buckets) - float(span_s))


def telescope_max(manifest: Dict) -> float:
    """Upper telescoping band for this manifest.

    Serial dispatch: stage sums may exceed the wall only by timer noise
    (``TELESCOPE_MAX``).  Pipelined dispatch overlaps host compile with
    device execute, so the stage SUM legitimately exceeds the shrunken
    wall — but never beyond the fully-overlapped bound
    ``serial_s / ideal_pipeline_s`` (plus the same noise factor)."""
    pipe = manifest.get("pipeline") or {}
    if not pipe.get("pipelined"):
        return TELESCOPE_MAX
    buckets = manifest.get("buckets") or []
    ideal = ideal_pipeline_s(buckets)
    if ideal <= 0.0:
        return TELESCOPE_MAX
    return (serial_s(buckets) / ideal) * TELESCOPE_MAX


def _require(manifest: Dict, name: str) -> Dict:
    if not isinstance(manifest, dict) or \
            manifest.get("kind") != "sweep_manifest":
        raise IncomparableSweep(f"{name} is not a sweep manifest "
                                f"(kind={manifest.get('kind')!r})")
    if manifest.get("schema_version") != SCHEMA_VERSION:
        raise IncomparableSweep(
            f"{name} schema_version {manifest.get('schema_version')!r} "
            f"!= {SCHEMA_VERSION}")
    return manifest


def compare_sweep(manifest: Dict, baseline: Dict,
                  headroom_band: float = HEADROOM_BAND,
                  timing_band: Optional[float] = None
                  ) -> List[SweepFinding]:
    """New manifest vs baseline -> regression findings (empty = in-band).

    Raises IncomparableSweep when a verdict would be dishonest (see
    module docstring); the CLI maps that to exit 3.
    """
    _require(manifest, "manifest")
    _require(baseline, "baseline")
    if manifest.get("platform") != baseline.get("platform"):
        raise IncomparableSweep(
            f"platform differs: {manifest.get('platform')!r} vs baseline "
            f"{baseline.get('platform')!r} — recapture on the baseline "
            f"platform or re-baseline")
    if manifest.get("scale") != baseline.get("scale"):
        raise IncomparableSweep(
            f"sweep scale differs: {manifest.get('scale')} vs baseline "
            f"{baseline.get('scale')}")

    findings: List[SweepFinding] = []
    hr = manifest.get("overlap_headroom_frac")
    base_hr = baseline.get("overlap_headroom_frac")
    if not isinstance(hr, (int, float)) or isinstance(hr, bool):
        findings.append(SweepFinding(
            "overlap_headroom_frac",
            f"overlap headroom missing/non-numeric ({hr!r}): the "
            f"pipeline attribution vanished — the worst observability "
            f"collapse, nothing prices item 4's async dispatch anymore"))
    elif isinstance(base_hr, (int, float)) and \
            not isinstance(base_hr, bool):
        if (hr > base_hr * headroom_band
                and hr - base_hr > HEADROOM_FRAC_SLACK):
            findings.append(SweepFinding(
                "overlap_headroom_frac",
                f"serialized-pipeline regression: overlap headroom "
                f"fraction {hr:.3f} > {headroom_band} x baseline "
                f"{base_hr:.3f} (delta over the {HEADROOM_FRAC_SLACK} "
                f"noise floor) — the sweep spends relatively more wall "
                f"clock with the host or device idle"))
    new_cc = manifest.get("compile_count")
    base_cc = baseline.get("compile_count")
    if isinstance(new_cc, int) and isinstance(base_cc, int) and \
            new_cc > base_cc:
        findings.append(SweepFinding(
            "compile_count",
            f"{new_cc} backend compiles vs baseline {base_cc} at the "
            f"same scale — the bucketing regressed toward "
            f"compile-per-point"))
    pipe = manifest.get("pipeline")
    base_pipe = baseline.get("pipeline") or {}
    if not isinstance(pipe, dict):
        findings.append(SweepFinding(
            "pipeline",
            f"pipeline block missing/malformed ({pipe!r}): a v2 "
            f"manifest must report whether dispatch was pipelined and "
            f"what it reclaimed"))
    else:
        model = pipe.get("headroom_model_s")
        reclaimed_frac = pipe.get("headroom_reclaimed_frac")
        model_num = isinstance(model, (int, float)) and \
            not isinstance(model, bool)
        frac_num = isinstance(reclaimed_frac, (int, float)) and \
            not isinstance(reclaimed_frac, bool)
        if pipe.get("pipelined") and model_num and \
                model >= RECLAIM_MODEL_FLOOR_S:
            if not frac_num:
                findings.append(SweepFinding(
                    "pipeline.headroom_reclaimed_frac",
                    f"pipelined manifest reports no reclaimed-headroom "
                    f"fraction ({reclaimed_frac!r}) against a "
                    f"{model:.2f}s serial model — the pipeline's whole "
                    f"before/after number vanished"))
            elif reclaimed_frac < RECLAIM_MIN_FRAC:
                findings.append(SweepFinding(
                    "pipeline.headroom_reclaimed_frac",
                    f"pipelined dispatch reclaimed {reclaimed_frac:.3f} "
                    f"of a {model:.2f}s modeled headroom "
                    f"(< {RECLAIM_MIN_FRAC}): the compile-ahead thread "
                    f"is serializing against execute"))
            elif base_pipe.get("pipelined"):
                base_frac = base_pipe.get("headroom_reclaimed_frac")
                if (isinstance(base_frac, (int, float))
                        and not isinstance(base_frac, bool)
                        and base_frac > 0
                        and reclaimed_frac < base_frac / RECLAIM_BAND):
                    findings.append(SweepFinding(
                        "pipeline.headroom_reclaimed_frac",
                        f"reclaimed-headroom fraction collapsed: "
                        f"{reclaimed_frac:.3f} < baseline "
                        f"{base_frac:.3f} / {RECLAIM_BAND}"))
    tel = manifest.get("telescoping") or {}
    cov = tel.get("coverage")
    if not isinstance(cov, (int, float)) or isinstance(cov, bool) or \
            cov < TELESCOPE_MIN:
        findings.append(SweepFinding(
            "telescoping.coverage",
            f"bucket stage clocks cover {cov!r} of the sweep wall clock "
            f"(< {TELESCOPE_MIN}): the stage model no longer accounts "
            f"for where the time goes"))
    if timing_band is not None:
        wall = float(manifest.get("wall_s") or 0.0)
        base_wall = float(baseline.get("wall_s") or 0.0)
        if base_wall > 0 and wall > base_wall * timing_band:
            findings.append(SweepFinding(
                "wall_s",
                f"sweep wall {wall:.2f}s > {timing_band} x baseline "
                f"{base_wall:.2f}s"))
    return findings
