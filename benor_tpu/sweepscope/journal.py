"""Durable sweep journal: append-only bucket records + exact resume.

A preempted 10M-point sweep restarts from zero today — every bucket
recompiles, every point reruns.  The journal closes that gap HOST-SIDE:
after each bucket of ``sweep.run_points_batched`` completes, ONE
JSON line (``kind: sweep_bucket``, written line-atomically via
``metrics.append_jsonl``) records everything needed to reassemble that
bucket's points without touching a device:

  * the bucket's position, kind (dyn/static) and point indices;
  * an INPUT FINGERPRINT — sha256 over every point config (canonical
    JSON of the frozen dataclass), the initial-values array (shape,
    dtype, bytes) and the fault masks — so a journal written for one
    sweep can never be silently replayed into a different one;
  * the measured stage wall clocks (prepare/compile/run/fetch) and the
    bucket's backend-compile count;
  * the per-point summary payloads, serialized value-exactly (Python
    floats round-trip through JSON bit-exactly; histograms and
    recorder/witness buffers as int lists).

``run_points_batched(..., journal_path=..., resume=True)`` then skips
every bucket whose fingerprint + point indices match a journal record
and reassembles its points through the IDENTICAL ``point_from_raw``
code path — bit-equal to an uninterrupted run, with exactly the
unfinished buckets recompiled (tests/test_sweepscope.py pins both,
including a SIGKILL-mid-bucket forensics run).  Any mismatch —
fingerprint drift, a truncated (killed-mid-append) trailing line,
reordered/edited point indices, a short or edited payload (every
record carries a digest of its payload list, recomputed before reuse)
— makes the bucket RERUN, never silently reuse: a tampered journal
costs time, not correctness.

Journal off is the absolute default and bit-identical in results AND
compile counts (everything here is host-side, out-of-band of the
compiled executables — the flight-recorder house rule applied to the
sweep plane).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import metrics

#: Record tag of one completed bucket (what ``watch`` renders and
#: ``resume`` keys on).
BUCKET_KIND = "sweep_bucket"

#: Terminal record of a completed sweep (``done: true`` — ``watch``
#: stops on it like a heartbeat close beat).
DONE_KIND = "sweep_done"

#: Bumped with any record-shape change; part of the fingerprint, so a
#: journal written by an older engine reruns rather than misparses.
#: v2: records carry the mesh shape + pipelined flag under an integrity
#: stamp (PR 16) — every v1 journal is stale by construction and reruns.
JOURNAL_VERSION = 2


def _hash_array(h, arr) -> None:
    a = np.asarray(arr)
    h.update(str(a.shape).encode())
    h.update(str(a.dtype).encode())
    h.update(a.tobytes())


def bucket_fingerprint(cfgs, initial_values, faults) -> str:
    """Input fingerprint of one bucket: config hash + seed + shapes.

    Covers every input the bucket executable consumes — the per-point
    frozen configs (canonical sorted-key JSON; the seed rides inside),
    the shared initial-values array and each point's fault masks
    (faulty + crash_round + the crash_recover recover_round when the
    churn plane is armed) — so "same fingerprint" means "same compiled
    program on the same operands" and a journaled payload may stand in
    for a rerun bit-for-bit."""
    h = hashlib.sha256()
    h.update(f"sweep-journal-v{JOURNAL_VERSION}".encode())
    for c in cfgs:
        h.update(json.dumps(dataclasses.asdict(c), sort_keys=True,
                            default=str).encode())
    _hash_array(h, initial_values)
    for fl in faults:
        _hash_array(h, fl.faulty)
        _hash_array(h, fl.crash_round)
        if fl.recover_round is not None:
            _hash_array(h, fl.recover_round)
    return "sha256:" + h.hexdigest()


def serialize_point(cfg_f, vals) -> dict:
    """One point's raw bucket outputs -> a JSON-exact payload.

    ``vals`` is the ``_summarize_inline`` layout ``point_from_raw``
    consumes: (rounds, decided, mean_k, ones, k_hist, disagree
    [, recorder][, witness]).  Scalars are stored as the exact Python
    floats ``point_from_raw`` would produce (``float()`` of a float32
    is exact in double, and JSON round-trips doubles exactly), so
    deserialize -> point_from_raw is bit-equal to the live path."""
    r, dec, mk, ones, khist, dis, *rest = vals
    rest = list(rest)
    d = {
        "rounds": int(r),
        "decided": float(dec),
        "mean_k": float(mk),
        "ones": float(ones),
        "k_hist": np.asarray(khist).astype(np.int64).tolist(),
        "disagree": float(dis),
    }
    if cfg_f.record:
        d["round_history"] = np.asarray(rest.pop(0),
                                        np.int32).tolist()
    if cfg_f.witness:
        d["witness"] = np.asarray(rest.pop(0), np.int32).tolist()
    return d


def deserialize_point(cfg_f, payload: dict) -> list:
    """A journal payload -> the raw ``vals`` list ``point_from_raw``
    consumes (the inverse of :func:`serialize_point`)."""
    vals = [payload["rounds"], payload["decided"], payload["mean_k"],
            payload["ones"], np.asarray(payload["k_hist"], np.int64),
            payload["disagree"]]
    if cfg_f.record:
        vals.append(np.asarray(payload["round_history"], np.int32))
    if cfg_f.witness:
        vals.append(np.asarray(payload["witness"], np.int32))
    return vals


def payload_digest(points: List[dict]) -> str:
    """Digest of a bucket record's per-point payload list (canonical
    JSON).  Written into every record and recomputed at resume time, so
    a payload tampered IN PLACE — a renamed key, an edited value — is
    as detectable as a drifted input fingerprint: the bucket reruns,
    it is never silently reused."""
    return "sha256:" + hashlib.sha256(
        json.dumps(points, sort_keys=True).encode()).hexdigest()


def record_stamp(fingerprint: str, point_indices: List[int],
                 mesh_shape, pipelined: bool,
                 payload_sha256: str) -> str:
    """Integrity stamp binding a record's identity fields together.

    The sweep summaries are integer-exact reductions, so a journal
    written on one mesh legitimately stands in on ANOTHER mesh shape —
    the mesh/pipeline fields are provenance, not part of the lookup key.
    But provenance must not drift silently: the stamp covers
    fingerprint + point indices + mesh_shape + pipelined + the payload
    digest, and ``match`` recomputes it before reuse.  Editing a
    record's mesh field in place (a "stale mesh" forgery) breaks the
    stamp and the bucket RERUNS."""
    blob = json.dumps({
        "fingerprint": fingerprint,
        "point_indices": [int(i) for i in point_indices],
        "mesh_shape": (None if mesh_shape is None
                       else [int(s) for s in mesh_shape]),
        "pipelined": bool(pipelined),
        "payload_sha256": payload_sha256,
    }, sort_keys=True)
    return "sha256:" + hashlib.sha256(blob.encode()).hexdigest()


def read_journal(path: str) -> List[dict]:
    """Parse a journal file -> bucket/done records, in file order.
    A torn (killed-mid-append) or hand-mangled line is SKIPPED, not an
    error: its bucket simply has no record, so resume reruns it."""
    out: List[dict] = []
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return out
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue                  # torn/tampered line -> no record
        if isinstance(rec, dict) and rec.get("kind") in (BUCKET_KIND,
                                                         DONE_KIND):
            out.append(rec)
    return out


class SweepJournal:
    """One run's journal handle: the write side appends bucket/done
    records; the resume side indexes existing records by
    (fingerprint, point indices) so lookup is tamper-evident by
    construction — ANY drift in either key misses and the bucket
    reruns."""

    def __init__(self, path: str, resume: bool = False,
                 label: str = "sweep"):
        self.path = path
        self.label = label
        self.reused = 0
        self._lookup: Dict[Tuple[str, Tuple[int, ...]], dict] = {}
        if resume:
            for rec in read_journal(path):
                if rec.get("kind") != BUCKET_KIND:
                    continue
                fp = rec.get("fingerprint")
                idx = rec.get("point_indices")
                if isinstance(fp, str) and isinstance(idx, list):
                    # latest record wins (an append-only journal may
                    # carry a superseded attempt for the same bucket)
                    self._lookup[(fp, tuple(int(i) for i in idx))] = rec
        else:
            # a fresh run must not inherit a stale journal: truncate so
            # the file holds exactly this run's records
            with open(path, "w"):
                pass

    def match(self, fingerprint: str,
              point_indices: List[int]) -> Optional[dict]:
        """The completed-bucket record for these exact inputs, or None.
        A record whose payload count disagrees with its own index list,
        or whose payloads no longer hash to the recorded digest (a key
        renamed, a value edited), is tampered and never reused."""
        rec = self._lookup.get((fingerprint, tuple(point_indices)))
        if rec is None:
            return None
        pts = rec.get("points")
        if (not isinstance(pts, list)
                or len(pts) != len(point_indices)
                or rec.get("payload_sha256") != payload_digest(pts)
                or rec.get("stamp_sha256") != record_stamp(
                    fingerprint, list(point_indices),
                    rec.get("mesh_shape"), rec.get("pipelined", False),
                    rec.get("payload_sha256"))):
            metrics.REGISTRY.counter("sweepscope.journal.tampered").inc()
            return None
        return rec

    def record_bucket(self, index: int, kind: str,
                      point_indices: List[int], fingerprint: str,
                      compile_count: int, stages: Dict[str, float],
                      points: List[dict], mesh_shape=None,
                      pipelined: bool = False) -> dict:
        digest = payload_digest(points)
        idx = [int(i) for i in point_indices]
        shape = (None if mesh_shape is None
                 else [int(s) for s in mesh_shape])
        rec = {
            "kind": BUCKET_KIND, "label": self.label,
            "journal_version": JOURNAL_VERSION,
            "bucket_index": int(index), "bucket_kind": kind,
            "point_indices": idx,
            "fingerprint": fingerprint,
            "mesh_shape": shape,
            "pipelined": bool(pipelined),
            "compile_count": int(compile_count),
            **{k: round(float(v), 6) for k, v in stages.items()},
            "payload_sha256": digest,
            "stamp_sha256": record_stamp(fingerprint, idx, shape,
                                         pipelined, digest),
            "points": points,
        }
        metrics.append_jsonl(self.path, rec)
        metrics.REGISTRY.counter("sweepscope.journal.buckets").inc()
        return rec

    def record_done(self, points_total: int, n_buckets: int,
                    overlap_headroom_s: float) -> dict:
        rec = {
            "kind": DONE_KIND, "label": self.label, "done": True,
            "points_total": int(points_total),
            "n_buckets": int(n_buckets),
            "buckets_reused": int(self.reused),
            "overlap_headroom_s": round(float(overlap_headroom_s), 6),
        }
        metrics.append_jsonl(self.path, rec)
        return rec
