"""The pinned-schema ``kind: sweep_manifest`` document.

Reduces one ``sweep.BatchedCurve`` (the per-bucket stage clocks PR 13
surfaced on it) to the committed-artifact contract the sweep gate
consumes: per-bucket prepare/compile/run/fetch wall clocks, their
stage totals, the strictly-serial wall, the ideal-pipeline bound and
the ``overlap_headroom`` attribution (sweepscope/gate.py owns the
model so the gate and the cross-field checker can never disagree),
plus the telescoping cross-check that the stage clocks account for the
sweep's measured end-to-end wall.  Schema:
tools/sweep_manifest_schema.json, auto-detected + cross-field-validated
by tools/check_metrics_schema.check_sweep_manifest; gated against the
committed SWEEP_BASELINE.json by tools/check_sweep_regression.py
(exit 0/2/3).
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

from . import gate

#: The manifest's ``kind`` tag (benorlint ``manifest-kind-parity`` pins
#: that a registered checker exists for it in
#: tools/check_metrics_schema.py MANIFEST_CHECKERS).
SWEEP_MANIFEST_KIND = "sweep_manifest"

SCHEMA_VERSION = gate.SCHEMA_VERSION


def default_sweep_scale() -> Dict:
    """The fixed CPU-smoke capture scale the committed
    SWEEP_BASELINE.json was taken at: the smallest geometry whose f
    grid exercises BOTH bucket kinds — three CF-regime points sharing
    one dyn bucket (quorum > sampling.EXACT_TABLE_MAX) plus one
    exact-table point in a static bucket of its own."""
    return {"n_nodes": 9000, "trials": 4, "max_rounds": 12, "seed": 0}


def capture_f_values(n_nodes: int) -> list:
    """The standard capture's f grid at ``n_nodes``: three dyn-bucket
    points + one quorum-specialized (exact-table) point."""
    from ..ops import sampling
    if n_nodes <= sampling.EXACT_TABLE_MAX:
        raise ValueError(
            f"the sweep capture needs n_nodes > "
            f"{sampling.EXACT_TABLE_MAX} so its CF points share a dyn "
            f"bucket (got {n_nodes})")
    dyn = [n_nodes // 15, n_nodes // 7, n_nodes // 5]
    static = [n_nodes - sampling.EXACT_TABLE_MAX + max(1, n_nodes // 18)]
    return dyn + static


def build_sweep_manifest(cb, base_cfg, platform: Optional[str] = None,
                         device_kind: Optional[str] = None) -> Dict:
    """A ``BatchedCurve`` + its base config -> the manifest document.

    Refuses a resumed curve: a journal-restored bucket's stage clocks
    price the ORIGINAL run's pipeline, so a manifest mixing them with
    this run's wall clock could not telescope honestly."""
    if any(cb.bucket_reused):
        raise ValueError(
            "cannot build a sweep manifest from a resumed curve "
            f"({sum(cb.bucket_reused)} of {cb.n_buckets} buckets were "
            "journal-restored): the stage clocks price the original "
            "run, not this wall clock — capture an uninterrupted run")
    if platform is None or device_kind is None:
        import jax
        dev = jax.devices()[0]
        platform = dev.platform if platform is None else platform
        device_kind = (dev.device_kind if device_kind is None
                       else device_kind)
    buckets = []
    for i in range(cb.n_buckets):
        buckets.append({
            "index": i,
            "kind": cb.bucket_kinds[i],
            "size": cb.bucket_sizes[i],
            "point_indices": [int(p) for p in cb.bucket_point_indices[i]],
            "prepare_s": round(cb.bucket_prepare_s[i], 6),
            "compile_s": round(cb.bucket_compile_s[i], 6),
            "run_s": round(cb.bucket_run_s[i], 6),
            "fetch_s": round(cb.bucket_fetch_s[i], 6),
            "compile_count": int(cb.bucket_compile_counts[i]),
        })
    totals = {s: round(sum(float(b[s]) for b in buckets), 6)
              for s in gate.STAGES}
    serial = round(gate.serial_s(buckets), 6)
    ideal = round(gate.ideal_pipeline_s(buckets), 6)
    headroom = round(max(0.0, serial - ideal), 6)
    wall = round(float(cb.wall_s), 6)
    coverage = round(serial / wall, 6) if wall > 0 else 0.0
    span = round(float(cb.span_s), 6)
    reclaimed = round(gate.headroom_reclaimed_s(buckets, span), 6)
    pipeline = {
        "pipelined": bool(cb.pipelined),
        "span_s": span,
        "headroom_model_s": headroom,
        "headroom_reclaimed_s": reclaimed,
        "headroom_reclaimed_frac": (round(reclaimed / headroom, 6)
                                    if headroom > 0 else 0.0),
    }
    return {
        "kind": SWEEP_MANIFEST_KIND,
        "schema_version": SCHEMA_VERSION,
        "platform": platform,
        "device_kind": device_kind,
        "scale": {
            "n_nodes": int(base_cfg.n_nodes),
            "trials": int(base_cfg.trials),
            "max_rounds": int(base_cfg.max_rounds),
            "seed": int(base_cfg.seed),
            "n_points": len(cb.points),
            "f_values": [int(p.n_faulty) for p in cb.points],
        },
        "n_buckets": int(cb.n_buckets),
        "compile_count": int(cb.compile_count),
        "wall_s": wall,
        "buckets": buckets,
        "stage_totals": totals,
        "serial_s": serial,
        "ideal_pipeline_s": ideal,
        "overlap_headroom_s": headroom,
        "overlap_headroom_frac": (round(headroom / serial, 6)
                                  if serial > 0 else 0.0),
        "pipeline": pipeline,
        "telescoping": {
            "stage_sum_s": serial,
            "wall_s": wall,
            "coverage": coverage,
        },
    }


def capture_base_config(f_values: Optional[Sequence[int]] = None,
                        **scale):
    """The standard capture workload -> (base SimConfig, f grid).  The
    ONE definition bench's ``_sweepscope_check`` and
    :func:`capture_sweep_manifest` (the committed-baseline
    regeneration) both build from, so the artifact and CI can never
    silently price different workloads."""
    from ..config import SimConfig

    sc = default_sweep_scale()
    sc.update(scale)
    fs = (capture_f_values(sc["n_nodes"]) if f_values is None
          else list(f_values))
    base = SimConfig(n_nodes=sc["n_nodes"], n_faulty=0,
                     trials=sc["trials"], max_rounds=sc["max_rounds"],
                     seed=sc["seed"], delivery="quorum",
                     scheduler="uniform", path="histogram")
    return base, fs


def capture_sweep_manifest(journal_path: Optional[str] = None,
                           f_values: Optional[Sequence[int]] = None,
                           pipeline: bool = False, mesh=None,
                           **scale):
    """Run the standard two-bucket capture curve and build its manifest
    -> (manifest, BatchedCurve).  ``pipeline=True`` captures the
    compile-ahead/execute-behind scheduler (the committed baseline's
    mode since PR 16, so its ``headroom_reclaimed`` prices real
    overlap); ``mesh`` places the dyn buckets on a 2D grid mesh."""
    from ..sweep import run_curve_batched

    base, fs = capture_base_config(f_values=f_values, **scale)
    cb = run_curve_batched(base, fs, journal_path=journal_path,
                           pipeline=pipeline, mesh=mesh)
    return build_sweep_manifest(cb, base), cb


def save_sweep_manifest(path: str, manifest: Dict) -> None:
    with open(path, "w") as fh:
        json.dump(manifest, fh, indent=1)


def load_sweep_manifest(path: str) -> Dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("kind") != SWEEP_MANIFEST_KIND:
        raise ValueError(
            f"{path}: not a sweep manifest (kind={doc.get('kind')!r})")
    return doc
