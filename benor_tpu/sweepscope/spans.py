"""Bucket-lifecycle span tracing for the batched sweep engine.

The PR 11 Span API (utils/metrics: ``SPANS``, Perfetto flow links)
applied to the sweep plane: every bucket of ``sweep.run_points_batched``
emits one whole-bucket span with four stage children — prepare/stack ->
AOT lower+compile -> execute -> fetch/assemble — and a flow arrow from
the bucket span to each POINT it carried (one thin span per point on
the ``sweep.points`` track, spanning the bucket's execute window), so
ui.perfetto.dev answers "which bucket spent the time, and which curve
points rode it" at a glance.  ``python -m benor_tpu sweep --batched
--trace-out trace.json`` arms it.

Tracing is DISABLED by default (``SPANS.add`` is a no-op) and only ever
consumes host-side ``perf_counter`` stamps the engine takes regardless
for its per-bucket stage clocks — so tracing on/off is bit-identical in
results AND compile counts (tests/test_sweepscope.py pins it, the same
house rule as servescope's).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..utils.metrics import SPANS, perf_to_epoch

#: Stage names in lifecycle order, as emitted on the bucket track.
STAGE_NAMES = ("prepare", "compile", "execute", "fetch")


def emit_bucket_spans(bucket_index: int, kind: str,
                      point_indices: List[int], cfgs,
                      stamps: Dict[str, Tuple[float, float]],
                      reused: bool = False,
                      label: str = "sweep") -> Optional[int]:
    """Emit one bucket's span tree into the process-wide SPANS log.

    ``stamps`` maps stage name -> (perf_counter start, duration s); a
    reused (journal-restored) bucket passes a single ``restore`` stamp
    instead of the four lifecycle stages.  Returns the bucket span id
    (None when tracing is off — the disabled path does no work beyond
    this one attribute read)."""
    if not SPANS.enabled:
        return None
    order = ("restore",) if reused else STAGE_NAMES
    present = [s for s in order if s in stamps]
    if not present:
        return None
    start = min(stamps[s][0] for s in present)
    end = max(stamps[s][0] + stamps[s][1] for s in present)
    flows = [SPANS.new_flow() for _ in point_indices]
    bucket_id = SPANS.add(
        f"{label}.bucket[{bucket_index}]", perf_to_epoch(start),
        end - start, track=f"{label}.buckets", flow_out=flows,
        args={"bucket": int(bucket_index), "kind": kind,
              "size": len(point_indices), "reused": bool(reused),
              "points": [int(i) for i in point_indices]})
    for stage in present:
        t0, dur = stamps[stage]
        SPANS.add(f"{label}.{stage}", perf_to_epoch(t0), dur,
                  track=f"{label}.buckets", parent_id=bucket_id,
                  args={"bucket": int(bucket_index)})
    # the execute window is when each point's summary was actually
    # computed; journal-restored buckets anchor points on the restore
    ex_start, ex_dur = stamps.get("execute", stamps[present[0]])
    for fid, idx, cfg in zip(flows, point_indices, cfgs):
        SPANS.add(f"{label}.point[{int(idx)}]", perf_to_epoch(ex_start),
                  ex_dur, track=f"{label}.points", flow_in=fid,
                  args={"point": int(idx), "bucket": int(bucket_index),
                        "n_faulty": int(cfg.n_faulty)})
    return bucket_id
