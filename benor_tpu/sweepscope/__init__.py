"""sweepscope — bucket-lifecycle observability + durable resumable
journal for the batched sweep engine (ISSUE 13).

Perfscope observes executables BEFORE they run, meshscope WHILE a mesh
runs, servescope the request plane's stages — sweepscope applies the
same discipline to the last uninstrumented plane, the bucket lifecycle
of ``sweep.run_points_batched`` / ``run_curve_batched``:

  spans     per-bucket Span timelines (prepare/stack -> AOT
            lower+compile -> execute -> fetch/assemble) through the
            PR 11 Span API, with Perfetto flow links from each bucket
            span to the point indices it carried (``sweep --batched
            --trace-out``).
  journal   the durable sweep journal: one line-atomic JSON record per
            completed bucket (input fingerprint, stage clocks, compile
            count, per-point payloads) such that ``run_points_batched
            (..., journal_path=..., resume=True)`` survives a SIGKILL —
            completed buckets reassemble bit-identically from disk,
            only unfinished buckets recompile, and ANY tamper
            (fingerprint drift, truncated line, reordered indices)
            reruns rather than reuses.  This is the preemption-survival
            substrate ROADMAP item 4's elastic giant sweeps build on.
  manifest  the pinned-schema ``kind: sweep_manifest`` document
            (tools/sweep_manifest_schema.json): per-bucket stage wall
            clocks, the strictly-serial wall, the ideal
            compile-ahead/execute-behind pipeline bound and the
            ``overlap_headroom`` it would reclaim — item 4's async
            dispatch lands with its before/after number already pinned.
  gate      the stdlib-only band comparator behind
            tools/check_sweep_regression.py (exit 0/2/3 vs the
            committed SWEEP_BASELINE.json; file-path-loaded, the same
            no-jax contract as perfscope/baseline.py).

House rule (PRs 2/3/5/6/11): journal and tracing OFF are bit-identical
in results AND compile counts across dyn and static buckets, and a
resumed sweep is bit-equal to an uninterrupted one
(tests/test_sweepscope.py pins all three).
"""

from .gate import (HEADROOM_BAND, TELESCOPE_MIN, IncomparableSweep,
                   compare_sweep, ideal_pipeline_s, overlap_headroom_s,
                   serial_s)
from .journal import (BUCKET_KIND, DONE_KIND, SweepJournal,
                      bucket_fingerprint, read_journal)
from .manifest import (SWEEP_MANIFEST_KIND, build_sweep_manifest,
                       capture_base_config, capture_f_values,
                       capture_sweep_manifest, default_sweep_scale,
                       load_sweep_manifest, save_sweep_manifest)
from .spans import emit_bucket_spans

__all__ = [
    "HEADROOM_BAND", "TELESCOPE_MIN", "IncomparableSweep",
    "compare_sweep", "ideal_pipeline_s", "overlap_headroom_s",
    "serial_s", "BUCKET_KIND", "DONE_KIND", "SweepJournal",
    "bucket_fingerprint", "read_journal", "SWEEP_MANIFEST_KIND",
    "build_sweep_manifest", "capture_base_config", "capture_f_values",
    "capture_sweep_manifest", "default_sweep_scale",
    "load_sweep_manifest", "save_sweep_manifest", "emit_bucket_spans",
]
