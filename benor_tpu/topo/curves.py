"""The topo workload curves: rounds-to-decide vs degree / committee axes.

The science harness of the ``benor_tpu/topo`` delivery plane, built on
the batched engine's generalized entry point
(``sweep.run_points_batched``) so the whole committee curve shares ONE
bucket executable (committee size/count ride DynParams) and every
topology point batches its own f-axis:

  ``degree_curve``     rounds-to-decide vs degree/diameter over a list
                       of topology specs (ring / torus2d /
                       random_regular / expander) — ROADMAP item 3a's
                       "Unknown Torus" axis.  Each spec is its own
                       static bucket (adjacency is compiled in);
                       rows carry the spec's degree/diameter metadata
                       so the curve plots against either.
  ``committee_curve``  rounds-to-decide vs committee size (or count) at
                       a fixed cap — ROADMAP item 3b's committee-
                       configuration axis.  All points share one
                       static shape, so the curve is one compile.

Rows are plain dicts (json-ready): the bench's ``topo`` blob embeds
them and tools/check_metrics_schema.py cross-checks the
degree/diameter metadata against the spec strings.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..config import SimConfig
from .graphs import parse_topology

#: The default degree-curve spec ladder at N nodes: ring degrees
#: climbing toward the torus and a random-regular point — the
#: ring/torus/random-regular mix the acceptance curve asks for.
def default_degree_specs(n_nodes: int) -> List[str]:
    import math

    side = int(math.isqrt(n_nodes))
    specs = ["ring:2", "ring:4", "ring:8"]
    if side * side == n_nodes and side >= 3:
        specs.append(f"torus2d:{side}x{side}")
    specs.append("random_regular:6:1")
    return specs


def unanimity_fault(spec_str: str) -> int:
    """The degree-curve's default protocol F for one spec: F = d, so
    deciding needs count > d — a UNANIMOUS d + 1 neighborhood.  The
    quorum rule relativized to the degree at its strictest useful
    setting: any laxer bar (e.g. a neighborhood majority) decides in
    round 1 on random inputs because odd neighborhoods always hold a
    strict local majority, flattening the curve.  Under unanimity the
    decide latency is the local-consensus-formation time, which
    genuinely varies with connectivity (richer neighborhoods mix
    faster — the rounds-vs-degree signal the curve exists to show).

    'complete' is rejected loudly: the complete graph is the BASELINE
    the curve is measured against (degree N-1, diameter 1 — no degree
    axis to sweep); compare against it via sweep.run_point /
    run_curve_batched on the untopologized config."""
    spec = parse_topology(spec_str)
    if spec is None:
        raise ValueError(
            "'complete' has no degree axis — it is the baseline, not a "
            "curve point; run it through sweep.run_point/"
            "run_curve_batched without a topology instead")
    return spec.degree


def degree_curve(base_cfg: SimConfig, specs: Sequence[str],
                 n_faulty_for=None, initial_values=None,
                 verbose: bool = False) -> List[Dict]:
    """Run one point per topology spec through the batched engine and
    return json-ready rows sorted by degree (the monotonicity axis the
    schema checker pins).

    ``n_faulty_for(spec_str) -> F`` defaults to ``unanimity_fault``;
    inputs default to run_point's per-trial random bits; faults default
    to FaultSpec.none (zero crashes — F is purely the neighborhood
    decide bar, the same decoupling the balanced curves use)."""
    from ..state import FaultSpec
    from ..sweep import run_points_batched

    for s in specs:
        if parse_topology(s) is None:
            raise ValueError(
                "degree_curve sweeps adjacency specs; 'complete' is "
                "the baseline, not a curve point (it has no degree "
                "axis) — measure it via sweep.run_point/"
                "run_curve_batched on the untopologized config")
    nf = n_faulty_for if n_faulty_for is not None else unanimity_fault
    cfgs = [base_cfg.replace(topology=s, n_faulty=int(nf(s)))
            for s in specs]
    cb = run_points_batched(
        base_cfg, cfgs, initial_values=initial_values,
        faults_for=lambda c: FaultSpec.none(c.trials, c.n_nodes),
        verbose=verbose)
    rows = []
    for cfg_f, spec_str, pt in zip(cfgs, specs, cb.points):
        spec = parse_topology(spec_str)
        row = {"spec": spec.spec_string(),
               **spec.metadata(cfg_f.n_nodes),
               "n_nodes": cfg_f.n_nodes,
               "n_faulty": cfg_f.n_faulty,
               "rounds_executed": pt.rounds_executed,
               "mean_k": round(pt.mean_k, 4),
               "decided_frac": round(pt.decided_frac, 4),
               "ones_frac": round(pt.ones_frac, 4),
               "disagree_frac": round(pt.disagree_frac, 4)}
        rows.append(row)
    rows.sort(key=lambda r: (r["degree"], r["spec"]))
    return rows


def committee_curve(base_cfg: SimConfig,
                    sizes: Optional[Sequence[int]] = None,
                    counts: Optional[Sequence[int]] = None,
                    committee_count: int = 4, committee_size: int = 16,
                    cap: Optional[int] = None,
                    verbose: bool = False):
    """Sweep committee size (or count) -> (rows, BatchedCurve).

    Exactly one of ``sizes`` / ``counts`` names the swept axis; the
    other knob is held at ``committee_count`` / ``committee_size``.
    Every point shares the static ``cap`` (default: the largest count
    in play), so the whole curve is ONE dyn bucket — the returned
    BatchedCurve's ``compile_count`` is the committee analog of the
    f-axis compile-amortization proof (bench's topo blob records it)."""
    from ..state import FaultSpec
    from ..sweep import run_points_batched

    if (sizes is None) == (counts is None):
        raise ValueError("sweep exactly one of sizes= / counts=")
    if counts is not None:
        g_cap = int(cap if cap is not None else max(counts))
        cfgs = [base_cfg.replace(committee_cap=g_cap,
                                 committee_count=int(g),
                                 committee_size=committee_size)
                for g in counts]
    else:
        g_cap = int(cap if cap is not None else committee_count)
        cfgs = [base_cfg.replace(committee_cap=g_cap,
                                 committee_count=committee_count,
                                 committee_size=int(c))
                for c in sizes]
    cb = run_points_batched(
        base_cfg, cfgs,
        faults_for=lambda c: FaultSpec.none(c.trials, c.n_nodes),
        verbose=verbose)
    rows = []
    for cfg_f, pt in zip(cfgs, cb.points):
        rows.append({"committee_size": cfg_f.committee_size,
                     "committee_count": cfg_f.committee_count,
                     "committee_cap": cfg_f.committee_cap,
                     "n_nodes": cfg_f.n_nodes,
                     "n_faulty": cfg_f.n_faulty,
                     "rounds_executed": pt.rounds_executed,
                     "mean_k": round(pt.mean_k, 4),
                     "decided_frac": round(pt.decided_frac, 4),
                     "disagree_frac": round(pt.disagree_frac, 4)})
    return rows, cb
