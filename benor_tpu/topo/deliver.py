"""Neighborhood tallies: the adjacency-structured delivery plane.

The complete-graph tally (``ops/tally.py``) reduces every live sender
into one global histogram; here each receiver tallies exactly its
topology neighborhood — the d senders its ``TopologySpec`` names plus
ITSELF (reference quirk 6: broadcasts include self) — via one
``[T, N, d]`` gather per phase, never a dense N x N anything: the
neighbor indices are closed-form arithmetic on global receiver ids
(ring / torus / expander) or a static ``[N, d]`` table constant
(random_regular), so the compiled path costs O(N * d)
(tests/test_topo.py asserts the shape bound on the jaxpr).

Quorum relativization: the tallied multiset has d + 1 members, so the
decide rule ``count(v) > F`` (node.ts:99-104 — unchanged code in
models/benor.py) now reads "count > F within the d + 1 neighborhood";
configs choose F relative to the degree, and benor_tpu/audit.py's
relaxed quorum-evidence check bounds every witnessed tally by d + 1
instead of the global quorum.

Mesh-safe by the same discipline as the dense path: senders are
all-gathered once per phase (``ctx.all_gather_nodes``), neighbor ids
derive from GLOBAL receiver ids, and the equivocator edge bits key on
(trial, global receiver id, neighbor slot), so results are
bit-identical across mesh shapes.

Fault models: crash / crash_at_round ride the ``alive`` mask (a dead
neighbor's edge simply goes silent); ``byzantine`` rides the flipped
``sent`` values; ``equivocate`` draws an independent fair bit per
delivered (receiver, equivocator) edge — including the equivocator's
self edge — exactly the per-edge semantics the dense path implements,
at O(N * d) instead of O(N^2).

The fused pallas kernels never engage under a topology: structured
delivery requires ``delivery='all'``, which ``tally.pallas_round_active``
/ ``pallas_stream_active`` already reject — the structural demotion
``sim.warn_structured_demotes_pallas`` announces (the debug-demotion
policy's sibling).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..config import SimConfig, VAL0, VAL1, VALQ
from ..ops import rng
from ..ops.collectives import SINGLE, ShardCtx
from .graphs import circulant_offsets, build_neighbor_table, parse_topology


def neighbor_ids(cfg: SimConfig, node_ids: jax.Array) -> jax.Array:
    """Global sender ids each local receiver tallies -> int32
    [N_local, d].

    ``node_ids`` are this shard's GLOBAL receiver ids (ctx.node_ids), so
    the same closed forms serve single-device and mesh runs.  Circulant
    specs (ring / expander) are pure index arithmetic; the torus is
    divmod arithmetic; random_regular gathers rows of its static table
    constant."""
    spec = parse_topology(cfg.topology)
    n = cfg.n_nodes
    if spec.kind in ("ring", "expander"):
        offs = jnp.asarray(circulant_offsets(spec), jnp.int32)
        return (node_ids[:, None] + offs[None, :]) % n
    if spec.kind == "torus2d":
        rows, cols = spec.rows, spec.cols
        r, c = node_ids // cols, node_ids % cols
        return jnp.stack([
            r * cols + (c + 1) % cols,
            r * cols + (c - 1) % cols,
            ((r + 1) % rows) * cols + c,
            ((r - 1) % rows) * cols + c,
        ], axis=1)
    # random_regular: the [N, d] table is a pure function of
    # (graph_seed, N) built once at trace time — a static constant the
    # executable bakes in; row-gather by global receiver id keeps the
    # mesh contract
    tbl = jnp.asarray(build_neighbor_table(spec, n))
    return tbl[node_ids]


def neighborhood_counts(cfg: SimConfig, base_key: jax.Array, r: jax.Array,
                        phase: int, sent: jax.Array, alive: jax.Array,
                        ctx: ShardCtx = SINGLE,
                        equiv: Optional[jax.Array] = None,
                        alive_g: Optional[jax.Array] = None,
                        equiv_g: Optional[jax.Array] = None) -> jax.Array:
    """Per-receiver class counts over the receiver's d + 1 neighborhood
    -> int32 [T, N_local, 3].

    The topology counterpart of ``tally.receiver_counts`` (which
    dispatches here when ``cfg.topology`` is set): ``sent``/``alive``/
    ``equiv`` are this shard's local [T_loc, N_loc] blocks; the sender
    axis is all-gathered (the dense path's exact pattern) and each
    local receiver gathers its d neighbor values — O(N * d) total, no
    N x N tensor at any point.

    ``alive_g``/``equiv_g`` are the ROUND-CONSTANT gathered masks the
    caller hoists once per round (the dense path's exact prefetch
    discipline — models/benor.py passes them for both phases); None
    gathers locally (standalone callers, tests)."""
    T, n_loc = sent.shape
    node_ids = ctx.node_ids(n_loc)
    nbr = neighbor_ids(cfg, node_ids)                     # [N_loc, d]
    sent_g = ctx.all_gather_nodes(sent)                   # [T, N_glob]
    if alive_g is None:
        alive_g = ctx.all_gather_nodes(alive)
    sv = jnp.take(sent_g, nbr, axis=1)                    # [T, N_loc, d]
    av = jnp.take(alive_g, nbr, axis=1)
    if cfg.partition is not None:
        # Epoch-structured partition (benor_tpu/faults/partitions.py)
        # composing with adjacency: during the epoch (r < heal_round)
        # a neighbor edge that crosses a group boundary goes silent —
        # deterministically, before any tallying — so a ring spanning
        # two groups loses exactly its boundary edges.  The self edge
        # is always same-group.  equivocate is rejected with partition
        # (config.py), so the equiv branch below never composes.
        from ..faults.partitions import group_of, parse_partition
        part = parse_partition(cfg.partition)
        g_recv = group_of(node_ids, cfg.n_nodes, part.groups)
        g_nbr = group_of(nbr, cfg.n_nodes, part.groups)
        same = g_nbr == g_recv[:, None]                   # [N_loc, d]
        healed = jnp.asarray(r, jnp.int32) >= part.heal_round
        av = av & (same[None, :, :] | healed)
    if equiv is not None:
        if equiv_g is None:
            equiv_g = ctx.all_gather_nodes(equiv)
        ev = jnp.take(equiv_g, nbr, axis=1)
        honest = av & ~ev
        self_honest = alive & ~equiv
    else:
        honest = av
        self_honest = alive

    def class_count(v):
        neigh = jnp.sum((sv == v) & honest, axis=-1, dtype=jnp.int32)
        return neigh + ((sent == v) & self_honest).astype(jnp.int32)

    counts = jnp.stack([class_count(v) for v in (VAL0, VAL1, VALQ)],
                       axis=-1)                           # [T, N_loc, 3]

    if equiv is not None:
        # per-edge fair bits for delivered equivocator messages — one
        # bit per (trial, receiver, neighbor slot) with slot d = the
        # self edge, keyed on GLOBAL receiver ids (mesh-bit-identical);
        # same stream family as the dense path's edge bits (phase + 32)
        bits = rng.edge_uniforms(base_key, r, phase + 32,
                                 ctx.trial_ids(T), node_ids,
                                 rng.ids(nbr.shape[1] + 1)) < 0.5
        deliv = jnp.concatenate(
            [av & ev, (alive & equiv)[:, :, None]], axis=-1)
        c1 = jnp.sum(deliv & bits, axis=-1, dtype=jnp.int32)
        c0 = jnp.sum(deliv & ~bits, axis=-1, dtype=jnp.int32)
        zeros = jnp.zeros_like(c0)
        counts = counts + jnp.stack([c0, c1, zeros], axis=-1)
    return counts
