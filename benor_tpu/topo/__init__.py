"""benor-topo: adjacency- and committee-structured consensus delivery.

The first delivery plane since PR 1 that changes WHO a receiver tallies
rather than how fast: ``SimConfig(topology=...)`` replaces the implicit
complete graph with a declarative sparse spec (ring / 2D torus /
expander / random-regular — closed-form neighbor indices or one static
[N, d] table, never a dense N x N adjacency tensor), and
``SimConfig(committee_cap/count/size)`` replaces it with per-round
``fold_in``-sampled committees whose size/count sweep as traced
DynParams.  Both planes run through the shared round kernel
(models/benor.py) on every regime that reaches it — traced loop,
batched sweep, sharded mesh — with the quorum rule relativized to the
neighborhood/committee (count > F within the d + 1 neighborhood) and
the witness auditor's quorum-evidence bound relaxed to match
(benor_tpu/audit.py).

Modules: ``graphs`` (spec grammar + metadata + tables, stdlib-loadable
for the schema checker), ``deliver`` (the O(N*d) gather tally),
``committees`` (membership + committee histograms), ``curves``
(rounds-vs-degree / committee-size science rows for bench's ``topo``
blob).
"""

from .graphs import (KINDS, TopologySpec, build_neighbor_table,
                     circulant_offsets, parse_topology)

__all__ = ["KINDS", "TopologySpec", "build_neighbor_table",
           "circulant_offsets", "parse_topology"]
