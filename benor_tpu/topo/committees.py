"""Per-round sampled committees: the second structured delivery plane.

Grounds the "Committee Configuration Optimization for Parallel Byzantine
Consensus" direction (ROADMAP item 3b): instead of every receiver
tallying the whole network, each round samples committees and every
participating node tallies ONLY its committee co-members (itself
included).  Two knobs, BOTH swept as traced ``DynParams`` members so a
whole committee-size/count curve shares one bucket executable
(sweep.run_points_batched):

  ``committee_count``  g — how many parallel committees each round draws
  ``committee_size``   c — the target (expected) members per committee

plus the STATIC ``committee_cap`` >= committee_count: the per-committee
histogram's shape bound ``[T, cap, 3]``, which is what lets g itself be
traced (shapes never depend on the swept value).

Membership is ``fold_in``-derived (ops/rng.py's chained counter
discipline, two dedicated phase tags): per (trial, round, node), a node
participates with probability min(1, c*g/N) and, when participating,
joins committee ``floor(u * g)`` — so membership is bit-reproducible
under a fixed seed, identical across mesh shapes (keys derive from
GLOBAL ids) and identical between the static and the traced-DynParams
paths (the arithmetic is float32 in both).  Expected committee size is
exactly c for c <= N/g; past that the participation probability clips
at 1 and membership SATURATES (everyone in, expected size N/g
regardless of c) — curve builders keep swept sizes at or below N/g so
every point is a distinct workload (results.topo_curves documents the
ladder).  All draws are independent per round (per-ROUND sampled
committees — both protocol phases of a round tally the same
membership).

Non-participants sit the round out: ``models/benor.py`` masks them out
of ``active`` (their state, including k, is untouched — the same
freeze discipline decided lanes get), and their broadcast is silent for
the round.  The decide rule is unchanged ``count(v) > F`` — now read
against the committee tally, the relaxed quorum rule the auditor
understands.

Cost: one [T, N] uniform pair for membership, three [T, N] -> [T, cap]
scatter-adds for the per-committee histograms, one gather back —
O(N + T * cap) per phase, never anything N x N.  Mesh: committee ids
key on global node ids and the histogram psums over node shards, the
exact discipline of the complete-graph histogram path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import SimConfig, VAL0, VAL1, VALQ
from ..ops import rng
from ..ops.collectives import SINGLE, ShardCtx

#: Dedicated rng phase tags (ops/rng.py uses 0-3 and their +16/+32/+48
#: offsets; these stay clear of every existing stream).
PHASE_MEMBER = 8     # participation draw
PHASE_ASSIGN = 9     # committee-id draw


def membership(cfg: SimConfig, base_key: jax.Array, r: jax.Array,
               trial_ids: jax.Array, node_ids: jax.Array,
               count, size):
    """Per-round committee membership -> (member bool [T, N],
    committee_id int32 [T, N]).

    ``count``/``size`` are g and c — python ints on the static path,
    traced int32 scalars under DynParams; the arithmetic below is
    float32 either way, so the two paths draw bit-identical
    memberships for equal values (the sweep-vs-oracle house rule).
    Drawn once per ROUND (both phases share it) from two dedicated
    fold_in streams keyed on global ids."""
    u_p = rng.grid_uniforms(base_key, r, PHASE_MEMBER, trial_ids,
                            node_ids)
    u_g = rng.grid_uniforms(base_key, r, PHASE_ASSIGN, trial_ids,
                            node_ids)
    g = jnp.asarray(count, jnp.int32).astype(jnp.float32)
    c = jnp.asarray(size, jnp.int32).astype(jnp.float32)
    p = jnp.minimum(jnp.float32(1.0),
                    (c * g) / jnp.float32(cfg.n_nodes))
    member = u_p < p
    cid = jnp.clip(jnp.floor(u_g * g).astype(jnp.int32), 0,
                   cfg.committee_cap - 1)
    return member, cid


def committee_counts(cfg: SimConfig, sent: jax.Array, senders: jax.Array,
                     cid: jax.Array, ctx: ShardCtx = SINGLE) -> jax.Array:
    """Per-receiver class counts over the receiver's committee -> int32
    [T, N, 3].

    ``senders`` masks the lanes whose broadcast lands this round
    (alive AND participating — killed lanes and sit-outs go silent);
    ``cid`` is the per-lane committee id from ``membership``.  Three
    scatter-adds build the [T, cap, 3] per-committee histogram (psum'd
    over node shards under a mesh), then every lane gathers its own
    committee's row.  A non-participant's gathered row is discarded by
    the round kernel's ``active`` mask."""
    T, n_loc = sent.shape
    G = cfg.committee_cap
    t_idx = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[:, None], (T, n_loc))
    hists = []
    for v in (VAL0, VAL1, VALQ):
        contrib = ((sent == v) & senders).astype(jnp.int32)
        hists.append(jnp.zeros((T, G), jnp.int32)
                     .at[t_idx, cid].add(contrib))
    hist = ctx.psum_nodes(jnp.stack(hists, axis=-1))      # [T, cap, 3]
    return jnp.take_along_axis(hist, cid[:, :, None], axis=1)
