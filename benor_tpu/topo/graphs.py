"""Topology specs: structured sparse delivery graphs as COMPACT tensors.

The whole repo, until PR 12, assumed the paper's implicit complete graph:
every receiver's tally sees every live sender (``ops/tally.py``'s global
histogram, the dense [T, N, N] mask at small N).  This module is the
declarative spec layer of the ``benor_tpu/topo`` delivery plane (ROADMAP
item 3, the "Consensus on an Unknown Torus with Dense Byzantine Faults"
direction): a topology names, per receiver, the d senders it tallies —
carried as closed-form index arithmetic (ring / torus / expander) or one
static ``[N, d]`` neighbor-index table (random-regular), NEVER a dense
N x N adjacency tensor, so 1M nodes costs O(N*d) memory and work
(tests/test_topo.py pins the shape bound on the compiled path).

Spec grammar (``SimConfig.topology``) — one string, colon-separated:

  ``complete``                the identity spec: today's all-to-all
                              delivery.  Normalized to ``topology=None``
                              by SimConfig, so selecting it is
                              bit-identical to the pre-topology path in
                              results AND compile counts (same config
                              hash -> same jit cache entry).
  ``ring:<d>``                circulant ring, EVEN degree d: receiver i
                              tallies i +- 1 .. i +- d/2 (mod N).
  ``torus2d:<rows>x<cols>``   4-neighbor 2D torus (N == rows * cols,
                              both >= 3): up/down/left/right with wrap.
  ``expander:<d>``            circulant expander, EVEN degree d:
                              offsets +- 2^j for j < d/2 — O(log N)
                              diameter with closed-form indices.
  ``random_regular:<d>[:seed]``  seeded random graph with in-degree
                              exactly d (each receiver tallies d
                              distinct uniform senders; out-degrees
                              concentrate around d).  The ``[N, d]``
                              table is built host-side once per
                              (spec, N) at trace time and baked into
                              the executable as a constant.

Every receiver additionally tallies ITSELF (reference quirk 6:
broadcasts include self, node.ts:72,149,173), so the tallied
neighborhood has d + 1 members and the quorum rule relativizes to
"count > F within the d + 1 neighborhood" (benor_tpu/topo/deliver.py;
the relaxed auditor bound in benor_tpu/audit.py).

This module stays stdlib-importable (numpy only inside the table
builder): ``tools/check_metrics_schema.py`` file-path-loads it to
recompute the degree/diameter cross-field checks on the bench's ``topo``
blob without a jax environment.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

#: The spec kinds ``parse_topology`` accepts ('complete' normalizes to
#: None at the SimConfig boundary and never reaches a TopologySpec).
KINDS = ("ring", "torus2d", "expander", "random_regular")


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """One parsed topology spec — hashable (rides the jit-static
    SimConfig as its parsed form) and cheap to re-derive from the
    string."""

    kind: str                 # one of KINDS
    degree: int               # d — tallied neighbors per receiver
    rows: int = 0             # torus2d only
    cols: int = 0             # torus2d only
    graph_seed: int = 0       # random_regular only

    def validate(self, n_nodes: int) -> None:
        """Raise ValueError unless this spec is realizable at N nodes."""
        n = n_nodes
        if self.kind in ("ring", "expander"):
            if self.degree % 2 or self.degree < 2:
                raise ValueError(
                    f"{self.kind} degree must be even and >= 2 "
                    f"(offsets come in +- pairs); got {self.degree}")
            if self.degree > n - 1:
                raise ValueError(
                    f"{self.kind}:{self.degree} needs at least "
                    f"degree + 1 = {self.degree + 1} nodes (got {n})")
            if self.kind == "expander" and (1 << (self.degree // 2 - 1)) \
                    >= n:
                raise ValueError(
                    f"expander:{self.degree} folds offsets +-2^j up to "
                    f"j={self.degree // 2 - 1}, which wraps past N={n}; "
                    "lower the degree or grow the network")
            # circulant offsets must name d DISTINCT non-self senders mod
            # N — an aliasing pair (e.g. +-N/2, or two powers congruent
            # mod N) would silently DOUBLE-COUNT that sender's vote in
            # every tally, a forged-evidence generator no audit could
            # distinguish from a real message
            offs = circulant_offsets(self)
            residues = {o % n for o in offs}
            if 0 in residues or len(residues) != len(offs):
                raise ValueError(
                    f"{self.kind}:{self.degree} offsets alias modulo "
                    f"N={n} (the +-offset pairs do not name "
                    f"{self.degree} distinct non-self senders); lower "
                    "the degree or grow the network")
        elif self.kind == "torus2d":
            if self.rows < 3 or self.cols < 3:
                raise ValueError(
                    "torus2d needs rows >= 3 and cols >= 3 (smaller "
                    "wraps alias two neighbors onto one sender); got "
                    f"{self.rows}x{self.cols}")
            if self.rows * self.cols != n:
                raise ValueError(
                    f"torus2d:{self.rows}x{self.cols} covers "
                    f"{self.rows * self.cols} nodes but the network has "
                    f"{n}")
        elif self.kind == "random_regular":
            # d <= N/2 keeps the table builder's collision re-roll
            # geometric (success prob >= ~1/2 per pass); past N/2 the
            # repair degenerates toward coupon-collecting the last few
            # free ids — an UNBOUNDED trace-time stall reachable from
            # the serve request plane (a cheap-to-validate job would
            # wedge the shared batcher at trace time).  A random graph
            # that dense approximates the complete graph anyway.
            if not (1 <= self.degree <= n // 2):
                raise ValueError(
                    f"random_regular degree must be in [1, N//2] (the "
                    f"seeded table repair is only geometric below "
                    f"half-density; denser graphs ~ 'complete'); got "
                    f"{self.degree} at N={n}")
        else:
            raise ValueError(f"unknown topology kind: {self.kind!r}")

    def diameter(self, n_nodes: int) -> int:
        """Graph diameter in hops — EXACT for ring and torus2d
        (consecutive-offset circulants and the 4-neighbor torus have
        closed forms), a documented UPPER-BOUND ESTIMATE for expander
        (largest-offset greedy + one adjust step per remaining power)
        and random_regular (the classic log_d N concentration bound).
        Closed-form on purpose: the schema checker recomputes this
        without numpy or a BFS."""
        n = n_nodes
        if self.kind == "ring":
            return max(1, math.ceil((n // 2) / (self.degree // 2)))
        if self.kind == "torus2d":
            return self.rows // 2 + self.cols // 2
        if self.kind == "expander":
            k = self.degree // 2
            return max(1, math.ceil((n // 2) / (1 << (k - 1))) + (k - 1))
        # random_regular: diameter concentrates at log_d N for d >= 2
        if self.degree < 2:
            return max(1, n - 1)
        return max(1, math.ceil(math.log(max(n, 2))
                                / math.log(self.degree)))

    def diameter_exact(self) -> bool:
        """True iff ``diameter`` is the exact graph diameter (ring,
        torus2d) rather than an upper-bound estimate."""
        return self.kind in ("ring", "torus2d")

    def metadata(self, n_nodes: int) -> dict:
        """The spec's science-row metadata: degree / diameter (+ whether
        the diameter is exact) — the fields the rounds-vs-degree curve
        rows carry and tools/check_metrics_schema.py recomputes."""
        return {"degree": int(self.degree),
                "diameter": int(self.diameter(n_nodes)),
                "diameter_exact": bool(self.diameter_exact())}

    def spec_string(self) -> str:
        """The canonical string form (round-trips through
        ``parse_topology``)."""
        if self.kind == "torus2d":
            return f"torus2d:{self.rows}x{self.cols}"
        if self.kind == "random_regular":
            return f"random_regular:{self.degree}:{self.graph_seed}"
        return f"{self.kind}:{self.degree}"


def parse_topology(spec: Optional[str]) -> Optional[TopologySpec]:
    """Spec string -> TopologySpec (None / 'complete' -> None).

    Raises ValueError on anything malformed — SimConfig surfaces these
    at construction and the serve plane as structured 400s
    (serve/jobs.py)."""
    if spec is None or spec == "complete":
        return None
    if not isinstance(spec, str):
        raise ValueError(
            f"topology must be a spec string (see benor_tpu/topo/"
            f"graphs.py); got {type(spec).__name__}")
    parts = spec.split(":")
    kind = parts[0]
    try:
        if kind in ("ring", "expander"):
            if len(parts) != 2:
                raise ValueError
            return TopologySpec(kind=kind, degree=int(parts[1]))
        if kind == "torus2d":
            if len(parts) != 2:
                raise ValueError
            rows, cols = (int(x) for x in parts[1].split("x"))
            return TopologySpec(kind=kind, degree=4, rows=rows, cols=cols)
        if kind == "random_regular":
            if len(parts) not in (2, 3):
                raise ValueError
            seed = int(parts[2]) if len(parts) == 3 else 0
            return TopologySpec(kind=kind, degree=int(parts[1]),
                                graph_seed=seed)
    except ValueError:
        # every ValueError inside the try is a parse-shape failure (bad
        # arity, non-integer field) — always answer with the grammar,
        # never a raw int()/unpack message (serve clients see this
        # verbatim in their structured 400)
        raise ValueError(
            f"malformed topology spec {spec!r}: expected "
            "'complete' | 'ring:<d>' | 'torus2d:<rows>x<cols>' | "
            "'expander:<d>' | 'random_regular:<d>[:seed]'") from None
    raise ValueError(
        f"unknown topology kind {kind!r} in {spec!r} "
        f"(known: complete, {', '.join(KINDS)})")


def circulant_offsets(spec: TopologySpec) -> list:
    """The signed neighbor offsets of a circulant spec (ring/expander) —
    the closed-form index arithmetic ``deliver.py`` applies to global
    receiver ids, O(d) integers instead of any adjacency tensor."""
    if spec.kind == "ring":
        half = [j for j in range(1, spec.degree // 2 + 1)]
    elif spec.kind == "expander":
        half = [1 << j for j in range(spec.degree // 2)]
    else:
        raise ValueError(f"{spec.kind} is not a circulant spec")
    return [o for j in half for o in (j, -j)]


def build_neighbor_table(spec: TopologySpec, n_nodes: int):
    """Static int32 ``[N, d]`` neighbor-index table: row i lists the d
    global sender ids receiver i tallies (self excluded — the delivery
    layer adds the self edge).  Closed-form specs derive rows
    arithmetically; random_regular draws each row as d distinct uniform
    senders from a generator seeded by ``graph_seed`` (reproducible
    across processes/mesh shapes by construction — the table is a pure
    function of (spec, N), built once per trace and baked in as a
    constant).  This is the test oracle's ground truth too
    (tests/test_topo.py compares the compiled gather against it)."""
    import numpy as np

    spec.validate(n_nodes)
    n, d = n_nodes, spec.degree
    # int32 throughout: the table feeds device gathers directly, and the
    # repo's state discipline is 32-bit (ids stay < 2^31 by the config's
    # own bounds)
    ids = np.arange(n, dtype=np.int32)
    if spec.kind in ("ring", "expander"):
        k = d // 2
        half = (np.arange(1, k + 1, dtype=np.int32) if spec.kind == "ring"
                else (np.int32(1) << np.arange(k, dtype=np.int32)))
        offs = np.stack([half, -half], axis=1).reshape(-1)
        return ((ids[:, None] + offs[None, :]) % n).astype(np.int32)
    if spec.kind == "torus2d":
        rows, cols = spec.rows, spec.cols
        r, c = ids // cols, ids % cols
        nb = np.stack([
            r * cols + (c + 1) % cols,
            r * cols + (c - 1) % cols,
            ((r + 1) % rows) * cols + c,
            ((r - 1) % rows) * cols + c,
        ], axis=1)
        return nb.astype(np.int32)
    # random_regular: iid draws per slot, then vectorized repair of
    # self-loops and within-row duplicates (re-roll the offending slots
    # until every row holds d distinct non-self senders; d << N makes
    # the collision mass shrink geometrically, so the loop terminates
    # in a handful of passes)
    # benorlint: allow-host-rng — seeded STATIC graph construction at
    # trace time (a pure function of (graph_seed, N) baked in as an
    # executable constant); protocol draws all use ops/rng.py
    gen = np.random.default_rng(spec.graph_seed)
    tbl = gen.integers(0, n, size=(n, d), dtype=np.int32)
    for _ in range(10_000):
        bad = tbl == ids[:, None]
        srt = np.sort(tbl, axis=1)
        dup_sorted = np.zeros_like(bad)
        dup_sorted[:, 1:] = srt[:, 1:] == srt[:, :-1]
        # map the sorted-duplicate flags back onto the unsorted slots
        order = np.argsort(tbl, axis=1, kind="stable")
        dup = np.zeros_like(bad)
        np.put_along_axis(dup, order, dup_sorted, axis=1)
        bad |= dup
        n_bad = int(bad.sum())
        if not n_bad:
            break
        tbl[bad] = gen.integers(0, n, size=n_bad, dtype=np.int32)
    else:  # pragma: no cover — d <= N-1 guarantees convergence
        raise RuntimeError("random_regular table repair did not converge")
    return tbl.astype(np.int32)
