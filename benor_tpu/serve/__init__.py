"""benor-serve: async multi-tenant request plane over warm AOT executors.

The "millions of users" leg of the north star (ROADMAP item 1): treat
the batched sweep executables the way an inference server treats a
model.  Five modules:

  jobs.py     the reusable job API — JobSpec -> SimConfig -> bucket ->
              batch slot -> result slice (the sweep/results entry-point
              refactor; CLI, bench.py and the HTTP plane all consume it)
  batcher.py  continuous trial-batching: bucket queues, the warm AOT
              executor pool (seed-erased sweep buckets, capacity rungs,
              donated buffers), zero steady-state compiles
  server.py   the asyncio HTTP+SSE front door (ServeApp); streams
              flight-recorder round rows and witness rows on the PR 6
              since_round cursor plane instead of poll-until-done
  loadgen.py  thousands of concurrent SSE clients -> the pinned-schema
              ``kind: serve_manifest`` (p50/p99 latency, saturation
              throughput, jobs-per-launch coalescing)
  gate.py     STDLIB-ONLY manifest comparator behind
              tools/check_serve_regression.py and the committed
              SERVE_BASELINE.json (exit 0 in-band / 2 regression /
              3 incomparable)

servescope (PR 11) threads through all five: every job carries the
nine-stamp stage timeline (jobs.STAGE_STAMPS), the batcher and the
HTTP front door emit batch/job/request spans into
``utils.metrics.SPANS`` when tracing is armed, the server answers
``/v1/jobs/<id>/timing``, and the v2 manifest carries per-stage
p50/p99 blocks plus the attribution-completeness cross-check that
``gate.py`` and the committed baseline now enforce.

Importing this package is cheap (no jax at import time); the device
work begins at the first launch on the batcher thread.
"""

from .batcher import (MAX_BATCH_JOBS, Batcher, Job, emit_job_spans,
                      serve_bucket_key)
from .gate import (ATTRIBUTION_BAND, COALESCING_BAND, STAGE_P99_BANDS,
                   IncomparableServe, ServeFinding, compare_serve)
from .jobs import (CONFIG_FIELDS, JOB_KINDS, STAGE_NAMES, STAGE_STAMPS,
                   STAGES, JobError, JobSpec, job_inputs, result_dict,
                   stage_durations, timing_dict)
from .loadgen import DEFAULT_JOB, build_serve_manifest, run_load
from .server import ServeApp, run_server

__all__ = [
    "MAX_BATCH_JOBS", "Batcher", "Job", "emit_job_spans",
    "serve_bucket_key", "ATTRIBUTION_BAND", "COALESCING_BAND",
    "STAGE_P99_BANDS", "IncomparableServe", "ServeFinding",
    "compare_serve", "CONFIG_FIELDS", "JOB_KINDS", "STAGE_NAMES",
    "STAGE_STAMPS", "STAGES", "JobError", "JobSpec", "job_inputs",
    "result_dict", "stage_durations", "timing_dict", "DEFAULT_JOB",
    "build_serve_manifest", "run_load", "ServeApp", "run_server",
]
