"""benor-serve: async multi-tenant request plane over warm AOT executors.

The "millions of users" leg of the north star (ROADMAP item 1): treat
the batched sweep executables the way an inference server treats a
model.  Five modules:

  jobs.py     the reusable job API — JobSpec -> SimConfig -> bucket ->
              batch slot -> result slice (the sweep/results entry-point
              refactor; CLI, bench.py and the HTTP plane all consume it)
  batcher.py  continuous trial-batching: bucket queues, the warm AOT
              executor pool (seed-erased sweep buckets, capacity rungs,
              donated buffers), zero steady-state compiles
  server.py   the asyncio HTTP+SSE front door (ServeApp); streams
              flight-recorder round rows and witness rows on the PR 6
              since_round cursor plane instead of poll-until-done
  loadgen.py  thousands of concurrent SSE clients -> the pinned-schema
              ``kind: serve_manifest`` (p50/p99 latency, saturation
              throughput, jobs-per-launch coalescing)
  gate.py     STDLIB-ONLY manifest comparator behind
              tools/check_serve_regression.py and the committed
              SERVE_BASELINE.json (exit 0 in-band / 2 regression /
              3 incomparable)

Importing this package is cheap (no jax at import time); the device
work begins at the first launch on the batcher thread.
"""

from .batcher import MAX_BATCH_JOBS, Batcher, Job, serve_bucket_key
from .gate import (COALESCING_BAND, IncomparableServe, ServeFinding,
                   compare_serve)
from .jobs import (CONFIG_FIELDS, JOB_KINDS, JobError, JobSpec,
                   job_inputs, result_dict)
from .loadgen import DEFAULT_JOB, build_serve_manifest, run_load
from .server import ServeApp, run_server

__all__ = [
    "MAX_BATCH_JOBS", "Batcher", "Job", "serve_bucket_key",
    "COALESCING_BAND", "IncomparableServe", "ServeFinding",
    "compare_serve", "CONFIG_FIELDS", "JOB_KINDS", "JobError", "JobSpec",
    "job_inputs", "result_dict", "DEFAULT_JOB", "build_serve_manifest",
    "run_load", "ServeApp", "run_server",
]
