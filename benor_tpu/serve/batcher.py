"""Continuous trial-batching over a warm AOT executor pool.

The serving insight (ROADMAP item 1): the batched sweep engine already
compiles ONE donated executable per static-shape bucket and vmaps a
batch of per-point (state, faults, dyn) triples through it — an
inference server's "model" in all but name.  This module turns that
executable into exactly that: a **warm executor pool** keyed by the
job's serve bucket (``sweep.sweep_bucket_key`` with the seed erased —
the seed is data, never a static; verified by rules_config's base-key
contract) and a **continuous batcher** that coalesces concurrent client
jobs into batch slots of the next launch.

Shape discipline: one bucket = one static shape, so jobs that share a
bucket stack along the leading axis into a ``[B, T, N]`` problem — B
jobs x T trials each, i.e. one launch carries ``B*T`` trials of
device work (the "continuous batches over the trial axis").  B is
rounded up to the next power of two (capacity rungs 1, 2, 4, ...,
``max_batch_jobs``) and padded by repeating the last job's inputs, so
the pool holds at most log2(max_batch_jobs)+1 executables per bucket —
after the warm-up launches, steady-state serving adds **zero** backend
compiles (tests/test_serve.py pins it via utils/compile_counter).

Bit-equality (the house rule): a job's batch slot runs
``sim.run_consensus_traced`` with run_point's exact inputs —
``serve/jobs.job_inputs`` — its own ``jax.random.key(seed)`` and its
own DynParams lane, then summarizes through ``sweep._summarize_inline``
and deserializes through ``sweep.point_from_raw``; every piece is the
same code the batched sweep engine runs, whose bit-identity to the
per-point oracle tests/test_batched_sweep.py already pins.
Quorum-specialized configs (pallas kernels, exact tables, dense top-k
masks — ``sweep.quorum_specialized``) cannot share a dynamic-F lane;
they get capacity-1 executors (still warm across seeds: the seed rides
in as a traced scalar), so their coalescing ratio is 1 and their
results stay on the classic ``run_consensus`` dispatch, pallas fast
path preserved.

Buffer reuse: the stacked state stack is DONATED to every launch
(``donate_argnums=(0,)``, the sweep engine's discipline), so the loop
carry aliases the request buffers instead of doubling the footprint;
the executor itself is reused across launches, which is where the
dispatch amortization comes from (``serve.jobs_per_launch``).
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
import warnings
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import SimConfig
from ..utils.metrics import REGISTRY, SPANS, perf_to_epoch
from .jobs import STAGES, JobError, JobSpec, job_inputs, result_dict

#: Capacity ceiling of one launch (jobs per executable).  Power of two;
#: the pool compiles at most log2(MAX_BATCH_JOBS)+1 capacity rungs per
#: bucket.
MAX_BATCH_JOBS = 32


def serve_bucket_key(cfg: SimConfig):
    """The executor-pool bucket of one job config: the sweep engine's
    static-shape bucket token with the SEED erased — the seed only ever
    feeds ``jax.random.key`` at the harness boundary (rules_config.py
    documents that contract), so jobs that differ only in seed share
    one warm executable and coalesce into one launch."""
    from ..sweep import sweep_bucket_key
    kind, c = sweep_bucket_key(cfg)
    return (kind, c.replace(seed=0))


class Job:
    """One batch slot: spec + config + the event stream clients follow.

    Events are (type, payload) tuples appended under the job lock;
    async subscribers (the SSE route) register (loop, asyncio.Event)
    waker pairs that ``publish`` fires thread-safely, host-side callers
    block on ``wait``.  ``cancel`` frees the batch slot: a queued job
    flips to 'cancelled' and the batcher skips it when forming the next
    batch; an in-flight job finishes on device (the executable cannot
    be interrupted) but its result is discarded unpublished.

    ``stamps`` is servescope's timeline: one ``perf_counter`` float per
    jobs.STAGE_STAMPS transition (the batcher writes accepted through
    result_sliced and the terminal done; the HTTP plane refines
    first_sse/done on the stream leg).  Stamps are taken UNCONDITIONALLY
    — nine floats per job — so the ``/v1/jobs/<id>/timing`` route and
    the load manifest's stage block never depend on tracing being armed;
    the SPANS plane only *renders* them when enabled.
    """

    _ids = itertools.count(1)

    def __init__(self, spec: JobSpec, cfg: SimConfig):
        self.spec = spec
        self.cfg = cfg
        self.id = f"j{next(self._ids):05d}-{uuid.uuid4().hex[:8]}"
        self.bucket = serve_bucket_key(cfg)
        self.state = "queued"     # queued|running|done|error|cancelled
        self.result: Optional[dict] = None
        self.error: Optional[dict] = None
        self.events: List[Tuple[str, dict]] = []
        self.stamps: Dict[str, float] = {}
        self.launch_jobs = 0          # batch size of the launch that ran it
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._waiters: List[tuple] = []   # (loop, asyncio.Event)
        self._flow: Optional[int] = None  # batch->job Perfetto flow id
        self._spans_emitted = False
        #: True when an SSE delivery leg owns this job's span emission
        #: (set BEFORE enqueue, so the publish path cannot race the
        #: stream's waiter registration and emit spans that lack the
        #: stream_out stage).
        self._streamed = False

    def stamp(self, name: str, t: Optional[float] = None,
              override: bool = False) -> None:
        """Record a stage transition (first write wins unless
        ``override`` — the stream leg legitimately re-stamps ``done``
        when SSE delivery, not result publication, completes)."""
        with self._lock:
            if override or name not in self.stamps:
                self.stamps[name] = (time.perf_counter()
                                     if t is None else t)

    # -- event plane ------------------------------------------------------
    def publish(self, etype: str, payload: dict) -> None:
        with self._lock:
            self.events.append((etype, payload))
            waiters = list(self._waiters)
        if etype in ("done", "error", "cancelled"):
            self._done.set()
        for loop, ev in waiters:
            try:
                loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass                      # subscriber's loop already closed

    def add_waiter(self, loop, ev) -> None:
        with self._lock:
            self._waiters.append((loop, ev))

    def drop_waiter(self, loop, ev) -> None:
        with self._lock:
            try:
                self._waiters.remove((loop, ev))
            except ValueError:
                pass

    @property
    def done(self) -> bool:
        return self.state in ("done", "error", "cancelled")

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Host-side completion barrier (loadgen's in-process mode and
        the tests use it; the HTTP plane awaits the event stream)."""
        return self._done.wait(timeout)

    def cancel(self) -> bool:
        """Free this job's batch slot (client went away).  True when the
        job had not yet reached a launch; an in-flight/finished job
        keeps its state but a disconnected client's result is simply
        never published to anyone."""
        with self._lock:
            if self.state == "queued":
                self.state = "cancelled"
                freed = True
            else:
                freed = False
        if freed:
            self.publish("cancelled", {"job": self.id})
            REGISTRY.counter("serve.jobs_cancelled").inc()
        return freed


class WarmExecutor:
    """One compiled capacity rung of one bucket."""

    def __init__(self, artifact, rep_cfg: SimConfig, capacity: int,
                 kind: str):
        self.artifact = artifact          # perfscope AotArtifact
        self.rep_cfg = rep_cfg
        self.capacity = capacity
        self.kind = kind                  # 'dyn' | 'static'
        self.launches = 0


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class Batcher:
    """The request queue + executor pool + launch loop.

    ``submit`` validates and enqueues; the worker thread (or an explicit
    ``step()`` from tests) pops the next non-empty bucket round-robin,
    forms a batch of up to ``max_batch_jobs`` live jobs, launches the
    bucket's warm executor at the matching capacity rung and publishes
    each slot's stream + result.  Round-robin over buckets is the
    no-starvation guarantee: a job whose bucket mismatches the batch
    being formed never blocks it and is at most one launch away from
    its own (tests/test_serve.py pins it).
    """

    def __init__(self, max_batch_jobs: int = MAX_BATCH_JOBS,
                 limits: Optional[dict] = None, start: bool = True):
        if max_batch_jobs < 1:
            raise ValueError("max_batch_jobs must be >= 1")
        self.max_batch_jobs = _next_pow2(max_batch_jobs)
        self.limits = limits
        self._queues: "OrderedDict[tuple, deque]" = OrderedDict()
        self._rr: deque = deque()                 # bucket round-robin
        self._pool: Dict[tuple, WarmExecutor] = {}
        self._jobs: Dict[str, Job] = {}
        self._cv = threading.Condition()
        self._stop = False
        self.launches = 0
        self.jobs_completed = 0
        self.jobs_submitted = 0
        self.executor_compiles = 0
        self.batch_errors = 0
        #: Structured snapshot of the most recent batch failure (the
        #: worker loop's boundary) — surfaced in /v1/stats so a
        #: misbehaving tenant's blast radius is observable without
        #: scraping stderr.  None until something fails.
        self.last_error: Optional[dict] = None
        self._thread = None
        if start:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="benor-serve-batcher")
            self._thread.start()

    # -- intake -----------------------------------------------------------
    def submit_dict(self, doc, accepted_t: Optional[float] = None,
                    streamed: bool = False) -> List[Job]:
        """Wire document -> validated, enqueued jobs (sweep kind expands
        to one job per f value).  Raises JobError — the structured 400.
        ``accepted_t`` back-dates the accepted stamp to when the request
        plane started handling the request, so the validate stage
        includes request read + JobSpec validation; ``streamed`` marks
        the jobs as owned by an SSE delivery leg (span emission waits
        for the stream — see ``emit_job_spans``)."""
        t_acc = time.perf_counter() if accepted_t is None else accepted_t
        return self.submit(JobSpec.from_dict(doc, limits=self.limits),
                           accepted_t=t_acc, streamed=streamed)

    def submit(self, spec: JobSpec,
               accepted_t: Optional[float] = None,
               streamed: bool = False) -> List[Job]:
        t_acc = time.perf_counter() if accepted_t is None else accepted_t
        jobs = []
        for sub in spec.expand():
            cfg = sub.to_config()         # JobError on invalid combos
            job = Job(sub, cfg)
            job._streamed = streamed
            job.stamp("accepted", t_acc)
            job.stamp("validated")
            jobs.append(job)
        with self._cv:
            for job in jobs:
                self._jobs[job.id] = job
                q = self._queues.get(job.bucket)
                if q is None:
                    q = deque()
                    self._queues[job.bucket] = q
                    self._rr.append(job.bucket)
                q.append(job)
                job.stamp("enqueued")
                self.jobs_submitted += 1
            depth = sum(len(q) for q in self._queues.values())
            self._cv.notify_all()
        REGISTRY.counter("serve.jobs_submitted").inc(len(jobs))
        REGISTRY.gauge("serve.queue_depth").set(depth)
        for job in jobs:
            job.publish("queued", {"job": job.id,
                                   "bucket": job.bucket[0]})
        return jobs

    def get(self, job_id: str) -> Optional[Job]:
        with self._cv:
            return self._jobs.get(job_id)

    # -- launch loop ------------------------------------------------------
    def _pop_batch(self, block: bool, timeout: Optional[float]):
        """Next (bucket, jobs) round-robin, cancelled slots skipped."""
        with self._cv:
            while True:
                for _ in range(len(self._rr)):
                    key = self._rr[0]
                    self._rr.rotate(-1)
                    q = self._queues[key]
                    jobs = []
                    while q and len(jobs) < self.max_batch_jobs:
                        job = q.popleft()
                        if job.state == "queued":
                            jobs.append(job)
                    if not q:
                        # drop the empty bucket from the rotation (the
                        # executor pool keeps its warm executables)
                        del self._queues[key]
                        self._rr.remove(key)
                    if jobs:
                        # queue depth sampled at DRAIN, not just submit:
                        # a submit-only gauge can only ever grow within
                        # a burst and never shows the batcher catching
                        # up — the drain-side sample is what queue-wait
                        # attribution correlates with
                        depth = sum(len(q) for q in self._queues.values())
                        REGISTRY.gauge("serve.queue_depth").set(depth)
                        return key, jobs
                if not block or self._stop:
                    return None, []
                self._cv.wait(timeout)
                if self._stop:
                    return None, []

    def step(self, block: bool = False,
             timeout: Optional[float] = None) -> int:
        """Process ONE batch (tests drive this synchronously; the worker
        thread loops it).  Returns the number of jobs launched."""
        key, popped = self._pop_batch(block, timeout)
        if not popped:
            return 0
        # claim the slots under each job's lock: a client that cancelled
        # between the queue pop and here keeps its 'cancelled' state (an
        # unlocked state write would overwrite it and later publish the
        # orphan result the cancel contract promises to discard)
        jobs = []
        t_claim = time.perf_counter()
        for job in popped:
            with job._lock:
                if job.state != "queued":
                    continue
                job.state = "running"
                job.stamps.setdefault("batch_assigned", t_claim)
            jobs.append(job)
        if not jobs:
            return 0
        try:
            self._execute(key, jobs)
        # benorlint: allow-broad-except — multi-tenant boundary: whatever
        # killed this batch must reach ITS clients as error events (and
        # re-raises for the caller); swallowing nothing, routing everything
        except Exception as e:  # noqa: BLE001
            for job in jobs:
                if job.done:
                    continue    # its result already published — keep it
                job.state = "error"
                job.error = {"error": f"{type(e).__name__}: {e}"}
                job.stamp("done")
                job.publish("error", job.error)
            raise
        return len(jobs)

    def _run(self) -> None:
        while not self._stop:
            try:
                self.step(block=True, timeout=0.5)
            # benorlint: allow-broad-except — the failed batch's jobs
            # already carry their error events (step's boundary); the
            # worker loop must survive to serve every OTHER tenant
            except Exception as e:  # noqa: BLE001
                import traceback
                REGISTRY.counter("serve.batch_errors").inc()
                snap = {
                    "error": f"{type(e).__name__}: {e}",
                    "ts": time.time(),
                    "traceback": traceback.format_exc(limit=20),
                }
                with self._cv:
                    self.batch_errors += 1
                    self.last_error = snap

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- the launch itself ------------------------------------------------
    def _capacity_for(self, key, n_jobs: int) -> int:
        """The capacity rung a batch of ``n_jobs`` launches at: the
        SMALLEST already-warm rung that fits, else the next power of
        two.  Preferring a warm (larger, padded) executable over
        compiling a tighter one is what keeps a partial tail batch —
        or any ragged arrival pattern — at zero steady-state compiles:
        once the top rung is warm, every batch reuses it."""
        want = min(_next_pow2(n_jobs), self.max_batch_jobs)
        warm = sorted(c for (k, c) in self._pool if k == key and c >= want)
        return warm[0] if warm else want

    def _executor(self, key, capacity: int, rep_cfg: SimConfig,
                  args) -> WarmExecutor:
        from ..perfscope.instrument import aot_compile

        pool_key = (key, capacity)
        ex = self._pool.get(pool_key)
        if ex is not None:
            return ex
        kind = key[0]
        runner = (_make_dyn_runner(rep_cfg, capacity) if kind == "dyn"
                  else _make_static_runner(rep_cfg))
        label = f"serve.bucket.{kind}.c{capacity}"
        with warnings.catch_warnings():
            # XLA:CPU has no donation support and warns the donated
            # buffers went unused — the platform gap the sweep engine
            # documents, not a serve bug
            warnings.filterwarnings(
                "ignore", message=".*donated buffers were not usable.*")
            art = aot_compile(runner, args, label=label,
                              donate_argnums=(0,))
        ex = WarmExecutor(art, rep_cfg, capacity, kind)
        with self._cv:
            # the batcher thread is the only writer, but readers
            # (the /v1/stats route on the event loop) snapshot under
            # the same lock — an unlocked insert would let a dict grown
            # mid-iteration 500 a stats request
            self._pool[pool_key] = ex
            self.executor_compiles += art.backend_compiles
        REGISTRY.counter("serve.executor_builds").inc()
        return ex

    def _execute(self, key, jobs: List[Job]) -> None:
        import jax
        import jax.numpy as jnp

        from ..state import DynParams, NetState, init_state
        from ..sweep import _stack_tree

        t_start = time.perf_counter()
        for job in jobs:
            # state already claimed as 'running' under the job lock in
            # step() — this is the announcement, not the transition
            job.publish("running", {"job": job.id, "batch": len(jobs)})
        # host-side slot prep: run_point's exact inputs, per job
        cfgs = [j.cfg for j in jobs]
        prep = [job_inputs(c) for c in cfgs]
        states = [init_state(c, iv, fl) for c, (iv, fl) in zip(cfgs, prep)]
        faults = [fl for (_, fl) in prep]
        kind = key[0]
        if kind == "dyn":
            capacity = self._capacity_for(key, len(jobs))
            pad = capacity - len(jobs)
            # pad slots repeat the last job's inputs; their result slices
            # are computed and discarded (a fixed capacity rung is what
            # keeps steady-state serving at zero new compiles)
            states = states + [states[-1]] * pad
            faults_p = faults + [faults[-1]] * pad
            cfgs_p = cfgs + [cfgs[-1]] * pad
            args = (_stack_tree(states), _stack_tree(faults_p),
                    DynParams.stack(cfgs_p),
                    jnp.asarray([c.seed for c in cfgs_p], jnp.int32))
            ex = self._executor(key, capacity, cfgs[0], args)
            t_launch = time.perf_counter()
            for job in jobs:
                job.stamp("launch_start", t_launch)
            with REGISTRY.timer("serve.launch").time():
                *summ, _fin = ex.artifact.compiled(*args)
                out = [np.asarray(o) for o in summ]     # fetch = barrier
            del _fin
            t_fetched = time.perf_counter()
            for job in jobs:
                job.stamp("launch_end", t_fetched)
            raws = [[o[i] for o in out] for i in range(len(jobs))]
        else:
            # quorum-specialized bucket (pallas kernels / exact tables /
            # dense top-k masks): capacity-1 launches, warm across seeds
            capacity, pad = 1, 0
            ex = None
            raws = []
            for job, st, fl, c in zip(jobs, states, faults, cfgs):
                # donated state must not alias the undonated faults arg
                # (init_state aliases killed to faults.faulty under the
                # crash model — the sweep engine's exact workaround)
                st = NetState(x=st.x, decided=st.decided, k=st.k,
                              killed=jnp.array(st.killed))
                args = (st, fl, jnp.int32(c.seed))
                ex = self._executor(key, 1, c, args)
                job.stamp("launch_start")
                with REGISTRY.timer("serve.launch").time():
                    *summ, _fin = ex.artifact.compiled(*args)
                    raws.append([np.asarray(o) for o in summ])
                del _fin
                job.stamp("launch_end")
                ex.launches += 1
                self.launches += 1
        if kind == "dyn":
            ex.launches += 1
            self.launches += 1
        launch_s = time.perf_counter() - t_start
        n_launches = 1 if kind == "dyn" else len(jobs)
        REGISTRY.counter("serve.launches").inc(n_launches)
        # batch occupancy/pad sampled per batch: how much of the rung
        # capacity this batch actually used vs repeated pad slots.  The
        # slot denominator is the DISPATCHED capacity — one padded rung
        # for dyn, len(jobs) sequential capacity-1 launches for a
        # quorum-specialized bucket (whose occupancy is 1.0 by
        # construction, never an impossible >100%)
        slots = capacity if kind == "dyn" else len(jobs)
        REGISTRY.gauge("serve.batch_occupancy").set(len(jobs) / slots)
        REGISTRY.gauge("serve.batch_pad_ratio").set(pad / slots)
        self._emit_batch_spans(key, jobs, capacity, pad, slots,
                               n_launches, t_start)

        # -- result slices, one per batch slot ----------------------------
        from ..sweep import point_from_raw
        for job, vals, fl in zip(jobs, raws, faults):
            point = point_from_raw(job.cfg, vals, launch_s / len(jobs))
            job.stamp("result_sliced")
            self._publish_result(job, point, fl, len(jobs))
        self.jobs_completed += len(jobs)
        done = self.jobs_completed
        REGISTRY.counter("serve.jobs_completed").inc(len(jobs))
        if self.launches:
            REGISTRY.gauge("serve.jobs_per_launch").set(
                done / self.launches)

    def _emit_batch_spans(self, key, jobs: List[Job], capacity: int,
                          pad: int, slots: int, n_launches: int,
                          t_start: float) -> None:
        """One batch-level span per drained batch (coalesce window, pad
        ratio, capacity rung, launch count — 1 padded rung for dyn,
        len(jobs) sequential capacity-1 launches for a
        quorum-specialized bucket), flow-linked to each job slot it
        carried — the Perfetto arrow from the launch to the jobs it
        amortized over.  No-op unless the SPANS plane is enabled."""
        if not SPANS.enabled:
            return
        t_end = time.perf_counter()
        enq = [j.stamps.get("enqueued") for j in jobs]
        enq = [t for t in enq if t is not None]
        # coalesce window: how long the OLDEST slot waited for the batch
        # to form — the submit-to-launch spread coalescing trades for
        coalesce_s = (t_start - min(enq)) if enq else 0.0
        flows = []
        for job in jobs:
            job._flow = SPANS.new_flow()
            flows.append(job._flow)
        SPANS.add(
            f"batch {key[0]} c{capacity}",
            perf_to_epoch(t_start), t_end - t_start,
            track="serve.batcher", flow_out=flows,
            args={"jobs": len(jobs), "capacity": capacity, "pad": pad,
                  "launches": n_launches,
                  "pad_ratio": round(pad / slots, 4),
                  "occupancy": round(len(jobs) / slots, 4),
                  "coalesce_window_s": round(max(0.0, coalesce_s), 6),
                  "queue_depth_at_drain":
                      REGISTRY.gauge("serve.queue_depth").value,
                  "job_ids": [j.id for j in jobs]})

    def _publish_result(self, job: Job, point, faults,
                        batch_jobs: int) -> None:
        """Stream the observability rows, then the result — the SSE feed
        a client receives instead of poll-until-done."""
        if job.state == "cancelled":
            return                        # disconnected client: discard
        if point.round_history is not None:
            from ..utils.metrics import round_history_rows
            for row in round_history_rows(point.round_history):
                job.publish("round", row)
        audit_blob = None
        if point.witness is not None:
            from ..audit import audit_witness, witness_rows, WitnessBundle
            from ..state import witness_node_ids
            for row in witness_rows(point.witness,
                                    job.cfg.witness_trials,
                                    witness_node_ids(job.cfg)):
                job.publish("witness", row)
            bundle = WitnessBundle.from_run(job.cfg, point.witness,
                                            faults=faults,
                                            label=f"serve {job.id}")
            report = audit_witness(bundle)
            audit_blob = {"ok": report.ok,
                          "violations": len(report.violations),
                          "summary": report.summary()}
            job.publish("audit", audit_blob)
        res = result_dict(point, job.spec)
        res["job"] = job.id
        res["batch_jobs"] = batch_jobs
        if audit_blob is not None:
            res["audit"] = audit_blob
        job.result = res
        job.launch_jobs = batch_jobs
        job.state = "done"
        job.stamp("done")
        job.publish("result", res)
        job.publish("done", {"job": job.id})
        # a job nobody is streaming gets its spans here; a streamed job
        # (the flag is set BEFORE enqueue, so this cannot race the SSE
        # leg's waiter registration) waits for server._forward_events
        # to emit after its last write, stream-out stage attributed
        if SPANS.enabled and not job._streamed and not job._waiters:
            emit_job_spans(job)

    # -- stats ------------------------------------------------------------
    def executors_snapshot(self):
        """A consistent [(pool_key, WarmExecutor)] snapshot for readers
        on other threads (the stats route) — taken under the queue lock
        the pool's writer holds during inserts."""
        with self._cv:
            return list(self._pool.items())

    def stats(self) -> dict:
        with self._cv:
            depth = sum(len(q) for q in self._queues.values())
            return {
                "jobs_submitted": self.jobs_submitted,
                "jobs_completed": self.jobs_completed,
                "queue_depth": depth,
                "launches": self.launches,
                "jobs_per_launch": (self.jobs_completed / self.launches
                                    if self.launches else 0.0),
                "executors": len(self._pool),
                "executor_compiles": self.executor_compiles,
                "buckets_live": len(self._queues),
                "max_batch_jobs": self.max_batch_jobs,
                "batch_errors": self.batch_errors,
                "last_error": self.last_error,
            }


def emit_job_spans(job: Job) -> None:
    """Render one job's stamp timeline as Perfetto spans: a whole-job
    parent span plus one child span per attributed stage on the job's
    own track (time containment nests them), the launch stage carrying
    the batch's flow link so the arrow from ``serve.batcher``'s launch
    slice lands on this job.  At most once per job; ownership is
    decided at SUBMIT time (``Job._streamed``) — a streamed job's spans
    are emitted by the SSE leg after its last write (stream-out stage
    included, done re-stamped at delivery), everything else by the
    result-publish path.  No-op with tracing off."""
    if not SPANS.enabled:
        return
    with job._lock:
        if job._spans_emitted:
            return
        job._spans_emitted = True
        stamps = dict(job.stamps)
    acc, done = stamps.get("accepted"), stamps.get("done")
    if acc is None or done is None:
        return
    track = f"job {job.id}"
    parent = SPANS.add(
        f"{job.spec.kind} {job.id}", perf_to_epoch(acc),
        done - acc, track=track,
        args={"bucket": job.bucket[0], "state": job.state,
              "batch_jobs": job.launch_jobs})
    for name, a, b in STAGES:
        if a in stamps and b in stamps:
            SPANS.add(name, perf_to_epoch(stamps[a]),
                      max(0.0, stamps[b] - stamps[a]), track=track,
                      parent_id=parent,
                      flow_in=job._flow if name == "launch" else None)


# --------------------------------------------------------------------------
# Bucket runners — the same compiled bodies the batched sweep engine
# builds, reshaped around the job axis
# --------------------------------------------------------------------------


def _make_dyn_runner(cfg: SimConfig, capacity: int):
    """[B]-vmapped dynamic-F runner: each batch slot runs its own
    (state, faults, dyn, seed) lane through ``run_consensus_traced`` +
    ``_summarize_inline`` — the sweep engine's bucket executable with
    the per-point base_key generalized to a traced per-slot seed."""
    import jax

    from ..sim import run_consensus_traced
    from ..sweep import _summarize_inline

    def runner(states, faults, dyn, seeds):
        def one(s, fl, d, seed):
            bk = jax.random.key(seed)
            out = run_consensus_traced(cfg, s, fl, bk, d)
            r, fin = out[0], out[1]
            summ = _summarize_inline(cfg, r, fin, fl)
            return summ + tuple(out[2:]) + (fin,)
        return jax.vmap(one)(states, faults, dyn, seeds)
    return runner


def _make_static_runner(cfg: SimConfig):
    """Capacity-1 runner for quorum-specialized buckets: the classic
    ``run_consensus`` dispatch (pallas fast path preserved), seed traced
    so one executable stays warm across clients."""
    import jax

    from ..sim import run_consensus
    from ..sweep import _summarize_inline

    def runner(state, faults, seed):
        bk = jax.random.key(seed)
        out = run_consensus(cfg, state, faults, bk)
        r, fin = out[0], out[1]
        summ = _summarize_inline(cfg, r, fin, faults)
        return summ + tuple(out[2:]) + (fin,)
    return runner
