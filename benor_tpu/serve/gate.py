"""Serve regression gate: band-compare two serve manifests.

STDLIB-ONLY by contract: ``tools/check_serve_regression.py`` loads this
file BY PATH so a CI image can gate a load-test manifest against the
committed SERVE_BASELINE.json without initializing any JAX backend —
the same discipline as ``perfscope/baseline.py`` and
``meshscope/scalegate.py`` (an import creep here breaks that gate
immediately).

What gates by default (structural, machine-insensitive):

  * ``errors``                 any client error is a regression — the
                               request plane's first contract is that
                               every accepted job completes
  * ``jobs_completed``         must equal ``jobs_submitted`` (a leaked
                               batch slot is a serving bug even when no
                               client noticed)
  * ``jobs_per_launch``        the coalescing efficiency — the number
                               serving exists to produce.  A ratio at
                               or below 1.0 where the baseline
                               amortized launches is the WORST
                               collapse (the request plane degenerated
                               to per-job dispatch); otherwise it bands
                               at ``COALESCING_BAND`` of baseline.
  * ``attribution.ok``         servescope's completeness cross-check:
                               the per-stage means must telescope to
                               the client mean latency within
                               ``ATTRIBUTION_BAND``.  A manifest whose
                               attribution broke is hiding where the
                               time went — structural, so it gates
                               unconditionally.
  * stage p99s                 ``stages.queue_wait.p99`` and
                               ``stages.launch.p99`` band against the
                               baseline at ``STAGE_P99_BANDS`` (a
                               generous ratio, and only when the
                               regression exceeds
                               ``MIN_STAGE_DELTA_MS`` — these are the
                               two stages whose blowups are SERVING
                               bugs, a starved batcher or a collapsed
                               executor, rather than machine noise).

Wall-clock metrics (p50/p99 latency, throughput) are carried for trend
reading and gate only under an explicit ``timing_band`` — shared CI
machines make them noisy, exactly like the perf gate's stage timings.
The two default-gated stage p99s trade that caution for coverage via
the wide band + absolute-delta floor.

Comparability (exit 3, never a confident verdict): kind/schema_version
mismatch, different platform, different job scale block, or a manifest
driven with fewer clients than the baseline (latency at 100 clients
says nothing about saturation at 1000).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

#: Floor on new/baseline jobs-per-launch ratio before it counts as a
#: coalescing regression.
COALESCING_BAND = 0.8

#: How far the stage-mean sum may drift from the client mean latency
#: before the attribution is considered incomplete (|coverage-1| <=
#: band).  The slack absorbs what the server legitimately cannot stamp:
#: connection setup and the wire time outside accepted->done.
ATTRIBUTION_BAND = 0.25

#: Default stage-p99 ceilings vs baseline: new_p99 regresses when it
#: exceeds band x baseline AND the delta clears MIN_STAGE_DELTA_MS.
STAGE_P99_BANDS = {"queue_wait": 2.0, "launch": 2.0}

#: Absolute floor under which a stage-p99 blowup is ignored (2x of
#: nothing is noise, not a regression).
MIN_STAGE_DELTA_MS = 50.0

#: Schema version this comparator understands (v2 = stage latencies +
#: attribution; a v1 manifest predates servescope and cannot be gated
#: honestly against a v2 baseline).
SCHEMA_VERSION = 2


class IncomparableServe(Exception):
    """The two manifests cannot be honestly compared."""


@dataclasses.dataclass
class ServeFinding:
    """One gated regression."""

    metric: str
    message: str

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


def _require(manifest: Dict, name: str) -> Dict:
    if not isinstance(manifest, dict) or \
            manifest.get("kind") != "serve_manifest":
        raise IncomparableServe(f"{name} is not a serve manifest "
                                f"(kind={manifest.get('kind')!r})")
    if manifest.get("schema_version") != SCHEMA_VERSION:
        raise IncomparableServe(
            f"{name} schema_version {manifest.get('schema_version')!r} "
            f"!= {SCHEMA_VERSION}")
    return manifest


def compare_serve(manifest: Dict, baseline: Dict,
                  coalescing_band: float = COALESCING_BAND,
                  timing_band: Optional[float] = None,
                  stage_bands: Optional[Dict[str, float]] = None
                  ) -> List[ServeFinding]:
    """New manifest vs baseline -> regression findings (empty = in-band).

    Raises IncomparableServe when a verdict would be dishonest (see
    module docstring); the CLI maps that to exit 3.
    """
    _require(manifest, "manifest")
    _require(baseline, "baseline")
    for key in ("platform",):
        if manifest.get(key) != baseline.get(key):
            raise IncomparableServe(
                f"{key} differs: {manifest.get(key)!r} vs baseline "
                f"{baseline.get(key)!r} — recapture on the baseline "
                f"platform or re-baseline")
    if manifest.get("scale") != baseline.get("scale"):
        raise IncomparableServe(
            f"job scale differs: {manifest.get('scale')} vs baseline "
            f"{baseline.get('scale')}")
    if manifest.get("clients", 0) < baseline.get("clients", 0):
        raise IncomparableServe(
            f"manifest drove {manifest.get('clients')} clients, baseline "
            f"{baseline.get('clients')} — saturation metrics at lower "
            f"concurrency are not comparable")

    findings: List[ServeFinding] = []
    errors = manifest.get("errors", 0)
    if errors:
        findings.append(ServeFinding(
            "errors", f"{errors} of {manifest.get('clients')} clients "
                      f"errored (baseline serves every accepted job)"))
    if manifest.get("jobs_completed") != manifest.get("jobs_submitted"):
        findings.append(ServeFinding(
            "jobs_completed",
            f"completed {manifest.get('jobs_completed')} of "
            f"{manifest.get('jobs_submitted')} submitted jobs — a batch "
            f"slot leaked"))
    new_jpl = float(manifest.get("jobs_per_launch") or 0.0)
    base_jpl = float(baseline.get("jobs_per_launch") or 0.0)
    if base_jpl > 1.0 and new_jpl <= 1.0:
        findings.append(ServeFinding(
            "jobs_per_launch",
            f"coalescing collapsed to {new_jpl:.3f} jobs/launch "
            f"(baseline {base_jpl:.3f}): the request plane degenerated "
            f"to per-job dispatch — the worst serving collapse"))
    elif base_jpl > 0 and new_jpl < base_jpl * coalescing_band:
        findings.append(ServeFinding(
            "jobs_per_launch",
            f"coalescing {new_jpl:.3f} < {coalescing_band} x baseline "
            f"{base_jpl:.3f} jobs/launch"))
    attr = manifest.get("attribution") or {}
    if not attr.get("ok", False):
        findings.append(ServeFinding(
            "attribution",
            f"stage attribution incomplete: stage means sum to "
            f"{attr.get('stage_mean_sum_ms')} ms vs client mean "
            f"{attr.get('client_mean_ms')} ms (coverage "
            f"{attr.get('coverage')}, band {attr.get('band')}) — a "
            f"transition went unstamped, the timeline is lying by "
            f"omission"))
    for stage, band in (STAGE_P99_BANDS if stage_bands is None
                        else stage_bands).items():
        new_p99 = float((manifest.get("stages") or {})
                        .get(stage, {}).get("p99") or 0.0)
        base_p99 = float((baseline.get("stages") or {})
                         .get(stage, {}).get("p99") or 0.0)
        if (new_p99 > base_p99 * band
                and new_p99 - base_p99 > MIN_STAGE_DELTA_MS):
            findings.append(ServeFinding(
                f"stages.{stage}.p99",
                f"{stage} p99 {new_p99:.1f} ms > {band} x baseline "
                f"{base_p99:.1f} ms (delta over the "
                f"{MIN_STAGE_DELTA_MS:.0f} ms noise floor) — the "
                f"request plane's {stage} stage regressed"))
    if timing_band is not None:
        thr = float(manifest.get("throughput_jobs_per_sec") or 0.0)
        base_thr = float(baseline.get("throughput_jobs_per_sec") or 0.0)
        if base_thr > 0 and thr < base_thr * timing_band:
            findings.append(ServeFinding(
                "throughput_jobs_per_sec",
                f"throughput {thr:.2f} < {timing_band} x baseline "
                f"{base_thr:.2f} jobs/s"))
        p99 = float((manifest.get("latency_ms") or {}).get("p99") or 0.0)
        base_p99 = float((baseline.get("latency_ms") or {}).get("p99")
                         or 0.0)
        if base_p99 > 0 and p99 * timing_band > base_p99:
            findings.append(ServeFinding(
                "latency_ms.p99",
                f"p99 latency {p99:.1f} ms > baseline {base_p99:.1f} ms "
                f"/ band {timing_band}"))
    return findings
