"""benor-serve: the async multi-tenant HTTP+SSE request plane.

One asyncio server, many concurrent clients, one batch plane: handlers
validate and enqueue jobs (serve/jobs.py) and stream results back as
**server-sent events** — the flight recorder's round rows and the
witness plane's forensic rows push to the client on the PR 6
``since_round`` cursor plane instead of the reference's
poll-until-done loop; the device work itself happens on the batcher
thread (serve/batcher.py), so no handler ever blocks the event loop on
a compile or a launch (benorlint's ``serve-blocking-call`` rule polices
exactly that).

Routes (all JSON unless SSE):

    GET  /healthz                      200 {"ok": true}
    GET  /v1/stats                     batch-plane stats: launches,
                                       jobs-per-launch coalescing ratio,
                                       queue depth, warm-executor pool
    POST /v1/jobs                      submit a JobSpec document.
         ?stream=sse (or Accept: text/event-stream): the response IS the
         job's event stream — queued/running status, ``round`` rows
         (id: = the round cursor), ``witness`` rows, ``audit`` verdict,
         ``result``, ``done``.  Without streaming: 202 with job ids +
         the events URL.  Malformed specs: 400 with the structured
         JobError body (field + reason), never a bare string.
    GET  /v1/jobs/<id>                 job status / result snapshot
    GET  /v1/jobs/<id>/timing          servescope stage attribution: the
         job's nine-stamp timeline reduced to per-stage seconds
         (jobs.STAGES), stream sub-stages, stamps relative to accepted
    GET  /v1/jobs/<id>/events          SSE stream of one job;
         ?since_round=N resumes the round feed past a cursor (rows with
         round <= N are skipped — the HTTP /getRoundHistory contract,
         pushed instead of polled).  Last-Event-ID is honored as the
         same cursor on reconnect.

Every response carries an ``X-Request-Id`` header — the client's own
(echoed when it is a sane correlation token) or a server-minted one —
and, when the servescope span plane is armed (``SPANS.enable()``, the
CLI's ``--trace-out``), each request lands as an ``http``-track span in
the Perfetto export next to the batcher's batch/job spans.

A client that disconnects mid-stream FREES its batch slot: the read
side of the connection is watched concurrently with the event
forwarder, and a closed socket cancels the job (a queued job leaves the
queue; an in-flight launch finishes on device but the orphan result is
discarded) — tests/test_serve.py pins it.

Scale posture: this is the demo-scale front door of the serving story —
stdlib-only HTTP on one event loop, thousands of concurrent
connections, with the throughput coming from the batch plane's
coalescing (serve/loadgen.py measures it; the committed
SERVE_BASELINE.json gates it).  ``backends/http_api.py`` remains the
reference-parity per-node control plane at port-per-node demo scale.
"""

from __future__ import annotations

import asyncio
import json
import re
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from ..utils.metrics import REGISTRY, SPANS, perf_to_epoch
from .batcher import Batcher, Job, emit_job_spans
from .jobs import JobError, timing_dict

#: Request caps: the request plane parses untrusted bytes.
MAX_HEADERS = 64
MAX_BODY = 1 << 20
READ_TIMEOUT_S = 30.0
#: SSE keepalive cadence while a stream is idle (a comment line, so
#: proxies don't reap the connection and the client can detect liveness).
KEEPALIVE_S = 10.0

_JSON = "application/json"

#: A client-supplied X-Request-Id is echoed VERBATIM only when it looks
#: like a sane correlation token; anything else (header-injection bytes,
#: unbounded length) is replaced by a server-minted id.
_REQ_ID_OK = re.compile(r"^[A-Za-z0-9_.:-]{1,64}$")


def _request_id(headers: Dict[str, str]) -> str:
    rid = headers.get("x-request-id", "")
    if _REQ_ID_OK.match(rid):
        return rid
    return f"r-{uuid.uuid4().hex[:16]}"


class _BadRequest(Exception):
    def __init__(self, body: dict, code: int = 400,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(body.get("error", "bad request"))
        self.body = body
        self.code = code
        #: Whatever request headers were parsed before the rejection —
        #: lets the error response still echo the client's
        #: X-Request-Id (the correlation matters MOST on errors).
        self.headers = headers or {}


def _sse_bytes(etype: str, payload, eid=None) -> bytes:
    out = f"event: {etype}\n"
    if eid is not None:
        out += f"id: {eid}\n"
    return (out + f"data: {json.dumps(payload)}\n\n").encode()


class ServeApp:
    """The serving front door: one asyncio server over one Batcher.

    Use as an async context (``await app.start_async()`` inside a
    running loop) or synchronously (``app.start()`` spins a daemon
    thread owning the loop — what the CLI's in-process load mode, the
    tests and bench.py's serve check do).  ``port=0`` binds an
    ephemeral port, re-read from ``app.port`` after start.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 batcher: Optional[Batcher] = None,
                 max_batch_jobs: Optional[int] = None,
                 limits: Optional[dict] = None):
        self.host = host
        self.port = port
        kw = {} if max_batch_jobs is None else \
            {"max_batch_jobs": max_batch_jobs}
        self.batcher = batcher if batcher is not None else \
            Batcher(limits=limits, **kw)
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._owns_batcher = batcher is None

    # -- lifecycle --------------------------------------------------------
    async def start_async(self) -> "ServeApp":
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._loop = asyncio.get_running_loop()
        return self

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start_async()
        async with self._server:
            await self._server.serve_forever()

    def start(self) -> "ServeApp":
        """Run the server on a background daemon thread (sync callers)."""
        started = threading.Event()

        def run():
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)
            loop.run_until_complete(self.start_async())
            started.set()
            try:
                loop.run_forever()
            finally:
                loop.run_until_complete(loop.shutdown_asyncgens())
                loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="benor-serve-http")
        self._thread.start()
        if not started.wait(timeout=10):
            raise RuntimeError("serve plane failed to start")
        return self

    def close(self) -> None:
        if self._loop is not None and self._thread is not None:
            def _stop():
                if self._server is not None:
                    self._server.close()
                self._loop.stop()
            try:
                self._loop.call_soon_threadsafe(_stop)
            except RuntimeError:
                pass
            self._thread.join(timeout=5)
        elif self._server is not None:
            self._server.close()
        if self._owns_batcher:
            self.batcher.close()

    def __enter__(self) -> "ServeApp":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request plumbing -------------------------------------------------
    async def _read_request(self, reader) -> Optional[Tuple]:
        line = await asyncio.wait_for(reader.readline(), READ_TIMEOUT_S)
        if not line:
            return None
        parts = line.decode("latin1").strip().split()
        if len(parts) != 3:
            raise _BadRequest({"error": "malformed request line"})
        method, target, _version = parts
        headers: Dict[str, str] = {}
        while True:
            h = await asyncio.wait_for(reader.readline(), READ_TIMEOUT_S)
            if h in (b"\r\n", b"\n", b""):
                break
            if len(headers) >= MAX_HEADERS:
                raise _BadRequest({"error": "too many headers"},
                                  headers=headers)
            k, _, v = h.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        try:
            length = int(headers.get("content-length", "0") or 0)
        except ValueError:
            raise _BadRequest({"error": "malformed Content-Length"},
                              headers=headers)
        if length < 0 or length > MAX_BODY:
            raise _BadRequest({"error": "body too large"}, code=413,
                              headers=headers)
        body = b""
        if length:
            body = await asyncio.wait_for(reader.readexactly(length),
                                          READ_TIMEOUT_S)
        url = urlsplit(target)
        query = {k: v[-1] for k, v in parse_qs(url.query).items()}
        return method, url.path, query, headers, body

    async def _respond(self, writer, code: int, body: dict,
                       content_type: str = _JSON,
                       req_id: Optional[str] = None) -> None:
        data = json.dumps(body).encode()
        reason = {200: "OK", 202: "Accepted", 400: "Bad Request",
                  404: "Not Found", 405: "Method Not Allowed",
                  413: "Payload Too Large",
                  500: "Internal Server Error"}.get(code, "OK")
        rid = f"X-Request-Id: {req_id}\r\n" if req_id else ""
        head = (f"HTTP/1.1 {code} {reason}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(data)}\r\n{rid}"
                f"Connection: close\r\n\r\n")
        writer.write(head.encode() + data)
        await writer.drain()

    async def _handle(self, reader, writer) -> None:
        REGISTRY.counter("serve.http_requests").inc()
        t_req = time.perf_counter()
        rid: Optional[str] = None
        method = path = "?"
        try:
            req = await self._read_request(reader)
            if req is None:
                return
            method, path, query, headers, body = req
            rid = _request_id(headers)
            await self._route(reader, writer, method, path, query,
                              headers, body, rid, accepted_t=t_req)
        except _BadRequest as e:
            if rid is None:
                # rejected inside _read_request: the exception carries
                # whatever headers were parsed, so the error response
                # still echoes the client's correlation id (or mints)
                rid = _request_id(e.headers)
            try:
                # drain whatever request bytes are still in flight before
                # replying and closing: responding with unread data
                # pending turns the close into a TCP RST that can discard
                # the error body (backends/http_api._drain_best_effort's
                # exact lesson, applied asyncio-side — matters most for
                # the 413 path, which rejects on the header alone)
                await _drain_reader(reader)
                await self._respond(writer, e.code, e.body, req_id=rid)
            except ConnectionError:
                pass
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionError):
            pass
        # benorlint: allow-broad-except — one bad request must never take
        # the request plane down; the failure surfaces to THIS client as
        # a 500 and ticks the serve.http_errors counter
        except Exception as e:  # noqa: BLE001
            REGISTRY.counter("serve.http_errors").inc()
            try:
                await self._respond(
                    writer, 500, {"error": f"{type(e).__name__}: {e}"},
                    req_id=rid or _request_id({}))
            except ConnectionError:
                pass
        finally:
            if SPANS.enabled:
                SPANS.add(f"{method} {path}", perf_to_epoch(t_req),
                          time.perf_counter() - t_req, track="http",
                          args={"request_id": rid or "?"})
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, RuntimeError):
                pass

    async def _route(self, reader, writer, method, path, query, headers,
                     body, req_id: Optional[str] = None,
                     accepted_t: Optional[float] = None) -> None:
        if path == "/healthz":
            await self._respond(writer, 200, {"ok": True}, req_id=req_id)
            return
        if path == "/v1/stats":
            await self._respond(writer, 200, self._stats(), req_id=req_id)
            return
        if path == "/v1/jobs":
            if method != "POST":
                raise _BadRequest({"error": "submit jobs with POST"},
                                  code=405)
            await self._submit(reader, writer, query, headers, body,
                               req_id, accepted_t)
            return
        if path.startswith("/v1/jobs/"):
            rest = path[len("/v1/jobs/"):]
            job_id, _, tail = rest.partition("/")
            job = self.batcher.get(job_id)
            if job is None:
                await self._respond(writer, 404,
                                    {"error": f"no job {job_id!r}"},
                                    req_id=req_id)
                return
            if tail == "events":
                since = _since_round(query, headers)
                await self._stream(reader, writer, [job], since, req_id)
            elif tail == "timing":
                await self._respond(writer, 200, _job_timing(job),
                                    req_id=req_id)
            elif tail == "":
                await self._respond(writer, 200, _job_snapshot(job),
                                    req_id=req_id)
            else:
                await self._respond(writer, 404,
                                    {"error": f"no route {path}"},
                                    req_id=req_id)
            return
        await self._respond(writer, 404, {"error": f"no route {path}"},
                            req_id=req_id)

    def _stats(self) -> dict:
        stats = self.batcher.stats()
        stats["executors_detail"] = [
            {"bucket": k[0][0], "capacity": k[1], "launches": ex.launches,
             "compile_s": round(ex.artifact.compile_s, 4),
             "label": ex.artifact.label}
            for k, ex in sorted(self.batcher.executors_snapshot(),
                                key=lambda kv: kv[1].artifact.label)]
        stats["sse_clients"] = REGISTRY.gauge("serve.sse_clients").value
        return stats

    # -- submit + stream --------------------------------------------------
    async def _submit(self, reader, writer, query, headers, body,
                      req_id: Optional[str] = None,
                      accepted_t: Optional[float] = None) -> None:
        # ``accepted`` anchors at HANDLER ENTRY (before the request was
        # even read off the socket), so the validate stage attributes
        # the ingress queueing a loaded event loop imposes between
        # accept and parse — without it, a connect storm's wait is
        # invisible to the stage sum and the attribution cross-check
        # rightly fails
        if accepted_t is None:
            accepted_t = time.perf_counter()
        try:
            doc = json.loads(body.decode("utf-8")) if body else {}
        except (ValueError, UnicodeDecodeError):
            raise _BadRequest({"error": "invalid job",
                               "field": "$",
                               "reason": "body must be valid JSON"})
        stream = (query.get("stream") == "sse"
                  or "text/event-stream" in headers.get("accept", ""))
        try:
            jobs = self.batcher.submit_dict(doc, accepted_t=accepted_t,
                                            streamed=stream)
        except JobError as e:
            raise _BadRequest(e.body)
        if not stream:
            await self._respond(writer, 202, {
                "jobs": [j.id for j in jobs],
                "bucket": jobs[0].bucket[0],
                "events": [f"/v1/jobs/{j.id}/events" for j in jobs],
            }, req_id=req_id)
            return
        await self._stream(reader, writer, jobs,
                           _since_round(query, headers), req_id)

    async def _stream(self, reader, writer, jobs: List[Job],
                      since_round: Optional[int],
                      req_id: Optional[str] = None) -> None:
        """The SSE leg: forward each job's event feed, racing a watcher
        on the connection's read side so a vanished client cancels its
        jobs instead of holding batch slots."""
        rid = (f"X-Request-Id: {req_id}\r\n" if req_id else "").encode()
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-store\r\n" + rid +
                     b"Connection: close\r\n\r\n")
        # the client gauge pairs with the finally-side decrement, so the
        # increment must cover EVERY await that can fail (the header
        # drain included — an increment outside this try leaked a
        # phantom client forever on a write failure there); the
        # opened/closed counters are the monotone audit pair the gauge
        # can be cross-checked against
        REGISTRY.gauge("serve.sse_clients").set(
            REGISTRY.gauge("serve.sse_clients").value + 1)
        REGISTRY.counter("serve.sse_opened").inc()
        try:
            await writer.drain()
            forward = asyncio.ensure_future(
                self._forward_events(writer, jobs, since_round))
            watch = asyncio.ensure_future(reader.read(1))
            try:
                done, _pending = await asyncio.wait(
                    {forward, watch}, return_when=asyncio.FIRST_COMPLETED)
                if forward not in done or forward.exception() is not None:
                    # client hung up (or the pipe broke mid-write): free
                    # every batch slot this stream was carrying
                    for job in jobs:
                        job.cancel()
            finally:
                for task in (forward, watch):
                    task.cancel()
                    try:
                        await task
                    except (asyncio.CancelledError, ConnectionError,
                            asyncio.IncompleteReadError):
                        pass
        finally:
            REGISTRY.gauge("serve.sse_clients").set(
                max(0.0, REGISTRY.gauge("serve.sse_clients").value - 1))
            REGISTRY.counter("serve.sse_closed").inc()

    async def _forward_events(self, writer, jobs: List[Job],
                              since_round: Optional[int]) -> None:
        for job in jobs:
            async for etype, payload in _job_events(job, since_round):
                if etype == "ping":
                    writer.write(b": keepalive\n\n")
                elif etype == "done":
                    # per-job completion is implied by its result event;
                    # ONE terminal done closes the whole stream, so a
                    # client reading until `done` gets every slot of a
                    # multi-point sweep, not just the first
                    continue
                else:
                    eid = payload.get("round") if etype == "round" else None
                    writer.write(_sse_bytes(etype, payload, eid=eid))
                await writer.drain()
                if etype in ("round", "witness", "audit", "result"):
                    # the first RESULT-PHASE byte on the wire — the
                    # stream_wait milestone inside stream_out (status
                    # events like queued/running don't count: they
                    # precede the result by construction)
                    job.stamp("first_sse")
            # this job's stream leg is fully written: re-stamp done so
            # stream_out covers SSE delivery, then render its spans
            job.stamp("done", override=True)
            emit_job_spans(job)
        writer.write(_sse_bytes("done", {"jobs": [j.id for j in jobs]}))
        await writer.drain()


async def _drain_reader(reader, cap: int = MAX_BODY,
                        idle_s: float = 0.05) -> None:
    """Best-effort async drain of a request's in-flight bytes (at most
    ``cap``), giving up after ``idle_s`` of quiet — a client awaiting
    the reply costs one short wait, never a stall."""
    drained = 0
    while drained < cap:
        try:
            chunk = await asyncio.wait_for(reader.read(1 << 16), idle_s)
        except asyncio.TimeoutError:
            return
        if not chunk:
            return
        drained += len(chunk)


def _since_round(query, headers) -> Optional[int]:
    raw = query.get("since_round", headers.get("last-event-id"))
    if raw in (None, ""):
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        raise _BadRequest({"error": "invalid job", "field": "since_round",
                           "reason": "must be an integer round index"})


def _job_snapshot(job: Job) -> dict:
    return {"id": job.id, "state": job.state, "kind": job.spec.kind,
            "bucket": job.bucket[0], "result": job.result,
            "error": job.error,
            "events_url": f"/v1/jobs/{job.id}/events"}


def _job_timing(job: Job) -> dict:
    """GET /v1/jobs/<id>/timing: the job's servescope timeline — each
    stage's attributed seconds, the stream sub-stages when it streamed,
    stamps relative to accepted, and the launch's batch size (how many
    slots amortized the launch this job rode)."""
    with job._lock:
        stamps = dict(job.stamps)
    out = {"job": job.id, "state": job.state, "kind": job.spec.kind,
           "batch_jobs": job.launch_jobs}
    out.update(timing_dict(stamps))
    return out


async def _job_events(job: Job, since_round: Optional[int]):
    """Async iterator over one job's event feed.  Wakes on the batcher
    thread's thread-safe notifications; yields ('ping', None) on idle
    keepalive cadence.  ``since_round`` filters ``round`` rows at or
    below the cursor (the /getRoundHistory contract, pushed)."""
    loop = asyncio.get_running_loop()
    ev = asyncio.Event()
    job.add_waiter(loop, ev)
    idx = 0
    try:
        while True:
            ev.clear()
            n = len(job.events)         # snapshot; list append is atomic
            while idx < n:
                etype, payload = job.events[idx]
                idx += 1
                if (etype == "round" and since_round is not None
                        and payload.get("round", 0) <= since_round):
                    continue
                yield etype, payload
            if job.done and idx >= len(job.events):
                return
            try:
                await asyncio.wait_for(ev.wait(), timeout=KEEPALIVE_S)
            except asyncio.TimeoutError:
                yield "ping", None
    finally:
        job.drop_waiter(loop, ev)


async def _amain(host: str, port: int, max_batch_jobs: Optional[int],
                 verbose: bool = True) -> None:
    app = ServeApp(host=host, port=port, max_batch_jobs=max_batch_jobs)
    await app.start_async()
    if verbose:
        import sys
        print(f"benor-serve listening on http://{app.host}:{app.port} "
              f"(POST /v1/jobs, GET /v1/stats; Ctrl-C stops)",
              file=sys.stderr, flush=True)
    try:
        await app.serve_forever()
    finally:
        app.close()


def run_server(host: str = "127.0.0.1", port: int = 8400,
               max_batch_jobs: Optional[int] = None,
               trace_out: Optional[str] = None) -> int:
    """`python -m benor_tpu serve` body: serve until interrupted.
    ``trace_out`` arms the servescope span plane for the server's
    lifetime and writes the Perfetto trace on shutdown."""
    if trace_out:
        SPANS.enable()
    try:
        asyncio.run(_amain(host, port, max_batch_jobs))
    except KeyboardInterrupt:
        pass
    finally:
        if trace_out:
            from ..utils.metrics import export_chrome_trace
            import sys
            n = export_chrome_trace(trace_out, spans=True)
            print(f"wrote {n} trace events to {trace_out} "
                  f"(open in ui.perfetto.dev)", file=sys.stderr,
                  flush=True)
    return 0
