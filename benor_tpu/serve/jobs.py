"""The reusable job API: JobSpec -> bucket -> batch slot -> result slice.

This module is the refactor ROADMAP item 1 asks for: the sweep/results
entry points (``sweep.run_point`` / ``run_curve_batched``) split into a
job-shaped API that the HTTP request plane (serve/server.py), the load
generator (serve/loadgen.py), the CLI (``python -m benor_tpu serve`` /
``load``) and bench.py's serve check all consume.  A ``JobSpec`` is the
wire-level description of one client request; validation turns it into a
``SimConfig`` plus the run_point-default inputs (per-trial random bits
seeded by the job's seed, first-F-lanes crash-faulty via
``sweep.default_crash_faults``) so that a job submitted through the
serve plane is BIT-IDENTICAL to the same config run through
``sweep.run_point`` directly — the house rule tests/test_serve.py pins.

Job kinds (the four client verbs of the request plane):

  simulate    one MC batch -> its on-device summary (a SweepPoint dict)
  sweep       a rounds-vs-f curve; expands into one simulate job per f
              value (each point is its own batch slot, so points from
              one client coalesce with other clients' points)
  trajectory  simulate with the flight recorder armed: the per-round
              history rows stream back as server-sent events on the
              ``since_round`` cursor plane (PR 6) instead of
              poll-until-done
  audit       simulate with the witness recorder armed at the
              audit.default_witness_overrides watch set; the Ben-Or
              invariants are machine-checked host-side
              (audit.audit_witness) and the verdict rides the result

Validation errors raise ``JobError`` carrying a structured body — the
server answers them as 400 with that body verbatim, so a client can
machine-read WHICH field was rejected and why.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ..config import SimConfig

#: JobSpec fields forwarded to SimConfig verbatim (everything else is
#: job-plane metadata).  A pure literal so the README's "what can a job
#: carry" table and the server's rejection messages cannot drift.
#: ``topology`` and the committee knobs (PR 12) ride here too, so the
#: request plane serves the structured-delivery workloads; they are in
#: ``serve_bucket_key`` by construction (the sweep bucket token keys on
#: the full config), so mismatched topologies never coalesce into one
#: launch while committee count/size coalesce as DynParams axes.
#: The faultlab planes (PR 15) ride here too: ``drop_prob`` coalesces as
#: a DynParams axis in ``serve_bucket_key`` (the sweep bucket token
#: erases it, so p-sweeping clients share one warm executable), while
#: ``recovery`` / ``partition`` specs are static config and separate
#: buckets — mismatched churn schedules or partition epochs never share
#: a launch.
CONFIG_FIELDS = ("n_nodes", "n_faulty", "trials", "max_rounds", "rule",
                 "seed", "coin_mode", "coin_eps", "delivery", "scheduler",
                 "adversary_strength", "fault_model", "path", "topology",
                 "committee_cap", "committee_count", "committee_size",
                 "drop_prob", "recovery", "partition")

#: The four client verbs.
JOB_KINDS = ("simulate", "sweep", "trajectory", "audit")

#: servescope's NINE job stamps, in transition order (README Serving's
#: stage model).  Every stamp is a host-side ``time.perf_counter()``
#: float taken at the transition — the batcher owns accepted through
#: result_sliced and the terminal done; the HTTP front door refines the
#: stream leg (``first_sse`` = the first result-phase event written to
#: the client, and it re-stamps ``done`` when the job's whole SSE feed
#: has been written, so stream-out time is attributed to the job).
STAGE_STAMPS = ("accepted", "validated", "enqueued", "batch_assigned",
                "launch_start", "launch_end", "result_sliced",
                "first_sse", "done")

#: The stage-latency attribution: name -> (from_stamp, to_stamp).
#: Stages are CONSECUTIVE stamp pairs, so their durations TELESCOPE —
#: when every stamp is present, the stage sum equals done - accepted
#: exactly, which is what makes the manifest's attribution
#: cross-check (stage means vs client mean latency) an honest
#: completeness test instead of an approximation.  ``first_sse`` is a
#: sub-milestone INSIDE stream_out (reported by the timing route as
#: stream_wait/stream_flush when present) so that a polled, never-
#: streamed job still attributes its full result_sliced -> done time.
STAGES = (
    ("validate", "accepted", "validated"),
    ("enqueue", "validated", "enqueued"),
    ("queue_wait", "enqueued", "batch_assigned"),
    ("batch_assemble", "batch_assigned", "launch_start"),
    ("launch", "launch_start", "launch_end"),
    ("result_slice", "launch_end", "result_sliced"),
    ("stream_out", "result_sliced", "done"),
)

#: Stage names in stage order (the manifest's ``stages`` block keys).
STAGE_NAMES = tuple(name for name, _, _ in STAGES)

#: stream_out's optional subdivision at the first_sse milestone.
SUB_STAGES = (
    ("stream_wait", "result_sliced", "first_sse"),
    ("stream_flush", "first_sse", "done"),
)


def stage_durations(stamps: Dict[str, float]) -> Dict[str, float]:
    """Stamps -> per-stage seconds (only stages whose BOTH stamps are
    present; negatives clamped to zero — a stamp pair that raced, e.g.
    a server-side done refinement landing before a slow result slice,
    must never produce negative attribution)."""
    out: Dict[str, float] = {}
    for name, a, b in STAGES:
        if a in stamps and b in stamps:
            out[name] = max(0.0, stamps[b] - stamps[a])
    return out


def timing_dict(stamps: Dict[str, float]) -> Dict[str, Any]:
    """The ``/v1/jobs/<id>/timing`` payload: per-stage seconds, the
    stream sub-stages when the job streamed, each stamp relative to
    ``accepted`` (absolute perf_counter values are meaningless across
    processes), and the fully-attributed total.  Values are rounded to
    6 dp INDEPENDENTLY, so the telescoping identity holds to ~N*0.5e-6
    in the payload (exact on the raw stamps) — consumers comparing
    sum-of-stages to total_s must allow that rounding slack."""
    stages = stage_durations(stamps)
    subs = {name: max(0.0, stamps[b] - stamps[a])
            for name, a, b in SUB_STAGES
            if a in stamps and b in stamps}
    acc = stamps.get("accepted")
    rel = {k: round(stamps[k] - acc, 6) for k in STAGE_STAMPS
           if k in stamps} if acc is not None else {}
    total = None
    if acc is not None and "done" in stamps:
        total = round(stamps["done"] - acc, 6)
    return {
        "stages_s": {k: round(v, 6) for k, v in stages.items()},
        "sub_stages_s": {k: round(v, 6) for k, v in subs.items()},
        "stamps_rel_s": rel,
        "total_s": total,
    }

#: Per-job ceilings for the DEMO-scale request plane: one over-sized job
#: would occupy a whole static-shape bucket and starve the coalescing
#: that makes serving pay (README Serving's cost model).  Operators
#: running a private instance can lift them via ServeApp(limits=...).
DEFAULT_LIMITS = {"n_nodes": 1 << 16, "trials": 1 << 12,
                  "max_rounds": 1 << 10, "f_values": 64,
                  # committee_cap sizes the [T, cap, 3] per-committee
                  # histogram inside the executable — an uncapped value
                  # would let one job allocate a trials*cap-scale buffer
                  "committee_cap": 1 << 10}


class JobError(ValueError):
    """A rejected JobSpec: ``body`` is the structured 400 payload."""

    def __init__(self, field: str, reason: str):
        super().__init__(f"{field}: {reason}")
        self.body = {"error": "invalid job", "field": field,
                     "reason": reason}


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One client job, as validated from the wire (``from_dict``)."""

    kind: str = "simulate"
    n_nodes: int = 64
    n_faulty: int = 0
    trials: int = 8
    max_rounds: int = 32
    rule: str = "reference"
    seed: int = 0
    coin_mode: str = "private"
    coin_eps: float = 0.0
    delivery: str = "all"
    scheduler: str = "uniform"
    adversary_strength: float = 0.0
    fault_model: str = "crash"
    path: str = "auto"
    #: structured delivery (benor_tpu/topo): an adjacency spec string
    #: ('complete' | 'ring:<d>' | 'torus2d:<r>x<c>' | 'expander:<d>' |
    #: 'random_regular:<d>[:seed]') or null, and the committee knobs.
    topology: Optional[str] = None
    committee_cap: int = 0
    committee_count: int = 0
    committee_size: int = 0
    #: faultlab (benor_tpu/faults): per-edge omission probability, the
    #: crash-recovery schedule spec ('at:<crash>:<down>[:amnesia|
    #: durable]' / 'stagger:...') and the partition spec
    #: ('halves:<heal>' / 'groups:<g>:<heal>') or null.
    drop_prob: float = 0.0
    recovery: Optional[str] = None
    partition: Optional[str] = None
    #: sweep kind only: the curve's f grid (expands to per-point jobs).
    f_values: Optional[Tuple[int, ...]] = None

    @classmethod
    def from_dict(cls, doc: Any,
                  limits: Optional[Dict[str, int]] = None) -> "JobSpec":
        """Validate a wire document -> JobSpec, raising JobError (the
        structured 400) on anything malformed rather than letting a bad
        value poison the batch plane downstream."""
        # an operator's limits dict MERGES over the defaults: a partial
        # override ({"n_nodes": 1 << 20}) lifts one cap without
        # KeyErroring every submit on the ones it didn't mention
        limits = {**DEFAULT_LIMITS, **(limits or {})}
        if not isinstance(doc, dict):
            raise JobError("$", "job body must be a JSON object")
        unknown = sorted(set(doc) - set(CONFIG_FIELDS)
                         - {"kind", "f_values"})
        if unknown:
            raise JobError(unknown[0],
                           f"unknown field (accepted: kind, f_values, "
                           f"{', '.join(CONFIG_FIELDS)})")
        kind = doc.get("kind", "simulate")
        if kind not in JOB_KINDS:
            raise JobError("kind", f"must be one of {list(JOB_KINDS)}")
        kw: Dict[str, Any] = {"kind": kind}
        defaults = cls()
        for f in CONFIG_FIELDS:
            if f not in doc:
                continue
            v = doc[f]
            if f in ("topology", "recovery", "partition"):
                # Optional[str]: the generic type check below would key
                # on NoneType.  Spec-string VALIDITY (grammar, degree
                # bounds, N coverage, heal rounds) is SimConfig's parse
                # at the to_config() probe — those surface as structured
                # 400s on the 'config' field.
                if v is not None and not isinstance(v, str):
                    hints = {"topology": "a topology spec string (e.g. "
                                         "'torus2d:8x8')",
                             "recovery": "a recovery schedule spec (e.g. "
                                         "'stagger:2:3:amnesia')",
                             "partition": "a partition spec (e.g. "
                                          "'halves:6')"}
                    raise JobError(f, f"must be {hints[f]} or null")
                kw[f] = v
                continue
            want = type(getattr(defaults, f))
            if want is float and isinstance(v, int) \
                    and not isinstance(v, bool):
                v = float(v)
            if not isinstance(v, want) or isinstance(v, bool):
                raise JobError(f, f"must be {want.__name__}, got "
                                  f"{type(v).__name__}")
            kw[f] = v
        fv = doc.get("f_values")
        if kind == "sweep":
            if not isinstance(fv, list) or not fv or not all(
                    isinstance(x, int) and not isinstance(x, bool)
                    for x in fv):
                raise JobError("f_values", "sweep jobs need a non-empty "
                                           "list of integer fault counts")
            if len(fv) > limits["f_values"]:
                raise JobError("f_values",
                               f"at most {limits['f_values']} points "
                               f"per sweep job")
            kw["f_values"] = tuple(int(x) for x in fv)
        elif fv is not None:
            raise JobError("f_values", f"only sweep jobs take an f grid "
                                       f"(kind={kind!r})")
        for f in ("n_nodes", "trials", "max_rounds"):
            v = kw.get(f, getattr(defaults, f))
            if v < 1:
                raise JobError(f, "must be >= 1")
            if v > limits[f]:
                raise JobError(f, f"demo-scale request plane caps {f} at "
                                  f"{limits[f]} (see README Serving)")
        if kw.get("committee_cap", 0) > limits["committee_cap"]:
            raise JobError(
                "committee_cap",
                f"demo-scale request plane caps committee_cap at "
                f"{limits['committee_cap']} (it sizes the per-committee "
                f"histogram; see README Serving)")
        if kw.get("seed", 0) < 0:
            # run_point's input stream (np.random.default_rng) rejects
            # negative seeds — surface it at validation, not in a batch
            raise JobError("seed", "must be >= 0")
        spec = cls(**kw)
        spec.to_config()        # surface SimConfig's own rejections as 400s
        return spec

    @classmethod
    def from_config(cls, cfg: SimConfig,
                    kind: str = "simulate") -> "JobSpec":
        """The serve-plane job document that replays ``cfg`` through the
        request plane with run_point's default inputs — the provenance
        hook results.py attaches to its study rows (``serve_replay``).
        Only the wire-representable fields travel (CONFIG_FIELDS);
        observability flags are the KIND's business (trajectory/audit),
        so a record/witness-armed config maps to the matching kind."""
        if cfg.witness:
            kind = "audit"
        elif cfg.record:
            kind = "trajectory"
        return cls(kind=kind,
                   **{f: getattr(cfg, f) for f in CONFIG_FIELDS})

    def to_dict(self) -> Dict[str, Any]:
        d = {f: getattr(self, f) for f in CONFIG_FIELDS}
        d["kind"] = self.kind
        if self.f_values is not None:
            d["f_values"] = list(self.f_values)
        return d

    def to_config(self) -> SimConfig:
        """The SimConfig this job runs — observability flags derived from
        the kind (trajectory arms the flight recorder, audit the witness
        plane), everything else forwarded verbatim.  SimConfig's own
        validation errors re-raise as structured JobErrors."""
        kw = {f: getattr(self, f) for f in CONFIG_FIELDS}
        if self.kind == "trajectory":
            kw["record"] = True
        elif self.kind == "audit":
            from ..audit import default_witness_overrides
            kw.update(default_witness_overrides(self.trials, self.n_nodes))
        try:
            return SimConfig(**kw)
        except ValueError as e:
            raise JobError("config", str(e)) from e

    def expand(self) -> List["JobSpec"]:
        """The batch-slot decomposition: a sweep job becomes one
        simulate job per f value (each point coalesces independently);
        every other kind is already one slot."""
        if self.kind != "sweep":
            return [self]
        return [dataclasses.replace(self, kind="simulate",
                                    n_faulty=int(f), f_values=None)
                for f in self.f_values]


def job_inputs(cfg: SimConfig):
    """(initial_values, faults) for one job — EXACTLY run_point's
    defaults (per-trial random bits from the job seed, first-F-faulty
    crash mask), shared with the oracle path so serve-vs-direct
    bit-equality is structural, not coincidental."""
    from ..sweep import default_crash_faults, random_inputs
    return (random_inputs(cfg.seed, cfg.trials, cfg.n_nodes),
            default_crash_faults(cfg))


def result_dict(point, spec: JobSpec) -> Dict[str, Any]:
    """A SweepPoint -> the JSON result payload a client receives.  The
    big per-round arrays are NOT embedded (trajectory/audit stream them
    as SSE rows); the summary matches SweepPoint.to_dict's fields."""
    out = {
        "kind": spec.kind,
        "n_nodes": point.n_nodes, "n_faulty": point.n_faulty,
        "trials": point.trials, "coin_mode": point.coin_mode,
        "scheduler": point.scheduler,
        "rounds_executed": point.rounds_executed,
        "decided_frac": point.decided_frac, "mean_k": point.mean_k,
        "ones_frac": point.ones_frac,
        "disagree_frac": point.disagree_frac,
        "k_hist": point.k_hist.tolist(),
        "seconds": point.seconds,
    }
    return out
