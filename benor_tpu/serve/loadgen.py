"""Load generator: thousands of concurrent SSE clients against the
serve plane, reduced to the pinned-schema ``kind: serve_manifest``.

Each simulated client is one asyncio task holding ONE real TCP
connection: it POSTs its JobSpec with ``?stream=sse`` and reads the
server-sent event stream until the ``done`` event, timing
submit-to-result latency end to end (connection setup included — that
is what a client experiences).  Clients get distinct seeds, so the
coalescing they exhibit is the serve plane's own (the seed-erased
bucket key), not an artifact of identical requests.

The manifest records what the acceptance gate needs: client count,
p50/p99/mean/max latency, saturation throughput (completed jobs over
the measurement wall-clock), and the **coalescing efficiency** —
jobs per executable launch, read from the server's /v1/stats delta —
plus the scale block that makes two manifests comparable.

Schema v2 adds servescope's **stage-latency attribution**: each client
captures its job id from the SSE ``queued`` event, the driver fetches
every job's ``/v1/jobs/<id>/timing`` after the measured window, and the
manifest carries per-stage p50/p99/mean blocks (jobs.STAGE_NAMES) plus
the ``attribution`` cross-check — the stage MEANS must sum to within
``gate.ATTRIBUTION_BAND`` of the client-observed mean latency, because
the stages are consecutive stamp deltas that telescope to the server's
accepted->done total; a sum that falls short means a transition went
unstamped and the attribution is lying by omission.
``tools/check_serve_regression.py`` bands it against the committed
SERVE_BASELINE.json (serve/gate.py owns the rules; stdlib-only so CI
gates without a backend).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, List, Optional

import numpy as np

from ..utils.metrics import REGISTRY
from .gate import ATTRIBUTION_BAND
from .jobs import STAGE_NAMES

#: The default per-client job: a dyn-bucket config (delivery='all',
#: crash faults, uniform scheduler — no quorum-specialized shapes), so
#: concurrent clients coalesce into shared launches.  Small enough that
#: dispatch, not device math, dominates — the regime a request plane is
#: actually measured by.
DEFAULT_JOB = {"kind": "simulate", "n_nodes": 32, "n_faulty": 4,
               "trials": 8, "max_rounds": 16, "delivery": "all"}

#: Manifest schema version (tools/serve_manifest_schema.json).  v2:
#: per-stage latency blocks + the attribution cross-check.
SCHEMA_VERSION = 2

#: Concurrency ceiling for the post-window timing fetches (one GET per
#: completed job; bounded so the fetch phase is not its own load test).
TIMING_FETCH_CONCURRENCY = 128


def _raise_fd_limit(need: int) -> None:
    """Best-effort RLIMIT_NOFILE bump: N concurrent clients cost ~2N
    descriptors (client + server side of each socket)."""
    try:
        import resource
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = min(hard, max(soft, need))
        if want > soft:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
    except (ImportError, ValueError, OSError):
        pass


async def _client(host: str, port: int, body: bytes,
                  timeout: float) -> Dict:
    """One client: POST + SSE read to completion -> {latency_s, ok,
    jobs} — the job ids captured from the stream's ``queued`` events
    feed the post-window ``/v1/jobs/<id>/timing`` attribution fetch."""
    t0 = time.perf_counter()
    try:
        reader, writer = await asyncio.open_connection(host, port)
    except OSError as e:
        return {"ok": False, "error": f"connect: {e}", "jobs": [],
                "latency_s": time.perf_counter() - t0}
    ok, err = False, None
    jobs: List[str] = []
    try:
        writer.write(
            b"POST /v1/jobs?stream=sse HTTP/1.1\r\n"
            b"Host: benor-serve\r\nContent-Type: application/json\r\n"
            + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        await writer.drain()
        status = await asyncio.wait_for(reader.readline(), timeout)
        if b" 200 " not in status:
            err = f"status {status.decode('latin1').strip()!r}"
            rest = await asyncio.wait_for(reader.read(2048), timeout)
            sep = b"\r\n\r\n"
            if sep in rest:
                body_txt = rest.split(sep, 1)[1].decode()[:200]
                err += f": {body_txt}"
        else:
            deadline = time.perf_counter() + timeout
            pending = None          # event name awaiting its data line
            while True:
                line = await asyncio.wait_for(
                    reader.readline(),
                    max(0.05, deadline - time.perf_counter()))
                if not line:
                    err = "connection closed before done event"
                    break
                if line.startswith(b"event: done"):
                    ok = True
                    break
                if line.startswith(b"event: error"):
                    err = "server error event"
                    break
                if line.startswith(b"event: "):
                    pending = line[len(b"event: "):].strip()
                elif line.startswith(b"data: ") and pending == b"queued":
                    try:
                        jobs.append(json.loads(line[len(b"data: "):])
                                    ["job"])
                    except (ValueError, KeyError):
                        pass
                    pending = None
    except (asyncio.TimeoutError, ConnectionError,
            asyncio.IncompleteReadError) as e:
        err = f"{type(e).__name__}: {e}"
    finally:
        try:
            writer.close()
        except ConnectionError:
            pass
    lat = time.perf_counter() - t0
    REGISTRY.timer("serve.client_latency").record(lat)
    return {"ok": ok, "error": err, "jobs": jobs, "latency_s": lat}


async def _get_json(host: str, port: int, path: str,
                    timeout: float = 10.0) -> dict:
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        await writer.drain()
        # read to EOF (the server sends Connection: close): a single
        # read() returns on the FIRST chunk and a segmented response
        # would hand json.loads a truncated body
        raw = b""
        deadline = time.perf_counter() + timeout
        while True:
            chunk = await asyncio.wait_for(
                reader.read(1 << 16),
                max(0.05, deadline - time.perf_counter()))
            if not chunk:
                break
            raw += chunk
    finally:
        writer.close()
    return json.loads(raw.split(b"\r\n\r\n", 1)[1])


async def _drive(host: str, port: int, clients: int, job: Dict,
                 timeout: float, ramp_s: float) -> Dict:
    stats0 = await _get_json(host, port, "/v1/stats")
    bodies = []
    for i in range(clients):
        doc = dict(job)
        doc["seed"] = int(doc.get("seed", 0)) + i
        bodies.append(json.dumps(doc).encode())
    t0 = time.perf_counter()

    async def one(i):
        if ramp_s:
            # spread connection setup across the ramp so the OS accept
            # queue isn't the thing measured; steady-state concurrency
            # is still `clients` (every client stays connected through
            # its SSE stream)
            await asyncio.sleep(ramp_s * i / max(1, clients))
        return await _client(host, port, bodies[i], timeout)

    results = await asyncio.gather(*(one(i) for i in range(clients)))
    wall = time.perf_counter() - t0
    stats1 = await _get_json(host, port, "/v1/stats")
    # attribution fetch: every completed job's stage timeline, OUTSIDE
    # the measured window (the wall clock above is already closed)
    timings = await _fetch_timings(
        host, port, [j for r in results for j in r["jobs"]])
    return {"results": results, "wall_s": wall,
            "stats0": stats0, "stats1": stats1, "timings": timings}


async def _fetch_timings(host: str, port: int,
                         job_ids: List[str]) -> List[Dict]:
    """GET /v1/jobs/<id>/timing for each id (bounded concurrency);
    unreachable/errored fetches are dropped, not fabricated."""
    sem = asyncio.Semaphore(TIMING_FETCH_CONCURRENCY)

    async def one(jid):
        async with sem:
            try:
                return await _get_json(host, port,
                                       f"/v1/jobs/{jid}/timing")
            except (OSError, ValueError, asyncio.TimeoutError):
                return None
    got = await asyncio.gather(*(one(j) for j in job_ids))
    return [t for t in got if t is not None]


def _stage_blocks(timings: List[Dict], client_mean_ms: float) -> Dict:
    """Per-stage p50/p99/mean blocks (ms) + the attribution cross-check.

    Only fully-attributed timelines count (every jobs.STAGE_NAMES stage
    present — an error job's partial timeline would skew the stage
    population low and break the telescoping identity the cross-check
    rests on); ``jobs_timed`` records the population honestly."""
    full = [t for t in timings
            if all(s in t.get("stages_s", {}) for s in STAGE_NAMES)]
    stages: Dict[str, Dict[str, float]] = {}
    mean_sum = 0.0
    for name in STAGE_NAMES:
        if full:
            arr = np.asarray([t["stages_s"][name] for t in full]) * 1e3
            blk = {"p50": round(float(np.percentile(arr, 50)), 3),
                   "p99": round(float(np.percentile(arr, 99)), 3),
                   "mean": round(float(arr.mean()), 3)}
        else:
            blk = {"p50": 0.0, "p99": 0.0, "mean": 0.0}
        stages[name] = blk
        mean_sum += blk["mean"]
    coverage = (mean_sum / client_mean_ms) if client_mean_ms > 0 else 0.0
    attribution = {
        "jobs_timed": len(full),
        "stage_mean_sum_ms": round(mean_sum, 3),
        "client_mean_ms": round(client_mean_ms, 3),
        "coverage": round(coverage, 4),
        "band": ATTRIBUTION_BAND,
        "ok": bool(full) and abs(coverage - 1.0) <= ATTRIBUTION_BAND,
    }
    return {"stages": stages, "attribution": attribution}


def build_serve_manifest(drive: Dict, clients: int, job: Dict) -> Dict:
    """Reduce one load run to the pinned-schema manifest document."""
    import jax

    results = drive["results"]
    lats_ms = np.asarray([r["latency_s"] for r in results]) * 1e3
    ok = [r for r in results if r["ok"]]
    errors = len(results) - len(ok)
    s0, s1 = drive["stats0"], drive["stats1"]
    jobs_completed = s1["jobs_completed"] - s0["jobs_completed"]
    jobs_submitted = s1["jobs_submitted"] - s0["jobs_submitted"]
    launches = s1["launches"] - s0["launches"]
    dev = jax.devices()[0]
    scale = {k: job.get(k, DEFAULT_JOB.get(k)) for k in
             ("n_nodes", "n_faulty", "trials", "max_rounds", "delivery")}
    scale["kind"] = job.get("kind", "simulate")
    blocks = _stage_blocks(drive.get("timings", []),
                           float(lats_ms.mean()))
    return {
        "kind": "serve_manifest",
        "schema_version": SCHEMA_VERSION,
        "platform": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "clients": clients,
        "jobs_submitted": jobs_submitted,
        "jobs_completed": jobs_completed,
        "errors": errors,
        "duration_s": round(drive["wall_s"], 4),
        "latency_ms": {
            "p50": round(float(np.percentile(lats_ms, 50)), 3),
            "p99": round(float(np.percentile(lats_ms, 99)), 3),
            "mean": round(float(lats_ms.mean()), 3),
            "max": round(float(lats_ms.max()), 3),
        },
        "throughput_jobs_per_sec": round(
            jobs_completed / drive["wall_s"], 3) if drive["wall_s"] else 0.0,
        "launches": launches,
        "jobs_per_launch": round(jobs_completed / launches, 4)
        if launches else 0.0,
        "executor_compiles": s1["executor_compiles"],
        "stages": blocks["stages"],
        "attribution": blocks["attribution"],
        "scale": scale,
    }


def run_load(url: Optional[str] = None, clients: int = 1000,
             job: Optional[Dict] = None, timeout: float = 120.0,
             ramp_s: float = 0.0, max_batch_jobs: Optional[int] = None,
             warmup: bool = True) -> Dict:
    """Drive a load test -> the serve manifest dict.

    ``url`` targets a running server (``http://host:port``); None spins
    an in-process ServeApp on an ephemeral port for the run (the CPU
    smoke mode bench.py and the CLI default to).  ``warmup`` runs one
    throwaway client first so executor compiles land outside the
    measured window — the steady-state the SERVE_BASELINE captures
    (compile-time observability lives in perfscope, not here).
    """
    job = dict(DEFAULT_JOB if job is None else job)
    _raise_fd_limit(2 * clients + 256)
    app = None
    if url is None:
        from .server import ServeApp
        app = ServeApp(max_batch_jobs=max_batch_jobs).start()
        host, port = app.host, app.port
    else:
        u = url.split("//", 1)[-1]
        host, _, p = u.partition(":")
        port = int(p.split("/")[0] or 80)
    try:
        if warmup:
            # warm the TOP capacity rung before the measured window: one
            # burst of max_batch_jobs concurrent clients compiles the
            # executable every later batch reuses (the capacity policy
            # prefers a warm larger rung over compiling a tighter one),
            # so the measurement sees steady-state serving — compile
            # observability is perfscope's job, not the load test's
            stats = asyncio.run(_get_json(host, port, "/v1/stats"))
            burst = int(stats.get("max_batch_jobs", 32))
            wjob = dict(job)
            wjob["seed"] = int(wjob.get("seed", 0)) + clients + 7
            asyncio.run(_drive(host, port, burst, wjob, timeout, 0.0))
        with REGISTRY.timer("serve.load_run").time():
            drive = asyncio.run(_drive(host, port, clients, job,
                                       timeout, ramp_s))
    finally:
        if app is not None:
            app.close()
    manifest = build_serve_manifest(drive, clients, job)
    REGISTRY.gauge("serve.load_p99_ms").set(manifest["latency_ms"]["p99"])
    REGISTRY.gauge("serve.load_jobs_per_launch").set(
        manifest["jobs_per_launch"])
    REGISTRY.gauge("serve.load_queue_wait_p99_ms").set(
        manifest["stages"]["queue_wait"]["p99"])
    REGISTRY.gauge("serve.load_attribution_coverage").set(
        manifest["attribution"]["coverage"])
    return manifest
